"""Fault-tolerant fleet: job ledger, epoch fencing, chaos harness.

The tentpole robustness suite (docs/fleet_robustness.md): unit tests for
the ledger state machine and the seeded chaos monkey, plus the acceptance
test — a real localhost master/slave fleet driven through mid-job death,
frame drops and duplicate-update replay must converge to **bit-identical**
final weights vs the fault-free run, with ``fleet_status()`` counters
proving each fault actually fired.

``VELES_TPU_CHAOS_SEED`` selects the chaos RNG seed (``make chaos`` runs
the suite under three fixed seeds); the default seed is 1. The fixed
seeds are PINNED to schedules where every configured fault fires within
the short toy run — fault firing is probabilistic, so an arbitrary seed
may roll e.g. zero deaths in ~18 jobs and fail the every-fault-fired
asserts (recovery itself is seed-independent).
"""

import asyncio
import os
import threading
import time

import numpy
import pytest

from veles_tpu.core import prng
from veles_tpu.fleet.chaos import ChaosConfig, ChaosMonkey
from veles_tpu.fleet.ledger import (
    DONE, FENCE_DUPLICATE, FENCE_FOREIGN, FENCE_REQUEUED,
    FENCE_STALE_EPOCH, FENCE_UNKNOWN, JobLedger, OUTSTANDING, REQUEUED)
from veles_tpu.fleet.protocol import encode_frame
from veles_tpu.launcher import Launcher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow

CHAOS_SEED = int(os.environ.get("VELES_TPU_CHAOS_SEED", "1"))

pytestmark = pytest.mark.chaos


class TestJobLedger:
    def test_issue_settle_exactly_once(self):
        ledger = JobLedger()
        job = ledger.issue("slave-1", timeout=60.0)
        assert ledger.state_of(job) == OUTSTANDING
        assert ledger.settle(job, "slave-1") is None  # apply
        assert ledger.state_of(job) == DONE
        # duplicate replay of the same update is fenced
        assert ledger.settle(job, "slave-1") == FENCE_DUPLICATE
        snap = ledger.snapshot()
        assert snap["issued"] == 1 and snap["done"] == 1
        assert snap["fenced"][FENCE_DUPLICATE] == 1

    def test_unknown_and_foreign_fenced(self):
        ledger = JobLedger()
        assert ledger.settle(99, "slave-1") == FENCE_UNKNOWN
        assert ledger.settle(None, "slave-1") == FENCE_UNKNOWN
        assert ledger.settle("1", "slave-1") == FENCE_UNKNOWN
        job = ledger.issue("slave-1", timeout=60.0)
        # another slave cannot settle someone else's lease
        assert ledger.settle(job, "slave-2") == FENCE_FOREIGN
        assert ledger.state_of(job) == OUTSTANDING
        assert ledger.settle(job, "slave-1") is None

    def test_drop_requeues_then_fences_zombie(self):
        ledger = JobLedger()
        j1 = ledger.issue("slave-1", timeout=60.0)
        j2 = ledger.issue("slave-1", timeout=60.0)
        j3 = ledger.issue("slave-2", timeout=60.0)
        assert sorted(ledger.requeue_for_slave("slave-1")) == [j1, j2]
        assert ledger.state_of(j1) == REQUEUED
        assert ledger.state_of(j3) == OUTSTANDING  # other slave untouched
        # the zombie's late update must not be applied
        assert ledger.settle(j1, "slave-1") == FENCE_REQUEUED
        snap = ledger.snapshot()
        assert snap["requeued_dropped"] == 2
        assert snap["fenced"][FENCE_REQUEUED] == 1

    def test_lease_expiry(self):
        ledger = JobLedger()
        job = ledger.issue("slave-1", timeout=10.0, now=1000.0)
        # before the deadline: nothing to expire
        assert not ledger.expire_if_outstanding(job, now=1005.0)
        assert ledger.expire_if_outstanding(job, now=1011.0)
        assert ledger.state_of(job) == REQUEUED
        # idempotent: a second timer firing must not double-count
        assert not ledger.expire_if_outstanding(job, now=1012.0)
        assert ledger.snapshot()["requeued_expired"] == 1
        # a DONE lease never expires
        done = ledger.issue("slave-1", timeout=10.0, now=1000.0)
        assert ledger.settle(done, "slave-1") is None
        assert not ledger.expire_if_outstanding(done, now=9999.0)

    def test_gc_watermark_keeps_fencing_duplicates(self):
        """Settled leases beyond keep_settled are GC'd, but their ids must
        still fence as duplicates — never as unknown-and-applicable."""
        ledger = JobLedger(keep_settled=5)
        jobs = [ledger.issue("s", timeout=60.0) for _ in range(20)]
        for job in jobs:
            assert ledger.settle(job, "s") is None
        # the oldest ids were GC'd out of the lease table
        assert len(ledger._leases) <= 5
        assert ledger.settle(jobs[0], "s") == FENCE_DUPLICATE
        assert ledger.state_of(jobs[0]) == DONE  # via watermark

    def test_requeue_after_gc_warmup(self):
        """Regression: requeue_for_slave retires leases (triggering GC
        pops on the same dict) while walking the lease table — must not
        die with 'dictionary changed size during iteration' once the
        settled backlog reaches keep_settled."""
        ledger = JobLedger(keep_settled=3)
        for _ in range(10):
            job = ledger.issue("s", timeout=60.0)
            assert ledger.settle(job, "s") is None
        open_job = ledger.issue("s", timeout=60.0)
        assert ledger.requeue_for_slave("s") == [open_job]
        assert ledger.state_of(open_job) == REQUEUED

    def test_outstanding_listing(self):
        ledger = JobLedger()
        j1 = ledger.issue("a", timeout=60.0)
        j2 = ledger.issue("b", timeout=60.0)
        assert sorted(ledger.outstanding()) == [j1, j2]
        assert ledger.outstanding("a") == [j1]
        ledger.settle(j1, "a")
        assert ledger.outstanding() == [j2]


class TestChaosMonkey:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="outside"):
            ChaosConfig(frame_drop=1.5)
        with pytest.raises(ValueError, match="death_mode"):
            ChaosConfig(death_mode="bogus")
        assert not ChaosConfig().any_enabled
        assert ChaosConfig(death=0.1).any_enabled

    def test_deterministic_schedule(self):
        """Same seed -> the exact same fault schedule; the whole point of
        the harness (chaos runs are replayable and assertable)."""
        def schedule(seed):
            monkey = ChaosMonkey(ChaosConfig(seed=seed, death=0.5))
            fired = []
            for _ in range(64):
                try:
                    monkey.maybe_die()
                    fired.append(False)
                except ConnectionResetError:
                    fired.append(True)
            return fired

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)
        monkey = ChaosMonkey(ChaosConfig(seed=7, death=0.5))
        for _ in range(64):
            try:
                monkey.maybe_die()
            except ConnectionResetError:
                pass
        assert monkey.counters["deaths"] == sum(schedule(7))

    def test_from_config_disabled_by_default(self):
        from veles_tpu.core.config import root
        saved = root.common.fleet.chaos.__content__()
        try:
            root.common.fleet.chaos.update(dict(
                enabled=False, frame_drop=0.0, death=0.0))
            assert ChaosMonkey.from_config() is None
            root.common.fleet.chaos.update(dict(
                enabled=True, frame_drop=0.25, seed=3))
            monkey = ChaosMonkey.from_config()
            assert monkey is not None
            assert monkey.config.frame_drop == 0.25
            assert monkey.config.seed == 3
            # probabilities set but enabled=False -> force-disabled
            root.common.fleet.chaos.enabled = False
            assert ChaosMonkey.from_config() is None
        finally:
            root.common.fleet.chaos.update(saved)
            root.common.fleet.chaos.enabled = saved.get("enabled", False)

    def test_duplicate_update_replays_frame(self):
        """An update frame rolls the duplicate fault and ships twice,
        with the chaos tallies stamped into the payload."""
        written = []

        class FakeWriter:
            def write(self, data):
                written.append(data)

            async def drain(self):
                pass

        monkey = ChaosMonkey(ChaosConfig(seed=1, duplicate_update=1.0))
        asyncio.run(monkey.write_frame(
            FakeWriter(), {"type": "update", "update": [], "job_id": 5},
            b"k"))
        assert len(written) == 2
        assert monkey.counters["updates_duplicated"] == 1
        # non-update frames are never duplicated
        written.clear()
        asyncio.run(monkey.write_frame(
            FakeWriter(), {"type": "job_request"}, b"k"))
        assert len(written) == 1


class TestEpochFencing:
    def _server(self):
        from veles_tpu.fleet.server import Server, SlaveDescription
        server = Server("127.0.0.1:0", None, secret="fence-test")
        server.epoch = "epoch-A"
        return server, SlaveDescription("slave-1", {})

    def test_stale_epoch_fenced(self):
        server, slave = self._server()
        job = server.ledger.issue(slave.id, timeout=60.0)
        msg = {"job_id": job, "epoch": "epoch-OLD", "update": []}
        assert server._fence_update(slave, msg) == FENCE_STALE_EPOCH
        # the lease is still open: fencing a stale answer must not
        # consume it
        assert server.ledger.state_of(job) == OUTSTANDING
        assert server.ledger.snapshot()["fenced"][FENCE_STALE_EPOCH] == 1

    def test_current_epoch_applies_once(self):
        server, slave = self._server()
        job = server.ledger.issue(slave.id, timeout=60.0)
        msg = {"job_id": job, "epoch": "epoch-A", "update": []}
        assert server._fence_update(slave, msg) is None
        assert server._fence_update(slave, msg) == FENCE_DUPLICATE

    def test_missing_epoch_fenced(self):
        server, slave = self._server()
        job = server.ledger.issue(slave.id, timeout=60.0)
        assert server._fence_update(
            slave, {"job_id": job, "update": []}) == FENCE_STALE_EPOCH

    def test_fleet_status_shape(self):
        server, _ = self._server()
        status = server.fleet_status()
        assert status["epoch"] == "epoch-A"
        assert status["ledger"]["issued"] == 0
        assert status["chaos"] == {}
        assert "queued_jobs" in status and "blacklist" in status


class _ScriptedWorkflow:
    """Minimal fleet workflow: serves ``jobs`` payloads, then
    ``when_empty`` (None = "no more jobs", False = park the request —
    keeps a slave waiting, for restart scenarios)."""

    def __init__(self, jobs, when_empty=None, on_applied=None):
        self.checksum = "chaos-restart"
        self.jobs = list(jobs)
        self.when_empty = when_empty
        self.on_applied = on_applied
        self.applied = []

    def generate_initial_data_for_slave(self, slave):
        return None

    def generate_data_for_slave(self, slave):
        return self.jobs.pop(0) if self.jobs else self.when_empty

    def apply_data_from_slave(self, update, slave):
        self.applied.append(update)
        if self.on_applied is not None:
            self.on_applied()

    def apply_initial_data_from_master(self, initial):
        pass

    def do_job(self, job, callback):
        callback(job * 10)

    def drop_slave(self, slave):
        pass

    def has_more_jobs(self):
        return bool(self.jobs)


class TestMasterRestart:
    def test_client_rejoins_new_epoch_with_restored_budget(self):
        """Recovery-matrix row "master restart": the client survives the
        master's death, re-handshakes with the successor (new epoch UUID)
        on the same port, gets its reconnect budget restored, and the new
        master's ledger fences nothing."""
        from veles_tpu.fleet.client import Client
        from veles_tpu.fleet.server import Server

        first_done = threading.Event()
        # serves one job, then PARKS the next request (backpressure) so
        # the client is mid-session when the master dies
        wf1 = _ScriptedWorkflow([1], when_empty=False,
                                on_applied=first_done.set)
        server1 = Server("127.0.0.1:0", wf1,
                         secret="chaos-restart").start()
        port = server1.port
        client = Client("127.0.0.1:%d" % port, _ScriptedWorkflow([]),
                        secret="chaos-restart",
                        max_reconnect_attempts=50, chaos=False).start()
        finished = threading.Event()
        client.on_finished = finished.set
        try:
            assert first_done.wait(10), "first master served no job"
            epoch1 = server1.epoch
            server1.stop()
            # burn some reconnect budget while the master is down
            deadline = time.time() + 5
            while client._attempts == 0 and time.time() < deadline:
                time.sleep(0.05)
            assert client._attempts > 0, "client never started retrying"
            wf2 = _ScriptedWorkflow([2])
            server2 = Server("127.0.0.1:%d" % port, wf2,
                             secret="chaos-restart").start()
            try:
                assert finished.wait(30), "client never finished on the "\
                    "restarted master"
                assert epoch1 != server2.epoch
                assert client.master_epoch == server2.epoch
                assert client._attempts == 0, "budget not restored"
                assert wf2.applied == [20]
                snap = server2.ledger.snapshot()
                assert snap["done"] == 1 and snap["fenced_total"] == 0
            finally:
                server2.stop()
        finally:
            client.stop()
            server1.stop()


class TestPausedBackoff:
    def test_paused_poll_backs_off_exponentially(self, monkeypatch):
        """A long-paused slave must not poll at a steady 2 Hz: the sleeps
        between job_requests double up to PAUSE_POLL_MAX and reset once a
        real job arrives."""
        from veles_tpu.fleet.client import Client

        from test_fleet import FakeReader

        key = b"backoff-test"
        frames = [
            {"type": "welcome", "id": "slave-1", "epoch": "e1"},
        ] + [{"type": "job", "paused": True}] * 6 + [
            {"type": "job", "job": None},
        ]
        reader = FakeReader(b"".join(encode_frame(f, key)
                                     for f in frames))

        class NullWriter:
            def write(self, data):
                pass

            async def drain(self):
                pass

        sleeps = []
        real_sleep = asyncio.sleep

        async def fake_sleep(duration, *args, **kwargs):
            sleeps.append(duration)
            await real_sleep(0)

        monkeypatch.setattr(asyncio, "sleep", fake_sleep)
        client = Client("127.0.0.1:1", _ScriptedWorkflow([]),
                        secret="backoff-test", chaos=False)
        done = asyncio.run(client._work(reader, NullWriter()))
        assert done is True
        assert sleeps == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0]


def _synthetic_kw(max_epochs=3):
    rng = numpy.random.RandomState(0)
    data = rng.rand(300, 8).astype(numpy.float32)
    labels = (data[:, 0] > 0.5).astype(numpy.int32)
    return dict(
        layers=(8, 2),
        loader_kwargs=dict(data=data, labels=labels,
                           class_lengths=[0, 60, 240],
                           minibatch_size=60,
                           normalization_type="linear"),
        learning_rate=0.3, max_epochs=max_epochs)


def _seed_training():
    prng.get("default").seed(42)
    prng.get("loader").seed(43)


def _run_fleet(kw, chaos=None):
    """One master + one slave over loopback; returns (final weight
    arrays, best validation errors, master fleet_status, slave agent)."""
    _seed_training()
    master = Launcher(listen_address="127.0.0.1:0")
    wf_m = MLPWorkflow(master, name="chaos-t", **kw)
    master.initialize()
    thread = threading.Thread(target=master.run, daemon=True)
    thread.start()
    _seed_training()
    slave = Launcher(master_address="127.0.0.1:%d" % master.agent.port,
                     chaos=chaos)
    MLPWorkflow(slave, name="chaos-t", **kw)
    slave.initialize()
    slave.run()
    thread.join(120)
    assert not thread.is_alive(), "master did not finish"
    status = master.agent.fleet_status()
    weights = []
    for gd in wf_m.gds:
        weights.append(numpy.asarray(gd.weights.mem).copy())
        weights.append(numpy.asarray(gd.bias.mem).copy())
    best = wf_m.decision.best_n_err[VALID]
    slave_agent = slave.agent
    master.stop()
    slave.stop()
    return weights, best, status, slave_agent


@pytest.fixture
def chaos_config_reset():
    from veles_tpu.core.config import root
    saved = root.common.fleet.chaos.__content__()
    yield
    root.common.fleet.chaos.update(dict(
        enabled=False, seed=1, frame_delay=0.0, frame_drop=0.0,
        slow_job=0.0, duplicate_update=0.0, death=0.0))
    root.common.fleet.chaos.update(saved)


def _control_kw(max_epochs=3):
    """Like :func:`_synthetic_kw` but with a minibatch size the
    8-device data axis divides (the sharded fused tick's
    requirement)."""
    rng = numpy.random.RandomState(0)
    data = rng.rand(320, 8).astype(numpy.float32)
    labels = (data[:, 0] > 0.5).astype(numpy.int32)
    return dict(
        layers=(8, 2),
        loader_kwargs=dict(data=data, labels=labels,
                           class_lengths=[0, 64, 256],
                           minibatch_size=64,
                           normalization_type="linear"),
        learning_rate=0.3, max_epochs=max_epochs)


def _final_weights(wf):
    return [numpy.asarray(gd.weights.mem).copy() for gd in wf.gds]


def _run_standalone_pod(kw):
    """The single-process reference: the SAME fused step on the SAME
    8-device CPU mesh, per-minibatch serving (fused_sweep=False mirrors
    the fleet's per-job cadence) — the bit-identity anchor for the
    in-program fleet runs."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    _seed_training()
    launcher = Launcher()
    wf = MLPWorkflow(launcher, name="mr-chaos",
                     mesh=build_mesh(devices=jax.devices()[:8], data=8),
                     fused_sweep=False, fused_pipeline=False, **kw)
    launcher.initialize()
    launcher.run()
    weights = _final_weights(wf)
    best = wf.decision.best_n_err[VALID]
    launcher.stop()
    return weights, best


def _run_fleet_control(kw, chaos=None):
    """One control-plane master + one mesh-sharded slave over loopback.
    Returns (master weights, slave weights, best, status, client)."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    _seed_training()
    master = Launcher(listen_address="127.0.0.1:0")
    wf_m = MLPWorkflow(master, name="mr-chaos", **kw)
    master.initialize()
    thread = threading.Thread(target=master.run, daemon=True)
    thread.start()
    _seed_training()
    slave = Launcher(master_address="127.0.0.1:%d" % master.agent.port,
                     chaos=chaos)
    wf_s = MLPWorkflow(slave, name="mr-chaos",
                       mesh=build_mesh(devices=jax.devices()[:8],
                                       data=8), **kw)
    slave.initialize()
    slave.run()
    thread.join(180)
    assert not thread.is_alive(), "master did not finish"
    status = master.agent.fleet_status()
    master_weights = _final_weights(wf_m)
    slave_weights = _final_weights(wf_s)
    best = wf_m.decision.best_n_err[VALID]
    client = slave.agent
    master.stop()
    slave.stop()
    return master_weights, slave_weights, best, status, client


@pytest.fixture
def control_plane_mode():
    from veles_tpu.core.config import root
    saved = root.common.fleet.get("plane", "data")
    root.common.fleet.plane = "control"
    yield
    root.common.fleet.plane = saved


class _FrameWriter:
    """Captures written frames, decoded."""

    def __init__(self, key=b"mr-test"):
        self.key = key
        self.frames = []

    def write(self, data):
        from veles_tpu.fleet.protocol import decode_frame_bytes
        self.frames.append(decode_frame_bytes(data, self.key))

    async def drain(self):
        pass


class _SyncRecordingWorkflow:
    """Master-side workflow double for the sync/payload unit tests."""

    checksum = "mr-test"

    def __init__(self):
        self.applied = []
        self.synced = []

    def apply_data_from_slave(self, data, slave=None):
        self.applied.append(data)

    def apply_sync_from_slave(self, data, slave=None):
        self.synced.append(data)

    def has_more_jobs(self):
        return True


class TestInProgramReduceChaos:
    """ROADMAP item 3's acceptance family (docs/compiler_fleet.md):
    the control-plane fleet runs the data-parallel math as ONE
    compiled program on the slave's mesh, and under chaos — slave
    death mid-step, duplicate update replay, frame drops — the run
    stays BIT-IDENTICAL to the fault-free single-process fused step on
    the same 8-device CPU mesh. The PR 1 idiom, with the math in
    XLA."""

    pytestmark = pytest.mark.fleet_mr

    def test_control_plane_chaos_bit_identical(self, chaos_config_reset,
                                               control_plane_mode):
        kw = _control_kw(max_epochs=3)
        ref_weights, ref_best = _run_standalone_pod(kw)

        # fault-free fleet first: the wire refit alone must not move a
        # bit vs the single-process run, and the fences must sync the
        # master to the slave's replica every epoch
        (m_clean, s_clean, clean_best, clean_status,
         _) = _run_fleet_control(kw)
        assert clean_status["plane"] == "control"
        assert clean_status["sync"]["applied"] == 3  # one per epoch
        assert clean_status["ledger"]["fenced_total"] == 0
        assert clean_best == ref_best
        for got, expected in zip(s_clean, ref_weights):
            numpy.testing.assert_array_equal(got, expected)
        for got, expected in zip(m_clean, ref_weights):
            numpy.testing.assert_array_equal(got, expected)

        # now with chaos: mid-step deaths (disconnect), dropped
        # frames, duplicate replay, stragglers
        chaos = dict(enabled=True, seed=CHAOS_SEED,
                     death=0.18, death_mode="disconnect",
                     frame_drop=0.04, frame_delay=0.10,
                     frame_delay_ms=5.0,
                     duplicate_update=0.25,
                     slow_job=0.25, slow_job_ms=20.0)
        (m_chaos, s_chaos, chaos_best, status,
         client) = _run_fleet_control(kw, chaos=chaos)

        counters = client.chaos.counters
        assert counters["deaths"] >= 1, counters
        assert counters["updates_duplicated"] >= 1, counters
        ledger = status["ledger"]
        # deaths/drops -> lease requeue -> re-issued work -> the
        # rollback protocol realigned the slave's local replica
        assert ledger["requeued"] >= 1, ledger
        assert ledger["fenced"]["duplicate"] >= 1, ledger
        assert client.rollbacks >= 1
        # every epoch fence still synced the master (resend-until-ack)
        assert status["sync"]["applied"] >= 3, status["sync"]
        # no weight payload ever crossed the post-handshake wire
        assert status.get("payload_rejects", 0) == 0

        # the point of it all, now with the math in XLA: bit-identical
        # to the fault-free SINGLE-PROCESS run
        assert chaos_best == ref_best
        for got, expected in zip(s_chaos, ref_weights):
            numpy.testing.assert_array_equal(got, expected)
        for got, expected in zip(m_chaos, ref_weights):
            numpy.testing.assert_array_equal(got, expected)

    def test_update_with_weight_payload_rejected(self):
        """Satellite: a control-plane master must REJECT (not silently
        ignore) a frame carrying the data-plane ``update`` key — a
        zombie cannot park stale weights a future refactor might
        apply. The lease stays OUTSTANDING (liveness: the hang timer
        requeues it)."""
        from veles_tpu.fleet.server import Server, SlaveDescription

        wf = _SyncRecordingWorkflow()
        server = Server("127.0.0.1:0", wf, secret="mr-test",
                        plane="control")
        server.epoch = "epoch-A"
        slave = SlaveDescription("slave-1", {})
        job = server.ledger.issue(slave.id, timeout=60.0)
        writer = _FrameWriter()
        msg = {"type": "update", "job_id": job, "epoch": "epoch-A",
               "update": [{"weights": [1.0]}], "tick": 1}

        async def drive():
            server._loop = asyncio.get_running_loop()
            await server._apply_update(slave, writer, msg)

        asyncio.run(drive())
        assert server._payload_rejects == 1
        assert wf.applied == []  # never touched master state
        assert slave.jobs_done == 0
        assert server.ledger.state_of(job) == OUTSTANDING
        assert writer.frames[-1]["fenced"] == "payload-rejected"
        assert server.fleet_status()["payload_rejects"] == 1

    def test_keepalive_frame_not_counted_as_work(self):
        """Satellite: completed-work bookkeeping (jobs_done, job
        timing, respawn-budget reset) happens AFTER the payload branch
        — a metrics-only keepalive must not masquerade as a finished
        job in fleet_status(). Holds on BOTH planes."""
        from veles_tpu.fleet.server import Server, SlaveDescription

        for plane, payload_key in (("data", "update"),
                                   ("control", "results")):
            wf = _SyncRecordingWorkflow()
            server = Server("127.0.0.1:0", wf, secret="mr-test",
                            plane=plane)
            server.epoch = "epoch-A"
            slave = SlaveDescription("slave-1", {})
            slave.job_started = time.time()
            writer = _FrameWriter()
            lease = server.ledger.issue(slave.id, 60.0)
            keepalive = {"type": "update", "job_id": lease,
                         "epoch": "epoch-A",
                         "metrics": [["veles_x", "gauge", [], 1.0]]}

            async def drive(msg):
                server._loop = asyncio.get_running_loop()
                await server._apply_update(slave, writer, msg)

            asyncio.run(drive(keepalive))
            assert slave.jobs_done == 0, plane
            assert slave.job_times == [], plane
            assert wf.applied == [], plane
            # ...and the lease is NOT consumed: settling a resultless
            # frame would silently drop that minibatch from the run —
            # the hang timer requeues it instead
            assert server.ledger.state_of(lease) == OUTSTANDING, plane
            assert writer.frames[-1]["fenced"] == "no-results", plane
            # a REAL update still books the work
            real = {"type": "update",
                    "job_id": server.ledger.issue(slave.id, 60.0),
                    "epoch": "epoch-A", payload_key: [{"n_err": 1}],
                    "tick": 1}
            asyncio.run(drive(real))
            assert slave.jobs_done == 1, plane
            assert wf.applied == [[{"n_err": 1}]], plane

    def test_zombie_sync_fenced(self):
        """The stale-epoch-zombie family: fence syncs from a previous
        master incarnation, or chasing a job this master never
        accepted from that process, are rejected — master weights
        stay untouched."""
        from veles_tpu.fleet.server import Server, SlaveDescription

        wf = _SyncRecordingWorkflow()
        server = Server("127.0.0.1:0", wf, secret="mr-test",
                        plane="control")
        server.epoch = "epoch-A"
        slave = SlaveDescription("slave-1", {})
        writer = _FrameWriter()

        async def drive(msg):
            server._loop = asyncio.get_running_loop()
            await server._apply_sync(slave, writer, msg)

        # zombie from the previous master incarnation
        asyncio.run(drive({"type": "sync", "job_id": 3,
                           "epoch": "epoch-OLD",
                           "sync": [{"weights": [9.0]}]}))
        assert writer.frames[-1]["fenced"] == FENCE_STALE_EPOCH
        # right epoch, but the job was never accepted from this process
        asyncio.run(drive({"type": "sync", "job_id": 3,
                           "epoch": "epoch-A",
                           "sync": [{"weights": [9.0]}]}))
        assert writer.frames[-1]["fenced"] == "unsettled-job"
        assert wf.synced == []
        assert server._sync_counters["fenced"] == 2
        # the accepted fence applies (idempotent on resend)
        server._accepted_jobs[(slave.mid, slave.pid)] = 3
        for _ in range(2):
            asyncio.run(drive({"type": "sync", "job_id": 3,
                               "epoch": "epoch-A",
                               "sync": [{"weights": [7.0]}]}))
        assert writer.frames[-1].get("fenced") is None
        assert wf.synced == [[{"weights": [7.0]}]] * 2
        assert server._sync_counters["applied"] == 2

    def test_reduce_stats_reach_master_scrape(self, control_plane_mode):
        """Observability end to end: with the metrics plane enabled,
        the slave's in-program reduce counters (veles_fleet_reduce_*,
        chip idle) piggyback on update frames, land in the master's
        fleet_status()["reduce"] summary, and re-export slave-labeled
        from the master's registry."""
        from veles_tpu.observe.metrics import (MetricsRegistry,
                                               get_metrics_registry,
                                               publish_fleet)
        from veles_tpu.observe.xla_stats import get_compile_tracker
        from veles_tpu.parallel.mapreduce import get_reduce_stats

        registry = get_metrics_registry()
        tracker = get_compile_tracker()
        was_metered, was_tracked = registry.enabled, tracker.enabled
        registry.enable()
        tracker.enabled = True
        get_reduce_stats().reset()
        try:
            kw = _control_kw(max_epochs=1)
            _, _, _, status, _ = _run_fleet_control(kw)
            reduce_rows = status.get("reduce") or {}
            assert reduce_rows, status
            entry = next(iter(reduce_rows.values()))
            assert entry["steps"] >= 1
            assert entry["bytes"] > 0
            # the master-side exposition re-exports the slave's rows
            scrape = MetricsRegistry(enabled=True)

            class _Server:
                def fleet_status(self):
                    return status

                def slave_metrics(self):
                    return {"slave-1": [
                        ("veles_fleet_reduce_steps_total", "counter",
                         {"precision": "f32"}, entry["steps"])]}

            publish_fleet(scrape, _Server())
            text = scrape.expose()
            assert 'veles_fleet_reduce_steps_total{precision="f32",' \
                'slave="slave-1"}' in text
        finally:
            if not was_metered:
                registry.disable()
            tracker.enabled = was_tracked
            get_reduce_stats().reset()

    def test_dashboard_renders_control_plane_cell(self):
        """The web-status fleet column shows the plane, fence syncs
        and the per-slave in-program reduce summary."""
        from veles_tpu.web_status import format_fleet_health
        cell = format_fleet_health({
            "plane": "control",
            "ledger": {"issued": 15, "done": 15},
            "sync": {"applied": 3, "fenced": 1},
            "reduce": {"slave-1": {"steps": 15, "bytes": 1.2e6,
                                   "idle": 0.04}}})
        assert "control-plane" in cell
        assert "3 syncs (1 fenced)" in cell
        assert "in-program reduce: 15 steps" in cell
        assert "1.2 MB wire" in cell
        assert "idle 4%" in cell
        # data-plane cells are unchanged (no plane/reduce noise)
        cell = format_fleet_health({"ledger": {"issued": 2, "done": 1}})
        assert cell == "1/2 jobs done"

    def test_plane_mismatch_fails_handshake(self):
        """A mixed data/control fleet must fail loudly at the
        handshake, naming the knob — not stall mid-run."""
        from veles_tpu.fleet.client import Client
        from veles_tpu.fleet.server import Server

        server = Server("127.0.0.1:0", _ScriptedWorkflow([1]),
                        secret="chaos-restart", plane="control").start()
        try:
            client = Client("127.0.0.1:%d" % server.port,
                            _ScriptedWorkflow([]),
                            secret="chaos-restart", chaos=False,
                            plane="data")
            finished = threading.Event()
            client.on_finished = finished.set
            client.start()
            assert finished.wait(10), "client never finished"
            assert client.refusal is not None
            assert "fleet plane mismatch" in client.refusal
            assert "root.common.fleet.plane" in client.refusal
            assert not server.slaves
            client.stop()
        finally:
            server.stop()


class TestChaosConvergence:
    """THE acceptance test: faults fire, training result is unchanged."""

    def test_fleet_survives_chaos_bit_identical(self, chaos_config_reset):
        kw = _synthetic_kw(max_epochs=3)
        clean_weights, clean_best, clean_status, _ = _run_fleet(kw)
        # the fault-free run must itself be clean
        assert clean_status["ledger"]["requeued"] == 0
        assert clean_status["ledger"]["fenced_total"] == 0

        chaos = dict(enabled=True, seed=CHAOS_SEED,
                     death=0.18, death_mode="disconnect",
                     frame_drop=0.04, frame_delay=0.10,
                     frame_delay_ms=5.0,
                     duplicate_update=0.25,
                     slow_job=0.25, slow_job_ms=20.0)
        weights, best, status, slave_agent = _run_fleet(kw, chaos=chaos)

        # every configured fault actually fired (slave-side tallies)...
        counters = slave_agent.chaos.counters
        assert counters["deaths"] >= 1, counters
        assert counters["frames_dropped"] >= 1, counters
        assert counters["updates_duplicated"] >= 1, counters
        assert counters["jobs_slowed"] >= 1, counters
        assert counters["frames_delayed"] >= 1, counters
        # ...and the master's ledger proves the recovery machinery ran:
        # deaths/drops -> explicit lease requeue, replays -> fencing
        ledger = status["ledger"]
        assert ledger["requeued"] >= 1, ledger
        assert ledger["fenced"]["duplicate"] >= 1, ledger
        assert ledger["done"] >= 15  # 3 epochs x 5 minibatches
        # chaos tallies reached the dashboard feed too
        assert status["chaos"].get("updates_duplicated", 0) >= 1

        # the point of it all: the faulted run converges to the SAME
        # model, bit for bit
        assert best == clean_best
        assert len(weights) == len(clean_weights)
        for got, expected in zip(weights, clean_weights):
            numpy.testing.assert_array_equal(got, expected)

    def test_dashboard_renders_chaos_counters(self):
        from veles_tpu.web_status import format_fleet_health
        cell = format_fleet_health({
            "ledger": {"issued": 20, "done": 17, "requeued": 2,
                       "fenced_total": 3},
            "chaos": {"deaths": 1, "frames_dropped": 2,
                      "updates_duplicated": 0}})
        assert "17/20 jobs done" in cell
        assert "2 requeued" in cell and "3 fenced" in cell
        assert "1 deaths" in cell and "2 frames dropped" in cell
        assert "updates" not in cell  # zero tallies are elided
        assert format_fleet_health(None) == ""
