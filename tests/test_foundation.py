"""Foundation tests, mirroring reference test_config.py / test_mutable.py /
test_random.py coverage."""

import pickle

import numpy
import pytest

from veles_tpu.core.config import Config, ConfigError, root, validate_kwargs
from veles_tpu.core.mutable import Bool, link, unlink
from veles_tpu.core import prng
from veles_tpu.core.registry import (
    UnitRegistry, damerau_levenshtein, MappedObjectsRegistry)
from veles_tpu.core.pickling import Pickleable


class TestConfig:
    def test_materialize_and_get(self):
        cfg = Config("test")
        cfg.a.b.c = 42
        assert cfg.a.b.c == 42
        assert cfg.a.b.get("c") == 42
        assert cfg.a.b.get("missing", 7) == 7
        assert "c" in cfg.a.b
        assert "missing" not in cfg.a.b

    def test_update_nested(self):
        cfg = Config("test")
        cfg.update({"x": {"y": 1, "z": 2}, "w": 3})
        assert cfg.x.y == 1 and cfg.x.z == 2 and cfg.w == 3
        cfg.x.update(y=10)
        assert cfg.x.y == 10 and cfg.x.z == 2

    def test_protect(self):
        cfg = Config("test")
        cfg.key = 1
        cfg.protect("key")
        with pytest.raises(ConfigError):
            cfg.key = 2

    def test_validate_kwargs(self):
        cfg = Config("test")
        with pytest.raises(ConfigError):
            validate_kwargs("caller", oops=cfg.not_set_anywhere)

    def test_root_defaults(self):
        assert root.common.engine.compute_dtype == "bfloat16"


class TestBool:
    def test_leaf(self):
        b = Bool(False)
        assert not b
        b <<= True
        assert b

    def test_expressions(self):
        a, b = Bool(True), Bool(False)
        c = a & b
        d = a | b
        e = a ^ b
        f = ~a
        assert not c and d and e and not f
        b <<= True
        assert c and d and not e

    def test_triggers(self):
        b = Bool(False)
        fired = []
        b.on_true = lambda: fired.append("t")
        b.on_false = lambda: fired.append("f")
        b <<= True
        b <<= True  # no edge
        b <<= False
        assert fired == ["t", "f"]

    def test_pickle(self):
        a, b = Bool(True), Bool(False)
        c = a | b
        c2 = pickle.loads(pickle.dumps(c))
        assert bool(c2) == bool(c)


class TestLinks:
    def test_link_and_unlink(self):
        class P:
            pass

        class C:
            pass

        p, c = P(), C()
        p.value = 5
        link(c, "value", p)
        assert c.value == 5
        p.value = 6
        assert c.value == 6
        unlink(c, "value")
        p.value = 7
        assert c.value == 6

    def test_two_way(self):
        class P:
            pass

        class C:
            pass

        p, c = P(), C()
        p.v = 1
        link(c, "v", p, two_way=True)
        c.v = 9
        assert p.v == 9


class TestPrng:
    def test_reproducible(self):
        a = prng.RandomGenerator("t1").seed(123)
        b = prng.RandomGenerator("t2").seed(123)
        assert numpy.array_equal(a.permutation(100), b.permutation(100))
        ka, kb = a.next_key(), b.next_key()
        import jax
        assert numpy.array_equal(
            jax.random.normal(ka, (4,)), jax.random.normal(kb, (4,)))

    def test_state_roundtrip(self):
        a = prng.RandomGenerator("t3").seed(7)
        a.permutation(10)
        a.next_key()
        state = a.__getstate__()
        b = prng.RandomGenerator.__new__(prng.RandomGenerator)
        b.__setstate__(state)
        assert numpy.array_equal(a.permutation(50), b.permutation(50))
        import jax
        assert numpy.array_equal(
            jax.random.key_data(a.next_key()),
            jax.random.key_data(b.next_key()))

    def test_registry(self):
        assert prng.get("k") is prng.get("k")
        assert prng.get("k") is not prng.get("other")

    def test_replay_key(self):
        rg = prng.RandomGenerator("t4").seed(1)
        import jax
        k1 = rg.next_key()
        assert numpy.array_equal(
            jax.random.key_data(k1), jax.random.key_data(rg.key_at(1)))


class TestRegistry:
    def test_damerau_levenshtein(self):
        assert damerau_levenshtein("abc", "abc") == 0
        assert damerau_levenshtein("abc", "acb") == 1
        assert damerau_levenshtein("abc", "xyz") == 3

    def test_kwattrs(self):
        class Base(metaclass=UnitRegistry):
            def __init__(self, alpha=1, beta=2):
                pass

        class Child(Base):
            def __init__(self, gamma=3, **kwargs):
                super().__init__(**kwargs)

        assert {"alpha", "beta", "gamma"} <= Child.KWATTRS

    def test_mapped_registry(self):
        class Codec(metaclass=MappedObjectsRegistry):
            REGISTRY = "test_codecs"

        class GzipCodec(Codec):
            MAPPING = "gz"

        assert MappedObjectsRegistry.get_mapping("test_codecs")["gz"] \
            is GzipCodec


class _Thing(Pickleable):
    def init_unpickled(self):
        super().init_unpickled()
        self.volatile_ = "rebuilt"


class _Holder(Pickleable):
    pass


class TestPickleable:
    def test_strips_underscored(self):
        t = _Thing()
        t.keep = 1
        t.volatile_ = "live"
        t2 = pickle.loads(pickle.dumps(t))
        assert t2.keep == 1
        assert t2.volatile_ == "rebuilt"

    def test_jax_arrays_to_numpy(self):
        import jax.numpy as jnp

        h = _Holder()
        h.weights = jnp.ones((3, 3))
        h2 = pickle.loads(pickle.dumps(h))
        assert isinstance(h2.weights, numpy.ndarray)
        assert numpy.array_equal(h2.weights, numpy.ones((3, 3)))


class TestMongoDuplication:
    """MongoLogHandler / duplicate_all_logging_to_mongo (reference
    logger.py:210,292) against an injected fake client — pymongo is not
    a hard dependency."""

    @staticmethod
    def _fake_client():
        class Coll:
            def __init__(self, database):
                self.database = database
                self.docs = []

            def insert_one(self, doc):
                self.docs.append(doc)

        class DB:
            def __init__(self):
                self._colls = {}

            def __getitem__(self, name):
                return self._colls.setdefault(name, Coll(self))

        class Client:
            def __init__(self):
                self._dbs = {}
                self.addr = None

            def __getitem__(self, name):
                return self._dbs.setdefault(name, DB())

        return Client()

    def test_logs_and_events_duplicate(self):
        from veles_tpu.core.logger import (
            Logger, duplicate_all_logging_to_mongo, get_event_recorder)

        client = self._fake_client()
        handler = duplicate_all_logging_to_mongo(
            "ignored:1", docid="sess", client_factory=lambda a: client,
            background=False)
        try:
            log = Logger(logger_name="mongo-test")
            # warning: above the root logger's default level, so the
            # record reaches root handlers without setup_logging()
            log.warning("hello %d", 42)
            logs = client["veles"]["logs"].docs
            assert any(d["message"] == "hello 42" and d["session"] == "sess"
                       for d in logs)
            log.event("epoch", "begin", number=3)
            events = client["veles"]["events"].docs
            assert any(e["name"] == "epoch" and e["etype"] == "begin"
                       and e["number"] == 3
                       and e["session"] == "sess" for e in events)
        finally:
            handler.close()
        # close() detached everything: nothing more arrives
        n_logs, n_events = len(logs), len(events)
        Logger(logger_name="mongo-test").warning("after close")
        Logger(logger_name="mongo-test").event("late", "single")
        assert (len(logs), len(events)) == (n_logs, n_events)
        assert not get_event_recorder()._sinks

    def test_background_emission_flushes_on_close(self):
        """The default QueueListener path: records emit off the caller's
        thread and close() flushes the queue before detaching."""
        from veles_tpu.core.logger import (
            Logger, duplicate_all_logging_to_mongo)

        client = self._fake_client()
        handler = duplicate_all_logging_to_mongo(
            "ignored:1", docid="bg", client_factory=lambda a: client)
        Logger(logger_name="mongo-bg").warning("queued %d", 7)
        handler.close()   # stops the listener, flushing the queue
        logs = client["veles"]["logs"].docs
        assert any(d["message"] == "queued 7" for d in logs)

    def test_failing_sink_is_kept_and_reported_once(self):
        from veles_tpu.core.logger import Logger, get_event_recorder

        rec = get_event_recorder()
        calls = []

        def flaky(attrs):
            calls.append(attrs)
            if len(calls) < 3:
                raise RuntimeError("sink boom")

        rec.add_sink(flaky)
        try:
            log = Logger(logger_name="sink-test")
            log.event("x", "single")   # raises: swallowed, logged once
            log.event("y", "single")   # raises: swallowed silently
            log.event("z", "single")   # recovers: delivered
            assert len(calls) == 3     # transient outage did NOT drop it
            assert rec._sinks == [flaky]
        finally:
            rec._sinks.clear()
            rec._sink_warned.clear()

    def test_missing_pymongo_reports_clearly(self, monkeypatch):
        import sys

        import pytest

        from veles_tpu.core.logger import MongoLogHandler

        # force the ImportError path even where pymongo IS installed
        # (MongoClient connects lazily, so a bad address raises nothing)
        monkeypatch.setitem(sys.modules, "pymongo", None)
        with pytest.raises(RuntimeError) as err:
            MongoLogHandler("127.0.0.1:1")
        assert "pymongo" in str(err.value)

    def test_background_events_flush_on_close(self):
        """Events in background mode ride a worker thread; close()
        drains the queue before detaching."""
        from veles_tpu.core.logger import (
            Logger, duplicate_all_logging_to_mongo)

        client = self._fake_client()
        handler = duplicate_all_logging_to_mongo(
            "ignored:1", docid="bg-ev", client_factory=lambda a: client)
        log = Logger(logger_name="mongo-bg-ev")
        for i in range(5):
            log.event("tick", "single", number=i)
        handler.close()
        events = client["veles"]["events"].docs
        assert [e["number"] for e in events] == list(range(5))
        assert all(e["session"] == "bg-ev" for e in events)
