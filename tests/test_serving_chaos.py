"""Serving survival layer: admission control, deadlines, self-healing
driver, chaos harness (docs/serving_robustness.md).

The acceptance property, mirroring ``tests/test_fleet_chaos.py``: with a
pinned seed, an injected decoder-step failure trips the circuit breaker,
the server sheds in-flight requests with retryable errors, rebuilds the
decoder and returns to ``/readyz`` OK *without a restart* — and the
re-issued greedy requests return tokens **bit-identical** to a fault-free
run. Saturation answers 429 (never a hang) and expired deadlines free
their decoder slots, both proven through the ``/healthz`` counters.

``VELES_TPU_CHAOS_SEED`` selects the chaos RNG seed (``make chaos-serve``
runs the suite under three fixed seeds). The breaker trip itself is
deterministic by construction — ``step_fail=1.0`` capped by
``step_fail_max`` — so recovery is asserted on every seed; the seed
varies the slow-step/hostile-client schedule.
"""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles_tpu.serving import ContinuousDecoder, GenerateAPI, ServingHealth
from veles_tpu.serving_chaos import (ChaosStepError, ServingChaosConfig,
                                     ServingChaosMonkey)

CHAOS_SEED = int(os.environ.get("VELES_TPU_CHAOS_SEED", "1"))

pytestmark = pytest.mark.chaos_serve


def post(url, payload, timeout=30):
    """POST JSON; returns (status_code, decoded_body) without raising."""
    data = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode()), dict(
                resp.headers)
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        try:
            body = json.loads(body)
        except ValueError:
            body = {"raw": body}
        return err.code, body, dict(err.headers)


def get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            raw = resp.read().decode()
            status = resp.status
    except urllib.error.HTTPError as err:
        raw = err.read().decode()
        status = err.code
    try:
        return status, json.loads(raw)
    except ValueError:
        return status, {"raw": raw}


@pytest.fixture(scope="module")
def model():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads, vocab


def make_api(model, **kw):
    """A toy GenerateAPI; chaos is OFF unless passed explicitly (the
    default root.common.serve.chaos has no probabilities set)."""
    params, table, heads, _ = model
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("n_tokens", 5)
    kw.setdefault("chunk", 2)
    kw.setdefault("port", 0)
    kw.setdefault("rebuild_backoff", 0.02)
    return GenerateAPI(params, table, heads, **kw)


class TestServingChaosMonkey:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="outside"):
            ServingChaosConfig(step_fail=1.5)
        with pytest.raises(ValueError, match="step_fail_max"):
            ServingChaosConfig(step_fail_max=-1)
        assert not ServingChaosConfig().any_enabled
        assert ServingChaosConfig(slow_step=0.1).any_enabled

    def test_deterministic_schedule_and_cap(self):
        def schedule(seed):
            monkey = ServingChaosMonkey(
                ServingChaosConfig(seed=seed, step_fail=0.5))
            fired = []
            for _ in range(64):
                try:
                    monkey.before_step()
                    fired.append(False)
                except ChaosStepError:
                    fired.append(True)
            return fired

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)
        capped = ServingChaosMonkey(
            ServingChaosConfig(seed=7, step_fail=1.0, step_fail_max=2))
        failures = 0
        for _ in range(16):
            try:
                capped.before_step()
            except ChaosStepError:
                failures += 1
        assert failures == 2  # the cap makes chaos runs settle
        assert capped.counters["steps_failed"] == 2

    def test_client_fault_roll_deterministic(self):
        def rolls(seed):
            monkey = ServingChaosMonkey(ServingChaosConfig(
                seed=seed, disconnect=0.3, garbage_body=0.3,
                oversize_body=0.3))
            return [monkey.roll_client_fault() for _ in range(32)]

        assert rolls(CHAOS_SEED) == rolls(CHAOS_SEED)
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=1, disconnect=1.0))
        assert monkey.roll_client_fault() == "disconnect"
        assert monkey.counters["disconnects"] == 1

    def test_from_config_disabled_by_default(self):
        from veles_tpu.core.config import root
        saved = root.common.serve.chaos.__content__()
        try:
            root.common.serve.chaos.update(dict(
                enabled=False, step_fail=0.0))
            assert ServingChaosMonkey.from_config() is None
            root.common.serve.chaos.update(dict(
                enabled=True, step_fail=0.25, seed=3,
                step_fail_max=4))
            monkey = ServingChaosMonkey.from_config()
            assert monkey is not None
            assert monkey.config.step_fail == 0.25
            assert monkey.config.step_fail_max == 4
            root.common.serve.chaos.enabled = False
            assert ServingChaosMonkey.from_config() is None
        finally:
            root.common.serve.chaos.update(saved)
            root.common.serve.chaos.enabled = saved.get("enabled", False)


class TestDecoderCancel:
    def test_cancel_queued_and_active_frees_slots(self, model):
        params, table, heads, vocab = model
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=32, n_tokens=6)
        active = dec.submit([1, 2, 3])
        queued = dec.submit([4, 5])
        dec.step()  # admits `active` into the only slot
        assert dec.cancel(queued)   # still in the admission queue
        assert dec.cancel(active)   # owns the slot
        assert not dec.cancel(active)  # idempotent
        assert dec._free == [0]
        assert queued not in dec.results and active not in dec.results
        assert not dec.busy
        assert dec.cancelled == 2
        # the freed slot admits and completes a new request cleanly
        fresh = dec.submit([1, 2, 3])
        results = dec.run_until_drained()
        assert len(results[fresh]) == 6

    def test_cancel_mid_chunk_discards_tail(self, model):
        params, table, heads, vocab = model
        ref = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=6)
        keep_ref = ref.submit([1, 2, 3])
        ref.run_until_drained()

        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=6)
        keep = dec.submit([1, 2, 3])
        victim = dec.submit([4, 5, 6])
        dec.step()
        dec.cancel(victim)
        results = dec.run_until_drained()
        assert victim not in results
        # the survivor's stream is untouched by the cancellation
        assert results[keep] == ref.results[keep_ref]


class TestHealthEndpoints:
    def test_healthz_readyz_roundtrip(self, model):
        api = make_api(model).start()
        try:
            base = "http://127.0.0.1:%d" % api.port
            code, body = get(base + "/readyz")
            assert code == 200 and body["ready"]
            code, body = get(base + "/healthz")
            assert code == 200
            assert body["breaker"] == "closed"
            assert body["counters"]["trips"] == 0
            code, _ = get(base + "/nope")
            assert code == 404
        finally:
            api.stop()
        # stopped -> not ready (the probe pair outlives the driver)
        assert not api.health.ready

    def test_restful_api_health(self):
        from test_serving import ServingHarness
        harness = ServingHarness()
        try:
            base = "http://127.0.0.1:%d" % harness.api.port
            code, body = get(base + "/readyz")
            assert code == 200 and body["ready"]
            code, body = get(base + "/healthz")
            assert code == 200 and body["name"] == "restful-api"
        finally:
            harness.close()

    def test_serving_health_admission_bookkeeping(self):
        health = ServingHealth()
        health.set_ready(True)
        assert health.try_admit(2) is None
        assert health.try_admit(2) is None
        assert health.try_admit(2) == "full"
        health.release("completed")
        assert health.try_admit(2) is None
        health.set_ready(False)
        assert health.try_admit(2) == "unready"
        snap = health.snapshot()
        assert snap["counters"]["admitted"] == 3
        assert snap["counters"]["rejected"] == 2
        assert snap["counters"]["completed"] == 1
        assert snap["inflight"] == 2


class TestAdmissionControl:
    def test_saturation_returns_429_not_a_hang(self, model):
        api = make_api(model, slots=1, max_queue=2, deadline=60.0)
        api.start()
        gate = threading.Event()
        real = api.decoder.dispatch_chunk

        def gated(n):
            gate.wait(20)
            return real(n)

        api.decoder.dispatch_chunk = gated
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            results = {}

            def call(i):
                results[i] = post(url, {"tokens": [1, 2, 3]},
                                  timeout=90)

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            deadline = time.time() + 10
            while api.health.inflight < 2 and time.time() < deadline:
                time.sleep(0.01)
            assert api.health.inflight == 2
            # the queue is full: the next arrival is shed immediately
            started = time.time()
            code, body, headers = post(url, {"tokens": [1, 2, 3]})
            assert code == 429
            assert "saturated" in body["error"]
            assert headers.get("Retry-After") == "1"
            assert time.time() - started < 5  # shed, not queued
            gate.set()
            for t in threads:
                t.join(timeout=90)
            for i in range(2):
                code, body, _ = results[i]
                assert code == 200 and len(body["tokens"]) == 5
            snap = api.health.snapshot()
            assert snap["counters"]["rejected"] >= 1
            assert snap["counters"]["completed"] == 2
        finally:
            gate.set()
            api.stop()


class TestDeadlines:
    def test_queued_and_active_expiry_free_slots(self, model):
        api = make_api(model, slots=1, chunk=1, deadline=30.0)
        api.start()
        real = api.decoder.dispatch_chunk

        def slow(n):  # ~50 ms per decode step: deadlines can lap it
            time.sleep(0.05)
            return real(n)

        api.decoder.dispatch_chunk = slow
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            results = {}

            def call(key, payload):
                results[key] = post(url, payload, timeout=90)

            # A occupies the only slot for ~1 s; B expires in the
            # admission queue long before a slot frees
            t_a = threading.Thread(target=call, args=(
                "a", {"tokens": [1, 2, 3], "n_tokens": 20}))
            t_a.start()
            deadline = time.time() + 10
            while not api.decoder.busy and time.time() < deadline:
                time.sleep(0.01)
            t_b = threading.Thread(target=call, args=(
                "b", {"tokens": [4, 5], "n_tokens": 2,
                      "deadline_s": 0.2}))
            t_b.start()
            t_a.join(timeout=90)
            t_b.join(timeout=90)
            code_a, body_a, _ = results["a"]
            assert code_a == 200 and len(body_a["tokens"]) == 20
            code_b, body_b, _ = results["b"]
            assert code_b == 504
            assert "deadline" in body_b["error"]
            # an ACTIVE request expiring mid-decode frees its slot too
            code_c, body_c, _ = post(
                url, {"tokens": [1, 2], "n_tokens": 20,
                      "deadline_s": 0.2}, timeout=90)
            assert code_c == 504
            snap = api.health.snapshot()
            assert snap["counters"]["expired"] == 2
            assert api.decoder.cancelled >= 1
            # the expired requests' slots and result entries are gone:
            # a fresh request decodes immediately
            code_d, body_d, _ = post(url, {"tokens": [1, 2, 3]},
                                     timeout=90)
            assert code_d == 200 and len(body_d["tokens"]) == 5
            assert not api.decoder._budget
            assert len(api.decoder._free) == 1
            assert not api.decoder.results  # reaped, not leaking
        finally:
            api.stop()

    def test_bad_server_default_deadline_fails_at_startup(self, model):
        """A misconfigured --serve-deadline must fail at construction,
        never surface as a 400 blaming a field the client didn't send
        (the per-request 86400 cap applies only to payload values)."""
        params, table, heads, _ = model
        for bad in (0, -5, float("inf"), float("nan"), 1e9):
            with pytest.raises(ValueError, match="serve-deadline"):
                GenerateAPI(params, table, heads, deadline=bad)
        # a server default ABOVE the per-request cap is the operator's
        # call and must not 400 implicit-deadline requests
        api = make_api(model, deadline=90000.0).start()
        try:
            code, body, _ = post(
                "http://127.0.0.1:%d/generate" % api.port,
                {"tokens": [1, 2]}, timeout=60)
            assert code == 200
        finally:
            api.stop()

    def test_wedged_driver_backstop_releases_admission(self, model):
        """A hung (non-raising) driver must not ratchet the in-flight
        gauge: the handler backstop resolves the holder itself, so the
        admission is released and the gauge cannot 429 forever."""
        api = make_api(model, slots=1, deadline=30.0)
        api.BACKSTOP_GRACE = 0.2
        api.start()
        gate = threading.Event()
        real = api.decoder.dispatch_chunk
        api.decoder.dispatch_chunk = lambda n: (gate.wait(30),
                                                real(n))[1]
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            code, body, _ = post(
                url, {"tokens": [1, 2], "deadline_s": 0.2}, timeout=30)
            assert code == 503
            assert "timed out" in body["error"]
            snap = api.health.snapshot()
            assert snap["inflight"] == 0  # released by the backstop
            assert snap["counters"]["errors"] >= 1
            gate.set()
            # the driver un-wedges and the server keeps serving
            code, body, _ = post(url, {"tokens": [1, 2]}, timeout=60)
            assert code == 200 and len(body["tokens"]) == 5
        finally:
            gate.set()
            api.stop()

    def test_bad_deadline_rejected(self, model):
        api = make_api(model).start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            # json accepts Infinity/NaN and huge floats; a non-finite
            # or overlarge deadline must 400, not crash the handler
            # (Event.wait overflows) or spuriously expire (NaN)
            for bad in (0, -1, "soon", True, float("inf"),
                        float("nan"), 1e300, 86401):
                code, body, _ = post(
                    url, {"tokens": [1], "deadline_s": bad})
                assert code == 400, bad
                assert "deadline_s" in body["error"]
            # the server survived all of them
            code, body, _ = post(url, {"tokens": [1, 2]}, timeout=60)
            assert code == 200
        finally:
            api.stop()


class TestBreakerRecovery:
    """THE acceptance test: an injected decoder-step failure trips the
    breaker; the server heals itself and the re-issued requests return
    bit-identical greedy tokens vs a fault-free run."""

    def _collect(self, api, prompts, retries=80):
        url = "http://127.0.0.1:%d/generate" % api.port
        results = {}

        def call(i):
            for attempt in range(retries):
                code, body, _ = post(url, {"tokens": prompts[i]},
                                     timeout=60)
                if code == 200:
                    results[i] = body["tokens"]
                    return
                assert code in (429, 503, 504), (code, body)
                time.sleep(0.02 * min(attempt + 1, 10))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        return results

    def test_breaker_trips_heals_and_tokens_bit_identical(self, model):
        rng = numpy.random.RandomState(CHAOS_SEED)
        vocab = model[3]
        prompts = [rng.randint(0, vocab, n).tolist() for n in (4, 6, 5)]

        clean_api = make_api(model).start()
        try:
            clean = self._collect(clean_api, prompts)
            assert clean_api.health.snapshot()["counters"]["trips"] == 0
        finally:
            clean_api.stop()
        assert sorted(clean) == [0, 1, 2]

        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, step_fail=1.0, step_fail_max=2,
            slow_step=0.25, slow_step_ms=2.0))
        api = make_api(model, chaos=monkey).start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            chaotic = self._collect(api, prompts)
            # every request completed despite the injected failures...
            assert sorted(chaotic) == [0, 1, 2]
            # ...the injected faults actually fired (both of them:
            # the trip AND the failed first rebuild probe)...
            assert monkey.counters["steps_failed"] == 2
            snap = api.health.snapshot()
            assert snap["counters"]["trips"] >= 1, snap
            assert snap["counters"]["rebuilds"] >= 1, snap
            assert snap["counters"]["shed"] >= 1, snap
            # ...the server healed WITHOUT a restart...
            assert snap["ready"] and snap["breaker"] == "closed"
            code, body = get(url + "/readyz")
            assert code == 200 and body["ready"]
            # ...and the greedy streams are bit-identical
            assert chaotic == clean
        finally:
            api.stop()

    def test_rebuild_preserves_request_id_keyspace(self, model):
        """Request ids stay monotonic across a rebuild so sampled
        requests never reuse another request's fold_in key stream."""
        api = make_api(model)
        api.decoder.submit([1, 2])
        next_before = api.decoder._next_id
        assert api._rebuild()
        assert api.decoder._next_id >= next_before + 1  # + probe

    def test_rebuild_probe_trips_cleanly_on_hung_probe(self, model):
        """A probe that makes no progress must exhaust its bounded
        step budget and fail the rebuild — never loop forever on the
        driver thread."""
        api = make_api(model)
        real_drain = ContinuousDecoder.run_until_drained

        def stuck(self, max_steps=100000, chunk=1, before_step=None):
            # simulate a decoder that dispatches but never finishes:
            # burn the budget without retiring the probe
            for _ in range(max_steps):
                if before_step is not None:
                    before_step()
            raise RuntimeError(
                "decoder did not drain in %d steps" % max_steps)

        ContinuousDecoder.run_until_drained = stuck
        try:
            assert not api._rebuild()
        finally:
            ContinuousDecoder.run_until_drained = real_drain
        # with the real drain the same rebuild succeeds
        assert api._rebuild()

    def test_trip_discards_chunk_in_flight(self, model):
        """The lag-1 pipelined driver keeps one chunk in flight; when
        the breaker trips that chunk must be DISCARDED — its tokens
        never collected into the shed request's results — and the
        retried request streams bit-identical tokens."""
        params, table, heads, vocab = model
        prompt = [1, 2, 3]
        clean_api = make_api(model).start()
        try:
            code, body, _ = post(
                "http://127.0.0.1:%d/generate" % clean_api.port,
                {"tokens": prompt}, timeout=60)
            assert code == 200
            want = body["tokens"]
        finally:
            clean_api.stop()

        api = make_api(model, chunk=2).start()
        real = api.decoder.dispatch_chunk
        calls = {"n": 0}

        def flaky(n):
            calls["n"] += 1
            if calls["n"] == 2:  # chunk 1 is pending when this raises
                raise RuntimeError("injected mid-flight failure")
            return real(n)

        api.decoder.dispatch_chunk = flaky
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            code, body, _ = post(url, {"tokens": prompt}, timeout=60)
            assert code == 503  # shed with a retryable error...
            assert "injected" in body["error"]
            deadline = time.time() + 30
            while not api.health.ready and time.time() < deadline:
                time.sleep(0.02)
            assert api.health.ready, api.health.snapshot()
            # ...the in-flight chunk was dropped, not collected: no
            # orphan token stream survives into the rebuilt decoder
            assert api._pending is None
            assert api.decoder.results == {}
            snap = api.health.snapshot()
            assert snap["counters"]["trips"] == 1
            assert snap["counters"]["shed"] == 1
            # the retry decodes the exact same greedy stream
            code, body, _ = post(url, {"tokens": prompt}, timeout=60)
            assert code == 200 and body["tokens"] == want
        finally:
            api.stop()


class TestHostileClients:
    def _raw_request(self, port, body, content_length=None,
                     read_reply=True):
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=10) as sock:
            length = (len(body) if content_length is None
                      else content_length)
            sock.sendall(
                b"POST /generate HTTP/1.1\r\n"
                b"Host: 127.0.0.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(length).encode() + b"\r\n"
                b"\r\n" + body)
            if not read_reply:
                return None  # disconnect without reading the reply
            sock.settimeout(10)
            return sock.recv(4096).decode(errors="replace")

    def test_seeded_hostile_client_mix_leaves_server_ready(self, model):
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, disconnect=0.25, garbage_body=0.25,
            oversize_body=0.25))
        api = make_api(model).start()
        vocab = model[3]
        good = [1, 2, 3]
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            want = None
            for _ in range(12):
                fault = monkey.roll_client_fault()
                if fault == "disconnect":
                    body = json.dumps({"tokens": good}).encode()
                    self._raw_request(api.port, body, read_reply=False)
                elif fault == "garbage_body":
                    code, _, _ = post(url, b"\x00\xffnot json at all")
                    assert code == 400
                elif fault == "oversize_body":
                    reply = self._raw_request(
                        api.port, b"", content_length=1 << 31)
                    assert "413" in reply.split("\r\n")[0]
                else:
                    code, body, _ = post(url, {"tokens": good},
                                         timeout=60)
                    assert code == 200
                    if want is None:
                        want = body["tokens"]
                    else:  # hostile traffic never corrupts decoding
                        assert body["tokens"] == want
            assert sum(monkey.counters.values()) >= 1
            code, body = get("http://127.0.0.1:%d/readyz" % api.port)
            assert code == 200 and body["ready"]
            # after the abuse a normal request still decodes correctly
            code, body, _ = post(url, {"tokens": good}, timeout=60)
            assert code == 200 and len(body["tokens"]) == 5
            assert api.health.snapshot()["counters"]["trips"] == 0
        finally:
            api.stop()


class TestErrorPathsLeaveServerServing:
    """Satellite coverage: every malformed-client path answers cleanly
    AND the very next request is served."""

    def test_generate_api_error_paths(self, model):
        api = make_api(model).start()
        vocab = model[3]
        try:
            base = "http://127.0.0.1:%d" % api.port
            url = base + "/generate"
            cases = [
                (b"{not json", 400),              # malformed JSON
                ({"tokens": [1.5, 2.5]}, 400),    # non-int tokens
                ({"tokens": [vocab + 7]}, 400),   # out-of-vocab ids
                ({"tokens": [1], "n_tokens": 0}, 400),  # zero budget
                ({"nope": 1}, 400),               # missing tokens
            ]
            for payload, want in cases:
                code, _, _ = post(url, payload)
                assert code == want, payload
                code, body, _ = post(url, {"tokens": [1, 2]},
                                     timeout=60)
                assert code == 200 and len(body["tokens"]) == 5
            code, _, _ = post(base + "/wrong", {"tokens": [1]})
            assert code == 404
            code, body, _ = post(url, {"tokens": [1, 2]}, timeout=60)
            assert code == 200
        finally:
            api.stop()

    def test_restful_api_error_paths(self):
        from test_serving import ServingHarness
        harness = ServingHarness()
        try:
            base = "http://127.0.0.1:%d" % harness.api.port
            code, _, _ = post(base + "/api", b"{nope")   # malformed
            assert code == 400
            code, _, _ = post(base + "/elsewhere",       # wrong path
                              {"input": [1.0] * 3, "codec": "list"})
            assert code == 404
            # disconnect mid-response: stage a request and hang up
            with socket.create_connection(
                    ("127.0.0.1", harness.api.port), timeout=10) as s:
                body = json.dumps({"input": [9.0] * 3,
                                   "codec": "list"}).encode()
                s.sendall(b"POST /api HTTP/1.1\r\nHost: x\r\n"
                          b"Content-Type: application/json\r\n"
                          b"Content-Length: " +
                          str(len(body)).encode() + b"\r\n\r\n" + body)
            # the server keeps serving after all of it
            code, body, _ = post(base + "/api",
                                 {"input": [2.0, 2.0, 2.0],
                                  "codec": "list"}, timeout=30)
            assert code == 200 and body["result"] == [4.0, 4.0, 4.0]
        finally:
            harness.close()

    def test_restful_api_oversized_body_413(self):
        """The read_body cap (core/httpd.py): an oversized body answers
        413 before buffering; the cap is per-unit configurable."""
        import jax  # noqa: F401  (keep import order consistent)
        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.serving import RESTfulAPI, RestfulLoader

        wf = DummyWorkflow()
        loader = RestfulLoader(wf, sample_shape=(3,), minibatch_size=2)
        loader.initialize()
        api = RESTfulAPI(wf, port=0, path="/api", max_body=4096)
        api.feed = loader.feed
        api.requests = []
        api.initialize()
        try:
            # raw socket: the server answers 413 BEFORE reading the
            # body, which can reset a client still streaming it — a
            # high-level client may see that as a dropped connection
            with socket.create_connection(("127.0.0.1", api.port),
                                          timeout=10) as sock:
                sock.sendall(b"POST /api HTTP/1.1\r\nHost: x\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: 10000\r\n\r\n")
                sock.settimeout(10)
                chunks = []
                while True:  # server closes after the 413: read to EOF
                    data = sock.recv(4096)
                    if not data:
                        break
                    chunks.append(data)
                reply = b"".join(chunks).decode(errors="replace")
            assert "413" in reply.split("\r\n")[0]
            assert "cap" in reply
        finally:
            api.stop()
            loader.stop()

    def test_restful_api_saturation_429(self):
        """Admission control on the reference surface: a full serving
        minibatch sheds with 429 + Retry-After, not an opaque 400."""
        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.serving import RESTfulAPI, RestfulLoader

        wf = DummyWorkflow()
        loader = RestfulLoader(wf, sample_shape=(3,), minibatch_size=2)
        loader.initialize()
        api = RESTfulAPI(wf, port=0, path="/api")
        api.feed = loader.feed
        api.requests = []
        api.initialize()
        try:
            # no workflow loop is draining the batch: fill it directly
            for _ in range(2):
                loader.feed(numpy.zeros(3, numpy.float32),
                            {"event": threading.Event(), "result": None})
            url = "http://127.0.0.1:%d/api" % api.port
            code, body, headers = post(
                url, {"input": [1.0] * 3, "codec": "list"})
            assert code == 429
            assert "saturated" in body["error"]
            assert headers.get("Retry-After") == "1"
            snap = api.health.snapshot()
            # the overflow rolls the admission back: the request books
            # as rejected-never-admitted and nothing is left in flight
            assert snap["counters"]["rejected"] >= 1
            assert snap["counters"]["admitted"] == 0
            assert snap["inflight"] == 0
        finally:
            api.stop()
            loader.stop()

    def test_generate_api_oversized_body_413(self, model):
        api = make_api(model).start()
        try:
            with socket.create_connection(("127.0.0.1", api.port),
                                          timeout=10) as sock:
                sock.sendall(
                    b"POST /generate HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: 9999999999\r\n\r\n")
                sock.settimeout(10)
                reply = sock.recv(4096).decode(errors="replace")
            assert "413" in reply.split("\r\n")[0]
            code, body, _ = post(
                "http://127.0.0.1:%d/generate" % api.port,
                {"tokens": [1, 2]}, timeout=60)
            assert code == 200
        finally:
            api.stop()

    def test_web_status_oversized_update_413(self):
        from veles_tpu.web_status import WebStatusServer

        server = WebStatusServer(port=0).start()
        try:
            with socket.create_connection(("127.0.0.1", server.port),
                                          timeout=10) as sock:
                sock.sendall(
                    b"POST /update HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: 9999999999\r\n\r\n")
                sock.settimeout(10)
                reply = sock.recv(4096).decode(errors="replace")
            assert "413" in reply.split("\r\n")[0]
        finally:
            server.stop()


class TestDashboardServingColumn:
    def test_format_serving_health_cell(self):
        from veles_tpu.web_status import format_serving_health
        cell = format_serving_health({
            "ready": True, "breaker": "closed", "inflight": 3,
            "counters": {"completed": 41, "trips": 1, "rebuilds": 1,
                         "shed": 2, "expired": 0, "rejected": 5,
                         "errors": 4}})
        assert "ready" in cell and "3 in flight" in cell
        assert "41 completed" in cell and "1 trips" in cell
        assert "2 shed" in cell and "5 rejected" in cell
        assert "4 errors" in cell  # a steadily-erroring unit shows it
        assert "expired" not in cell  # zero tallies are elided
        assert "breaker" not in cell  # closed breaker is elided
        open_cell = format_serving_health({
            "ready": False, "breaker": "open", "counters": {}})
        assert "NOT READY" in open_cell and "breaker open" in open_cell
        assert format_serving_health(None) == ""
        assert format_serving_health("junk") == ""

    def test_notifier_mirrors_serving_health(self, model):
        from veles_tpu.web_status import StatusNotifier, WebStatusServer

        server = WebStatusServer(port=0).start()
        api = make_api(model).start()
        try:
            class FakeLauncher:
                workflow = type("W", (), {"name": "serving-wf"})()
                mode = "standalone"
                serving_api = api

            notifier = StatusNotifier(
                FakeLauncher(),
                url="http://127.0.0.1:%d/update" % server.port)
            assert notifier.notify_once()
            status = next(iter(server.statuses().values()))
            assert status["serving"]["ready"] is True
            assert status["serving"]["breaker"] == "closed"
            # the other attachment point: a serving unit hosted IN the
            # workflow (RESTfulAPI) is discovered via its health attr
            unit = type("U", (), {"health": api.health})()

            class HostedLauncher:
                workflow = type("W", (), {
                    "name": "hosted-wf",
                    "__iter__": lambda self: iter([unit])})()
                mode = "standalone"

            hosted = StatusNotifier(
                HostedLauncher(),
                url="http://127.0.0.1:%d/update" % server.port)
            assert hosted.notify_once()
            hosted_status = server.statuses()[
                [k for k in server.statuses() if "hosted" in k][0]]
            assert hosted_status["serving"]["breaker"] == "closed"
            # and the dashboard row renders it
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/" % server.port,
                    timeout=10) as resp:
                html = resp.read().decode()
            assert "<th>serving</th>" in html
            assert "ready" in html
        finally:
            api.stop()
            server.stop()
