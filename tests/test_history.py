"""Metric flight recorder (docs/observability.md, ISSUE 12): bounded
time-series rings with counter-rate math and a series-count cap, the
threshold/slope/drop anomaly predicates on synthetic series, incident
artifacts (schema, atomic counter-suffixed writes, leading-indicator
math), the ``/debug/history`` round trip, fleet slave-labeled history
piggyback, sparkline cells, the ``observe incident`` CLI on saved and
live payloads, the governor-reads-history seam (control and autopsy
trends share ONE store) — and the chaos acceptance: under each seeded
burn profile (latency ramp, pool flood, compile storm) an incident is
produced whose leading indicator names the injected fault's series.
``make history`` runs this module standalone; the chaos end-to-end
cases ride the ``slow`` marker so tier-1 keeps its timeout margin."""

import json
import os
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles_tpu.observe.history import (AnomalyRule, FLEET_MAX_SERIES,
                                       HistoryConfig, IncidentRecorder,
                                       MetricHistory, default_rules,
                                       get_metric_history,
                                       incident_main, load_incident,
                                       parse_history_spec,
                                       render_incident,
                                       set_metric_history, sparkline)
from veles_tpu.observe.metrics import MetricsRegistry

pytestmark = pytest.mark.history


def make_history(tmp_path, registry=None, capacity=64, series_cap=64,
                 cooldown=3600.0, rules=()):
    return MetricHistory(
        registry=registry or MetricsRegistry(enabled=True),
        interval_s=0.01, capacity=capacity, series_cap=series_cap,
        rules=list(rules),
        incidents=IncidentRecorder(cooldown_s=cooldown,
                                   directory=str(tmp_path)))


def gauge_rows(**values):
    return [(name, "gauge", (), value)
            for name, value in values.items()]


class TestConfig:
    def test_spec_parsing_defaults_and_off(self):
        config = parse_history_spec(None)
        assert isinstance(config, HistoryConfig)  # unset = default ON
        assert config.interval_s == 1.0
        config = parse_history_spec("interval_s=0.5,capacity=600,"
                                    "series_cap=32,seed_rules=0")
        assert config.interval_s == 0.5
        assert config.capacity == 600
        assert config.series_cap == 32
        assert config.seed_rules is False
        assert parse_history_spec("off") is None
        assert parse_history_spec("enabled=0") is None
        assert parse_history_spec({"enabled": False}) is None
        for bad in ("nope=1", "interval_s=x", "interval_s=0",
                    "capacity=1", "series_cap=0", "seed_rules=maybe",
                    "interval_s"):
            with pytest.raises(ValueError, match="--serve-history"):
                parse_history_spec(bad, flag="--serve-history")

    def test_default_rules_cover_the_seed_set(self):
        names = {rule.name for rule in default_rules()}
        assert {"slo_burn", "tpot_p95_slope", "mfu_collapse",
                "pool_exhaustion", "compile_storm"} <= names


class TestStore:
    def test_ring_drops_oldest_at_capacity(self, tmp_path):
        hist = make_history(tmp_path, capacity=4)
        for i in range(10):
            hist.sample(now=100.0 + i, rows=gauge_rows(veles_g=float(i)))
        series = hist.get("veles_g")
        assert list(series.values) == [6.0, 7.0, 8.0, 9.0]
        assert list(series.stamps) == [106.0, 107.0, 108.0, 109.0]

    def test_series_cap_books_overflow_tally(self, tmp_path):
        """A hostile label set cannot balloon memory: past the cap,
        new series are counted and dropped."""
        hist = make_history(tmp_path, series_cap=2)
        rows = [("veles_g", "gauge", (("evil", str(i)),), 1.0)
                for i in range(8)]
        hist.sample(now=100.0, rows=rows)
        assert len(hist.series_list()) == 2
        assert hist.series_dropped == 6
        # existing series keep sampling fine past the cap
        hist.sample(now=101.0, rows=rows)
        assert hist.series_dropped == 12
        assert len(hist.get("veles_g",
                            labels={"evil": "0"}).values) == 2

    def test_counter_rate_math(self, tmp_path):
        hist = make_history(tmp_path)
        for now, total in ((100.0, 50), (101.0, 60), (103.0, 80),
                           (104.0, 5), (105.0, 25)):
            hist.sample(now=now,
                        rows=[("veles_c_total", "counter", (), total)])
        series = hist.get("veles_c_total")
        # first sample = baseline (no point); the reset (80 -> 5)
        # re-baselines without a point; rates are per second
        assert list(series.values) == [10.0, 10.0, 20.0]
        assert list(series.stamps) == [101.0, 103.0, 105.0]

    def test_counter_first_seen_midflight_anchors_at_zero(self,
                                                          tmp_path):
        """A counter appearing AFTER the first pass (the first
        recompile storm) rates against an implicit 0 at the previous
        pass — the spike that must not vanish into a baseline."""
        hist = make_history(tmp_path)
        hist.sample(now=100.0, rows=gauge_rows(veles_g=1.0))
        hist.sample(now=101.0,
                    rows=[("veles_storms_total", "counter", (), 2)])
        series = hist.get("veles_storms_total")
        assert list(series.values) == [2.0]
        # but the very FIRST pass books baselines only: attaching to a
        # long-lived process must not spike every counter
        fresh = make_history(tmp_path)
        fresh.sample(now=100.0,
                     rows=[("veles_old_total", "counter", (), 12345)])
        assert list(fresh.get("veles_old_total").values) == []

    def test_registry_sample_accessor_runs_collectors(self):
        """The satellite: MetricsRegistry.sample() materializes
        collector-backed series without rendering exposition text;
        disabled, it returns nothing and never runs a collector."""
        registry = MetricsRegistry(enabled=True)
        registry.add_collector(
            lambda: registry.set("veles_collected", 7.0))
        registry.incr("veles_n_total", 3)
        registry.observe("veles_h_seconds", 0.2, buckets=(0.1, 1.0))
        rows = {(name, labels): (kind, value)
                for name, kind, labels, value in registry.sample()}
        assert rows[("veles_collected", ())] == ("gauge", 7.0)
        assert rows[("veles_n_total", ())] == ("counter", 3)
        # histograms surface as synthesized _count/_sum counters
        assert rows[("veles_h_seconds_count", ())][1] == 1
        assert rows[("veles_h_seconds_sum", ())][1] == 0.2
        disabled = MetricsRegistry(enabled=False)
        ran = []
        disabled.add_collector(lambda: ran.append(1))
        assert disabled.sample() == ()
        assert ran == []


class TestRules:
    def test_threshold_for_n_samples(self, tmp_path):
        rule = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=3,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        for i, value in enumerate((1.0, 5.0, 5.0)):
            hist.sample(now=100.0 + i, rows=gauge_rows(veles_b=value))
        assert rule.fired_total == 0  # streak 2 < for_samples 3
        assert rule.breach_since == 101.0
        hist.sample(now=103.0, rows=gauge_rows(veles_b=5.0))
        assert rule.fired_total == 1
        assert hist.anomalies_total == 1
        # recovery resets the streak and the breach instant
        hist.sample(now=104.0, rows=gauge_rows(veles_b=0.1))
        assert rule.streak == 0 and rule.breach_since is None

    def test_slope_predicate(self, tmp_path):
        rule = AnomalyRule("ramp", "veles_lat", kind="slope", op=">=",
                           threshold=5.0, window_s=4.0,
                           for_samples=1, cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        for i in range(5):  # +1/s: under the 5/s threshold
            hist.sample(now=100.0 + i,
                        rows=gauge_rows(veles_lat=10.0 + i))
        assert rule.fired_total == 0
        for i in range(3):  # +8/s: breaches
            hist.sample(now=105.0 + i,
                        rows=gauge_rows(veles_lat=14.0 + 8.0 * (i + 1)))
        assert rule.fired_total >= 1

    def test_drop_vs_baseline_predicate(self, tmp_path):
        rule = AnomalyRule("mfu", "veles_mfu", kind="drop",
                           drop_frac=0.5, window_s=4.0,
                           baseline_s=10.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        for i in range(10):  # healthy baseline ~1.0
            hist.sample(now=100.0 + i,
                        rows=gauge_rows(veles_mfu=1.0))
        assert rule.fired_total == 0
        for i in range(4):  # collapse to 0.3 (< 50% of baseline)
            hist.sample(now=110.0 + i,
                        rows=gauge_rows(veles_mfu=0.3))
        assert rule.fired_total >= 1

    def test_tenant_and_slave_slices_are_excluded(self, tmp_path):
        rule = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        rows = [("veles_b", "gauge", (("tenant", "evil"),), 99.0),
                ("veles_b", "gauge", (("slave", "s1"),), 99.0),
                ("veles_b", "gauge", (), 0.5)]
        hist.sample(now=100.0, rows=rows)
        assert rule.fired_total == 0  # only the aggregate counts

    def test_retired_series_stops_driving_the_rule(self, tmp_path):
        """A gauge family the source retired (set_gauge_family with no
        rows) vanishes from later passes — the rule must not keep
        breaching on the frozen ring tail."""
        rule = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=2,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        hist.sample(now=100.0, rows=gauge_rows(veles_b=9.0))
        assert rule.streak == 1
        hist.sample(now=101.0, rows=gauge_rows(veles_other=1.0))
        assert rule.streak == 0 and rule.fired_total == 0

    def test_firings_book_counters_and_flight_entries(self, tmp_path):
        from veles_tpu.observe.flight import get_flight_recorder

        registry = MetricsRegistry(enabled=True)
        rule = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, registry=registry, rules=[rule])
        recorder = get_flight_recorder()
        before = len([e for e in recorder.entries()
                      if e.get("kind") == "anomaly"])
        registry.set("veles_b", 9.0)
        hist.sample(now=100.0)
        fired = {(name, labels): value
                 for name, kind, labels, value in registry.sample()
                 if name == "veles_anomaly_fired_total"}
        assert fired[("veles_anomaly_fired_total",
                      (("rule", "burn"),))] == 1
        marks = [e for e in recorder.entries()
                 if e.get("kind") == "anomaly"]
        assert len(marks) == before + 1
        assert marks[-1]["rule"] == "burn"

    def test_blackbox_summary_counts_entries_by_kind(self, tmp_path,
                                                     capsys):
        """The satellite: `observe blackbox` counts ring entries by
        kind — the PR-11 governor entries and the new anomaly kind
        included."""
        from veles_tpu.observe.flight import (FlightRecorder,
                                              blackbox_main)

        recorder = FlightRecorder()
        recorder.note("governor", action="demote", tier="int8")
        recorder.note("anomaly", rule="slo_burn", value=3.0)
        recorder.note("anomaly", rule="pool_exhaustion", value=40.0)
        recorder.note("dispatch", kind_detail="x")
        path = str(tmp_path / "blackbox-test.json")
        recorder.dump("test", path=path)
        assert blackbox_main(path, tail=0) == 0
        out = capsys.readouterr().out
        assert "kinds:" in out
        assert "anomaly=2" in out
        assert "governor=1" in out


class TestIncidents:
    def trigger_two_rules(self, tmp_path, cooldown=0.0):
        lead = AnomalyRule("pool_exhaustion", "veles_pool",
                           kind="threshold", op=">=", threshold=5.0,
                           for_samples=1, cooldown_s=0.0)
        burn = AnomalyRule("slo_burn", "veles_slo_burn_rate",
                           kind="threshold", op=">=", threshold=2.0,
                           for_samples=1, cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[lead, burn],
                            cooldown=cooldown)
        # t=100: only the pool series breaches; t=102: burn follows
        hist.sample(now=100.0,
                    rows=gauge_rows(veles_pool=9.0,
                                    veles_slo_burn_rate=0.5))
        hist.sample(now=102.0,
                    rows=gauge_rows(veles_pool=9.0,
                                    veles_slo_burn_rate=4.0))
        return hist

    def test_artifact_schema_and_leading_indicator_math(self,
                                                        tmp_path):
        hist = self.trigger_two_rules(tmp_path)
        doc = hist.incidents.last_doc
        assert doc["schema"] == 1 and doc["kind"] == "incident"
        lead = doc["leading_indicator"]
        assert lead["rule"] == "pool_exhaustion"
        assert lead["series"] == "veles_pool"
        assert lead["reference"] == "slo_burn"
        assert lead["lead_ms"] == 2000.0
        names = {state["name"] for state in doc["breaching"]}
        assert names == {"pool_exhaustion", "slo_burn"}
        series = {row["name"] for row in doc["history"]["series"]}
        assert {"veles_pool", "veles_slo_burn_rate"} <= series
        # round-trips through the loader; a non-incident is refused
        saved = load_incident(hist.incidents.last_path)
        assert saved["leading_indicator"]["rule"] == "pool_exhaustion"
        bogus = tmp_path / "not_incident.json"
        bogus.write_text(json.dumps({"entries": []}))
        with pytest.raises(ValueError, match="not an incident"):
            load_incident(str(bogus))

    def test_atomic_counter_suffixed_writes(self, tmp_path):
        hist = self.trigger_two_rules(tmp_path)
        paths = sorted(p for p in os.listdir(str(tmp_path))
                       if p.startswith("incident-"))
        # cooldown 0: every firing pass writes; names never collide
        # even inside one second (the dumps-counter suffix)
        assert len(paths) == hist.incidents.count >= 2
        assert len(set(paths)) == len(paths)
        assert not [p for p in os.listdir(str(tmp_path))
                    if p.endswith(".tmp")]

    def test_cooldown_bounds_artifact_count(self, tmp_path):
        hist = self.trigger_two_rules(tmp_path, cooldown=3600.0)
        for i in range(5):
            hist.sample(now=103.0 + i,
                        rows=gauge_rows(veles_pool=9.0,
                                        veles_slo_burn_rate=4.0))
        assert hist.incidents.count == 1

    def test_failed_write_does_not_consume_the_cooldown(self,
                                                        tmp_path):
        """A transiently unwritable run dir must not burn the incident
        cooldown: the next firing retries the write."""
        lead = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[lead], cooldown=3600.0)
        # a regular FILE where the dump dir should be -> OSError
        (tmp_path / "blocked").write_text("x")
        hist.incidents.directory = str(tmp_path / "blocked" / "sub")
        hist.sample(now=100.0, rows=gauge_rows(veles_b=9.0))
        assert hist.incidents.count == 0
        hist.incidents.directory = str(tmp_path)
        hist.sample(now=101.0, rows=gauge_rows(veles_b=9.0))
        assert hist.incidents.count == 1

    def test_check_rules_false_ingests_data_only(self, tmp_path):
        """The governor's driver-thread fallback path: data lands in
        the rings, but no rule evaluation (and so no incident write)
        ever runs there."""
        rule = AnomalyRule("burn", "veles_b", kind="threshold",
                           op=">=", threshold=2.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        hist.sample(now=100.0, rows=gauge_rows(veles_b=9.0),
                    check_rules=False)
        assert hist.get("veles_b").values[-1] == 9.0
        assert rule.streak == 0 and hist.incidents.count == 0
        hist.sample(now=101.0, rows=gauge_rows(veles_b=9.0))
        assert rule.fired_total == 1

    def test_breach_severity_is_direction_aware(self, tmp_path):
        """A drop-kind rule's worst breach is the LOWEST ratio — the
        incident must name the most-collapsed series, and last_value
        must never show a healthy sibling's number."""
        rule = AnomalyRule("mfu", "veles_mfu", kind="drop",
                           drop_frac=0.5, window_s=2.0,
                           baseline_s=10.0, for_samples=1,
                           cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        rows = lambda a, b: [  # noqa: E731
            ("veles_mfu", "gauge", (("program", "a"),), a),
            ("veles_mfu", "gauge", (("program", "b"),), b)]
        for i in range(10):
            hist.sample(now=100.0 + i, rows=rows(1.0, 1.0))
        for i in range(4):
            hist.sample(now=110.0 + i, rows=rows(0.45, 0.10))
        assert rule.fired_total >= 1
        # once both programs breach (window ratios ~0.45 and ~0.10),
        # severity must pick the LOWER ratio — program b's 90%
        # collapse, not a's milder one
        assert dict(rule.breach_labels)["program"] == "b"
        assert rule.breach_value < 0.3

    def test_incident_cli_renders_saved_artifact(self, tmp_path,
                                                 capsys):
        hist = self.trigger_two_rules(tmp_path)
        assert incident_main(hist.incidents.last_path) == 0
        out = capsys.readouterr().out
        assert "leading indicator: pool_exhaustion" in out
        assert "veles_pool" in out
        assert "led slo_burn by 2000ms" in out
        # a directory lists and renders the newest
        assert incident_main(str(tmp_path)) == 0
        assert "leading indicator" in capsys.readouterr().out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert incident_main(str(empty)) == 1

    def test_render_includes_sparkline_timeline(self, tmp_path):
        hist = self.trigger_two_rules(tmp_path)
        text = render_incident(hist.incidents.last_doc)
        assert "timeline" in text
        assert any(block in text for block in "▁▂▃▄▅▆▇█")


class TestSparklines:
    def test_sparkline_shape(self):
        assert sparkline([]) == ""
        assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"
        ramp = sparkline(list(range(8)))
        assert ramp[0] == "▁" and ramp[-1] == "█"
        assert len(sparkline(list(range(100)), width=16)) == 16

    def test_web_status_trends_cell(self, tmp_path):
        from veles_tpu.web_status import format_trends_cell

        hist = make_history(tmp_path)
        for i in range(6):
            hist.sample(now=100.0 + i, rows=[
                ("veles_slo_burn_rate", "gauge",
                 (("objective", "ttft"), ("window", "60s")), 0.5 * i),
                ("veles_kv_pages_free", "gauge", (), 30.0 - i)])
        cells = hist.dashboard_cells()
        assert cells, "summary-prefix series must produce cells"
        text = format_trends_cell(cells)
        assert "slo_burn_rate" in text
        assert any(block in text for block in "▁▂▃▄▅▆▇█")
        assert format_trends_cell(None) == ""
        assert format_trends_cell([{"label": "x", "spark": [1, 2],
                                    "last": 2}]).startswith("x ")


class TestFleetPiggyback:
    def test_summary_round_trips_slave_labeled(self, tmp_path):
        slave = make_history(tmp_path)
        for i in range(40):
            slave.sample(now=100.0 + i, rows=[
                ("veles_slo_burn_rate", "gauge",
                 (("window", "60s"),), 0.1 * i),
                ("veles_private_gauge", "gauge", (), 1.0)])
        rows = slave.fleet_summary(now=140.0)
        # only the summary prefixes ride the frame, points bounded
        assert {row[0] for row in rows} == {"veles_slo_burn_rate"}
        assert len(rows[0][2]) <= 32
        master = make_history(tmp_path)
        assert master.ingest_summary("s1", rows, now=500.0) == 1
        series = master.get("veles_slo_burn_rate",
                            labels={"window": "60s", "slave": "s1"})
        assert series is not None
        assert list(series.values)[-1] == pytest.approx(3.9)
        # ages rebased onto the master's clock, order preserved
        assert list(series.stamps)[-1] <= 500.0
        assert list(series.stamps) == sorted(series.stamps)
        # a re-sent frame REPLACES the ring (no duplicated overlap)
        master.ingest_summary("s1", rows, now=501.0)
        assert len(series.values) == len(rows[0][3])

    def test_hostile_rows_are_rejected_and_bounded(self, tmp_path):
        master = make_history(tmp_path, series_cap=4)
        bad = [
            ["not a metric!", [], [0.0], [1.0]],        # invalid name
            ["veles_ok", [], [0.0, 1.0], [1.0]],        # len mismatch
            "garbage",                                   # not a row
            ["veles_spoof", [["slave", "other"]], [0.0], [1.0]],
        ]
        assert master.ingest_summary("s1", bad, now=100.0) == 1
        series = master.get("veles_spoof")
        # the spoofed slave label was dropped; ours was stamped
        assert series.label_dict() == {"slave": "s1"}
        flood = [["veles_f%d" % i, [], [0.0], [1.0]]
                 for i in range(FLEET_MAX_SERIES + 50)]
        master.ingest_summary("s2", flood, now=101.0)
        assert len(master.series_list()) <= 4
        assert master.series_dropped > 0


def _history_httpd(history):
    from http.server import BaseHTTPRequestHandler
    from veles_tpu.core.httpd import (QuietHandlerMixin,
                                      serve_debug_history,
                                      start_server)

    class Handler(QuietHandlerMixin, BaseHTTPRequestHandler):
        def do_GET(self):
            if not serve_debug_history(self, history):
                self.send_error(404)

    return start_server(Handler, port=0, name="test-history")


class TestDebugHistoryEndpoint:
    def test_round_trip_with_series_and_window_filters(self, tmp_path):
        rule = AnomalyRule("burn", "veles_slo_burn_rate",
                           kind="threshold", op=">=", threshold=2.0,
                           for_samples=1, cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        # stamps land in the recent PAST so a live-clock ?window=
        # filter (serve_debug_history defaults now to monotonic) keeps
        # a strict subset
        base = time.monotonic() - 20.0
        for i in range(20):
            hist.sample(now=base + i, rows=gauge_rows(
                veles_slo_burn_rate=3.0, veles_kv_pages_free=9.0))
        httpd, port = _history_httpd(hist)
        try:
            url = "http://127.0.0.1:%d/debug/history" % port
            with urllib.request.urlopen(url, timeout=10) as resp:
                payload = json.loads(resp.read().decode())
            names = {row["name"] for row in payload["series"]}
            assert names == {"veles_slo_burn_rate",
                             "veles_kv_pages_free"}
            assert payload["samples_total"] == 20
            assert payload["rules"][0]["name"] == "burn"
            assert payload["rules"][0]["fired_total"] >= 1
            with urllib.request.urlopen(
                    url + "?series=slo_burn&window=5", timeout=10) \
                    as resp:
                filtered = json.loads(resp.read().decode())
            assert [row["name"] for row in filtered["series"]] \
                == ["veles_slo_burn_rate"]
            assert 0 < len(filtered["series"][0]["values"]) < 20
            # ages are relative seconds, newest last (smallest age)
            ages = filtered["series"][0]["ages"]
            assert ages == sorted(ages, reverse=True)
        finally:
            httpd.shutdown()

    def test_disabled_history_answers_404(self, tmp_path):
        previous = get_metric_history()
        set_metric_history(None)
        try:
            httpd, port = _history_httpd(None)
            try:
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        "http://127.0.0.1:%d/debug/history" % port,
                        timeout=10)
                assert err.value.code == 404
            finally:
                httpd.shutdown()
        finally:
            set_metric_history(previous)

    def test_incident_cli_live(self, tmp_path, capsys):
        rule = AnomalyRule("burn", "veles_slo_burn_rate",
                           kind="threshold", op=">=", threshold=2.0,
                           for_samples=1, cooldown_s=0.0)
        hist = make_history(tmp_path, rules=[rule])
        base = time.monotonic()
        for i in range(5):
            hist.sample(now=base + i,
                        rows=gauge_rows(veles_slo_burn_rate=4.0))
        httpd, port = _history_httpd(hist)
        try:
            assert incident_main(
                live="http://127.0.0.1:%d" % port) == 0
            out = capsys.readouterr().out
            assert "leading indicator: burn" in out
            assert "veles_slo_burn_rate" in out
        finally:
            httpd.shutdown()
        assert incident_main(live="http://127.0.0.1:1/") == 1


class TestGovernorReadsHistory:
    """The no-second-bookkeeping-path seam: with a history attached,
    the governor's burn readings ARE history samples
    (veles_ctrl_burn_rate), so the incident autopsy replays exactly
    what the control loop acted on."""

    class StubSLO:
        def __init__(self, burns):
            self.burns = list(burns)

        def summary(self):
            burn = self.burns.pop(0) if self.burns else 0.0
            if burn is None:
                return None
            return {"burn_rate": burn, "objective": "ttft_p95_ms",
                    "window": "60s"}

    class StubDecoder:
        def __init__(self):
            self.pool = None
            self.quantize = None
            self.aot = None

    class StubApi:
        def __init__(self, burns):
            self.slo = TestGovernorReadsHistory.StubSLO(burns)
            self.decoder = TestGovernorReadsHistory.StubDecoder()
            self.max_queue = 64
            self._base_tier = "bf16"

        def request_tier(self, tier):
            self.decoder.quantize = None if tier == "bf16" else tier

        def request_trip(self, reason):
            pass

    def test_demote_reads_the_recorded_ctrl_series(self, tmp_path):
        from veles_tpu.observe.governor import (GovernorConfig,
                                                ServingGovernor)

        rule = AnomalyRule("ctrl_burn", "veles_ctrl_burn_rate",
                           kind="threshold", op=">=", threshold=2.0,
                           for_samples=1, cooldown_s=0.0,
                           exclude_labels=())
        hist = make_history(tmp_path, rules=[rule])
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=0.01,
            interval_s=0.001, ladder=("int8",), prewarm=False,
            breaker_guard=False))
        governor.attach_history(hist)
        burns = [3.5, 3.0, 0.4, 0.4]
        api = self.StubApi(list(burns))
        for _ in burns:
            time.sleep(0.015)
            governor.tick(api)
        assert governor.counters["demotions"] == 1
        assert governor.counters["promotions"] == 1
        series = hist.get("veles_ctrl_burn_rate")
        # every burn the governor acted on is in the ring, verbatim
        assert list(series.values) == burns
        assert governor.last_burn == burns[-1]
        # an incident built NOW reports the same ctrl series
        hist.sample(rows=[])
        event = rule.evaluate(hist, time.monotonic())
        doc = hist.incidents.build(hist, rule, event
                                   or {"rule": "ctrl_burn"})
        names = {row["name"] for row in doc["history"]["series"]}
        assert "veles_ctrl_burn_rate" in names

    def test_empty_window_holds_the_tier(self, tmp_path):
        from veles_tpu.observe.governor import (GovernorConfig,
                                                ServingGovernor)

        hist = make_history(tmp_path)
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=0.01,
            interval_s=0.001, ladder=("int8",), prewarm=False,
            breaker_guard=False))
        governor.attach_history(hist)
        api = self.StubApi([3.0, None, None])
        for _ in range(3):
            time.sleep(0.015)
            governor.tick(api)
        # the None summaries (no traffic) must HOLD, not promote
        assert governor.level == 1
        assert governor.last_burn is None
        series = hist.get("veles_ctrl_burn_rate")
        assert list(series.values) == [3.0]  # silence records nothing


# -- chaos acceptance: fault injection -> incident naming the fault ---------

@pytest.fixture(scope="module")
def model():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads


def _post(url, tokens, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps({"tokens": tokens}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
    except Exception:
        pass


def _drive_until(api, hist, predicate, timeout=90.0):
    url = "http://127.0.0.1:%d/generate" % api.port
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and not predicate():
        _post(url, [1, 2, 3])
        hist.maybe_sample()
    return predicate()


def _chaos_setup(tmp_path, rules, registry=None):
    from veles_tpu.observe.metrics import get_metrics_registry

    # incident cooldown 0: artifact count is bounded by the RULES'
    # own cooldowns (each fires once), and the LAST artifact is the
    # one triggered by the latest-breaching rule
    hist = MetricHistory(
        registry=registry or get_metrics_registry(),
        interval_s=0.05, capacity=512,
        rules=list(rules),
        incidents=IncidentRecorder(cooldown_s=0.0,
                                   directory=str(tmp_path)))
    previous = get_metric_history()
    set_metric_history(hist)
    return hist, previous


@pytest.mark.slow
class TestChaosIncidents:
    def test_pool_flood_incident_names_the_pool_series(self, model,
                                                       tmp_path,
                                                       capsys):
        from veles_tpu.observe.metrics import get_metrics_registry
        from veles_tpu.observe.reqledger import get_request_ledger
        from veles_tpu.serving import GenerateAPI
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        params, table, heads = model
        get_metrics_registry().reset()
        # serial posts reserve at most 1 page at a time; only the
        # flood's hostage reservation reaches 2+ — a deterministic
        # threshold for the seeded profile
        rules = [
            AnomalyRule("pool_exhaustion", "veles_kv_pages_reserved",
                        kind="threshold", op=">=", threshold=2.0,
                        for_samples=1, cooldown_s=3600.0),
            AnomalyRule("slo_burn", "veles_slo_burn_rate",
                        kind="threshold", op=">=", threshold=2.0,
                        for_samples=2, cooldown_s=3600.0),
        ]
        hist, previous = _chaos_setup(tmp_path, rules)
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=1, pool_flood_pages=2, pool_flood_at=1,
            pool_flood_steps=1 << 30))
        expected = monkey.config.expected_leading_series()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0, paged=True,
                          rebuild_backoff=0.02, chaos=monkey)
        api.start()
        try:
            assert _drive_until(
                api, hist, lambda: hist.incidents.count >= 1), \
                "flood never produced an incident"
            doc = hist.incidents.last_doc
            assert doc["leading_indicator"]["series"] \
                == expected["pool_flood"]
            assert doc["leading_indicator"]["rule"] \
                == "pool_exhaustion"
            # the CLI renders it from the saved artifact AND live
            assert incident_main(hist.incidents.last_path) == 0
            saved_out = capsys.readouterr().out
            assert expected["pool_flood"] in saved_out
            assert incident_main(
                live="http://127.0.0.1:%d" % api.port) == 0
            assert expected["pool_flood"] in capsys.readouterr().out
            # request truth rode along: the bundle carries ledger rows
            if get_request_ledger().enabled:
                assert "requests" in doc
        finally:
            monkey.release_flood()
            api.stop()
            set_metric_history(previous)

    def test_compile_storm_incident_names_the_storm_counter(
            self, model, tmp_path):
        from veles_tpu.observe.metrics import get_metrics_registry
        from veles_tpu.serving import GenerateAPI
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        params, table, heads = model
        get_metrics_registry().reset()
        rules = [
            AnomalyRule("compile_storm",
                        "veles_xla_recompile_storms_total",
                        kind="threshold", op=">=", threshold=0.01,
                        for_samples=1, cooldown_s=0.0),
        ]
        hist, previous = _chaos_setup(tmp_path, rules)
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=1, compile_storm_at=1))
        expected = monkey.config.expected_leading_series()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0,
                          rebuild_backoff=0.02, chaos=monkey)
        api.start()
        try:
            assert _drive_until(
                api, hist, lambda: hist.incidents.count >= 1), \
                "storm never produced an incident"
            doc = hist.incidents.last_doc
            assert doc["leading_indicator"]["series"] \
                == expected["compile_storm"]
        finally:
            api.stop()
            set_metric_history(previous)

    def test_latency_ramp_incident_and_governor_share_trends(
            self, model, tmp_path):
        """The full acceptance: a held latency ramp burns the SLO; the
        latency series breaches BEFORE the burn (the leading
        indicator), the governed demote decisions are the recorded
        veles_ctrl_burn_rate samples, and the incident artifact
        reports that same series."""
        from veles_tpu.observe.governor import (GovernorConfig,
                                                ServingGovernor)
        from veles_tpu.observe.metrics import get_metrics_registry
        from veles_tpu.observe.reqledger import RequestLedger
        from veles_tpu.observe.slo import SLOEngine
        from veles_tpu.serving import GenerateAPI
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        params, table, heads = model
        get_metrics_registry().reset()
        # the latency gauge updates at FIRST TOKEN while the burn
        # gauges need the request to RESOLVE — the injected fault's
        # series deterministically breaches first. Each rule fires
        # once (own cooldown); the incident recorder (cooldown 0)
        # rewrites on the later slo_burn firing, so last_doc carries
        # both breaching rules and the latency lead.
        rules = [
            AnomalyRule("ttft_p95_high", "veles_serving_latency_ms",
                        match={"kind": "ttft", "quantile": "p95"},
                        kind="threshold", op=">=", threshold=60.0,
                        for_samples=1, cooldown_s=3600.0),
            AnomalyRule("slo_burn", "veles_slo_burn_rate",
                        kind="threshold", op=">=", threshold=2.0,
                        for_samples=2, cooldown_s=3600.0),
        ]
        hist, previous = _chaos_setup(tmp_path, rules)
        engine = SLOEngine({"ttft_p95_ms": 120.0}, windows=(2.0, 8.0),
                           bucket_seconds=0.25)
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=3.0,
            interval_s=0.05, ladder=("int8",), prewarm=False,
            breaker_guard=False))
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=1, latency_ramp_ms=250.0, latency_ramp_steps=6,
            latency_ramp_hold=1 << 30))
        expected = monkey.config.expected_leading_series()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0,
                          rebuild_backoff=0.02, slo=engine,
                          governor=governor, chaos=monkey,
                          ledger=RequestLedger())
        assert governor.history is hist  # attached at construction
        api.start()
        try:
            assert _drive_until(
                api, hist,
                lambda: governor.demoted
                and any(r.name == "slo_burn" and r.fired_total
                        for r in hist.rules)), \
                "ramp never demoted + burned"
            # deterministic leading indicator: the injected fault's
            # series breached before the user-visible SLO breach
            doc = hist.incidents.last_doc
            assert doc is not None
            assert doc["leading_indicator"]["series"] \
                == expected["latency_ramp"]
            assert doc["leading_indicator"]["lead_ms"] >= 0.0
            assert doc["leading_indicator"]["reference"] == "slo_burn"
            # no second bookkeeping path: the burn the governor
            # demoted on is a recorded history sample, and the
            # artifact reports that exact series
            ctrl = hist.get("veles_ctrl_burn_rate")
            assert ctrl is not None
            assert max(ctrl.values) >= governor.config.demote_burn
            assert governor.last_burn in list(ctrl.values)
            artifact_series = {row["name"]
                               for row in doc["history"]["series"]}
            assert "veles_ctrl_burn_rate" in artifact_series
        finally:
            monkey.clear_ramp()
            api.stop()
            set_metric_history(previous)
