"""Compiler-visible fleet aggregation: the mapreduce primitive layer.

``parallel/mapreduce.py`` (docs/compiler_fleet.md): broadcast / map_fn
/ reduce_sum / reduce_mean over the ``"data"`` mesh axis, the
bf16/int8 quantized-all-reduce wire tiers with per-leaf scales, the
analytic wire-byte accounting, the instrumented ``fleet_train_step``
(xla_stats compiles/FLOPs/MFU + the veles_fleet_reduce_* metric
families), and the int8 tier's convergence parity against bf16 through
real pod-mode training. Runs on the 8-device virtual CPU mesh
(``make fleet-mr``).
"""

import time

import numpy
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from veles_tpu.core.config import root
from veles_tpu.parallel import mapreduce as mr
from veles_tpu.parallel.mesh import build_mesh, shard_map

pytestmark = pytest.mark.fleet_mr

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N, "conftest must force 8 CPU devices"
    return build_mesh(devices=jax.devices()[:N], data=N)


def _tree(rng):
    return {"w": rng.randn(N, 96, 32).astype(numpy.float32),
            "b": rng.randn(N, 33).astype(numpy.float32)}


def _run_reduce(mesh, tree, precision, mean=False):
    """Each device reduces its own distinct shard slice; the output
    keeps a leading device dim so the test can ASSERT replication
    instead of trusting the out_spec."""
    reducer = mr.reduce_mean if mean else mr.reduce_sum

    def body(t):
        local = jax.tree.map(lambda x: x[0], t)
        out = reducer(local, "data", precision=precision)
        return jax.tree.map(lambda x: x[None], out)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("data"),),
                           out_specs=P("data")))
    return jax.tree.map(numpy.asarray, fn(tree))


class TestPrimitives:
    def test_f32_reduce_is_bit_identical_to_psum(self, mesh):
        """The default tier IS lax.psum — the pre-existing pod-mode
        gradient merge must not change by a single bit."""
        tree = _tree(numpy.random.RandomState(0))

        def psum_body(t):
            local = jax.tree.map(lambda x: x[0], t)
            out = lax.psum(local, "data")
            return jax.tree.map(lambda x: x[None], out)

        ref_fn = jax.jit(shard_map(psum_body, mesh=mesh,
                                   in_specs=(P("data"),),
                                   out_specs=P("data")))
        ref = jax.tree.map(numpy.asarray, ref_fn(tree))
        got = _run_reduce(mesh, tree, "f32")
        for key in tree:
            numpy.testing.assert_array_equal(got[key], ref[key])

    def test_reduce_mean(self, mesh):
        tree = _tree(numpy.random.RandomState(1))
        got = _run_reduce(mesh, tree, "f32", mean=True)
        summed = _run_reduce(mesh, tree, "f32")
        for key in tree:
            numpy.testing.assert_allclose(got[key][0],
                                          summed[key][0] / N,
                                          rtol=1e-6)

    @pytest.mark.parametrize("precision", ["bf16", "int8"])
    def test_compressed_tiers_replicate_identically(self, mesh,
                                                    precision):
        """Determinism is what keeps lockstep replicas in lockstep:
        every device must hold the exact same reduced bytes."""
        tree = _tree(numpy.random.RandomState(2))
        got = _run_reduce(mesh, tree, precision)
        for key in tree:
            for row in range(N):
                numpy.testing.assert_array_equal(got[key][row],
                                                 got[key][0])

    def test_bf16_tier_error_bounded(self, mesh):
        tree = _tree(numpy.random.RandomState(3))
        exact = {k: v.sum(0, dtype=numpy.float64) for k, v in
                 tree.items()}
        got = _run_reduce(mesh, tree, "bf16")
        for key in tree:
            numpy.testing.assert_allclose(got[key][0], exact[key],
                                          rtol=0.05, atol=0.5)

    def test_int8_tier_error_bound(self, mesh):
        """Two bounded rounding stages: per-element error <=
        n*scale1/2 (stage-1 quantization summed over n shards) +
        scale2/2 (re-quantizing the reduced chunk)."""
        tree = _tree(numpy.random.RandomState(4))
        got = _run_reduce(mesh, tree, "int8")
        for key, value in tree.items():
            exact = value.sum(0, dtype=numpy.float64)
            scale1 = numpy.abs(value).max() / 127.0
            scale2 = numpy.abs(exact).max() / 127.0
            bound = N * scale1 / 2 + scale2 / 2
            err = numpy.abs(got[key][0].astype(numpy.float64)
                            - exact).max()
            assert err <= bound * 1.05, (key, err, bound)

    def test_int_leaves_always_exact(self, mesh):
        """Non-float leaves (error counts, confusion increments) take
        the exact psum regardless of the requested tier."""
        tree = {"n": numpy.arange(N, dtype=numpy.int32)
                .reshape(N, 1) * 1000 + 7}
        for precision in ("bf16", "int8"):
            got = _run_reduce(mesh, tree, precision)
            assert int(got["n"][0][0]) == sum(i * 1000 + 7
                                              for i in range(N))

    def test_broadcast_and_map_fn(self, mesh):
        """broadcast is the replication identity; map_fn is the
        shard_map seam — together a psum of a broadcast value times
        the per-shard index sums the index range."""
        value = jnp.float32(3.0)

        def body(v):
            shard = mr.broadcast(v) * lax.axis_index("data")
            return mr.reduce_sum(shard, "data")[None]

        fn = jax.jit(mr.map_fn(body, mesh, in_specs=(P(),),
                               out_specs=P("data")))
        out = numpy.asarray(fn(value))
        assert out[0] == pytest.approx(3.0 * sum(range(N)))

    def test_bad_precision_rejected(self, mesh):
        with pytest.raises(ValueError, match="reduce precision"):
            mr.reduce_sum({"x": jnp.zeros(4)}, "data", precision="fp4")
        with pytest.raises(ValueError, match="fleet.reduce"):
            mr.reduce_precision_of("fp4")
        saved = root.common.fleet.get("reduce", None)
        root.common.fleet.reduce = "bogus"
        try:
            with pytest.raises(ValueError, match="--fleet-reduce"):
                mr.reduce_precision_of()
        finally:
            root.common.fleet.reduce = saved if saved is not None \
                else "f32"


class TestWireBytes:
    def test_formulas(self):
        tree = {"w": numpy.zeros((96, 32), numpy.float32),
                "b": numpy.zeros(33, numpy.float32)}
        elems = 96 * 32 + 33
        assert mr.reduce_wire_bytes(tree, 8, "f32") \
            == 2 * 7 * elems * 4
        assert mr.reduce_wire_bytes(tree, 8, "bf16") \
            == 2 * 7 * elems * 2
        int8 = mr.reduce_wire_bytes(tree, 8, "int8")
        # int8 payloads (padded to the axis) + 2 scalar pmaxes per leaf
        padded = (96 * 32) + (33 + (-33) % 8)
        assert int8 == 2 * 7 * padded + 2 * (2 * 2 * 7 * 4)
        # ordering: the whole point of the tiers
        assert mr.reduce_wire_bytes(tree, 8, "int8") \
            < mr.reduce_wire_bytes(tree, 8, "bf16") \
            < mr.reduce_wire_bytes(tree, 8, "f32")

    def test_single_device_is_zero(self):
        assert mr.reduce_wire_bytes({"x": numpy.zeros(10)}, 1) == 0

    def test_int_leaf_never_compressed(self):
        tree = {"n": numpy.zeros(16, numpy.int32)}
        assert mr.reduce_wire_bytes(tree, 8, "int8") \
            == mr.reduce_wire_bytes(tree, 8, "f32")


def _dense_specs():
    leaves = (("w", "weights", "_velocity_w", False, True),
              ("b", "bias", "_velocity_b", True, False))
    return [{"kind": "dense", "activation": "tanh", "leaves": leaves,
             "has_params": True, "solver": "momentum"},
            {"kind": "dense", "activation": "linear", "leaves": leaves,
             "has_params": True, "solver": "momentum"}]


def _dense_params(rng, in_f=64, hidden=32, classes=10):
    params = []
    fan = in_f
    for width in (hidden, classes):
        w = jnp.asarray(rng.randn(fan, width).astype(numpy.float32)
                        * 0.05)
        params.append({"p": {"w": w,
                             "b": jnp.zeros(width, jnp.float32)},
                       "v": {"w": jnp.zeros_like(w),
                             "b": jnp.zeros(width, jnp.float32)}})
        fan = width
    return params


def _step_args(rng, batch=128, in_f=64, classes=10):
    hyper = jnp.asarray([0.05, 0.05, 0.0, 0.0, 0.9, 0.9, 0.999, 1e-8],
                        jnp.float32)
    data = jnp.asarray(rng.rand(batch, in_f).astype(numpy.float32))
    labels = jnp.asarray(rng.randint(0, classes, batch))
    indices = jnp.arange(batch, dtype=jnp.int64)
    return ([hyper, hyper], {}, data, labels, indices,
            numpy.float32(batch), numpy.int64(0))


class TestFleetTrainStep:
    def test_instrumented_and_metered(self, mesh):
        """The compiled step books compiles + FLOPs under the
        mapreduce program name, per-step wire bytes/cadence land in
        ReduceStats, and the scrape path exposes the
        veles_fleet_reduce_* families + the chip-idle gauge."""
        from veles_tpu.observe.metrics import MetricsRegistry
        from veles_tpu.observe.xla_stats import get_compile_tracker

        tracker = get_compile_tracker()
        was_enabled = tracker.enabled
        tracker.enabled = True
        # the tracker is process-global and CUMULATIVE: other suites
        # (the fleet chaos family) book the same program names — the
        # absolute compile/hit counts below need a clean slate
        tracker.reset()
        stats = mr.get_reduce_stats()
        stats.reset()
        rng = numpy.random.RandomState(0)
        try:
            steps = mr.fleet_train_step(mesh, _dense_specs(), "none",
                                        with_confusion=False,
                                        reduce_precision="f32")
            train_step = steps[0]
            assert train_step.program_name == \
                "mapreduce.fleet_train_step"
            # UNIQUE shapes (in_f=80): other tests share this wrapped
            # program, and pytest-randomly can order them first — a
            # fresh shape guarantees the compile (and its FLOPs) books
            # into the just-reset tracker regardless of order
            params = _dense_params(rng, in_f=80)
            args = _step_args(rng, in_f=80)
            for _ in range(3):
                params, metrics = train_step(params, *args)
                jax.block_until_ready(metrics)
            snap = tracker.snapshot()
            # two compiles, not three: the first call places
            # uncommitted host params, the second sees the donated
            # mesh-sharded outputs (steady state), the third HITS —
            # i.e. no per-step recompile storm
            assert snap["compiles"].get(
                "mapreduce.fleet_train_step") <= 2
            assert snap["hits"].get("mapreduce.fleet_train_step", 0) \
                >= 1
            # cost analysis produced program FLOPs for the SPMD tick
            assert snap["flops"].get("mapreduce.fleet_train_step", 0) \
                > 0
            reduce_snap = stats.snapshot()
            assert reduce_snap["f32"]["steps"] == 3
            grads = [entry["p"] for entry in params]
            expected = mr.reduce_wire_bytes(grads, N, "f32")
            assert reduce_snap["f32"]["bytes"] == 3 * expected
            assert stats.idle_fraction() is not None
            registry = MetricsRegistry(enabled=True)
            mr.publish_reduce_stats(registry)
            text = registry.expose()
            assert "veles_fleet_reduce_steps_total" in text
            assert "veles_fleet_reduce_bytes_total" in text
            assert "veles_fleet_chip_idle_fraction" in text
        finally:
            tracker.enabled = was_enabled
            stats.reset()

    def test_idle_fraction_tracks_host_gaps(self, mesh):
        """The chip-idle gauge must read LOW for a chip-bound loop and
        HIGH when the host dawdles between steps — i.e. busy is the
        synced step wall, not the async dispatch microseconds (which
        would book every run as ~100% idle)."""
        from veles_tpu.observe.xla_stats import get_compile_tracker

        tracker = get_compile_tracker()
        was_enabled = tracker.enabled
        tracker.enabled = True
        stats = mr.get_reduce_stats()
        rng = numpy.random.RandomState(5)
        try:
            train_step = mr.fleet_train_step(
                mesh, _dense_specs(), "none", with_confusion=False,
                reduce_precision="f32")[0]
            params = _dense_params(rng)
            args = _step_args(rng)
            params, _ = train_step(params, *args)  # compile + place
            params, _ = train_step(params, *args)

            stats.reset()
            for _ in range(5):
                params, _ = train_step(params, *args)
            tight = stats.idle_fraction()
            # generous absolute bound (a loaded CI box stretches the
            # python loop between steps); the RELATIVE ordering below
            # is the discriminating assertion
            assert tight is not None and tight < 0.75, tight

            stats.reset()
            for _ in range(4):
                params, _ = train_step(params, *args)
                time.sleep(0.15)  # a dawdling host protocol
            gappy = stats.idle_fraction()
            assert gappy is not None, gappy
            assert gappy > tight + 0.1, (tight, gappy)
            assert gappy > 0.5, gappy
        finally:
            tracker.enabled = was_enabled
            stats.reset()

    def test_f32_step_bit_identical_to_raw_tick(self, mesh):
        """fleet_train_step is the SAME compiled program as
        build_tick(mesh=...) at the default tier — instrumentation
        must not perturb a single bit."""
        from veles_tpu.parallel import fused

        rng = numpy.random.RandomState(1)
        params_a = _dense_params(rng)
        params_b = jax.tree.map(jnp.copy, params_a)
        args = _step_args(numpy.random.RandomState(2))
        wrapped = mr.fleet_train_step(mesh, _dense_specs(), "none",
                                      with_confusion=False,
                                      reduce_precision="f32")[0]
        raw = fused.build_tick(_dense_specs(), "none", mesh=mesh,
                               with_confusion=False,
                               grad_reduce="f32")[0]
        out_a, m_a = wrapped(params_a, *args)
        out_b, m_b = raw(params_b, *args)
        for layer_a, layer_b in zip(out_a, out_b):
            for leaf in layer_a["p"]:
                numpy.testing.assert_array_equal(
                    numpy.asarray(layer_a["p"][leaf]),
                    numpy.asarray(layer_b["p"][leaf]))
        assert float(m_a[0]) == float(m_b[0])

    def test_in_program_reduce_beats_host_roundtrip(self, mesh):
        """The acceptance bar in miniature: one in-program all-reduce
        of a gradient-sized tree must beat the data-plane host path
        (device->frame encode->decode->device->merge) on the same
        tree."""
        from veles_tpu.fleet.protocol import (decode_frame_bytes,
                                              encode_frame)

        rng = numpy.random.RandomState(3)
        tree = {"w1": rng.randn(N, 784, 256).astype(numpy.float32),
                "b1": rng.randn(N, 256).astype(numpy.float32)}
        sharded = jax.device_put(tree, NamedSharding(mesh, P("data")))

        def body(t):
            local = jax.tree.map(lambda x: x[0], t)
            return mr.reduce_sum(local, "data")

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("data"),),
                               out_specs=P()))
        jax.block_until_ready(fn(sharded))
        in_program = min(_timed(lambda: jax.block_until_ready(
            fn(sharded))) for _ in range(7))

        replica = jax.device_put(jax.tree.map(lambda x: x[0], tree))
        master = jax.device_put(jax.tree.map(lambda x: x[0], tree))

        def host_path():
            host = jax.device_get(replica)
            frame = encode_frame({"update": host}, b"k")
            update = decode_frame_bytes(frame, b"k")["update"]
            merged = jax.tree.map(
                lambda cur, new: (cur + jnp.asarray(new)) * 0.5,
                master, update)
            jax.block_until_ready(merged)

        host_path()
        host = min(_timed(host_path) for _ in range(7))
        assert in_program < host, (
            "in-program reduce %.1fms not faster than host "
            "aggregation %.1fms" % (in_program * 1e3, host * 1e3))


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


class TestInt8ConvergenceParity:
    def test_int8_training_tracks_bf16(self):
        """The quantized-reduce tier's pinned convergence-parity bar
        (docs/compiler_fleet.md): the SAME pod-mode training run under
        int8 gradient reduce must track the bf16 tier's loss curve
        within tolerance and reach the same best-error
        neighborhood."""
        from veles_tpu.core import prng
        from veles_tpu.launcher import Launcher
        from veles_tpu.loader.base import VALID
        from veles_tpu.models.mlp import MLPWorkflow

        def run(tier):
            saved = root.common.fleet.get("reduce", "f32")
            root.common.fleet.reduce = tier
            try:
                prng.get("default").seed(42)
                prng.get("loader").seed(43)
                rng = numpy.random.RandomState(0)
                data = rng.rand(320, 8).astype(numpy.float32)
                labels = (data[:, 0] > 0.5).astype(numpy.int32)
                launcher = Launcher()
                wf = MLPWorkflow(
                    launcher, layers=(8, 2), name="int8-parity",
                    loader_kwargs=dict(
                        data=data, labels=labels,
                        class_lengths=[0, 64, 256],
                        minibatch_size=64,
                        normalization_type="linear"),
                    learning_rate=0.3, max_epochs=3,
                    mesh=build_mesh(devices=jax.devices()[:N],
                                    data=N))
                launcher.initialize()
                launcher.run()
                best = wf.decision.best_n_err[VALID]
                loss = float(wf.decision.last_epoch_loss[VALID])
                weights = [numpy.asarray(gd.weights.mem).copy()
                           for gd in wf.gds]
                launcher.stop()
                return best, loss, weights
            finally:
                root.common.fleet.reduce = saved

        bf16_best, bf16_loss, bf16_w = run("bf16")
        int8_best, int8_loss, int8_w = run("int8")
        # pinned parity bars: the compressed run converges to the same
        # neighborhood (loss within 15% rel, best-error within 3
        # samples of 64), weights stay close
        assert abs(int8_loss - bf16_loss) <= 0.15 * abs(bf16_loss), \
            (int8_loss, bf16_loss)
        assert abs(int8_best - bf16_best) <= 3, (int8_best, bf16_best)
        for got, ref in zip(int8_w, bf16_w):
            numpy.testing.assert_allclose(got, ref, atol=0.08)
