"""Tests for InputJoiner, Avatar, Shell, and the callable-module API
(reference test_input_joiner.py / test_avatar coverage + __init__ API)."""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.avatar import Avatar
from veles_tpu.core.mutable import Bool
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.interaction import Shell
from veles_tpu.memory import Array
from veles_tpu.nn.joiner import InputJoiner


class TestInputJoiner:
    def test_join_two(self):
        a, b = Array(), Array()
        a.reset(numpy.arange(12, dtype=numpy.float32).reshape(4, 3))
        b.reset(numpy.arange(8, dtype=numpy.float32).reshape(4, 2))
        joiner = InputJoiner(DummyWorkflow(), inputs=[a, b])
        joiner.initialize()
        assert (joiner.offset_0, joiner.length_0) == (0, 3)
        assert (joiner.offset_1, joiner.length_1) == (3, 2)
        a.to_device()
        b.to_device()
        joiner.run()
        out = numpy.asarray(joiner.output.mem)
        assert out.shape == (4, 5)
        numpy.testing.assert_array_equal(out[:, :3], a.mem)
        numpy.testing.assert_array_equal(out[:, 3:], b.mem)

    def test_join_flattens_trailing_dims(self):
        a, b = Array(), Array()
        a.reset(numpy.ones((2, 2, 2), numpy.float32))
        b.reset(numpy.zeros((2, 3), numpy.float32))
        joiner = InputJoiner(DummyWorkflow(), inputs=[a, b])
        joiner.initialize()
        a.to_device()
        b.to_device()
        joiner.run()
        assert joiner.output.shape == (2, 7)

    def test_shorter_first_axis_truncates(self):
        a, b = Array(), Array()
        a.reset(numpy.ones((4, 2), numpy.float32))
        b.reset(numpy.ones((3, 2), numpy.float32))
        joiner = InputJoiner(DummyWorkflow(), inputs=[a, b])
        joiner.initialize()
        a.to_device()
        b.to_device()
        joiner.run()
        assert joiner.output.shape == (3, 4)

    def test_no_inputs_raises(self):
        with pytest.raises(ValueError):
            InputJoiner(DummyWorkflow()).initialize()


class TestAvatar:
    def test_clones_arrays_bools_and_plain(self):
        wf = DummyWorkflow()

        class Producer:
            weights = Array()
            flag = Bool(False)
            epoch = 3
            stats = {"a": 1}

        producer = Producer()
        producer.weights.reset(numpy.ones((2, 2), numpy.float32))
        producer.weights.to_device()
        avatar = Avatar(wf)
        avatar.link_clones(producer, "weights", "flag", "epoch", "stats")
        avatar.initialize()
        numpy.testing.assert_array_equal(
            numpy.asarray(avatar.weights.mem), numpy.ones((2, 2)))
        assert not bool(avatar.flag)
        assert avatar.epoch == 3
        # mutate producer: avatar stays stale until next clone
        producer.weights.data = jnp.zeros((2, 2))
        producer.flag.set(True)
        producer.stats["a"] = 2
        assert float(numpy.asarray(avatar.weights.mem).max()) == 1.0
        assert avatar.stats == {"a": 1}
        avatar.run()
        assert float(numpy.asarray(avatar.weights.mem).max()) == 0.0
        assert bool(avatar.flag)
        assert avatar.stats == {"a": 2}


class TestShell:
    def test_noop_without_trigger(self):
        shell = Shell(DummyWorkflow())
        shell.run()  # no trigger: silently continues

    def test_interrupt_embeds(self, monkeypatch):
        shell = Shell(DummyWorkflow())
        opened = []
        monkeypatch.setattr(shell, "embed",
                            lambda: opened.append(True))
        shell.run()
        assert not opened
        shell.interrupt()
        shell.run()
        assert opened == [True]
        shell.run()  # trigger consumed
        assert opened == [True]

    def test_file_trigger(self, tmp_path, monkeypatch):
        trigger = tmp_path / "shell"
        shell = Shell(DummyWorkflow(), trigger_path=str(trigger))
        opened = []
        monkeypatch.setattr(shell, "embed", lambda: opened.append(True))
        shell.run()
        assert not opened
        trigger.write_text("")
        shell.run()
        assert opened == [True]
        assert not trigger.exists()  # consumed


class TestCallableModule:
    def test_kwargs_to_argv(self):
        from veles_tpu.cli import kwargs_to_argv
        argv = kwargs_to_argv("wf.py", "cfg.py",
                              overrides=("root.a=1",),
                              listen="0.0.0.0:5050", seed=42,
                              async_slave=True, dump_config=False)
        assert argv == ["wf.py", "cfg.py", "root.a=1",
                        "--listen", "0.0.0.0:5050", "--seed", "42",
                        "--async-slave"]

    def test_kwargs_to_argv_repeats_list_flags(self):
        """List/tuple values repeat the flag (argparse append actions
        like --nodes) and the serving-survival knobs pass through."""
        from veles_tpu.cli import kwargs_to_argv
        argv = kwargs_to_argv("wf.py", nodes=["h1", "h2"],
                              serve_max_queue=16, serve_deadline=2.5,
                              chaos_serve_step_fail=0.1)
        assert argv == ["wf.py", "-", "--nodes", "h1", "--nodes", "h2",
                        "--serve-max-queue", "16",
                        "--serve-deadline", "2.5",
                        "--chaos-serve-step-fail", "0.1"]

    def test_module_is_callable_end_to_end(self, tmp_path):
        import veles_tpu
        wf_file = tmp_path / "tiny_wf.py"
        wf_file.write_text("""
import numpy
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(80, 8).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(8, 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 20, 60],
                            minibatch_size=20),
         learning_rate=0.5, max_epochs=2)
    main()
""")
        launcher = veles_tpu(str(wf_file))
        assert launcher is not None
        assert launcher.workflow.decision.epochs_done >= 2
