"""Pod mode as a PRODUCT mode (VERDICT r2 #1/#2).

Three tiers:

- the mesh config (``root.common.mesh.axes`` / ``--mesh``) actually
  reaches a running ``StandardWorkflow`` through the real ``Launcher``;
- the CLI flag trains sharded end to end (subprocess over a 4-device
  virtual CPU platform);
- a 2-process ``jax.distributed`` pod (1 device each) matches the
  single-process 2-device run bit-for-bit — the multi-host path.
"""

import json
import os
import socket
import subprocess
import sys

import numpy
import pytest

from veles_tpu.core import prng
from veles_tpu.core.config import root
from veles_tpu.launcher import Launcher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _digits():
    from dataset_fixtures import digits_dataset
    return digits_dataset()


def _build(mesh=None, minibatch_size=96):
    # default 96: divisible by the 8-device data axis AND the 4-device
    # reference mesh; the 2-process parity test uses 100 to match
    # tests/pod_child.py
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    X, y = _digits()
    launcher = Launcher()
    wf = MLPWorkflow(
        launcher, layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=minibatch_size,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=3, mesh=mesh, name="pod-product")
    return launcher, wf


def test_mesh_config_reaches_product_path():
    """root.common.mesh.axes alone must put the workflow into sharded
    pod mode through the real Launcher (no mesh= kwarg anywhere), and
    the numbers must match the explicitly-meshed run."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    launcher_ref, ref = _build(
        mesh=build_mesh(devices=jax.devices()[:4], data=4))
    launcher_ref.initialize()
    launcher_ref.run()

    root.common.mesh.axes.data = -1  # absorb all 8 virtual devices
    try:
        launcher, wf = _build()
        launcher.initialize()
        assert wf.fused_tick is not None
        assert wf.fused_tick.mesh is not None, \
            "configured mesh did not reach the workflow"
        assert wf.fused_tick.mesh.shape["data"] == len(jax.devices())
        launcher.run()
    finally:
        root.common.mesh.axes.data = 1
    # dp8 vs dp4: psum-merged grads equal full-batch grads up to float
    # reassociation (different reduction trees), compounding over the
    # run — metrics stay exact, weights stay close
    assert wf.decision.best_n_err[VALID] == ref.decision.best_n_err[VALID]
    for fa, fb in zip(wf.forwards, ref.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fa.weights.data), numpy.asarray(fb.weights.data),
            atol=2e-2)


@pytest.mark.slow
def test_cli_mesh_flag_trains_sharded(tmp_path):
    """`python -m veles_tpu samples/digits_mlp.py --mesh data=4` — the
    VERDICT r2 done-criterion for CLI reachability."""
    result_file = str(tmp_path / "results.json")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               VELES_TPU_HOME=str(tmp_path / "home"),
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env["PYTHONPATH"].split(os.pathsep)
        if p and ".axon_site" not in p)
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
         "samples/digits_config.py", "root.digits.max_epochs=2",
         "--mesh", "data=4", "--seed", "7", "--result-file", result_file],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "pod mode: mesh" in proc.stderr + proc.stdout
    results = json.load(open(result_file))
    assert results["epochs"] == 2
    assert results["best_validation_errors"] < 297


@pytest.mark.slow
def test_two_process_pod_matches_single_process(tmp_path):
    """Two jax.distributed processes (1 device each) running the product
    path must reproduce the single-process 2-device run exactly."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    launcher, ref = _build(mesh=build_mesh(devices=jax.devices()[:2],
                                           data=2), minibatch_size=100)
    launcher.initialize()
    launcher.run()

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
    out = str(tmp_path / "pod0.json")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "pod_child.py"),
         str(pid), "2", str(port), out],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in range(2)]
    fail = []
    try:
        for pid, proc in enumerate(procs):
            _, err = proc.communicate(timeout=600)
            if proc.returncode:
                fail.append("child %d rc=%d:\n%s"
                            % (pid, proc.returncode, err[-2000:]))
    finally:
        for proc in procs:
            if proc.poll() is None:
                # a crashed sibling leaves the other parked in the
                # jax.distributed barrier — never leak it past the test
                proc.kill()
    assert not fail, "\n".join(fail)
    got = json.load(open(out))
    assert got["epochs"] == ref.decision._epochs_done
    assert got["best_n_err"] == ref.decision.best_n_err[VALID]
    for child_w, fwd in zip(got["weights"], ref.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(child_w, numpy.float32),
            numpy.asarray(fwd.weights.data), atol=1e-6)
