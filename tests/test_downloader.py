"""Downloader unit + idx/MNIST pipeline, exercised fully offline via
local files (the reference tested its downloader against fixture
archives the same way)."""

import gzip
import hashlib
import os
import struct
import tarfile

import numpy
import pytest

from veles_tpu.downloader import Downloader, fetch
from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.loader.mnist import FILES, MNISTLoader, read_idx


def _write_idx(path, array):
    codes = {numpy.uint8: 0x08, numpy.int32: 0x0C, numpy.float32: 0x0D}
    code = codes[array.dtype.type]
    with open(path, "wb") as out:
        out.write(struct.pack(">HBB", 0, code, array.ndim))
        out.write(struct.pack(">" + "I" * array.ndim, *array.shape))
        out.write(array.astype(array.dtype.newbyteorder(">")).tobytes())


def _fake_mnist(directory, n_train=120, n_test=40):
    rng = numpy.random.RandomState(0)
    os.makedirs(directory, exist_ok=True)
    sets = {"train": n_train, "t10k": n_test}
    for prefix, n in sets.items():
        images = rng.randint(0, 256, (n, 28, 28)).astype(numpy.uint8)
        labels = rng.randint(0, 10, n).astype(numpy.uint8)
        _write_idx(os.path.join(
            directory, "%s-images-idx3-ubyte" % prefix), images)
        _write_idx(os.path.join(
            directory, "%s-labels-idx1-ubyte" % prefix), labels)


def test_idx_roundtrip(tmp_path):
    arr = numpy.arange(24, dtype=numpy.int32).reshape(2, 3, 4)
    path = str(tmp_path / "x.idx")
    _write_idx(path, arr)
    numpy.testing.assert_array_equal(read_idx(path), arr)
    # gzipped variant
    with open(path, "rb") as fin, gzip.open(path + ".gz", "wb") as out:
        out.write(fin.read())
    numpy.testing.assert_array_equal(read_idx(path + ".gz"), arr)


def test_fetch_local_targz(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "a.txt").write_text("hello")
    archive = str(tmp_path / "data.tar.gz")
    with tarfile.open(archive, "w:gz") as tar:
        tar.add(str(src / "a.txt"), arcname="a.txt")
    out = str(tmp_path / "out")
    extracted = fetch(archive, out)
    assert os.path.exists(os.path.join(out, "a.txt"))
    assert any(p.endswith("a.txt") for p in extracted)


def test_fetch_checksum_mismatch(tmp_path):
    payload = tmp_path / "x.bin"
    payload.write_bytes(b"data")
    with pytest.raises(ValueError):
        fetch(str(payload), str(tmp_path / "out"), checksum="0" * 64)
    good = hashlib.sha256(b"data").hexdigest()
    fetch(str(payload), str(tmp_path / "out2"), checksum=good)


def test_downloader_unit_file_url(tmp_path):
    src = tmp_path / "dataset.tar.gz"
    inner = tmp_path / "weights.npy"
    numpy.save(str(inner), numpy.zeros(3))
    with tarfile.open(str(src), "w:gz") as tar:
        tar.add(str(inner), arcname="weights.npy")
    wf = DummyWorkflow()
    dl = Downloader(wf, url="file://" + str(src),
                    directory=str(tmp_path / "dst"),
                    files=["weights.npy"])
    assert dl.initialize() is None
    assert os.path.exists(str(tmp_path / "dst" / "weights.npy"))
    # second initialize short-circuits (no refetch of a removed source)
    src.unlink()
    assert dl.initialize() is None


def test_mnist_loader_and_training(tmp_path):
    """The full MNIST784 pipeline on synthetic idx files: load, split
    [0, test, train], train one epoch through the product path."""
    from veles_tpu.core import prng
    from veles_tpu.models.mlp import MLPWorkflow

    data_dir = str(tmp_path / "mnist")
    _fake_mnist(data_dir)
    prng.get("default").seed(1)
    prng.get("loader").seed(1)
    wf = MLPWorkflow(
        DummyLauncher(), layers=(16, 10), loader_cls=MNISTLoader,
        loader_kwargs=dict(directory=data_dir, minibatch_size=20),
        learning_rate=0.05, max_epochs=1, name="mnist-test")
    wf.initialize()
    assert wf.loader.class_lengths == [0, 40, 120]
    assert wf.loader.original_data.shape == (160, 784)
    wf.run()
    assert wf.decision._epochs_done == 1
    assert wf.decision.best_n_err[VALID] is not None


@pytest.mark.parametrize("topology", ["conv", "caffe"])
def test_mnist_conv_sample_topologies(tmp_path, topology):
    """The mnist_conv sample's topologies (reference mnist_conv /
    mnist_caffe configs, anchors 0.73%/0.86%) train end-to-end over the
    NHWC idx pipeline (flat=False)."""
    import sys
    sys.path.insert(0, "samples")
    try:
        from mnist_conv import TOPOLOGIES
    finally:
        sys.path.pop(0)
    from veles_tpu.core import prng
    from veles_tpu.models.standard import StandardWorkflow

    data_dir = str(tmp_path / "mnist")
    _fake_mnist(data_dir)
    prng.get("default").seed(1)
    prng.get("loader").seed(1)
    wf = StandardWorkflow(
        DummyLauncher(), layers=TOPOLOGIES[topology],
        loader_cls=MNISTLoader,
        loader_kwargs=dict(directory=data_dir, minibatch_size=20,
                           normalization_type="linear", flat=False),
        learning_rate=0.03, decision_kwargs=dict(max_epochs=1),
        name="mnist-%s" % topology)
    wf.initialize()
    assert wf.loader.original_data.shape == (160, 28, 28, 1)
    assert wf.fused_tick is not None, "conv chain must fuse"
    wf.run()
    assert wf.decision._epochs_done == 1


def test_mnist_loader_missing_files(tmp_path):
    wf = DummyWorkflow()
    loader = MNISTLoader(wf, directory=str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError):
        loader.load_data()
