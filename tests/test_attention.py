"""Attention op tests: fused vs naive, and ring attention vs single-device
on the 8-way virtual mesh (sequence parallelism)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops.attention import attention, make_ring_attention
from veles_tpu.parallel.mesh import build_mesh


def naive_attention(q, k, v, causal=False):
    import math
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = numpy.random.RandomState(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(b, t, h, d).astype(numpy.float32) * 0.5)
    return mk(), mk(), mk()


class TestFused:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_naive(self, causal):
        q, k, v = _qkv()
        out = attention(q, k, v, causal=causal)
        ref = naive_attention(q, k, v, causal=causal)
        numpy.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


class TestRing:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        """Ring attention over an 8-way seq mesh == plain attention."""
        q, k, v = _qkv(b=2, t=128, h=2, d=16)
        mesh = build_mesh(data=1, seq=8)
        ring = make_ring_attention(mesh, causal=causal)
        out = ring(q, k, v)
        ref = naive_attention(q, k, v, causal=causal)
        numpy.testing.assert_allclose(
            numpy.asarray(out), numpy.asarray(ref), rtol=2e-2, atol=2e-3)

    def test_long_sequence_memory_shape(self):
        """Each device only holds T/8 of the sequence."""
        q, k, v = _qkv(b=1, t=256, h=2, d=16)
        mesh = build_mesh(data=1, seq=8)
        ring = make_ring_attention(mesh, causal=True)
        out = ring(q, k, v)
        assert out.shape == (1, 256, 2, 16)
        # sharded over seq: 8 addressable shards of 32 tokens
        assert len(out.addressable_shards) == 8
        assert out.addressable_shards[0].data.shape == (1, 32, 2, 16)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        """Ulysses all-to-all SP over an 8-way seq mesh == plain
        attention (drop-in alternative to the ring)."""
        from veles_tpu.ops.attention import make_ulysses_attention

        q, k, v = _qkv(b=2, t=128, h=8, d=16)
        mesh = build_mesh(data=1, seq=8)
        ulysses = make_ulysses_attention(mesh, causal=causal)
        out = ulysses(q, k, v)
        ref = naive_attention(q, k, v, causal=causal)
        numpy.testing.assert_allclose(
            numpy.asarray(out), numpy.asarray(ref), rtol=2e-2, atol=2e-3)
        # output stays sequence-sharded like the ring's
        assert len(out.addressable_shards) == 8
        assert out.addressable_shards[0].data.shape == (2, 16, 8, 16)

    def test_matches_ring(self):
        """The two SP strategies agree with each other."""
        from veles_tpu.ops.attention import make_ulysses_attention

        q, k, v = _qkv(b=1, t=128, h=8, d=16, seed=3)
        mesh = build_mesh(data=1, seq=8)
        ring = make_ring_attention(mesh, causal=True)
        ulysses = make_ulysses_attention(mesh, causal=True)
        numpy.testing.assert_allclose(
            numpy.asarray(ring(q, k, v)),
            numpy.asarray(ulysses(q, k, v)), rtol=2e-2, atol=2e-3)

    def test_heads_divisibility_required(self):
        from veles_tpu.ops.attention import make_ulysses_attention

        q, k, v = _qkv(b=1, t=64, h=6, d=8)  # 6 heads, 8 devices
        mesh = build_mesh(data=1, seq=8)
        ulysses = make_ulysses_attention(mesh)
        with pytest.raises(ValueError, match="divisible"):
            ulysses(q, k, v)
