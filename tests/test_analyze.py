"""ISSUE 13: the invariant-checking static-analysis pass
(``veles_tpu analyze``, docs/static_analysis.md).

- every shipped rule is proven LIVE: it fires at the exact
  ``file:line`` of its seeded fixture violation (and nowhere else in
  that fixture), and the clean negative-control file yields zero
  findings under the full rule set even when declared record-path and
  thread-shared;
- the baseline round-trips: findings -> ``--update-baseline`` ->
  exit 0, and a NEW violation still surfaces through a populated
  baseline (with triage justifications preserved across updates);
- the CLI exit-code matrix holds: 0 clean / 1 findings /
  2 unreadable;
- the acceptance gate: ``veles_tpu analyze veles_tpu/`` exits 0
  against the committed baseline.
"""

import json
import os
import shutil

import pytest

from veles_tpu.analyze import AnalysisRegistry, run_analysis
from veles_tpu.analyze.cli import main as analyze_main
from veles_tpu.analyze.rules import default_rules

pytestmark = pytest.mark.analyze

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "analyze")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (rule id, fixture file) — one seeded violation per shipped rule
RULE_FIXTURES = [
    ("lock.record-path", "record_path.py"),
    ("lock.ordering", "lock_ordering.py"),
    ("retrace.unpinned-out-shardings", "unpinned_out_shardings.py"),
    ("retrace.local-jit-dispatch", "local_jit_dispatch.py"),
    ("retrace.unhashable-static", "unhashable_static.py"),
    ("retrace.jit-in-loop", "jit_in_loop.py"),
    ("retrace.shape-key", "shape_key.py"),
    # ISSUE 18: the fused paged-attention kernel's jit surface —
    # the static-arg wrapper rebuilt per request + an unhashable
    # block-shape static, both in one fixture (the real
    # ops/paged_attention.py is asserted clean below)
    ("retrace.jit-in-loop", "paged_kernel_retrace.py"),
    ("retrace.unhashable-static", "paged_kernel_retrace.py"),
    ("donation.read-after-dispatch", "donation.py"),
    ("shared.rmw", "shared_rmw.py"),
    ("deploy.swap-seam", "swap_seam.py"),
    ("metric.naming", "metric_naming.py"),
    ("metric.help", "metric_help.py"),
]


def fixture_registry():
    """Fixture-scoped declarations (the real tree's live in
    veles_tpu/analyze/registry.py)."""
    return AnalysisRegistry(
        record_path={"analyze/record_path.py": {"ToyLedger.record"},
                     "analyze/clean.py": {"CleanLedger.record"}},
        shared_classes={"analyze/shared_rmw.py": {"SharedCounters": ()},
                        "analyze/clean.py": {"CleanShared": ()}})


def expected_markers(path):
    """``(rule id, line)`` rows from the ``# analyze-expect:`` markers
    the fixtures carry on their violation lines."""
    out = []
    with open(path) as fin:
        for lineno, line in enumerate(fin, 1):
            if "# analyze-expect:" in line:
                rule = line.split("# analyze-expect:")[1].strip()
                out.append((rule, lineno))
    return out


class TestRuleCorpus:
    @pytest.mark.parametrize("rule_id,filename", RULE_FIXTURES,
                             ids=[r for r, _ in RULE_FIXTURES])
    def test_rule_fires_at_exact_line(self, rule_id, filename):
        """The seeded violation is found at its exact file:line — and
        is the ONLY finding the full rule set raises on the fixture
        (no cross-rule contamination)."""
        path = os.path.join(FIXTURES, filename)
        findings, errors = run_analysis([path],
                                        registry=fixture_registry())
        assert not errors
        assert [(f.rule, f.line) for f in findings] \
            == expected_markers(path)
        assert all(f.path == path for f in findings)
        assert any(f.rule == rule_id for f in findings)

    def test_every_shipped_rule_has_a_fixture(self):
        """A rule without a seeded-violation fixture is not proven
        live — adding a rule forces adding its fixture."""
        assert {rule for rule, _ in RULE_FIXTURES} \
            == {rule.id for rule in default_rules()}

    def test_clean_file_zero_findings(self):
        """The negative control: clean under the FULL rule set even
        while declared record-path and thread-shared."""
        path = os.path.join(FIXTURES, "clean.py")
        findings, errors = run_analysis([path],
                                        registry=fixture_registry())
        assert not errors
        assert findings == []

    def test_paged_kernel_surface_retrace_clean(self):
        """ISSUE 18 acceptance: the fused kernel's static-arg
        signature (page_size/block_h statics in ops/paged_attention.py
        and the probe-switched attend seam in parallel/kv_pool.py)
        must not reintroduce a per-request retrace — the whole retrace
        rule family yields ZERO findings on the REAL files, with the
        real package registry (paged_kernel_retrace.py proves the
        same rules fire on the seeded regressions)."""
        paths = [os.path.join(REPO_ROOT, "veles_tpu", "ops",
                              "paged_attention.py"),
                 os.path.join(REPO_ROOT, "veles_tpu", "parallel",
                              "kv_pool.py")]
        findings, errors = run_analysis(paths, rule_filter="retrace")
        assert not errors
        assert [(f.rule, f.line) for f in findings] == []

    def test_whole_corpus_matches_markers(self):
        """Directory run: the union of every fixture's markers, each
        at its own path — cross-file rules (metric.help) included."""
        findings, errors = run_analysis([FIXTURES],
                                        registry=fixture_registry())
        assert not errors
        got = {(os.path.basename(f.path), f.rule, f.line)
               for f in findings}
        want = set()
        for _, filename in RULE_FIXTURES:
            path = os.path.join(FIXTURES, filename)
            want |= {(filename, rule, line)
                     for rule, line in expected_markers(path)}
        assert got == want

    def test_record_path_nested_def_reported_once(self, tmp_path):
        """A violation inside a nested def yields ONE finding — under
        a whole-module declaration it is attributed to the nested
        qualname; under an explicit declaration of the outer function
        the closure inherits the discipline."""
        mod = tmp_path / "probe.py"
        mod.write_text(
            "import time\n"
            "def outer():\n"
            "    def inner():\n"
            "        time.sleep(1)\n"
            "    return inner\n")
        whole = AnalysisRegistry(record_path={"probe.py": None},
                                 shared_classes={})
        findings, _ = run_analysis([str(mod)], registry=whole)
        assert [(f.rule, f.line) for f in findings] \
            == [("lock.record-path", 4)]
        assert "outer.inner" in findings[0].message
        explicit = AnalysisRegistry(record_path={"probe.py": {"outer"}},
                                    shared_classes={})
        findings, _ = run_analysis([str(mod)], registry=explicit)
        assert [(f.rule, f.line) for f in findings] \
            == [("lock.record-path", 4)]

    def test_donation_rebind_shape_is_sanctioned(self, tmp_path):
        """`state = step(state, b)` (single call and the canonical
        training loop) rebinds the name to the RETURNED value — no
        finding; a read of a buffer donated to an earlier statement
        still fires."""
        mod = tmp_path / "ticks.py"
        mod.write_text(
            "import jax\n"
            "def _t(state, b):\n"
            "    return state\n"
            "step = jax.jit(_t, donate_argnums=(0,))\n"
            "def tick(state, b):\n"
            "    state = step(state, b)\n"
            "    return state\n"
            "def loop(state, batches):\n"
            "    for b in batches:\n"
            "        state = step(state, b)\n"
            "    return state\n"
            "def double(state, b):\n"
            "    out = step(state, b)\n"
            "    again = step(state, b)\n"
            "    return out, again\n")
        findings, errors = run_analysis(
            [str(mod)], rule_filter="donation",
            registry=AnalysisRegistry(record_path={},
                                      shared_classes={}))
        assert not errors
        assert [(f.rule, f.line) for f in findings] \
            == [("donation.read-after-dispatch", 14)]

    def test_donation_same_statement_read_fires(self, tmp_path):
        """A read of the donated buffer in the SAME statement as the
        donating call (`return step(state, b) + state`) is the bug
        class the rule gates — it must fire."""
        mod = tmp_path / "same.py"
        mod.write_text(
            "import jax\n"
            "def _t(state, b):\n"
            "    return state\n"
            "step = jax.jit(_t, donate_argnums=(0,))\n"
            "def tick(state, b):\n"
            "    return step(state, b) + state\n")
        findings, _ = run_analysis(
            [str(mod)], rule_filter="donation",
            registry=AnalysisRegistry(record_path={},
                                      shared_classes={}))
        assert [(f.rule, f.line) for f in findings] \
            == [("donation.read-after-dispatch", 6)]

    def test_jit_in_loop_cache_exemption_is_scope_local(self,
                                                        tmp_path):
        """An unrelated function's `cache[k] = fn` must not silence a
        same-named uncached jit-in-loop elsewhere in the file."""
        mod = tmp_path / "twofn.py"
        mod.write_text(
            "import jax\n"
            "_C = {}\n"
            "def _step(x):\n"
            "    return x\n"
            "def hot(batches):\n"
            "    for b in batches:\n"
            "        fn = jax.jit(_step)\n"
            "        fn(b)\n"
            "def other(fn):\n"
            "    _C['k'] = fn\n")
        findings, _ = run_analysis(
            [str(mod)], rule_filter="retrace.jit-in-loop",
            registry=AnalysisRegistry(record_path={},
                                      shared_classes={}))
        assert [(f.rule, f.line) for f in findings] \
            == [("retrace.jit-in-loop", 7)]

    def test_unguarded_nonlocal_jit_still_fires(self, tmp_path):
        """A nonlocal slot rebuilt UNCONDITIONALLY per call re-traces
        every call — only the `if slot is None:` memo-guard shape is
        sanctioned."""
        mod = tmp_path / "slots.py"
        mod.write_text(
            "import jax\n"
            "def shard_map(fn, mesh=None):\n"
            "    return fn\n"
            "def _run(x):\n"
            "    return x\n"
            "def make(mesh):\n"
            "    slot = None\n"
            "    def bad(x):\n"
            "        nonlocal slot\n"
            "        slot = jax.jit(shard_map(_run, mesh=mesh))\n"
            "        return slot(x)\n"
            "    def good(x):\n"
            "        nonlocal slot\n"
            "        if slot is None:\n"
            "            slot = jax.jit(shard_map(_run, mesh=mesh))\n"
            "        return slot(x)\n"
            "    return bad, good\n")
        findings, _ = run_analysis(
            [str(mod)], rule_filter="retrace.local-jit-dispatch",
            registry=AnalysisRegistry(record_path={},
                                      shared_classes={}))
        assert [(f.rule, f.line) for f in findings] \
            == [("retrace.local-jit-dispatch", 11)]

    def test_jit_in_loop_miss_branch_is_sanctioned(self, tmp_path):
        """The keyed-cache miss-branch inside a loop (clean.py's
        sanctioned shape: `fn = jax.jit(...)` then `cache[key] = fn`)
        must not fire."""
        mod = tmp_path / "warm.py"
        mod.write_text(
            "import jax\n"
            "_FN_CACHE = {}\n"
            "def _step(x):\n"
            "    return x\n"
            "def warm(keys):\n"
            "    for key in keys:\n"
            "        fn = _FN_CACHE.get(key)\n"
            "        if fn is None:\n"
            "            fn = jax.jit(_step)\n"
            "            _FN_CACHE[key] = fn\n"
            "        fn(key)\n")
        findings, errors = run_analysis(
            [str(mod)], rule_filter="retrace.jit-in-loop",
            registry=AnalysisRegistry(record_path={},
                                      shared_classes={}))
        assert not errors
        assert findings == []

    def test_registry_suffix_matches_at_segment_boundary(self):
        """`serving.py` declarations must not leak onto a file that
        merely ENDS with the same characters (llm_serving.py)."""
        registry = AnalysisRegistry()
        assert registry.shared_classes_for("veles_tpu/serving.py")
        assert not registry.shared_classes_for(
            "samples/llm_serving.py")
        assert registry.record_path_functions(
            "veles_tpu/observe/reqledger.py") is None
        assert registry.record_path_functions(
            "other/my_reqledger.py") == ()

    def test_lockish_names_are_boundary_anchored(self, tmp_path):
        """`with blocker:` must NOT count as holding a lock — a false
        lock would silently satisfy shared.rmw (masking the exact race
        the rule exists to catch) and mis-fire the lock rules."""
        mod = tmp_path / "notlocks.py"
        mod.write_text(
            "class Gauges:\n"
            "    def book(self, blocker):\n"
            "        with blocker:\n"
            "            self.served += 1\n"
            "        with self.clock:\n"
            "            self.ticks += 1\n")
        registry = AnalysisRegistry(
            record_path={},
            shared_classes={"notlocks.py": {"Gauges": ()}})
        findings, errors = run_analysis([str(mod)], registry=registry)
        assert not errors
        assert [(f.rule, f.line) for f in findings] \
            == [("shared.rmw", 4), ("shared.rmw", 6)]

    def test_rule_filter_selects_family_and_id(self):
        path = os.path.join(FIXTURES, "metric_naming.py")
        findings, _ = run_analysis([path], rule_filter="metric.naming",
                                   registry=fixture_registry())
        assert [f.rule for f in findings] == ["metric.naming"]
        findings, _ = run_analysis([path], rule_filter="lock",
                                   registry=fixture_registry())
        assert findings == []
        with pytest.raises(ValueError, match="unknown rule"):
            run_analysis([path], rule_filter="nonsense",
                         registry=fixture_registry())


class TestCliExitCodes:
    def test_exit_0_on_clean(self, capsys):
        assert analyze_main([os.path.join(FIXTURES, "clean.py")]) == 0
        assert "clean" in capsys.readouterr().out

    def test_exit_1_on_findings(self, capsys):
        path = os.path.join(FIXTURES, "metric_naming.py")
        assert analyze_main([path]) == 1
        out = capsys.readouterr().out
        assert "[metric.naming]" in out
        assert "metric_naming.py" in out

    def test_exit_2_on_unreadable(self, tmp_path, capsys):
        bad = tmp_path / "broken.py"
        bad.write_text("def broken(:\n")
        assert analyze_main([str(bad)]) == 2
        assert "UNREADABLE" in capsys.readouterr().err

    def test_exit_2_on_corrupt_baseline(self, tmp_path, capsys):
        """A merge-mangled baseline is an unreadable INPUT (exit 2),
        never 'new findings' (exit 1)."""
        corrupt = tmp_path / "baseline.json"
        corrupt.write_text("{bad json")
        clean = os.path.join(FIXTURES, "clean.py")
        assert analyze_main([clean, "--baseline",
                             str(corrupt)]) == 2
        assert "UNREADABLE" in capsys.readouterr().err
        corrupt.write_text('{"wrong": "shape"}')
        assert analyze_main([clean, "--baseline",
                             str(corrupt)]) == 2
        # valid JSON, entry missing its fingerprint (bad merge
        # resolution): still exit 2, never a KeyError traceback
        corrupt.write_text(
            '{"version": 1, "findings": [{"rule": "x"}]}')
        assert analyze_main([clean, "--baseline",
                             str(corrupt)]) == 2
        # and --update-baseline rebuilds it from scratch as promised
        assert analyze_main([clean, "--baseline", str(corrupt),
                             "--update-baseline"]) == 0
        assert analyze_main([clean, "--baseline",
                             str(corrupt)]) == 0

    def test_rule_flag(self, capsys):
        path = os.path.join(FIXTURES, "metric_help.py")
        assert analyze_main([path, "--rule", "lock"]) == 0
        assert analyze_main([path, "--rule", "metric"]) == 1

    def test_unknown_rule_is_an_error(self, capsys):
        with pytest.raises(SystemExit):
            analyze_main([os.path.join(FIXTURES, "clean.py"),
                          "--rule", "nonsense"])

    def test_list_rules(self, capsys):
        assert analyze_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in default_rules():
            assert rule.id in out

    def test_record_path_and_shared_class_flags(self, tmp_path,
                                                capsys):
        """The one-off registry extension seam: the same fixture that
        is silent without declarations fires with them."""
        record = os.path.join(FIXTURES, "record_path.py")
        shared = os.path.join(FIXTURES, "shared_rmw.py")
        assert analyze_main([record, shared]) == 0
        capsys.readouterr()
        assert analyze_main(
            [record, shared,
             "--record-path", "analyze/record_path.py:ToyLedger.record",
             "--shared-class", "analyze/shared_rmw.py:SharedCounters"]
        ) == 1
        out = capsys.readouterr().out
        assert "[lock.record-path]" in out
        assert "[shared.rmw]" in out


class TestBaseline:
    def _seed(self, tmp_path):
        target = tmp_path / "shape_key.py"
        shutil.copy(os.path.join(FIXTURES, "shape_key.py"), target)
        return str(target), str(tmp_path / "baseline.json")

    def test_round_trip_then_new_violation_surfaces(self, tmp_path,
                                                    capsys):
        target, baseline = self._seed(tmp_path)
        assert analyze_main([target, "--baseline", baseline]) == 1
        capsys.readouterr()
        # adopt: record the pre-existing finding, gate goes green
        assert analyze_main([target, "--baseline", baseline,
                             "--update-baseline"]) == 0
        assert analyze_main([target, "--baseline", baseline]) == 0
        assert "baselined" in capsys.readouterr().out
        # a NEW violation still surfaces through the populated baseline
        with open(target, "a") as fout:
            fout.write("\n\ndef more(fn):\n"
                       "    _PROGRAM_CACHE[[1, 2]] = fn\n")
        assert analyze_main([target, "--baseline", baseline]) == 1
        out = capsys.readouterr().out
        assert out.count("[retrace.shape-key]") == 1  # only the new one

    def test_update_preserves_justifications(self, tmp_path):
        target, baseline = self._seed(tmp_path)
        assert analyze_main([target, "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(baseline) as fin:
            data = json.load(fin)
        assert len(data["findings"]) == 1
        data["findings"][0]["justification"] = "fixture: deliberate"
        with open(baseline, "w") as fout:
            json.dump(data, fout)
        assert analyze_main([target, "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(baseline) as fin:
            kept = json.load(fin)["findings"][0]
        assert kept["justification"] == "fixture: deliberate"
        assert kept["rule"] == "retrace.shape-key"

    def test_update_refuses_rule_filter(self, tmp_path):
        """A rule-filtered rewrite would silently drop every other
        rule's triaged entries — the CLI refuses the combination."""
        target, baseline = self._seed(tmp_path)
        with pytest.raises(SystemExit):
            analyze_main([target, "--baseline", baseline,
                          "--rule", "metric", "--update-baseline"])

    def test_subtree_update_preserves_other_subtrees(self, tmp_path):
        """--update-baseline scoped to one subtree must carry over the
        other subtree's baselined entries untouched."""
        sub_a = tmp_path / "a"
        sub_b = tmp_path / "b"
        sub_a.mkdir()
        sub_b.mkdir()
        for sub in (sub_a, sub_b):
            shutil.copy(os.path.join(FIXTURES, "shape_key.py"),
                        sub / "shape_key.py")
        baseline = str(tmp_path / "baseline.json")
        assert analyze_main([str(tmp_path), "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(baseline) as fin:
            assert len(json.load(fin)["findings"]) == 2
        # re-update from subtree a only: b's entry must survive
        assert analyze_main([str(sub_a), "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(baseline) as fin:
            paths = {e["path"] for e in json.load(fin)["findings"]}
        assert paths == {"a/shape_key.py", "b/shape_key.py"}
        assert analyze_main([str(tmp_path), "--baseline", baseline]) \
            == 0

    def test_update_prunes_entries_of_deleted_files(self, tmp_path):
        """Carried-over baseline entries must still point at code that
        exists — a deleted file's entries are pruned on the next
        update instead of rotting forever."""
        sub = tmp_path / "a"
        sub.mkdir()
        doomed = sub / "doomed.py"
        shutil.copy(os.path.join(FIXTURES, "shape_key.py"), doomed)
        keeper = tmp_path / "shape_key.py"
        shutil.copy(os.path.join(FIXTURES, "shape_key.py"), keeper)
        baseline = str(tmp_path / "baseline.json")
        assert analyze_main([str(tmp_path), "--baseline", baseline,
                             "--update-baseline"]) == 0
        doomed.unlink()
        # update scoped AWAY from the deleted file's subtree: the
        # dead entry is pruned, the live out-of-scope one survives
        assert analyze_main([str(keeper), "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(baseline) as fin:
            paths = {e["path"] for e in json.load(fin)["findings"]}
        assert paths == {"shape_key.py"}

    def test_fingerprint_survives_line_drift(self, tmp_path):
        """An unrelated edit ABOVE a baselined finding must not
        resurrect it (fingerprints are line-number independent)."""
        target, baseline = self._seed(tmp_path)
        assert analyze_main([target, "--baseline", baseline,
                             "--update-baseline"]) == 0
        with open(target) as fin:
            source = fin.read()
        with open(target, "w") as fout:
            fout.write("# an unrelated comment pushing lines down\n"
                       "\n" + source)
        assert analyze_main([target, "--baseline", baseline]) == 0


class TestTreeGate:
    def test_package_clean_against_committed_baseline(self, capsys):
        """The acceptance criterion: the analyzer, default registry
        and committed baseline agree the package is clean."""
        package = os.path.join(REPO_ROOT, "veles_tpu")
        baseline = os.path.join(REPO_ROOT, "analyze_baseline.json")
        assert analyze_main([package, "--baseline", baseline]) == 0

    def test_default_paths_cover_the_package(self):
        """CLI with no paths analyzes the installed package tree."""
        from veles_tpu.analyze.engine import iter_python_files
        package = os.path.dirname(
            os.path.dirname(os.path.abspath(analyze_main.__code__
                                            .co_filename)))
        files = iter_python_files([package])
        names = {os.path.basename(p) for p in files}
        assert "serving.py" in names and "reqledger.py" in names
