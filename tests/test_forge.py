"""Forge model-hub tests (reference test_forge_client/server.py roles)."""

import io
import json
import os
import tarfile
import urllib.error

import numpy
import pytest

from veles_tpu.forge import ForgeClient, ForgeServer, package as pkg


def make_model_dir(tmp_path, name="toy-model", version="1.0"):
    d = tmp_path / name
    d.mkdir(parents=True)
    (d / "manifest.json").write_text(json.dumps({
        "name": name, "version": version,
        "short_description": "toy model",
        "workflow": "wf.py", "config": "cfg.py",
        "requires": ["numpy"]}))
    (d / "wf.py").write_text("""
import numpy
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(60, 6).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(6, 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 20, 40],
                            minibatch_size=20),
         learning_rate=0.5, max_epochs=2)
    main()
""")
    (d / "cfg.py").write_text("root.toy.x = 1\n")
    return str(d)


class TestPackage:
    def test_pack_unpack_roundtrip(self, tmp_path):
        d = make_model_dir(tmp_path)
        path, manifest = pkg.pack(d)
        assert manifest["name"] == "toy-model"
        with open(path, "rb") as fin:
            blob = fin.read()
        assert pkg.read_manifest(blob)["version"] == "1.0"
        dest = str(tmp_path / "out")
        pkg.unpack(blob, dest)
        assert sorted(os.listdir(dest)) == ["cfg.py", "manifest.json",
                                            "wf.py"]

    def test_manifest_validation(self):
        with pytest.raises(ValueError):
            pkg.validate_manifest({"workflow": "wf.py"})  # no name
        with pytest.raises(ValueError):
            pkg.validate_manifest({"name": "../evil", "workflow": "w"})
        with pytest.raises(ValueError):
            pkg.validate_manifest({"name": "x", "workflow": "w",
                                   "requires": ["numpy", "numpy>=1"]})
        # the version is a server path component AND a deploy/SLO
        # identity: reject traversal-shaped versions at pack time
        with pytest.raises(ValueError, match="version"):
            pkg.validate_manifest({"name": "x", "workflow": "w",
                                   "version": "../2.0"})

    def test_deploy_version_identity(self):
        """``deploy_version`` is the string rollouts/incidents stamp —
        name@version, server-default 1.0 when the manifest omits it."""
        manifest = {"name": "toy-model", "workflow": "w.py",
                    "version": "2.0"}
        assert pkg.deploy_version(manifest) == "toy-model@2.0"
        assert pkg.deploy_version({"name": "toy-model",
                                   "workflow": "w.py"}) == "toy-model@1.0"
        with pytest.raises(ValueError, match="version"):
            pkg.deploy_version({"name": "toy-model", "workflow": "w.py",
                                "version": "v 2"})

    def test_unpack_rejects_traversal(self, tmp_path):
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            manifest = json.dumps({"name": "evil",
                                   "workflow": "w.py"}).encode()
            info = tarfile.TarInfo("manifest.json")
            info.size = len(manifest)
            tar.addfile(info, io.BytesIO(manifest))
            payload = b"boom"
            info = tarfile.TarInfo("../escape.txt")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
        with pytest.raises(ValueError, match="unsafe"):
            pkg.unpack(buf.getvalue(), str(tmp_path / "dest"))
        assert not (tmp_path / "escape.txt").exists()


class TestForgeRoundtrip:
    @pytest.fixture
    def server(self, tmp_path):
        srv = ForgeServer(str(tmp_path / "store"), token="sekrit")
        srv.start()
        yield srv
        srv.stop()

    def client(self, server, token="sekrit"):
        return ForgeClient("http://127.0.0.1:%d" % server.port,
                           token=token)

    def test_upload_list_details_fetch_delete(self, server, tmp_path):
        client = self.client(server)
        result = client.upload(make_model_dir(tmp_path))
        assert result == {"name": "toy-model", "version": "1.0"}
        listing = client.list()
        assert [m["name"] for m in listing] == ["toy-model"]
        details = client.details("toy-model")
        assert details["latest"] == "1.0"
        assert details["versions"]["1.0"]["workflow"] == "wf.py"
        dest, manifest = client.fetch(
            "toy-model", dest=str(tmp_path / "fetched"))
        assert manifest["name"] == "toy-model"
        assert os.path.isfile(os.path.join(dest, "wf.py"))
        assert client.delete("toy-model") == {"deleted": True}
        assert client.list() == []

    def test_versioning(self, server, tmp_path):
        client = self.client(server)
        client.upload(make_model_dir(tmp_path, version="1.0"))
        d2 = make_model_dir(tmp_path / "v2", version="2.0")
        client.upload(d2)
        assert client.details("toy-model")["latest"] == "2.0"
        # duplicate version rejected
        with pytest.raises(urllib.error.HTTPError) as err:
            client.upload(make_model_dir(tmp_path / "dup", version="2.0"))
        assert err.value.code == 400
        # fetch a pinned old version
        dest, _ = client.fetch("toy-model", version="1.0",
                               dest=str(tmp_path / "old"))
        assert os.path.isdir(dest)

    def test_version_traversal_rejected(self, server, tmp_path):
        # regression: version strings are filesystem path components
        client = self.client(server)
        client.upload(make_model_dir(tmp_path))
        with pytest.raises(urllib.error.HTTPError) as err:
            client.fetch("toy-model", version="../../etc/passwd",
                         dest=str(tmp_path / "x"))
        assert err.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as err:
            client.upload(make_model_dir(tmp_path / "t2"),
                          version="../../../tmp/evil")
        assert err.value.code == 400

    def test_malformed_upload_gets_400(self, server):
        # regression: junk bytes must 400, not crash the handler
        import urllib.request
        req = urllib.request.Request(
            "http://127.0.0.1:%d/upload" % server.port,
            data=b"this is not a tarball",
            headers={"X-Forge-Token": "sekrit",
                     "Content-Type": "application/octet-stream"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400

    def test_oversized_register_gets_single_413(self, server):
        """The shared read_body cap applies to forge's JSON endpoints:
        an oversized /register body answers ONE 413 (not a 413 followed
        by a 400 on the same socket) before buffering anything; uploads
        keep their own much larger bound (UPLOAD_MAX_BODY)."""
        import socket

        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as sock:
            sock.sendall(b"POST /register HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Type: application/json\r\n"
                         b"Content-Length: 9999999999\r\n\r\n")
            sock.settimeout(10)
            chunks = []
            while True:
                data = sock.recv(4096)
                if not data:
                    break
                chunks.append(data)
        reply = b"".join(chunks).decode(errors="replace")
        assert "413" in reply.split("\r\n")[0]
        assert reply.count("HTTP/1.0") == 1  # exactly one response
        # the server keeps serving afterwards
        import urllib.request
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/service?query=list" % server.port,
                timeout=10) as resp:
            assert resp.status == 200

    def test_write_actions_need_token(self, server, tmp_path):
        anon = self.client(server, token=None)
        with pytest.raises(urllib.error.HTTPError) as err:
            anon.upload(make_model_dir(tmp_path))
        assert err.value.code == 403
        # reads are open
        assert anon.list() == []

    def test_history_and_diff(self, server, tmp_path):
        """VERDICT r4 #8: upload twice -> history lists both versions
        chronologically -> fetch either -> diff reports the manifest
        and file-content changes between them (the reference's git-tag
        history, forge_server.py:103-440)."""
        client = self.client(server)
        client.upload(make_model_dir(tmp_path, version="1.0"))
        d2 = make_model_dir(tmp_path / "v2", version="2.0")
        # change a file and add one in 2.0
        with open(os.path.join(d2, "cfg.py"), "w") as fout:
            fout.write("root.toy.x = 2\n")
        with open(os.path.join(d2, "README.md"), "w") as fout:
            fout.write("new in 2.0\n")
        client.upload(d2)

        hist = client.history("toy-model")
        assert hist["latest"] == "2.0"
        assert [h["version"] for h in hist["history"]] == ["1.0", "2.0"]
        assert all(h["uploaded"] for h in hist["history"])
        assert hist["history"][0]["uploaded_by"] == "master"

        for version in ("1.0", "2.0"):
            dest, manifest = client.fetch(
                "toy-model", version=version,
                dest=str(tmp_path / ("f" + version)))
            assert manifest["version"] == version

        delta = client.diff("toy-model", "1.0", "2.0")
        assert delta["files"]["added"] == ["README.md"]
        assert "cfg.py" in delta["files"]["changed"]
        assert "wf.py" not in delta["files"]["changed"]
        assert delta["manifest"]["changed"] == ["version"]
        # unknown version 404s
        with pytest.raises(urllib.error.HTTPError) as err:
            client.diff("toy-model", "1.0", "9.9")
        assert err.value.code == 404

    def test_register_issues_working_token(self, server, tmp_path):
        """Registration flow: /register issues a token that authorizes
        uploads, and the version records the registered email."""
        anon = self.client(server, token=None)
        with pytest.raises(urllib.error.HTTPError):
            anon.upload(make_model_dir(tmp_path / "denied"))
        issued = anon.register("dev@example.com")
        assert issued["email"] == "dev@example.com"
        registered = self.client(server, token=issued["token"])
        registered.upload(make_model_dir(tmp_path))
        hist = registered.history("toy-model")
        assert hist["history"][0]["uploaded_by"] == "dev@example.com"
        # garbage email rejected
        with pytest.raises(urllib.error.HTTPError) as err:
            anon.register("not-an-email")
        assert err.value.code == 400
        # a registered token must NOT authorize deletes — destructive
        # actions stay behind the master token
        with pytest.raises(urllib.error.HTTPError) as err:
            registered.delete("toy-model")
        assert err.value.code == 403
        # ...nor may ANOTHER registered identity add versions to a
        # model it doesn't own (hijacking "latest" of someone else's
        # model); the owner and the master token still can
        other = self.client(
            server, token=anon.register("eve@example.com")["token"])
        d2 = make_model_dir(tmp_path / "hijack", version="9.9")
        with pytest.raises(urllib.error.HTTPError) as err:
            other.upload(d2)
        assert err.value.code == 403
        registered.upload(make_model_dir(tmp_path / "own2",
                                         version="2.0"))
        self.client(server).upload(make_model_dir(tmp_path / "master3",
                                                  version="3.0"))
        assert self.client(server).delete("toy-model")["deleted"]

    def test_legacy_store_owner_seeded_from_history(self, server,
                                                    tmp_path):
        """A meta.json written before the ownership feature (no
        'owner' key) must seed the owner from the recorded uploader
        history — NOT let the next registered uploader claim it."""
        client = self.client(server)
        client.upload(make_model_dir(tmp_path))
        # simulate a pre-ownership store
        meta_path = os.path.join(server.root_dir, "toy-model",
                                 "meta.json")
        meta = json.load(open(meta_path))
        del meta["owner"]
        json.dump(meta, open(meta_path, "w"))

        anon = self.client(server, token=None)
        eve = self.client(
            server, token=anon.register("eve@example.com")["token"])
        with pytest.raises(urllib.error.HTTPError) as err:
            eve.upload(make_model_dir(tmp_path / "legacy-hijack",
                                      version="9.0"))
        assert err.value.code == 403
        # the historical uploader (the master token) still can
        client.upload(make_model_dir(tmp_path / "legit",
                                     version="2.0"))
        assert json.load(open(meta_path))["owner"] == "master"

    def test_fetched_model_runs(self, server, tmp_path):
        """The full hub story: upload, fetch, run the fetched workflow."""
        import veles_tpu
        client = self.client(server)
        client.upload(make_model_dir(tmp_path))
        dest, manifest = client.fetch("toy-model",
                                      dest=str(tmp_path / "run"))
        launcher = veles_tpu(os.path.join(dest, manifest["workflow"]),
                             os.path.join(dest, manifest["config"]))
        assert launcher.workflow.decision.epochs_done >= 2
        from veles_tpu.core.config import root
        assert root.toy.x == 1
