"""Tests for the veles_tpu.ops library (the Znicz-kernel equivalents)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import ops
from veles_tpu.ops import activations, losses
from veles_tpu.ops.gemm import matmul, pallas_matmul


class TestGemm:
    def test_matmul_matches_numpy(self):
        rng = numpy.random.RandomState(0)
        a = rng.rand(17, 33).astype(numpy.float32)
        b = rng.rand(33, 9).astype(numpy.float32)
        out = matmul(jnp.asarray(a), jnp.asarray(b), precision_level=2)
        numpy.testing.assert_allclose(out, a @ b, rtol=1e-5)

    def test_precision_levels_all_close(self):
        rng = numpy.random.RandomState(1)
        a = rng.rand(32, 64).astype(numpy.float32)
        b = rng.rand(64, 16).astype(numpy.float32)
        ref = a @ b
        for level in (0, 1, 2):
            out = matmul(jnp.asarray(a), jnp.asarray(b),
                         precision_level=level)
            # level 0 is bf16 passes; level 1 ~ bf16x3 ("Kahan" tier)
            tol = {0: 2e-2, 1: 1e-3, 2: 1e-5}[level]
            numpy.testing.assert_allclose(out, ref, rtol=tol)

    def test_pallas_matmul_interpret(self):
        """Blocked Pallas kernel vs numpy, incl. ragged shapes (padding)."""
        rng = numpy.random.RandomState(2)
        for m, k, n in ((128, 128, 128), (130, 70, 50)):
            a = rng.rand(m, k).astype(numpy.float32)
            b = rng.rand(k, n).astype(numpy.float32)
            out = pallas_matmul(jnp.asarray(a), jnp.asarray(b),
                                out_dtype=jnp.float32,
                                bm=64, bn=64, bk=64, interpret=True)
            numpy.testing.assert_allclose(out, a @ b, rtol=1e-4)


class TestAutotuneCacheHygiene:
    """ISSUE 5 satellite (VERDICT r5 #3): the autotune cache must
    reject physically impossible entries — the two-length slope
    estimator can go negative under tunnel jitter, and a persisted
    negative timing gated a product matmul on a measurement that never
    happened."""

    @pytest.fixture
    def cache_file(self, tmp_path, monkeypatch):
        from veles_tpu.core.config import root
        from veles_tpu.ops import gemm

        path = str(tmp_path / "pallas_tuning.json")
        monkeypatch.setattr(root.common.engine, "pallas_autotune_cache",
                            path, raising=False)
        monkeypatch.setattr(gemm, "_tuning_cache", None, raising=False)
        monkeypatch.setattr(gemm, "_insane_warned", False,
                            raising=False)
        return path

    def test_poisoned_rows_dropped_at_load_and_file_cleaned(
            self, cache_file, caplog):
        import json
        import logging

        from veles_tpu.ops import gemm

        # the literal r5 artifact shape: a negative xla_seconds beside
        # healthy rows
        poisoned = {
            "bfloat16:10": {"blocks": [256, 256, 512],
                            "seconds": 9.4e-05,
                            "xla_seconds": -0.000107,
                            "beats_xla": True},
            "bfloat16:11": {"blocks": [512, 512, 512],
                            "seconds": 2e-4, "xla_seconds": 3e-4,
                            "beats_xla": True},
            "int8:1024x4096": {"use_pallas": True, "block_n": 512,
                               "seconds": 0.0},
        }
        with open(cache_file, "w") as fout:
            json.dump(poisoned, fout)
        with caplog.at_level(logging.WARNING, logger="gemm.autotune"):
            cache = gemm._load_cache()
        assert set(cache) == {"bfloat16:11"}
        # the artifact on disk is cleaned too — it stops advertising
        # the impossible measurement
        assert set(json.load(open(cache_file))) == {"bfloat16:11"}
        warnings = [r for r in caplog.records
                    if "physically impossible" in r.getMessage()]
        assert len(warnings) == 1  # warn-once

    def test_dropped_bucket_retunes_as_default(self, cache_file):
        import json

        from veles_tpu.ops import gemm

        with open(cache_file, "w") as fout:
            json.dump({"bfloat16:10": {"blocks": [128, 128, 512],
                                       "seconds": -1.0,
                                       "beats_xla": True}}, fout)
        # the poisoned verdict must not engage the kernel...
        a = jnp.ones((1024, 1024), jnp.bfloat16)
        assert gemm._tuned_beats_xla(a, a) is False
        # ...and the block lookup falls back to the defaults
        assert gemm._tuned_blocks(1024, 1024, 1024, "bfloat16") \
            == gemm._DEFAULT_BLOCKS

    def test_persist_rejects_insane_rows(self, cache_file):
        import json

        from veles_tpu.ops import gemm

        gemm._persist_cache({
            "good": {"blocks": [1, 1, 1], "seconds": 1e-4,
                     "xla_seconds": 2e-4, "beats_xla": True},
            "negative": {"blocks": [1, 1, 1], "seconds": -1e-4},
            "zero": {"blocks": [1, 1, 1], "seconds": 0.0},
            "nan": {"blocks": [1, 1, 1], "seconds": float("nan")},
            "inf": {"blocks": [1, 1, 1], "xla_seconds": float("inf")},
            "not-a-dict": 7,
        })
        assert set(json.load(open(cache_file))) == {"good"}

    def test_sane_entry_predicate(self):
        from veles_tpu.ops import gemm

        assert gemm._sane_entry({"seconds": 1e-5, "xla_seconds": 2e-5})
        assert gemm._sane_entry({"blocks": [1, 2, 3]})  # no timings
        assert not gemm._sane_entry({"seconds": -1e-5})
        assert not gemm._sane_entry({"xla_seconds": 0})
        assert not gemm._sane_entry({"seconds": True})
        assert not gemm._sane_entry([1, 2])


class TestActivations:
    @pytest.mark.parametrize("name", list(activations.ACTIVATIONS))
    def test_deriv_matches_autodiff(self, name):
        fwd, deriv = activations.ACTIVATIONS[name]
        x = jnp.linspace(-2.0, 2.0, 41)
        if name == "strict_relu":
            x = x + 0.013  # avoid the kink
        y = fwd(x)
        expected = jax.vmap(jax.grad(lambda v: fwd(v)))(x)
        numpy.testing.assert_allclose(deriv(y), expected,
                                      rtol=1e-3, atol=1e-4)


class TestLosses:
    def test_softmax_xent_err_matches_autodiff(self):
        rng = numpy.random.RandomState(3)
        logits = jnp.asarray(rng.randn(8, 5).astype(numpy.float32))
        labels = jnp.asarray(rng.randint(0, 5, 8))
        err, loss, n_err, max_conf = losses.softmax_cross_entropy(
            logits, labels)
        grad = jax.grad(
            lambda lg: losses.softmax_cross_entropy(lg, labels)[1])(logits)
        numpy.testing.assert_allclose(err, grad, rtol=1e-4, atol=1e-6)
        assert 0 <= int(n_err) <= 8
        assert 0.0 < float(max_conf) <= 1.0

    def test_confusion_matrix(self):
        logits = jnp.asarray([[9.0, 0.0], [0.0, 9.0], [9.0, 0.0]])
        labels = jnp.asarray([0, 1, 1])
        cm = losses.confusion_matrix(logits, labels, 2)
        numpy.testing.assert_array_equal(cm, [[1, 0], [1, 1]])

    def test_mse_err_matches_autodiff(self):
        rng = numpy.random.RandomState(4)
        out = jnp.asarray(rng.randn(6, 3).astype(numpy.float32))
        tgt = jnp.asarray(rng.randn(6, 3).astype(numpy.float32))
        err, loss, max_err = losses.mse(out, tgt)
        grad = jax.grad(lambda o: losses.mse(o, tgt)[1])(out)
        numpy.testing.assert_allclose(err, grad, rtol=1e-4, atol=1e-6)


class TestDataOps:
    def test_gather_minibatch(self):
        data = jnp.arange(20.0).reshape(10, 2)
        labels = jnp.arange(10)
        idx = jnp.asarray([3, 7, 1])
        batch, lab = ops.gather_minibatch(data, idx, labels)
        numpy.testing.assert_array_equal(lab, [3, 7, 1])
        numpy.testing.assert_array_equal(batch[0], [6.0, 7.0])

    def test_gather_with_normalize(self):
        data = jnp.ones((4, 3))
        idx = jnp.asarray([0, 1])
        batch = ops.gather_minibatch(data, idx, scale=2.0, shift=-1.0)
        numpy.testing.assert_array_equal(batch, numpy.ones((2, 3)))

    def test_rng_reproducible(self):
        key = jax.random.PRNGKey(42)
        a = ops.uniform(key, (4, 4))
        b = ops.uniform(key, (4, 4))
        numpy.testing.assert_array_equal(a, b)
        assert float(jnp.min(a)) >= -1.0 and float(jnp.max(a)) <= 1.0

    def test_reduce(self):
        x = jnp.arange(12.0).reshape(3, 4)
        numpy.testing.assert_array_equal(ops.reduce_sum(x, 0),
                                         [12.0, 15.0, 18.0, 21.0])
        assert float(ops.reduce_max(x, None)) == 11.0


class TestDenseEpilogue:
    """Fused matmul+bias+activation kernel (the Pallas product consumer,
    VERDICT r2 #7) — forward parity in interpret mode, and the custom
    VJP against jax.grad of the XLA path."""

    def test_pallas_dense_interpret_matches_xla(self):
        import numpy
        from veles_tpu.ops.gemm import pallas_dense

        rng = numpy.random.RandomState(0)
        x = rng.randn(96, 80).astype(numpy.float32)
        w = rng.randn(80, 64).astype(numpy.float32)
        b = rng.randn(64).astype(numpy.float32)
        got = pallas_dense(jnp.asarray(x), jnp.asarray(w),
                           jnp.asarray(b), activation="tanh",
                           bm=32, bn=32, bk=16, interpret=True)
        # the library "tanh" is Znicz's scaled 1.7159*tanh(0.6666x)
        from veles_tpu.ops import activations as act_lib
        want = act_lib.ACTIVATIONS["tanh"][0](jnp.asarray(x @ w + b))
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(want),
                                      rtol=2e-5, atol=2e-5)

    def test_dense_layer_custom_vjp_matches_xla_grads(self, monkeypatch):
        import numpy
        from veles_tpu.core.config import root
        from veles_tpu.ops import gemm

        rng = numpy.random.RandomState(1)
        x = jnp.asarray(rng.randn(64, 48).astype(numpy.float32))
        w = jnp.asarray(rng.randn(48, 32).astype(numpy.float32))
        b = jnp.asarray(rng.randn(32).astype(numpy.float32))

        # force the pallas path through interpret-mode (CPU) by
        # monkeypatching eligibility + the kernel call
        monkeypatch.setattr(gemm, "_pallas_eligible",
                            lambda a, bb: True)
        real = gemm.pallas_dense

        def interp(a, bb, bias, activation="linear", **kw):
            kw.update(bm=32, bn=32, bk=16, interpret=True)
            return real(a, bb, bias, activation=activation, **kw)

        monkeypatch.setattr(gemm, "pallas_dense", interp)
        real_mm = gemm.pallas_matmul

        def interp_mm(a, bb, **kw):
            # the custom bwd's matmuls hit the patched eligibility too
            kw.update(bm=32, bn=32, bk=16, interpret=True)
            return real_mm(a, bb, **kw)

        monkeypatch.setattr(gemm, "pallas_matmul", interp_mm)
        monkeypatch.setattr(root.common.engine, "precision_level", 1,
                            raising=False)
        gemm._dense_with_vjp.cache_clear()

        def loss_pallas(x, w, b):
            return jnp.sum(gemm.dense_layer(x, w, b, activation="tanh",
                                            use_pallas=True) ** 2)

        def loss_xla(x, w, b):
            return jnp.sum(gemm.dense_layer(x, w, b, activation="tanh",
                                            use_pallas=False) ** 2)

        got = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
        want = jax.grad(loss_xla, argnums=(0, 1, 2))(x, w, b)
        for g, e in zip(got, want):
            numpy.testing.assert_allclose(numpy.asarray(g),
                                          numpy.asarray(e),
                                          rtol=2e-4, atol=2e-4)
        gemm._dense_with_vjp.cache_clear()
