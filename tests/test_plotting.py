"""Tests for the plotting tier (reference test_plotting_units.py role —
golden-image fixtures replaced by render-to-file assertions)."""

import os

import numpy
import pytest

from veles_tpu.core.config import root
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.plotting import (AccumulatingPlotter, AutoHistogramPlotter,
                                GraphicsServer, Histogram, ImagePlotter,
                                ImmediatePlotter, MatrixPlotter,
                                MultiHistogram, SlaveStats, TableMaxMin)

pytest.importorskip("matplotlib")


@pytest.fixture
def server(tmp_path, monkeypatch):
    monkeypatch.setattr(root.common.disable, "plotting", False,
                        raising=False)
    srv = GraphicsServer(backend="file", directory=str(tmp_path))
    yield srv
    srv.shutdown()


def render(server, plotter):
    server.enqueue(plotter)
    server.flush()
    path = server.rendered.get(plotter.name)
    assert path and os.path.exists(path) and os.path.getsize(path) > 0
    return path


class TestAccumulatingPlotter:
    def test_accumulates_and_renders(self, server):
        p = AccumulatingPlotter(DummyWorkflow(), name="errors")
        p.graphics_server = server
        for v in (10.0, 8.0, 5.0, 4.0, 3.5):
            p.input = v
            p.fill()
        assert p.values == [10.0, 8.0, 5.0, 4.0, 3.5]
        render(server, p)

    def test_input_field_and_offset(self, server):
        p = AccumulatingPlotter(DummyWorkflow(), name="field")

        class Source:
            epoch_metrics = numpy.array([1.0, 2.0, 3.0])

        p.input = Source()
        p.input_field = "epoch_metrics"
        p.input_offset = 1
        p.fill()
        assert p.values == [2.0]

    def test_throttling(self, server):
        p = AccumulatingPlotter(DummyWorkflow(), name="throttled",
                                redraw_threshold=3600)
        p.graphics_server = server
        p.input = 1.0
        p.run()  # first run renders
        p.input = 2.0
        p.run()  # within threshold: fill only
        server.flush()
        assert p.values == [1.0, 2.0]
        assert "throttled" in server.rendered

    def test_disabled_by_config(self, tmp_path, monkeypatch):
        monkeypatch.setattr(root.common.disable, "plotting", True,
                            raising=False)
        srv = GraphicsServer(backend="file", directory=str(tmp_path))
        p = AccumulatingPlotter(DummyWorkflow(), name="off")
        p.graphics_server = srv
        p.input = 1.0
        p.run()
        srv.flush()
        assert srv.rendered == {}


class TestOtherPlotters:
    def test_matrix(self, server):
        p = MatrixPlotter(DummyWorkflow(), name="confusion")
        p.graphics_server = server
        p.input = numpy.array([[5, 1], [0, 6]])
        p.reversed_labels_mapping = ["cat", "dog"]
        render(server, p)

    def test_image(self, server):
        p = ImagePlotter(DummyWorkflow(), name="imgs")
        p.graphics_server = server
        p.inputs = [numpy.random.rand(8, 8), numpy.random.rand(8, 8, 3)]
        render(server, p)

    def test_immediate(self, server):
        p = ImmediatePlotter(DummyWorkflow(), name="imm")
        p.graphics_server = server
        p.inputs = [numpy.arange(10.0), numpy.arange(10.0) ** 2]
        render(server, p)

    def test_histogram(self, server):
        p = Histogram(DummyWorkflow(), name="hist")
        p.graphics_server = server
        p.x = numpy.arange(10.0)
        p.y = numpy.arange(10.0) * 2
        render(server, p)

    def test_auto_histogram(self, server):
        p = AutoHistogramPlotter(DummyWorkflow(), name="autohist")
        p.graphics_server = server
        p.input = numpy.random.randn(100)
        render(server, p)

    def test_multi_histogram(self, server):
        p = MultiHistogram(DummyWorkflow(), name="multihist",
                           hist_number=4)
        p.graphics_server = server
        p.input = numpy.random.randn(6, 20)
        render(server, p)

    def test_table_max_min(self, server):
        p = TableMaxMin(DummyWorkflow(), name="maxmin")
        p.graphics_server = server
        p.inputs = [numpy.arange(5.0), numpy.ones(3)]
        p.input_names = ["weights", "bias"]
        render(server, p)

    def test_slave_stats(self, server):
        p = SlaveStats(DummyWorkflow(), name="slaves")
        p.graphics_server = server

        class FakeServer:
            @staticmethod
            def fleet_status():
                return {"slaves": [
                    {"id": "s1", "mid": "m", "power": 2.0, "jobs_done": 7}]}

        p.fleet_server = FakeServer()
        render(server, p)


class TestListeners:
    def test_listener_fires(self, server):
        seen = []
        server.add_listener(lambda name, path: seen.append((name, path)))
        p = AccumulatingPlotter(DummyWorkflow(), name="listened")
        p.graphics_server = server
        p.input = 1.0
        p.fill()
        render(server, p)
        assert seen and seen[0][0] == "listened"

    def test_snapshot_is_picklable(self):
        import pickle
        p = AccumulatingPlotter(DummyWorkflow(), name="x")
        p.input = 3.0
        p.fill()
        blob = pickle.dumps((type(p), p.name, p.snapshot()))
        cls, name, snap = pickle.loads(blob)
        assert snap["values"] == [3.0]
