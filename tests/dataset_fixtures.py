"""Shared dataset fixtures (NOT a test module).

THE canonical digits split used by the fusion/pod/fleet parity tests and
the two-process pod child lives in ``veles_tpu.parity`` (the accuracy
harness consumes the same bytes on the product path) — this module just
re-exports it for the tests. Several assertions (validation error counts
out of 297, bit-for-bit child-vs-parent comparisons) depend on every
consumer using the exact same split — change it THERE only.
"""

from veles_tpu.parity import (  # noqa: F401  (re-export)
    DIGITS_CLASS_LENGTHS, digits_dataset)
