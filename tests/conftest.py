"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (see README / driver
contract). Must set env before jax initializes."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import tempfile  # noqa: E402

# keep test cache/seed artifacts out of the user's home
_tmp = tempfile.mkdtemp(prefix="veles_tpu_test_")
os.environ.setdefault("VELES_TPU_CACHE", _tmp)

from veles_tpu.core.config import root  # noqa: E402

root.common.dirs.cache = os.path.join(_tmp, "cache")
root.common.dirs.snapshots = os.path.join(_tmp, "snapshots")
root.common.dirs.events = os.path.join(_tmp, "events")
root.common.disable.plotting = True
