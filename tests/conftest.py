"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (see README / driver
contract). Must set env before jax initializes."""

import os

# the axon sitecustomize force-registers the TPU backend and overrides
# JAX_PLATFORMS from the environment, so pin the platform via jax.config
# (wins as long as no backend has initialized yet)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import tempfile  # noqa: E402

# keep ALL framework cache/state artifacts out of the user's home:
# config.py derives every dir (incl. the pallas autotune cache) from
# VELES_TPU_HOME, which must be set before veles_tpu imports
_tmp = tempfile.mkdtemp(prefix="veles_tpu_test_")
os.environ["VELES_TPU_HOME"] = _tmp

from veles_tpu.core.config import root  # noqa: E402

root.common.disable.plotting = True
# the metric flight recorder (observe/history.py) is default-on at a
# 1 s cadence wherever /metrics mounts; each sample runs EVERY
# registry collector, including the per-device live-buffer memory
# walk, for the remainder of the session — at test scale that bleeds
# tier-1's timeout margin. Keep the default-on wiring exercised but
# sample lazily; tests that need a fast cadence build their own
# MetricHistory (tests/test_history.py does).
root.common.observe.history = "interval_s=30"
