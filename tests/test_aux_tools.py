"""Tests for the streaming loader, device benchmark, compare_snapshots
script, and the --visualize/--dump-unit-attributes CLI additions."""

import json
import threading

import numpy
import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow


class TestStreamLoader:
    def test_push_and_serve(self):
        from veles_tpu.loader.stream import StreamFeeder, StreamLoader

        loader = StreamLoader(DummyWorkflow(), sample_shape=(4,),
                              minibatch_size=8, secret="s3")
        loader.initialize()
        feeder = StreamFeeder("127.0.0.1:%d" % loader.port, secret="s3")
        feeder.push(numpy.arange(4.0), numpy.arange(4.0) * 2)
        loader.run()
        assert loader.minibatch_valid_size == 2
        got = numpy.asarray(loader.minibatch_data.mem)
        numpy.testing.assert_array_equal(got[0], [0, 1, 2, 3])
        numpy.testing.assert_array_equal(got[1], [0, 2, 4, 6])
        mask = numpy.asarray(loader.sample_mask.mem)
        assert mask.sum() == 2
        feeder.end()
        loader.run()
        assert bool(loader.complete)
        loader.stop()

    def test_wrong_secret_rejected(self):
        from veles_tpu.loader.stream import StreamFeeder, StreamLoader

        loader = StreamLoader(DummyWorkflow(), sample_shape=(2,),
                              minibatch_size=4, secret="right")
        loader.initialize()
        feeder = StreamFeeder("127.0.0.1:%d" % loader.port,
                              secret="wrong")
        with pytest.raises(Exception):
            feeder.push(numpy.zeros(2))
        assert loader._queue_.qsize() == 0
        loader.stop()


class TestDeviceBenchmark:
    def test_returns_positive_power(self):
        from veles_tpu.ops.benchmark import device_benchmark

        power = device_benchmark(size=128, depth=2, iters=2)
        assert power > 0
        # deterministic enough to be a balancing weight: two runs within
        # an order of magnitude
        power2 = device_benchmark(size=128, depth=2, iters=2)
        assert 0.1 < power / power2 < 10


class TestCompareSnapshots:
    def test_identical_and_diverged(self, tmp_path):
        from veles_tpu.models.mlp import MLPWorkflow
        from veles_tpu.scripts.compare_snapshots import compare
        from veles_tpu.snapshotter import Snapshotter, SnapshotterToFile

        rng = numpy.random.RandomState(0)
        X = rng.rand(60, 6).astype(numpy.float32)
        y = (X[:, 0] > 0.5).astype(numpy.int32)

        def build(epochs):
            wf = MLPWorkflow(
                DummyLauncher(), layers=(6, 2),
                loader_kwargs=dict(data=X, labels=y,
                                   class_lengths=[0, 20, 40],
                                   minibatch_size=20),
                learning_rate=0.5, max_epochs=epochs, name="cmp")
            wf.initialize()
            wf.run()
            return wf

        wf_a = build(1)
        wf_b = build(3)
        report = compare(wf_a, wf_a)
        assert report["identical"]
        report = compare(wf_a, wf_b)
        assert not report["identical"]
        assert any("weights" in k for k in report["array_diffs"])

    def test_cli(self, tmp_path):
        from veles_tpu.dummy import DummyWorkflow as DW  # noqa: F401
        from veles_tpu.models.mlp import MLPWorkflow
        from veles_tpu.scripts.compare_snapshots import main
        from veles_tpu.snapshotter import Snapshotter

        rng = numpy.random.RandomState(0)
        X = rng.rand(40, 4).astype(numpy.float32)
        y = (X[:, 0] > 0.5).astype(numpy.int32)
        wf = MLPWorkflow(
            DummyLauncher(), layers=(4, 2),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 10, 30],
                               minibatch_size=10),
            learning_rate=0.5, max_epochs=1, name="cli-cmp")
        snap = Snapshotter(wf, prefix="cmp", directory=str(tmp_path),
                           interval=1, time_interval=0)
        wf.initialize()
        snap.initialize()
        wf.run()
        snap.run()
        path = snap.destination
        assert main([path, path]) == 0  # identical with itself


class TestFrontendGenerator:
    def test_generates_form(self, tmp_path):
        from veles_tpu.scripts.generate_frontend import generate

        path = generate(str(tmp_path / "frontend.html"))
        html = open(path).read()
        assert "--listen" in html and "--optimize" in html
        assert "command-line composer" in html
        assert 'data-flag="--seed"' in html


class TestStandardPlotters:
    def test_add_standard_plotters(self, tmp_path, monkeypatch):
        pytest.importorskip("matplotlib")
        from veles_tpu.core.config import root
        from veles_tpu.models.standard import StandardWorkflow
        from veles_tpu.plotting import GraphicsServer

        monkeypatch.setattr(root.common.disable, "plotting", False,
                            raising=False)
        rng = numpy.random.RandomState(0)
        X = rng.rand(60, 6).astype(numpy.float32)
        y = (X[:, 0] > 0.5).astype(numpy.int32)
        wf = StandardWorkflow(
            DummyLauncher(),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 20, 40],
                               minibatch_size=20),
            layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                    {"type": "softmax", "output_sample_shape": 2}],
            learning_rate=0.5, fused=False,
            decision_kwargs=dict(max_epochs=3), name="plotted")
        plotters = wf.add_standard_plotters(weights=True)
        assert len(plotters) == 3
        gs = GraphicsServer(backend="file", directory=str(tmp_path))
        for p in plotters:
            p.graphics_server = gs
            p.redraw_threshold = 0
        wf.initialize()
        wf.run()
        gs.flush()
        rendered = gs.rendered
        gs.shutdown()
        assert any("validation errors" in name for name in rendered)
        assert any("confusion" in name for name in rendered)
        # regression: the decision freezes per-epoch snapshots BEFORE
        # resetting its accumulators — the error plotter must record the
        # REAL count, and the confusion must cover the WHOLE valid sweep
        err = plotters[0]
        assert err.values, "no plotter firings recorded"
        assert all(float(v).is_integer() and v >= 0 for v in err.values)
        cm = wf.decision.last_epoch_confusion
        assert cm is not None and int(cm.sum()) == 20  # all VALID rows


class TestCLIIntrospection:
    @pytest.fixture
    def wf_file(self, tmp_path):
        p = tmp_path / "wf.py"
        p.write_text("""
import numpy
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(40, 4).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(4, 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 10, 30],
                            minibatch_size=10),
         learning_rate=0.5, max_epochs=1)
    main()
""")
        return str(p)

    def test_visualize_writes_dot(self, tmp_path, wf_file):
        from veles_tpu.__main__ import main

        dot = str(tmp_path / "graph.dot")
        assert main([wf_file, "-", "--dry-run", "init",
                     "--visualize", dot]) == 0
        text = open(dot).read()
        assert text.startswith("digraph")
        assert "FullBatchLoader" in text

    def test_dump_unit_attributes(self, capsys, wf_file):
        from veles_tpu.__main__ import main

        assert main([wf_file, "-", "--dry-run", "init",
                     "--dump-unit-attributes"]) == 0
        out = capsys.readouterr().out
        lines = [json.loads(l) for l in out.splitlines()
                 if l.startswith("{")]
        names = {entry["unit"] for entry in lines}
        assert any("Loader" in entry["type"] for entry in lines)
        assert len(names) >= 5


class TestBBoxer:
    """The bounding-box labeling tool (reference scripts/bboxer.py):
    discovery, selection save/load, path containment."""

    @pytest.fixture
    def served(self, tmp_path):
        import numpy
        from PIL import Image
        from veles_tpu.scripts.bboxer import serve

        (tmp_path / "sub").mkdir()
        for rel in ("a.png", "sub/b.png"):
            arr = numpy.zeros((10, 10, 3), numpy.uint8)
            Image.fromarray(arr).save(str(tmp_path / rel))
        (tmp_path / "notes.txt").write_text("not an image")
        server = serve(str(tmp_path), port=0, block=False)
        yield "http://127.0.0.1:%d" % server.server_port, tmp_path
        server.shutdown()

    def test_list_save_roundtrip(self, served):
        import json
        import urllib.request

        base, tree = served
        with urllib.request.urlopen(base + "/list") as resp:
            items = json.loads(resp.read())
        assert [i["path"] for i in items] == ["a.png", "sub/b.png"]
        assert not any(i["labeled"] for i in items)
        boxes = [{"x": 1, "y": 2, "width": 3, "height": 4,
                  "label": "cat"}]
        req = urllib.request.Request(
            base + "/selections",
            data=json.dumps({"path": "sub/b.png",
                             "bboxes": boxes}).encode(),
            method="POST")
        with urllib.request.urlopen(req) as resp:
            assert json.loads(resp.read())["saved"] == "sub/b.png"
        sidecar = tree / "sub" / "b.png.json"
        assert json.loads(sidecar.read_text())["bboxes"] == boxes
        with urllib.request.urlopen(base + "/selections/sub/b.png") as r:
            assert json.loads(r.read())["bboxes"] == boxes
        with urllib.request.urlopen(base + "/list") as resp:
            items = {i["path"]: i["labeled"]
                     for i in json.loads(resp.read())}
        assert items == {"a.png": False, "sub/b.png": True}

    def test_path_containment(self, served):
        import json
        import urllib.error
        import urllib.request

        base, tree = served
        (tree.parent / "outside.png").write_bytes(b"x")
        req = urllib.request.Request(
            base + "/selections",
            data=json.dumps({"path": "../outside.png",
                             "bboxes": []}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req)
        assert err.value.code == 404
        assert not (tree.parent / "outside.png.json").exists()


class TestManhole:
    """core/manhole.py — the --manhole live debug console."""

    def _drain_until(self, sock, marker, limit=65536):
        data = b""
        while marker not in data and len(data) < limit:
            chunk = sock.recv(4096)
            if not chunk:
                break
            data += chunk
        return data

    def test_attach_eval_detach(self, tmp_path):
        import socket

        from veles_tpu.core.manhole import Manhole

        sentinel = {"value": 41}
        path = str(tmp_path / "mh.sock")
        manhole = Manhole(namespace={"sentinel": sentinel},
                          path=path).start()
        try:
            client = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            client.settimeout(10)
            client.connect(path)
            self._drain_until(client, b">>> ")
            # expression result printing + LIVE mutation of process state
            client.sendall(b"print(sentinel['value'] + 1)\n")
            out = self._drain_until(client, b">>> ")
            assert b"42" in out
            client.sendall(b"sentinel['value'] = 100\n")
            self._drain_until(client, b">>> ")
            # multi-line block compiles incrementally (the "... " prompt)
            client.sendall(b"for i in range(2):\n")
            out = self._drain_until(client, b"... ")
            client.sendall(b"    print('x%d' % i)\n\n")
            out = self._drain_until(client, b">>> ")
            assert b"x0" in out and b"x1" in out
            # errors are reported, connection survives
            client.sendall(b"1/0\n")
            out = self._drain_until(client, b">>> ")
            assert b"ZeroDivisionError" in out
            client.sendall(b"exit\n")
            out = self._drain_until(client, b"detached")
            assert b"detached" in out
            client.close()
            assert sentinel["value"] == 100  # the process really mutated
            # a SECOND connection is served after the first detaches
            client2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            client2.settimeout(10)
            client2.connect(path)
            self._drain_until(client2, b">>> ")
            client2.sendall(b"print(sentinel['value'])\n")
            assert b"100" in self._drain_until(client2, b">>> ")
            client2.close()
        finally:
            manhole.stop()

    def test_socket_permissions(self, tmp_path):
        import os
        import stat

        from veles_tpu.core.manhole import Manhole

        path = str(tmp_path / "mh.sock")
        manhole = Manhole(path=path).start()
        try:
            mode = stat.S_IMODE(os.stat(path).st_mode)
            assert mode == 0o600
        finally:
            manhole.stop()
        assert not os.path.exists(path)

    def test_restart_after_stop(self, tmp_path):
        """stop() then start() must serve again (regression: _closing
        stayed True, the fresh serve loop exited instantly and clients
        hung on the kernel backlog forever)."""
        import socket

        from veles_tpu.core.manhole import Manhole

        path = str(tmp_path / "mh.sock")
        manhole = Manhole(namespace={"x": 7}, path=path)
        manhole.start()
        manhole.stop()
        manhole.start()
        try:
            client = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            client.settimeout(10)
            client.connect(path)
            self._drain_until(client, b">>> ")
            client.sendall(b"print(x * 6)\n")
            assert b"42" in self._drain_until(client, b">>> ")
            client.close()
        finally:
            manhole.stop()


class TestPluginScan:
    """veles_tpu.scan_plugins(): the reference's ``veles.__plugins__``
    namespace scan (``__init__.py:191-215``) in its TPU-era form —
    installed ``veles_tpu_*`` modules are imported and their units
    register through the same metaclass registry as in-tree units."""

    def test_scans_and_registers(self, tmp_path, monkeypatch):
        import sys
        import veles_tpu
        from veles_tpu.core.registry import UnitRegistry

        plugin = tmp_path / "veles_tpu_demo_plugin.py"
        plugin.write_text(
            "from veles_tpu.core.units import TrivialUnit\n"
            "class DemoPluginUnit(TrivialUnit):\n"
            "    pass\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        monkeypatch.setattr(veles_tpu, "__plugins__", None)
        plugins = veles_tpu.scan_plugins()
        names = [p.__name__ for p in plugins]
        assert "veles_tpu_demo_plugin" in names
        assert any(cls.__name__ == "DemoPluginUnit"
                   for cls in UnitRegistry.units)
        # cached: a second call returns the same list without rescanning
        assert veles_tpu.scan_plugins() is plugins
        sys.modules.pop("veles_tpu_demo_plugin", None)
        monkeypatch.setattr(veles_tpu, "__plugins__", None)


class TestYarnDiscovery:
    """yarn:// node specs resolve through the ResourceManager REST API
    (reference YARN discovery, launcher.py:887-906)."""

    def _serve(self, payload, status=200):
        import http.server
        import threading

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                assert self.path.startswith("/ws/v1/cluster/nodes")
                body = payload.encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server

    def test_discovers_running_nodes(self):
        import json as jsonlib

        from veles_tpu.launcher import discover_yarn_nodes

        payload = jsonlib.dumps({"nodes": {"node": [
            {"nodeHostName": "worker-1", "state": "RUNNING"},
            {"nodeHostName": "worker-2", "state": "RUNNING"},
            {"rack": "/default", "state": "RUNNING"},  # no hostname
        ]}})
        server = self._serve(payload)
        try:
            hosts = discover_yarn_nodes(
                "127.0.0.1:%d" % server.server_address[1])
            assert hosts == ["worker-1", "worker-2"]
        finally:
            server.shutdown()

    def test_expand_mixes_plain_and_yarn_and_survives_failure(self):
        import json as jsonlib

        from veles_tpu.launcher import Launcher

        launcher = Launcher()
        payload = jsonlib.dumps({"nodes": {"node": [
            {"nodeHostName": "w1"}]}})
        server = self._serve(payload)
        try:
            specs = ["hostA",
                     "yarn://127.0.0.1:%d" % server.server_address[1],
                     "yarn://127.0.0.1:1"]  # refused: must skip, not die
            assert launcher._expand_node_specs(specs) == ["hostA", "w1"]
        finally:
            server.shutdown()
