"""Paged KV pool + shared-prefix serving (docs/paged_kv.md): the page
pool engine must stream bit-identical tokens to the dense slot engine
and to greedy `generate()` on CPU — bf16/f32 and int8-KV tiers,
shared-prefix admissions with mid-stream divergence, mid-flight joins,
cancel returning pages, LRU eviction under pool pressure, and the
8-device CPU mesh (pool pages sharded over heads) — plus the dispatch
economy / zero-recompile-storm bound for the paged programs and the
pool-aware admission gate's no-deadlock invariant. `make paged` runs
this file standalone, mirroring `make mesh`."""

import json
import threading
import urllib.error
import urllib.request

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.observe.xla_stats import get_compile_tracker
from veles_tpu.parallel.kv_pool import PagePool, pages_for
from veles_tpu.parallel.mesh import build_mesh
from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.serving import ContinuousDecoder, ServingHealth

pytestmark = pytest.mark.paged

PS = 8  # page size: tiny so short prompts span several pages


def post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class TestPagePool:
    """Host-side page table: free list, refcounts, reservations, the
    release-rate window — the invariants the serving gate relies on."""

    def test_alloc_release_refcounts(self):
        pool = PagePool(8, PS)
        assert pool.capacity == 7  # page 0 is scratch
        pages = pool.alloc(3)
        assert len(pages) == 3 and 0 not in pages
        assert (pool.used_pages, pool.free_pages) == (3, 4)
        pool.retain(pages)  # a second holder
        pool.release(pages)
        assert pool.used_pages == 3  # still held once
        pool.release(pages)
        assert (pool.used_pages, pool.free_pages) == (0, 7)

    def test_alloc_refuses_past_capacity(self):
        pool = PagePool(4, PS)
        assert pool.alloc(3) is not None
        assert pool.alloc(1) is None  # empty free list, nothing to evict

    def test_reservations_bound_by_capacity(self):
        pool = PagePool(6, PS)
        assert pool.try_reserve(3)
        assert pool.try_reserve(2)
        assert not pool.try_reserve(1)  # 3 + 2 + 1 > capacity 5
        pool.unreserve(2)
        assert pool.try_reserve(1)

    def test_retry_after_priced_from_release_rate(self):
        pool = PagePool(8, PS)
        # cold window: the fallback, floored at 1 s
        assert pool.retry_after(4, fallback=2.5) == 2.5
        pages = pool.alloc(4)
        pool.release(pages)
        # 4 pages released just now -> a high observed rate -> the
        # clamp floor, never the fallback constant
        assert pool.retry_after(4) == 1.0

    def test_rejects_degenerate_shapes(self):
        with pytest.raises(ValueError, match="scratch"):
            PagePool(1, PS)
        with pytest.raises(ValueError, match="page_size"):
            PagePool(4, 0)

    def test_boundary_keys_match_prefix_key(self):
        """The O(T) incremental boundary hash must produce digests
        byte-identical to _prefix_key of each whole-page prefix — the
        cache keys written by insert() and probed by lookup()."""
        from veles_tpu.parallel.kv_pool import (_boundary_keys,
                                                _prefix_key)

        tokens = numpy.arange(5 * PS + 3, dtype=numpy.int32)
        keys = _boundary_keys(tokens, PS, 5)
        assert keys == [_prefix_key(tokens[:k * PS])
                        for k in range(1, 6)]

    def test_hit_counter_monotone_under_rollback(self):
        """A lookup rolled back by unlookup (tail-page alloc failed)
        must not move the hits counter: it is exported as a Prometheus
        counter, and a decrement reads as a counter reset — rate()
        would book the whole value as spurious hits."""
        pool = PagePool(8, PS)
        tokens = numpy.arange(PS, dtype=numpy.int32)
        pages = pool.alloc(1)
        pool.insert(tokens, pages, {"k": jnp.zeros((1, 8, PS, 1, 1)),
                                    "v": jnp.zeros((1, 8, PS, 1, 1))})
        longer = numpy.arange(2 * PS, dtype=numpy.int32)
        entry, shared = pool.lookup(longer)
        assert entry is not None and shared == PS
        pool.unlookup(entry)  # rollback: no pages for the tail
        assert pool.cache.counters["hits"] == 0
        entry, shared = pool.lookup(longer)
        pool.book_hit()       # the retried admission commits once
        assert pool.cache.counters["hits"] == 1


class TestCacheRestore:
    """restore_entries adopts a previous decoder's prefix cache into a
    fresh pool — including one SMALLER than the cached page set."""

    def _seeded_pool(self, entries, pool_pages=32):
        pool = PagePool(pool_pages, PS)
        state = {"k": jnp.zeros((1, pool_pages, PS, 1, 1)),
                 "v": jnp.zeros((1, pool_pages, PS, 1, 1))}
        for i in range(entries):
            tokens = numpy.full(PS, i, numpy.int32)
            pages = pool.alloc(1)
            pool.insert(tokens, pages, state)
            pool.release(pages)  # the "slot" retires; cache ref stays
        # the rebuild prelude: shadows are captured from the dying
        # pool's state, never on the admission path
        pool.capture_shadows(state)
        return pool

    def test_restore_into_smaller_pool_keeps_newest(self):
        """A fresh pool too small for every cached page drops OLDEST
        entries (never a crash, never a full wipe) and restores the
        survivors — alloc()'s own LRU eviction cannot free old-pool
        page ids, so the drop loop must size against the free list."""
        old = self._seeded_pool(entries=5)
        assert len(old.cache) == 5
        fresh = PagePool(4, PS, cache=old.cache)  # room for 3 pages
        restored = []
        state = fresh.restore_entries(
            {"k": jnp.zeros((1, 4, PS, 1, 1)),
             "v": jnp.zeros((1, 4, PS, 1, 1))},
            lambda st, ids, vals: restored.append(list(ids)) or st)
        assert len(fresh.cache) == 3  # newest three survive
        # rebuild-pressure drops book as evictions (the exported
        # counter must move when entries vanish)
        assert fresh.cache.counters["evictions"] == 2
        kept = {int(e["tokens"][0])
                for e in fresh.cache.entries.values()}
        assert kept == {2, 3, 4}
        assert restored and len(restored[0]) == 3
        assert fresh.used_pages == 3
        # the survivors are live: an exact re-lookup hits
        entry, shared = fresh.lookup(numpy.full(PS, 4, numpy.int32))
        assert entry is None or shared == PS  # logits-less full match
        # and a pool with no room at all ends up empty, not crashed
        tiny = PagePool(2, PS, cache=self._seeded_pool(3).cache)
        tiny.alloc(1)  # occupy the only page
        state = tiny.restore_entries(
            {"k": jnp.zeros((1, 2, PS, 1, 1)),
             "v": jnp.zeros((1, 2, PS, 1, 1))},
            lambda st, ids, vals: st)
        assert len(tiny.cache) == 0


class TestPagedBitIdentity:
    """The acceptance composite: every paged admission family and the
    paged step must reproduce the dense engine's tokens exactly."""

    @pytest.fixture(scope="class")
    def model(self):
        rng = numpy.random.RandomState(0)
        heads, embed, vocab = 4, 16, 11
        params = init_transformer_params(rng, 2, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        return params, table, heads, vocab

    @pytest.mark.parametrize("quantize", [None, "int8-kv"])
    def test_composite_matches_dense_and_generate(self, model,
                                                  quantize):
        """Staggered submissions joining mid-flight through the
        pipelined chunked drain: paged streams equal the dense
        engine's AND single-request generate() — both KV tiers."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(1)
        prompts = [rng.randint(0, vocab, n) for n in (5, 3, 16, 4, 9)]
        decs = []
        for paged in (False, True):
            dec = ContinuousDecoder(params, table, heads, slots=2,
                                    max_len=32, n_tokens=6,
                                    quantize=quantize, paged=paged,
                                    page_size=PS)
            pending = list(prompts)
            for _ in range(2):
                dec.submit(pending.pop(0))
            dec.drain_pipelined(
                4, admit=lambda dec=dec, pending=pending:
                    pending and dec.submit(pending.pop(0)))
            decs.append(dec)
        dense, paged_dec = decs
        assert dense.results == paged_dec.results
        for rid, prompt in enumerate(prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=6, max_len=32,
                               quantize=quantize)
            assert paged_dec.results[rid] == \
                numpy.asarray(want)[0].tolist(), \
                "quantize=%s request %d diverged" % (quantize, rid)
        # every retired slot returned its pages (minus what the
        # prefix cache intentionally keeps resident)
        held = {page
                for entry in paged_dec.pool.cache.entries.values()
                for page in entry["pages"]}
        assert paged_dec.pool.snapshot()["pages_used"] == len(held)
        assert not paged_dec._slot_pages

    def test_shared_prefix_tail_hit_and_divergence(self, model):
        """The prefix-reuse families: a page-aligned system prompt is
        prefilled once; later admissions sharing it run tail-only
        prefills (divergent suffixes — copy-on-write degenerating to
        fresh-page allocation) or, for the exact page-aligned prompt,
        a zero-prefill control-row hit — all bit-identical to
        generate()."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(2)
        system = rng.randint(0, vocab, 2 * PS)  # two whole pages
        tails = [rng.randint(0, vocab, n) for n in (5, 3, 9)]
        prompts = [system.copy()]  # cold: publishes pages AND logits
        prompts += [numpy.concatenate([system, t]) for t in tails]
        prompts.append(system.copy())  # exact page-aligned re-admit

        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=6, paged=True,
                                page_size=PS)
        first = dec.submit(prompts[0], 6)
        dec.run_until_drained()
        shared_pages = dec.pool.cache.entries[next(iter(
            dec.pool.cache.entries))]["pages"]
        rest = [dec.submit(p, 6) for p in prompts[1:]]
        dec.run_until_drained(chunk=4)
        for rid, prompt in zip([first] + rest, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=6, max_len=64)
            assert dec.results[rid] == \
                numpy.asarray(want)[0].tolist(), \
                "request %d diverged from generate()" % rid
        # the divergent tails really did reuse the pooled prefix
        # (tail prefills + one full hit, never a second cold prefill
        # of the system pages)
        assert dec.dispatch_counts["admit_tail"] >= 1
        assert dec.dispatch_counts["admit_hit"] >= 1
        snap = dec.pool.snapshot()
        assert snap["prefix_hits"] >= 3
        # shared pages stayed where the cold admission put them: the
        # cache entry still names the SAME page ids (sharing never
        # re-allocates or mutates the prefix — docs/paged_kv.md)
        assert dec.pool.cache.entries[next(iter(
            dec.pool.cache.entries))]["pages"] == shared_pages

    def test_int8_kv_reuses_exact_prompts_only(self, model):
        """The int8-KV pool stores ROUNDED K/V, so partial-prefix
        tails would not be bit-identical — the tier must take
        exact-prompt hits only, and those must match generate()."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(3)
        system = rng.randint(0, vocab, 2 * PS)
        longer = numpy.concatenate([system, rng.randint(0, vocab, 4)])
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=5, paged=True,
                                page_size=PS, quantize="int8-kv")
        a = dec.submit(system, 5)
        dec.run_until_drained()
        b = dec.submit(longer, 5)   # shares the prefix: must go COLD
        c = dec.submit(system, 5)   # exact prompt: the hit path
        dec.run_until_drained(chunk=4)
        assert dec.dispatch_counts["admit_tail"] == 0
        assert dec.dispatch_counts["admit_hit"] == 1
        for rid, prompt in ((a, system), (b, longer), (c, system)):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=5, max_len=64,
                               quantize="int8-kv")
            assert dec.results[rid] == \
                numpy.asarray(want)[0].tolist()

    def test_cancel_returns_pages(self, model):
        """cancel() — the path deadline expiry also routes through —
        must return the slot's pages to the pool and feed the
        release-rate window that prices Retry-After."""
        params, table, heads, vocab = model
        rng = numpy.random.RandomState(4)
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=16, paged=True,
                                page_size=PS)
        rid = dec.submit(rng.randint(0, vocab, 12), 16)
        dec.step()
        dec.step()
        held = dict(dec._slot_pages)
        assert held  # the live slot maps real pages
        before = dec.pool.free_pages
        dec.cancel(rid)
        assert not dec._slot_pages
        assert dec.pool.free_pages > before
        assert dec.pool.release_rate() > 0
        # the freed slot admits a fresh request cleanly
        rid2 = dec.submit(rng.randint(0, vocab, 5), 3)
        dec.run_until_drained()
        assert len(dec.results[rid2]) == 3

    def test_eviction_under_pool_pressure(self, model):
        """A pool too small for every cached prefix must evict LRU
        refcount-0 entries to admit new work — and the streams stay
        exact throughout."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(5)
        # budget 4 + chunkless steps: each prompt needs
        # pages_for(16 + 4) = 3 pages; 7-page capacity holds at most
        # two cached 2-page prefixes -> wave three forces eviction
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=24, n_tokens=4, paged=True,
                                page_size=PS, pool_pages=8)
        prompts = [rng.randint(0, vocab, 2 * PS) for _ in range(4)]
        rids = [dec.submit(p, 4) for p in prompts]
        dec.run_until_drained()
        snap = dec.pool.snapshot()
        assert snap["prefix_evictions"] >= 1
        for rid, prompt in zip(rids, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=4, max_len=24)
            assert dec.results[rid] == \
                numpy.asarray(want)[0].tolist()

    def test_repeated_extended_prompt_converges_to_hit(self, model):
        """A tail admission publishes the EXTENDED prompt too (prefix
        pages + tail whole pages hold exactly a cold prefill's bytes),
        so the SECOND admission of system+tail is a zero-prefill hit —
        not a tail re-prefill forever — and streams stay exact."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(12)
        system = rng.randint(0, vocab, 2 * PS)
        extended = numpy.concatenate(
            [system, rng.randint(0, vocab, PS)])
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=6, paged=True,
                                page_size=PS)
        dec.submit(system, 6)
        dec.run_until_drained()
        r1 = dec.submit(extended, 6)   # tail family
        dec.run_until_drained()
        assert dec.dispatch_counts.get("admit_tail", 0) == 1
        hits = dec.dispatch_counts.get("admit_hit", 0)
        r2 = dec.submit(extended, 6)   # published by the tail admit
        dec.run_until_drained()
        assert dec.dispatch_counts.get("admit_hit", 0) == hits + 1
        assert dec.dispatch_counts.get("admit_tail", 0) == 1
        want, _ = generate(params, table, jnp.asarray(extended)[None],
                           heads, n_tokens=6, max_len=64)
        assert dec.results[r1] == dec.results[r2] == \
            numpy.asarray(want)[0].tolist()

    def test_default_pool_serves_slab_parity_workload(self, model):
        """The default pool must serve every workload the dense slab
        serves: slots running ``prompt + budget == max_len`` under the
        lag-1 pipelined drain overshoot ``max_len`` by up to two
        chunks per slot (lanes advance past retirement), which the
        slab absorbs with a clamped in-place write. A pool sized
        without the ``2 * n_tokens`` slack raises 'kv page pool
        exhausted mid-decode' here."""
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(11)
        prompts = [rng.randint(0, vocab, 32 - 6) for _ in range(2)]
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=6, paged=True,
                                page_size=PS)
        rids = [dec.submit(p, 6) for p in prompts]
        dec.drain_pipelined(4)  # never raises: the slack covers it
        for rid, prompt in zip(rids, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=6, max_len=32)
            assert dec.results[rid] == \
                numpy.asarray(want)[0].tolist()

    def test_page_size_must_match_span_tile_on_tpu(self, model,
                                                   monkeypatch):
        """--serve-page-size not a multiple of SLOT_SPAN_TILE fails at
        construction on TPU backends, naming the knob — not as an
        opaque XLA tiling error in the first dispatch. (CPU keeps
        arbitrary page sizes: the whole suite runs PS=8.)"""
        params, table, heads, _ = model
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        with pytest.raises(ValueError, match="serve-page-size"):
            ContinuousDecoder(params, table, heads, slots=2,
                              max_len=256, paged=True, page_size=100)
        # aligned sizes construct fine under the same backend
        ContinuousDecoder(params, table, heads, slots=2, max_len=256,
                          paged=True, page_size=128)

    def test_dispatch_economy_and_zero_recompile_storm(self, model):
        """One admit dispatch per (kind, shape) group, one chunk
        dispatch per slot_step_many — and driving SIX same-shape waves
        compiles each paged program at most twice (layout + one jit
        fastpath committedness variant) with ZERO recompile storms:
        the (bucket, group, pages bucket) keying really bounds the
        compile set."""
        params, table, heads, vocab = model
        waves = 6
        tracker = get_compile_tracker()
        was_enabled = tracker.enabled
        tracker.reset()
        tracker.enabled = True
        try:
            rng = numpy.random.RandomState(6)
            dec = ContinuousDecoder(params, table, heads, slots=2,
                                    max_len=32, n_tokens=4,
                                    paged=True, page_size=PS)
            for _ in range(waves):
                for _ in range(2):
                    dec.submit(rng.randint(0, vocab, 6))
                dec.run_until_drained(chunk=4)
            snap = tracker.snapshot()
        finally:
            tracker.reset()
            tracker.enabled = was_enabled
        assert sum(snap["storms"].values()) == 0
        assert dec.dispatch_counts["admit"] <= waves  # one per wave
        assert dec.dispatch_counts["admit_requests"] == 2 * waves
        for program in ("paged.admit", "paged.dispatch"):
            compiles = snap["compiles"].get(program, 0)
            hits = snap["hits"].get(program, 0)
            assert compiles <= 2, \
                "%s retraced %d times over %d same-shape waves" % (
                    program, compiles, waves)
            assert hits >= waves - 2, \
                "%s only hit %d times" % (program, hits)


class TestPagedMesh:
    """PR-6 composition: pool pages shard over HEADS under the serve
    mesh exactly like the dense slab."""

    @pytest.fixture(scope="class")
    def model8(self):
        rng = numpy.random.RandomState(0)
        heads, embed, vocab = 8, 32, 16
        params = init_transformer_params(rng, 2, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        return params, table, heads, vocab

    def test_mesh_paged_streams_and_stay_sharded(self, model8):
        """The 8-device CPU mesh: the paged engine streams the exact
        single-chip dense tokens (mid-flight joins, prefix hit
        included) and the pool leaves STAY sharded over heads across
        admit/step/chunk round trips."""
        params, table, heads, vocab = model8
        mesh = build_mesh(devices=jax.devices()[:8], data=1, model=8)
        rng = numpy.random.RandomState(7)
        prompts = [rng.randint(0, vocab, n) for n in (2 * PS, 19, 5)]

        ref = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=5)
        got = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=5, paged=True,
                                page_size=PS, mesh=mesh)
        for dec in (ref, got):
            pending = [p for p in prompts]
            for _ in range(2):
                dec.submit(pending.pop(0))
            dec.drain_pipelined(
                4, admit=lambda dec=dec, pending=pending:
                    pending and dec.submit(pending.pop(0)))
        assert ref.results == got.results
        assert not got.state["k"].sharding.is_fully_replicated
        # the page-aligned prompt re-admits as a zero-prefill hit
        # under the mesh, still bit-identical
        rid = got.submit(prompts[0])
        got.run_until_drained()
        assert got.results[rid] == ref.results[0]
        assert got.dispatch_counts["admit_hit"] == 1
        assert not got.state["k"].sharding.is_fully_replicated


class TestPoolAwareAdmission:
    """Satellite: ServingHealth.try_admit extended to KV page
    pressure — a full pool 429s with an honest Retry-After, and an
    ADMITTED request can never deadlock waiting for pages it was
    promised (its worst case is reserved under the admission lock)."""

    @pytest.fixture(scope="class")
    def model(self):
        rng = numpy.random.RandomState(0)
        heads, embed, vocab = 4, 16, 11
        params = init_transformer_params(rng, 2, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        return params, table, heads, vocab

    def test_try_admit_pool_verdict(self):
        health = ServingHealth(name="t")
        health.set_ready(True)
        # gate admits: pages reserved, request counted in
        assert health.try_admit(None, pool_gate=lambda: None) is None
        # gate refuses: the ("pool", retry_after) verdict, counted as
        # a rejection, inflight unchanged
        verdict = health.try_admit(None, pool_gate=lambda: 7.5)
        assert verdict == ("pool", 7.5)
        snap = health.snapshot()
        assert snap["inflight"] == 1
        assert snap["counters"]["rejected"] == 1

    def test_worst_case_pages_covers_tail_family(self, model):
        """The reservation must dominate TAIL holdings too: prefix
        whole pages + a re-bucketed tail can exceed the cold prompt
        bucket when power-of-two rounding and the max_len clamp
        interact — under-reserving would let _ensure_tail_pages
        exhaust the pool mid-decode, the exact failure the gate
        promises is unreachable."""
        params, table, heads, vocab = model
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=770, n_tokens=1, paged=True,
                                page_size=128)
        # 769-token prompt, 512-token page-aligned cached prefix:
        # holdings = 4 prefix pages + pages_for(bucket(257)=512) = 8,
        # while the cold bound is only ceil((770+1+16)/128) = 7
        assert dec.worst_case_pages(769, 1, chunk=8) >= 8
        # short prompts keep the tight cold bound (no tail split fits)
        assert dec.worst_case_pages(3, 12, chunk=2) == \
            pages_for(min(16, 770) + 12 + 4, 128)

    def test_pool_gate_runs_after_queue_bound(self):
        """A queue-full rejection must NOT reserve pages: the gate
        only runs for requests that are otherwise admitted."""
        health = ServingHealth(name="t2")
        health.set_ready(True)
        ran = []
        assert health.try_admit(1, pool_gate=lambda: None) is None
        verdict = health.try_admit(
            1, pool_gate=lambda: ran.append(1) or None)
        assert verdict == "full"
        assert not ran

    def test_http_pool_full_429_with_priced_retry_after(self, model):
        """A pool sized for one in-flight request: the second
        concurrent POST must 429 with a Retry-After header (pool
        verdict), never hang — and the pool snapshot rides /healthz
        through the attached health."""
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model
        # one request's worst case: the 16-token minimum prompt
        # bucket, the 12-token budget, the lag-1 pipeline's two
        # chunks of slack — exactly what worst_case_pages reserves
        need = pages_for(16 + 12 + 2 * 2, PS)
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=12, chunk=2, port=0, paged=True,
                          page_size=PS, pool_pages=need + 1)
        # wedge the driver so the first request stays in flight while
        # the second arrives (reservations held until resolve)
        gate = threading.Event()
        orig = api.decoder.dispatch_chunk

        def slow_chunk(chunk):
            gate.wait(timeout=30)
            return orig(chunk)
        api.decoder.dispatch_chunk = slow_chunk
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            first = {}
            t = threading.Thread(target=lambda: first.update(
                post(url, {"tokens": [1, 2, 3], "n_tokens": 12},
                     timeout=60)))
            t.start()
            # wait until the first request's reservation is booked
            deadline = threading.Event()
            for _ in range(200):
                if api.decoder.pool.snapshot()["reserved_pages"]:
                    break
                deadline.wait(0.02)
            assert api.decoder.pool.snapshot()["reserved_pages"] == need
            with pytest.raises(urllib.error.HTTPError) as err:
                post(url, {"tokens": [4, 5], "n_tokens": 12},
                     timeout=30)
            assert err.value.code == 429
            assert "pool" in err.value.read().decode()
            assert int(err.value.headers["Retry-After"]) >= 1
            snap = api.health.snapshot()
            assert snap["pool"]["pages_total"] == need
            gate.set()
            t.join(timeout=60)
            assert len(first["tokens"]) == 12
            # resolution released the reservation
            assert api.decoder.pool.snapshot()["reserved_pages"] == 0
        finally:
            gate.set()
            api.stop()

    def test_admitted_requests_never_deadlock(self, model):
        """The no-deadlock invariant under pressure: many concurrent
        POSTs against a small pool — every response is either a full
        token stream or an immediate 429, and every admitted request
        COMPLETES (nothing blocks waiting for pages it was promised,
        because admission reserved its worst case up front)."""
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model
        need = pages_for(16 + 6 + 2 * 2, PS)  # min bucket 16
        api = GenerateAPI(params, table, heads, slots=4, max_len=32,
                          n_tokens=6, chunk=2, port=0, paged=True,
                          page_size=PS, pool_pages=2 * need + 1)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            rng = numpy.random.RandomState(8)
            outcomes = {}

            def call(i, prompt):
                try:
                    outcomes[i] = post(
                        url, {"tokens": prompt, "n_tokens": 6},
                        timeout=60)["tokens"]
                except urllib.error.HTTPError as err:
                    outcomes[i] = err.code
            threads = [
                threading.Thread(target=call, args=(
                    i, rng.randint(0, vocab, 5).tolist()))
                for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=90)
            assert not any(t.is_alive() for t in threads), \
                "an admitted request deadlocked waiting for pages"
            done = [o for o in outcomes.values() if isinstance(o, list)]
            shed = [o for o in outcomes.values() if o == 429]
            assert len(done) + len(shed) == 8
            assert done  # progress was made under pressure
            assert all(len(tokens) == 6 for tokens in done)
            assert api.decoder.pool.snapshot()["reserved_pages"] == 0
        finally:
            api.stop()

    def test_breaker_rebuild_preserves_prefix_cache(self, model):
        """The breaker's rebuild path must carry the prefix cache into
        the fresh decoder's pool by page copy — the cached system
        prompt admits as a HIT after the trip, never a re-prefill, and
        its stream still equals generate()."""
        import time

        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(9)
        system = rng.randint(0, vocab, 2 * PS).tolist()
        api = GenerateAPI(params, table, heads, slots=2, max_len=64,
                          n_tokens=4, chunk=2, port=0, paged=True,
                          page_size=PS, rebuild_backoff=0.02)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            first = post(url, {"tokens": system}, timeout=60)
            old_decoder = api.decoder
            assert old_decoder.pool.snapshot()["prefix_entries"] >= 1

            def boom(*a, **k):
                raise RuntimeError("injected device failure")
            api.decoder.dispatch_chunk = boom
            with pytest.raises(urllib.error.HTTPError) as err:
                post(url, {"tokens": [1, 2, 3]}, timeout=30)
            assert err.value.code == 503
            deadline = time.time() + 30
            while not api.health.ready and time.time() < deadline:
                time.sleep(0.02)
            assert api.health.ready, api.health.snapshot()
            assert api.decoder is not old_decoder
            # the fresh pool adopted the cache: same entries, restored
            # pages, ZERO cold admissions for the cached prompt (the
            # rebuild's probe decode books one cold admit of its own —
            # the delta across the re-admission must stay zero)
            cold_before = api.decoder.dispatch_counts["admit"]
            again = post(url, {"tokens": system}, timeout=60)
            assert again["tokens"] == first["tokens"]
            want, _ = generate(params, table,
                               jnp.asarray(system)[None], heads,
                               n_tokens=4, max_len=64)
            assert again["tokens"] == numpy.asarray(want)[0].tolist()
            assert api.decoder.dispatch_counts["admit_hit"] == 1
            assert api.decoder.dispatch_counts["admit"] == cold_before
            # /healthz mirrors the FRESH pool
            assert api.health.snapshot()["pool"]["prefix_hits"] >= 1
        finally:
            api.stop()


class TestPagedObservability:
    """Satellite: pool gauges + prefix counters on /metrics, page
    occupancy and hit rate in the web-status serving column."""

    def test_pool_gauges_on_metrics(self):
        from veles_tpu.observe.metrics import (MetricsRegistry,
                                               publish_kv_pool)

        pool = PagePool(8, PS)
        pool.alloc(3)
        pool.cache.counters.update(hits=2, misses=1, evictions=1)
        registry = MetricsRegistry(enabled=True)
        publish_kv_pool(registry, pool)
        text = registry.expose()
        assert "veles_kv_pages_used 3" in text
        assert "veles_kv_pages_free 4" in text
        assert "veles_kv_page_size %d" % PS in text
        assert "veles_prefix_cache_hits_total 2" in text
        assert "veles_prefix_cache_misses_total 1" in text
        assert "veles_prefix_cache_evictions_total 1" in text

    def test_web_status_serving_column_shows_pool(self):
        from veles_tpu.web_status import format_serving_health

        line = format_serving_health({
            "ready": True, "breaker": "closed", "inflight": 0,
            "counters": {}, "latency_ms": {},
            "pool": {"pages_used": 3, "pages_total": 7,
                     "prefix_hit_rate": 0.5}})
        assert "pages 3/7" in line
        assert "prefix hit 50%" in line
