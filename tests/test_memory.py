"""Tests for veles_tpu.memory.Array (mirrors reference test_memory.py)."""

import pickle

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.memory import Array, Watcher, assert_addr


def test_empty_array():
    a = Array()
    assert not a
    assert a.shape is None
    assert a.mem is None
    assert len(a) == 0


def test_reset_and_mem():
    a = Array(numpy.arange(6, dtype=numpy.float32).reshape(2, 3))
    assert a
    assert a.shape == (2, 3)
    assert a.size == 6
    assert a.sample_size == 3
    numpy.testing.assert_array_equal(a.mem, numpy.arange(6).reshape(2, 3))


def test_device_round_trip():
    a = Array(numpy.ones((4, 4), numpy.float32))
    assert not a.on_device
    a.to_device()
    assert a.on_device
    numpy.testing.assert_array_equal(a.mem, numpy.ones((4, 4)))
    a.to_host()
    assert not a.on_device


def test_map_write_realizes_host():
    a = Array(jnp.zeros((2, 2)))
    assert a.on_device
    a.map_write()
    assert not a.on_device
    a.mem[0, 0] = 5.0
    assert a.mem[0, 0] == 5.0


def test_watcher_accounting():
    # the Watcher is process-global: collect stragglers from other tests
    # first and assert DELTAS so gc of unrelated Arrays can't skew us
    import gc
    gc.collect()
    Watcher.reset()
    base = Watcher.mem_in_use()
    a = Array(jnp.zeros((8, 8), jnp.float32))
    assert Watcher.mem_in_use() - base == 8 * 8 * 4
    a.reset(None)
    assert Watcher.mem_in_use() - base == 0
    assert Watcher.max_mem_in_use() - base >= 8 * 8 * 4


def test_pickle_device_array_becomes_numpy():
    a = Array(jnp.arange(4.0))
    b = pickle.loads(pickle.dumps(a))
    assert isinstance(b.data, numpy.ndarray)
    numpy.testing.assert_array_equal(b.mem, [0, 1, 2, 3])


def test_shallow_pickle_stores_metadata_only():
    a = Array(numpy.zeros((3, 5), numpy.float32), shallow_pickle=True)
    b = pickle.loads(pickle.dumps(a))
    assert b.data is None
    assert b.__dict__["_shape_hint"] == (3, 5)


def test_assert_addr():
    x = jnp.ones(3)
    a, b = Array(x), Array(x)
    assert_addr(a, b)
    c = Array(jnp.ones(3))
    with pytest.raises(ValueError):
        assert_addr(a, c)


def test_array_from_array():
    a = Array(numpy.ones(3))
    b = Array(a)
    numpy.testing.assert_array_equal(b.mem, [1, 1, 1])
