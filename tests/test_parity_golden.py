"""Golden accuracy-parity harness (VERDICT r2 #3).

Offline it always runs: the three reference topology families on the
real 8x8 UCI digits with ABSOLUTE error bounds (3.0% / 0.7% / 0.7% —
the convnets at sub-anchor error via the shift1 augmentation), writing
PARITY.json. On a host with real MNIST idx files, set
``VELES_TPU_MNIST_DIR`` and the full reference-anchor run
(≤2.2% / ≤1.0% / ≤0.9%) executes instead.
"""

import json
import os

import pytest

from veles_tpu import parity


@pytest.mark.slow
def test_parity_synthetic_mlp(tmp_path, monkeypatch):
    """The MLP family must beat its absolute bound on digits — the
    quick anchor (the conv families run in the full harness below).
    Synthetic mode is pinned: without the delenv, a host with
    VELES_TPU_MNIST_DIR exported would silently train the digits
    topologies on real MNIST (run_parity falls back to the env var)."""
    monkeypatch.delenv("VELES_TPU_MNIST_DIR", raising=False)
    out = str(tmp_path / "PARITY.json")
    verdict = parity.run_parity(
        mnist_dir=None, out=out,
        topologies=parity.DIGITS_TOPOLOGIES[:1])
    assert verdict["mode"] == "real-digits-8x8"
    written = json.load(open(out))
    assert written["results"][0]["name"] == "digits784"
    assert written["results"][0]["pass"], written
    assert written["pass"]


@pytest.mark.slow
def test_parity_full_harness(tmp_path):
    """The complete harness: all three topology families produce a
    verdict artifact; real MNIST when VELES_TPU_MNIST_DIR is set,
    the digits analogue otherwise. Every family must pass its bound."""
    mnist_dir = os.environ.get("VELES_TPU_MNIST_DIR") or None
    out = str(tmp_path / "PARITY.json")
    verdict = parity.run_parity(mnist_dir=mnist_dir, out=out)
    assert os.path.exists(out)
    assert len(verdict["results"]) == 3
    for entry in verdict["results"]:
        assert entry["pass"], entry
    assert verdict["pass"]
