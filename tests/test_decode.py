"""KV-cache decoding: the scan-decode path must match recomputing the
full causal forward over the growing sequence, token for token."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.parallel.decode import (decode_step, generate,
                                       init_kv_cache, prefill)
from veles_tpu.parallel.transformer_step import (_forward,
                                                 init_transformer_params)

HEADS, EMBED, BLOCKS, VOCAB = 4, 16, 2, 11


@pytest.fixture(scope="module")
def model():
    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, BLOCKS, EMBED, HEADS, VOCAB)
    embed_table = jnp.asarray(
        rng.randn(VOCAB, EMBED).astype(numpy.float32) * 0.3)
    return params, embed_table


def test_prefill_matches_full_forward(model):
    params, table = model
    rng = numpy.random.RandomState(1)
    toks = rng.randint(0, VOCAB, (2, 5))
    x = table[jnp.asarray(toks)]
    logits, cache = prefill(params, x, HEADS,
                            init_kv_cache(BLOCKS, 2, 12, HEADS,
                                          EMBED // HEADS))
    full = _forward(params, x, HEADS, 1, "ulysses")
    numpy.testing.assert_allclose(numpy.asarray(logits),
                                  numpy.asarray(full[:, -1]),
                                  rtol=2e-4, atol=2e-5)
    assert int(cache["length"]) == 5


def test_decode_steps_match_growing_forward(model):
    """Each decoded step's logits == the full forward's last position on
    the concatenated sequence (the KV cache changes the computation
    order, not the math)."""
    params, table = model
    rng = numpy.random.RandomState(2)
    toks = rng.randint(0, VOCAB, (3, 4))
    x = table[jnp.asarray(toks)]
    logits, cache = prefill(params, x, HEADS,
                            init_kv_cache(BLOCKS, 3, 10, HEADS,
                                          EMBED // HEADS))
    seq = x
    for _ in range(5):
        tok = jnp.argmax(logits, axis=-1)
        x_tok = table[tok][:, None, :]
        logits, cache = decode_step(params, x_tok, HEADS, cache)
        seq = jnp.concatenate([seq, x_tok], axis=1)
        full = _forward(params, seq, HEADS, 1, "ulysses")
        numpy.testing.assert_allclose(numpy.asarray(logits),
                                      numpy.asarray(full[:, -1]),
                                      rtol=2e-4, atol=2e-5)


def test_generate_greedy_matches_reference_loop(model):
    """generate() (one jitted scan, donated cache) produces the same
    token ids as the naive recompute-everything greedy loop."""
    params, table = model
    rng = numpy.random.RandomState(3)
    prompt = jnp.asarray(rng.randint(0, VOCAB, (2, 6)))
    toks, cache = generate(params, table, prompt, HEADS, n_tokens=7)
    assert toks.shape == (2, 7)
    assert int(cache["length"]) == 13

    seq = table[prompt]
    ref = []
    for _ in range(7):
        logits = _forward(params, seq, HEADS, 1, "ulysses")[:, -1]
        tok = jnp.argmax(logits, axis=-1)
        ref.append(tok)
        seq = jnp.concatenate([seq, table[tok][:, None, :]], axis=1)
    numpy.testing.assert_array_equal(
        numpy.asarray(toks), numpy.asarray(jnp.stack(ref, axis=1)))


def test_generate_rejects_overflow(model):
    params, table = model
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError):
        generate(params, table, prompt, HEADS, n_tokens=5, max_len=8)


def test_generate_bf16_matches_bf16_reference(model):
    """The bf16 serving configuration (params/table/cache all bf16 —
    the bench's decode_bfloat16 keys): scan-decode tokens equal the
    bf16 full-recompute loop."""
    params, table = model
    bf16 = jnp.bfloat16
    params16 = jax.tree.map(lambda a: a.astype(bf16), params)
    table16 = table.astype(bf16)
    rng = numpy.random.RandomState(4)
    prompt = jnp.asarray(rng.randint(0, VOCAB, (2, 5)))

    toks, _ = generate(params16, table16, prompt, HEADS, n_tokens=6)

    seq = table16[prompt]
    ref = []
    for _ in range(6):
        logits = _forward(params16, seq, HEADS, 1, "ulysses")[:, -1]
        tok = jnp.argmax(logits, axis=-1)
        ref.append(tok)
        seq = jnp.concatenate([seq, table16[tok][:, None, :]], axis=1)
    numpy.testing.assert_array_equal(
        numpy.asarray(toks), numpy.asarray(jnp.stack(ref, axis=1)))


def test_generate_sampling_reproducible_and_topk_bounded(model):
    """temperature sampling: same key => same tokens; different key =>
    (almost surely) different; top_k=1 degenerates to greedy."""
    params, table = model
    rng = numpy.random.RandomState(5)
    prompt = jnp.asarray(rng.randint(0, VOCAB, (2, 5)))
    key = jax.random.key(42)

    t1, _ = generate(params, table, prompt, HEADS, n_tokens=8,
                     temperature=1.0, key=key)
    t2, _ = generate(params, table, prompt, HEADS, n_tokens=8,
                     temperature=1.0, key=key)
    numpy.testing.assert_array_equal(numpy.asarray(t1),
                                     numpy.asarray(t2))
    t3, _ = generate(params, table, prompt, HEADS, n_tokens=8,
                     temperature=1.0, key=jax.random.key(43))
    assert not numpy.array_equal(numpy.asarray(t1), numpy.asarray(t3))

    greedy, _ = generate(params, table, prompt, HEADS, n_tokens=8)
    top1, _ = generate(params, table, prompt, HEADS, n_tokens=8,
                       temperature=0.7, top_k=1, key=key)
    numpy.testing.assert_array_equal(numpy.asarray(greedy),
                                     numpy.asarray(top1))


def test_slot_step_span_tiling_is_inert(model):
    """The tiled slot attention contract: any span covering the
    longest live sequence (+1 for the appended token) produces
    bit-identical state updates and emitted tokens vs attending the
    whole max_len lane — masked positions contribute exact zeros."""
    from veles_tpu.parallel.decode import (init_slot_state, slot_admit,
                                           slot_step)

    params, table = model
    rng = numpy.random.RandomState(7)
    state = init_slot_state(BLOCKS, 2, 24, HEADS, EMBED // HEADS, VOCAB)
    for slot, n in enumerate((5, 3)):
        prompt = jnp.asarray(rng.randint(0, VOCAB, (1, n)))
        state = slot_admit(params, table, HEADS, state,
                           jnp.int32(slot), table[prompt])
    active = jnp.asarray([True, True])
    full_state = jax.tree.map(jnp.copy, state)
    for span in (8, 16, 24):
        tiled, tok_tiled = slot_step(params, table, HEADS,
                                     jax.tree.map(jnp.copy, state),
                                     active, span=span)
        full, tok_full = slot_step(params, table, HEADS,
                                   jax.tree.map(jnp.copy, full_state),
                                   active)
        numpy.testing.assert_array_equal(numpy.asarray(tok_tiled),
                                         numpy.asarray(tok_full))
        numpy.testing.assert_array_equal(
            numpy.asarray(tiled["logits"]), numpy.asarray(full["logits"]))


def test_slot_admit_many_matches_single_admits(model):
    """One batched same-bucket admission dispatch produces the same
    slot state as admitting each prompt alone — including duplicate
    padding rows (the host pads groups to powers of two)."""
    from veles_tpu.parallel.decode import (init_slot_state, slot_admit,
                                           slot_admit_many)

    params, table = model
    rng = numpy.random.RandomState(8)
    lens = (5, 7, 3)
    prompts = [rng.randint(0, VOCAB, n) for n in lens]
    bucket = 8
    padded = numpy.zeros((4, bucket), numpy.int32)  # padded to 4 rows
    for j, p in enumerate(prompts + [prompts[-1]]):  # duplicate row
        padded[j, :len(p)] = p
    keys = jax.random.split(jax.random.key(3), 4)
    ref = init_slot_state(BLOCKS, 4, 24, HEADS, EMBED // HEADS, VOCAB)
    for slot, (p, n) in enumerate(zip(prompts, lens)):
        row = numpy.zeros(bucket, numpy.int32)
        row[:n] = p
        ref = slot_admit(params, table, HEADS, ref, jnp.int32(slot),
                         table[jnp.asarray(row)][None],
                         req_key=keys[slot], length=jnp.int32(n))
    batched = init_slot_state(BLOCKS, 4, 24, HEADS, EMBED // HEADS,
                              VOCAB)
    batched = slot_admit_many(
        params, table, HEADS, batched,
        jnp.asarray([0, 1, 2, 2], jnp.int32),
        table[jnp.asarray(padded)],
        keys.at[3].set(keys[2]),
        jnp.asarray(list(lens) + [lens[-1]], jnp.int32))
    numpy.testing.assert_array_equal(numpy.asarray(ref["lengths"]),
                                     numpy.asarray(batched["lengths"]))
    numpy.testing.assert_array_equal(numpy.asarray(ref["logits"]),
                                     numpy.asarray(batched["logits"]))
    # the written K/V slabs agree wherever a real prompt lives
    for slot, n in enumerate(lens):
        numpy.testing.assert_array_equal(
            numpy.asarray(ref["k"][:, slot, :n]),
            numpy.asarray(batched["k"][:, slot, :n]))


def test_tensor_parallel_decode_smoke_2dev():
    """Cheap TP-decode smoke tier: 2-device mesh, 2 tokens, tiny model —
    fast enough to run on every suite invocation so the TP call path
    (repack → _tp_specs → shard_map) is always exercised."""
    from veles_tpu.parallel.decode import make_tp_generate
    from veles_tpu.parallel.mesh import build_mesh

    rng = numpy.random.RandomState(9)
    heads, embed, vocab = 2, 8, 4
    tp_params = init_transformer_params(rng, 1, embed, heads, vocab)
    tp_table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    prompt = jnp.asarray(rng.randint(0, vocab, (1, 3)))

    single, _ = generate(tp_params, tp_table, prompt, heads, n_tokens=2)
    mesh = build_mesh(devices=jax.devices()[:2], data=1, model=2)
    run = make_tp_generate(mesh, heads, n_tokens=2)
    sharded = run(tp_params, tp_table, prompt)
    numpy.testing.assert_array_equal(numpy.asarray(sharded),
                                     numpy.asarray(single))


def test_tensor_parallel_decode_matches_single_device(model):
    """Megatron-style TP decode over an 8-device model axis: the
    sharded run's tokens equal the single-device generate()."""
    from veles_tpu.parallel.decode import make_tp_generate
    from veles_tpu.parallel.mesh import build_mesh

    params, table = model
    # vocab 11 doesn't divide 8 — build a TP-compatible model instead
    rng = numpy.random.RandomState(6)
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    heads, embed, vocab = 8, 32, 16
    tp_params = init_transformer_params(rng, 2, embed, heads, vocab)
    tp_table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    prompt = jnp.asarray(rng.randint(0, vocab, (2, 6)))

    single, _ = generate(tp_params, tp_table, prompt, heads, n_tokens=7)

    mesh = build_mesh(devices=jax.devices()[:8], data=1, model=8)
    run = make_tp_generate(mesh, heads, n_tokens=7)
    sharded = run(tp_params, tp_table, prompt)
    numpy.testing.assert_array_equal(numpy.asarray(sharded),
                                     numpy.asarray(single))
    _ = params, table


def test_tensor_parallel_rejects_indivisible(model):
    from veles_tpu.parallel.decode import make_tp_generate
    from veles_tpu.parallel.mesh import build_mesh

    params, table = model  # HEADS=4, vocab 11: not divisible by 8
    mesh = build_mesh(devices=jax.devices()[:8], data=1, model=8)
    run = make_tp_generate(mesh, HEADS, n_tokens=3)
    with pytest.raises(ValueError):
        run(params, table, jnp.zeros((1, 4), jnp.int32))
