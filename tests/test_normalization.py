"""Tests for the normalizer registry (mirrors reference
test_normalization.py semantics) and the loader label analysis."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE
from veles_tpu.loader.normalization import (make_normalizer,
                                            normalizer_registry)


def sample_data():
    rng = numpy.random.RandomState(7)
    return rng.uniform(-3, 5, size=(40, 6)).astype(numpy.float32)


class TestRegistry:
    def test_all_eight_registered(self):
        assert set(normalizer_registry) == {
            "none", "mean_disp", "linear", "range_linear", "exp",
            "pointwise", "external_mean", "internal_mean"}

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            make_normalizer("bogus")


class TestRoundTrips:
    """normalize → denormalize recovers the input for every invertible
    normalizer (the reference guarantees this via coefficients/state)."""

    def test_none(self):
        n = make_normalizer("none")
        data = sample_data()
        numpy.testing.assert_array_equal(n.normalize(data), data)
        numpy.testing.assert_array_equal(n.denormalize(data), data)

    def test_mean_disp(self):
        n = make_normalizer("mean_disp")
        data = sample_data()
        n.analyze(data)
        normed = n.normalize(data)
        assert abs(float(normed.mean(axis=0).max())) < 1e-4
        numpy.testing.assert_allclose(n.denormalize(normed), data,
                                      rtol=1e-4, atol=1e-4)

    def test_mean_disp_incremental_equals_single_pass(self):
        data = sample_data()
        whole, parts = make_normalizer("mean_disp"), \
            make_normalizer("mean_disp")
        whole.analyze(data)
        parts.analyze(data[:13])
        parts.analyze(data[13:])
        numpy.testing.assert_allclose(whole.normalize(data),
                                      parts.normalize(data), rtol=1e-5)

    def test_linear_samplewise(self):
        n = make_normalizer("linear", interval=(-1, 1))
        data = sample_data()
        normed, stats = n.normalize_with_stats(data)
        assert normed.min() >= -1.0 - 1e-5 and normed.max() <= 1.0 + 1e-5
        # every sample spans the full interval
        numpy.testing.assert_allclose(normed.max(axis=1),
                                      numpy.ones(len(data)), rtol=1e-5)
        numpy.testing.assert_allclose(n.denormalize(normed, **stats), data,
                                      rtol=1e-4, atol=1e-4)

    def test_linear_uniform_sample_midpoint(self):
        n = make_normalizer("linear", interval=(0, 2))
        data = numpy.ones((2, 4), numpy.float32) * 9.0
        normed = n.normalize(data)
        numpy.testing.assert_allclose(normed, 1.0)

    def test_range_linear(self):
        n = make_normalizer("range_linear", interval=(0, 1))
        data = sample_data()
        n.analyze(data)
        normed = n.normalize(data)
        assert normed.min() >= -1e-6 and normed.max() <= 1 + 1e-6
        numpy.testing.assert_allclose(n.denormalize(normed), data,
                                      rtol=1e-4, atol=1e-4)

    def test_range_linear_negative_max(self):
        # regression: dmax == 0 must not be treated as "no range"
        n = make_normalizer("range_linear", interval=(-1, 1))
        data = numpy.linspace(-5, 0, 20, dtype=numpy.float32).reshape(4, 5)
        n.analyze(data)
        normed = n.normalize(data)
        assert abs(float(normed.min()) + 1) < 1e-5
        assert abs(float(normed.max()) - 1) < 1e-5
        numpy.testing.assert_allclose(n.denormalize(normed), data,
                                      rtol=1e-4, atol=1e-4)

    def test_range_linear_rejects_drifting_range(self):
        n = make_normalizer("range_linear")
        n.analyze(sample_data())
        with pytest.raises(ValueError):
            n.analyze(sample_data() * 100)

    def test_exp_is_softmax(self):
        n = make_normalizer("exp")
        data = sample_data()
        normed, stats = n.normalize_with_stats(data)
        numpy.testing.assert_allclose(normed.sum(axis=1),
                                      numpy.ones(len(data)), rtol=1e-5)
        numpy.testing.assert_allclose(n.denormalize(normed, **stats), data,
                                      rtol=1e-3, atol=1e-3)

    def test_pointwise(self):
        n = make_normalizer("pointwise")
        data = sample_data()
        data[:, 2] = 4.0  # constant feature
        n.analyze(data)
        normed = n.normalize(data)
        assert normed[:, 2].max() == 0.0  # constant -> 0
        assert normed.min() >= -1 - 1e-5 and normed.max() <= 1 + 1e-5
        numpy.testing.assert_allclose(n.denormalize(normed), data,
                                      rtol=1e-4, atol=1e-4)

    def test_internal_mean(self):
        n = make_normalizer("internal_mean", scale=2.0)
        data = sample_data()
        n.analyze(data)
        normed = n.normalize(data)
        numpy.testing.assert_allclose(
            normed, (data - data.mean(axis=0)) * 2.0, rtol=1e-4, atol=1e-4)
        numpy.testing.assert_allclose(n.denormalize(normed), data,
                                      rtol=1e-4, atol=1e-4)

    def test_external_mean_from_npy(self, tmp_path):
        mean = sample_data().mean(axis=0)
        path = str(tmp_path / "mean.npy")
        numpy.save(path, mean)
        n = make_normalizer("external_mean", mean_source=path)
        data = sample_data()
        numpy.testing.assert_allclose(n.normalize(data), data - mean,
                                      rtol=1e-4, atol=1e-4)

    def test_external_mean_from_ndarray(self):
        mean = numpy.ones(6, numpy.float32)
        n = make_normalizer("external_mean", mean_source=mean)
        data = sample_data()
        numpy.testing.assert_allclose(n.normalize(data), data - 1.0,
                                      rtol=1e-5)


class TestStatePersistence:
    def test_state_roundtrip(self):
        n = make_normalizer("mean_disp")
        data = sample_data()
        n.analyze(data)
        clone = make_normalizer("mean_disp", state=n.state)
        numpy.testing.assert_allclose(clone.normalize(data),
                                      n.normalize(data))

    def test_uninitialized_normalize_raises(self):
        with pytest.raises(RuntimeError):
            make_normalizer("mean_disp").normalize(sample_data())


class TestLoaderIntegration:
    def test_fullbatch_normalization_types(self):
        for norm in ("none", "mean_disp", "pointwise", "internal_mean"):
            loader = FullBatchLoader(
                DummyWorkflow(), data=sample_data(),
                labels=numpy.arange(40) % 4,
                class_lengths=[0, 8, 32], minibatch_size=8,
                normalization_type=norm)
            loader.initialize()
            loader.run()
            assert loader.minibatch_data.shape == (8, 6)

    def test_label_automapping_strings(self):
        labels = numpy.array((["cat"] * 5 + ["dog"] * 5) * 4)
        loader = FullBatchLoader(
            DummyWorkflow(), data=sample_data(),
            labels=labels, class_lengths=[0, 10, 30], minibatch_size=10)
        loader.initialize()
        assert loader.labels_mapping == {"cat": 0, "dog": 1}
        assert loader.reversed_labels_mapping == ["cat", "dog"]
        assert loader.unique_labels_count == 2
        mapped = numpy.asarray(loader.original_labels.mem)
        assert set(mapped.tolist()) == {0, 1}

    def test_unknown_validation_label_rejected(self):
        labels = numpy.array(["odd"] * 10 + ["a"] * 15 + ["b"] * 15)
        loader = FullBatchLoader(
            DummyWorkflow(), data=sample_data(),
            labels=labels, class_lengths=[0, 10, 30])
        with pytest.raises(ValueError, match="missing from the training"):
            loader.initialize()


class TestMSELoader:
    def make(self, **kwargs):
        data = sample_data()
        targets = (data[:, :2] * 3.0 + 1.0).astype(numpy.float32)
        loader = FullBatchLoaderMSE(
            DummyWorkflow(), data=data, targets=targets,
            class_lengths=[0, 8, 32], minibatch_size=8, **kwargs)
        loader.initialize()
        return loader, targets

    def test_serves_targets(self):
        loader, targets = self.make()
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        got = numpy.asarray(loader.minibatch_targets.mem)
        numpy.testing.assert_allclose(got, targets[idx], rtol=1e-5)
        assert loader.targets_shape == (2,)

    def test_target_normalizer_denormalizes(self):
        loader, targets = self.make(
            target_normalization_type="mean_disp")
        loader.run()
        got = numpy.asarray(loader.minibatch_targets.mem)
        idx = numpy.asarray(loader.minibatch_indices.mem)
        back = loader.target_normalizer.denormalize(got)
        numpy.testing.assert_allclose(back, targets[idx], rtol=1e-3,
                                      atol=1e-3)

    def test_targets_respliced_with_validation_ratio(self):
        # regression: resplit must keep targets row-aligned with data
        data = sample_data()
        targets = (data[:, :1] * 2.0).astype(numpy.float32)
        loader = FullBatchLoaderMSE(
            DummyWorkflow(), data=data, targets=targets,
            class_lengths=[0, 0, 40], minibatch_size=10,
            validation_ratio=0.25)
        loader.initialize()
        assert loader.class_lengths == [0, 10, 30]
        for _ in range(4):
            loader.run()
            idx = numpy.asarray(loader.minibatch_indices.mem)
            got = numpy.asarray(loader.minibatch_targets.mem)
            rows = numpy.asarray(loader.original_data.mem)[idx]
            numpy.testing.assert_allclose(got, rows[:, :1] * 2.0,
                                          rtol=1e-5)

    def test_samplewise_target_normalizer_rejected(self):
        # linear/exp need per-sample stats -> cannot invert at test time
        with pytest.raises(ValueError, match="per-sample"):
            FullBatchLoaderMSE(
                DummyWorkflow(), data=sample_data(),
                targets=sample_data()[:, :2],
                target_normalization_type="exp")

    def test_external_mean_target_normalizer_allowed(self):
        # regression: external_mean is stateless but fully invertible
        data = sample_data()
        loader = FullBatchLoaderMSE(
            DummyWorkflow(), data=data, targets=data[:, :2],
            class_lengths=[0, 8, 32], minibatch_size=8,
            target_normalization_type="external_mean",
            target_normalization_parameters=dict(
                mean_source=numpy.ones(2, numpy.float32)))
        loader.initialize()
        loader.run()
        got = numpy.asarray(loader.minibatch_targets.mem)
        idx = numpy.asarray(loader.minibatch_indices.mem)
        numpy.testing.assert_allclose(got, data[idx][:, :2] - 1.0,
                                      rtol=1e-5)


class TestOnInitialized:
    def test_callback_fires(self):
        fired = []
        loader = FullBatchLoader(
            DummyWorkflow(), data=sample_data(),
            class_lengths=[0, 8, 32],
            on_initialized=lambda: fired.append(True))
        loader.initialize()
        assert fired == [True]
