"""Mesh-sharded slot-engine serving (docs/sharded_serving.md): the
tensor-parallel layout path must stream bit-identical tokens to the
single-chip engine on the suite's 8-device virtual CPU mesh, keep the
KV slab sharded across dispatches, compile one program per
(bucket, group, layout) with zero recompile storms, and compose with
the measured train→serve reshard. `make mesh` runs this file +
test_reshard.py, mirroring `make chaos`."""

import numpy
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.observe.xla_stats import get_compile_tracker
from veles_tpu.parallel.mesh import build_mesh
from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.serving import ContinuousDecoder, build_serve_mesh

pytestmark = pytest.mark.mesh

HEADS, EMBED, BLOCKS, VOCAB = 8, 32, 2, 16


@pytest.fixture(scope="module")
def model():
    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, BLOCKS, EMBED, HEADS, VOCAB)
    table = jnp.asarray(
        rng.randn(VOCAB, EMBED).astype(numpy.float32) * 0.3)
    return params, table


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(devices=jax.devices()[:8], data=1, model=8)


def _drain_pair(params, table, mesh, quantize=None, chunk=4):
    """One single-chip and one sharded decoder through the SAME
    composite drive: staggered submissions joining mid-flight, tiled
    spans, pipelined chunked drain. Returns (ref, got)."""
    rng = numpy.random.RandomState(3)
    prompts = [rng.randint(0, VOCAB, n)
               for n in (5, 9, 3, 7, 6, 11, 4)]
    out = []
    for m in (None, mesh):
        dec = ContinuousDecoder(params, table, HEADS, slots=3,
                                max_len=256, n_tokens=6,
                                quantize=quantize, tile=8, mesh=m)
        pending = list(prompts)
        for _ in range(3):
            dec.submit(pending.pop(0))
        dec.drain_pipelined(
            chunk,
            admit=lambda dec=dec, pending=pending:
                pending and dec.submit(pending.pop(0)))
        out.append(dec)
    return out


class TestShardedSlotEngine:
    @pytest.mark.parametrize("quantize", [None, "int8-kv"])
    def test_streams_bit_identical_to_single_chip(self, model, mesh,
                                                  quantize):
        """The acceptance composite: mid-flight joins, span tiling and
        the pipelined drain — sharded and single-chip engines must
        produce identical token streams for every request, for the
        bf16/f32 tier AND the int8-KV tier."""
        params, table = model
        ref, got = _drain_pair(params, table, mesh, quantize=quantize)
        assert ref.results.keys() == got.results.keys()
        for rid in ref.results:
            assert ref.results[rid] == got.results[rid], \
                "request %d diverged under the mesh" % rid

    def test_state_stays_sharded_across_dispatches(self, model, mesh):
        """The layout must survive admit/step/chunk round trips — a
        silently replicated KV slab would pass the token test while
        storing H x the memory per device."""
        params, table = model
        _, got = _drain_pair(params, table, mesh)
        assert not got.state["k"].sharding.is_fully_replicated
        assert not got.params["blocks"][0]["wqkv"] \
            .sharding.is_fully_replicated
        _, got8 = _drain_pair(params, table, mesh, quantize="int8-kv")
        assert not got8.state["k"].sharding.is_fully_replicated
        assert not got8.state["k_scale"].sharding.is_fully_replicated

    def test_dispatch_counts_one_admit_per_bucket_group(self, model,
                                                        mesh):
        """The sharded path must keep the PR-3 dispatch economy: one
        admit dispatch per (bucket, group), one chunk dispatch per
        slot_step_many — meshes must not reintroduce per-request
        dispatches."""
        params, table = model
        ref, got = _drain_pair(params, table, mesh)
        assert got.dispatch_counts["admit"] <= \
            got.dispatch_counts["admit_requests"]
        assert got.dispatch_counts["admit"] == \
            ref.dispatch_counts["admit"]
        assert got.dispatch_counts["chunk"] == \
            ref.dispatch_counts["chunk"]

    def test_no_recompile_storm_under_mesh(self, model, mesh):
        """Per (bucket, group, layout) compile caching: driving the
        sharded decoder through SIX waves of same-bucket prompts must
        not retrace per request — at most two cache entries per
        program (the layout compile plus one committedness variant of
        the jit fastpath cache), the rest cache hits, ZERO recompile
        storms (the xla_stats counter the CI guard reads). A broken
        layout pin puts compiles at one per wave, which this bound
        catches."""
        params, table = model
        waves = 6
        tracker = get_compile_tracker()
        was_enabled = tracker.enabled
        tracker.reset()
        tracker.enabled = True
        try:
            rng = numpy.random.RandomState(5)
            dec = ContinuousDecoder(params, table, HEADS, slots=2,
                                    max_len=128, n_tokens=4, tile=8,
                                    mesh=mesh)
            for _ in range(waves):
                for _ in range(2):
                    dec.submit(rng.randint(0, VOCAB, 6))
                dec.run_until_drained(chunk=4)
            snap = tracker.snapshot()
        finally:
            tracker.reset()
            tracker.enabled = was_enabled
        assert sum(snap["storms"].values()) == 0
        for program in ("decode.admit", "decode.dispatch"):
            compiles = snap["compiles"].get(program, 0)
            hits = snap["hits"].get(program, 0)
            assert compiles <= 2, \
                "%s retraced %d times over %d same-shape waves" % (
                    program, compiles, waves)
            assert hits >= waves - 2, \
                "%s only hit %d times" % (program, hits)

    def test_rejects_indivisible_heads(self, model):
        params, table = model  # heads=8: a 3-way axis cannot divide
        mesh3 = build_mesh(devices=jax.devices()[:3], data=1, model=3)
        with pytest.raises(ValueError, match="divisible"):
            ContinuousDecoder(params, table, HEADS, mesh=mesh3)

    def test_generate_api_serves_sharded_over_http(self, model, mesh):
        """GenerateAPI(mesh=...) — the --serve-mesh surface — answers
        HTTP requests from the sharded engine with the same tokens the
        single-chip decoder streams."""
        import json
        import urllib.request

        from veles_tpu.serving import GenerateAPI

        params, table = model
        rng = numpy.random.RandomState(11)
        prompts = [rng.randint(0, VOCAB, n).tolist() for n in (6, 9)]
        ref = ContinuousDecoder(params, table, HEADS, slots=2,
                                max_len=64, n_tokens=5)
        for p in prompts:
            ref.submit(p)
        ref.run_until_drained(chunk=4)
        api = GenerateAPI(params, table, HEADS, slots=2, max_len=64,
                          n_tokens=5, chunk=4, mesh=mesh).start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            for rid, prompt in enumerate(prompts):
                req = urllib.request.Request(
                    url, data=json.dumps({"tokens": prompt}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as resp:
                    tokens = json.loads(resp.read().decode())["tokens"]
                assert tokens == ref.results[rid]
        finally:
            api.stop()

    def test_serve_mesh_config_string(self, model):
        """build_serve_mesh: the --serve-mesh syntax builds a mesh;
        bad axes fail naming the flag; empty means single-chip."""
        mesh = build_serve_mesh("model=8")
        assert dict(mesh.shape)["model"] == 8
        assert build_serve_mesh(None) is None
        assert build_serve_mesh("") is None
        with pytest.raises(ValueError, match="serve-mesh"):
            build_serve_mesh("bogus=2")
        with pytest.raises(ValueError, match="serve-mesh"):
            build_serve_mesh("model=x")
        # the device-count product check must ALSO blame the serve
        # knob, not the training mesh config it doesn't read
        with pytest.raises(ValueError, match="serve.mesh"):
            build_serve_mesh("model=3")

    def test_serve_mesh_ignores_training_mesh_config(self, model):
        """A pod-training root.common.mesh.axes must never leak into
        the serving mesh — --serve-mesh model=8 with a training data=2
        set would otherwise build data2.model8 (16 devices) and blame
        the serve flag, or silently replicate the slot engine over the
        data axis."""
        from veles_tpu.core.config import root

        root.common.mesh.axes.data = 2
        try:
            mesh = build_serve_mesh("model=8")
            assert dict(mesh.shape)["model"] == 8
            assert dict(mesh.shape)["data"] == 1
        finally:
            root.common.mesh.axes.data = 1


class TestMeshHygiene:
    def test_build_mesh_clear_errors(self):
        with pytest.raises(ValueError, match="mesh.axes"):
            build_mesh(devices=jax.devices()[:8], data=0)
        with pytest.raises(ValueError, match="unknown mesh axis"):
            build_mesh(devices=jax.devices()[:8], bogus=2)
        with pytest.raises(ValueError, match="mesh.axes"):
            build_mesh(devices=jax.devices()[:8], data="two")
        with pytest.raises(ValueError, match="8 devices"):
            build_mesh(devices=jax.devices()[:8], data=3)

    def test_mesh_shape_on_metrics_and_dashboard(self):
        """The active mesh shape must surface on /metrics
        (veles_mesh_axis_size) and in the web-status device cell."""
        from veles_tpu.observe.xla_stats import (device_summary,
                                                 format_device_stats,
                                                 publish_device_stats)

        build_mesh(devices=jax.devices()[:8], data=2, model=4)
        registry = MetricsRegistry(enabled=True)
        publish_device_stats(registry)
        text = registry.expose()
        assert 'veles_mesh_axis_size{axis="data"} 2' in text
        assert 'veles_mesh_axis_size{axis="model"} 4' in text
        assert "veles_mesh_devices 8" in text
        summary = device_summary()
        assert summary["mesh"] == "data2.model4"
        assert "mesh data2.model4" in format_device_stats(summary)

    def test_fleet_metric_rows_carry_mesh_coordinates(self):
        from veles_tpu.parallel.mesh import mesh_coordinate_labels

        build_mesh(devices=jax.devices()[:8], data=2, model=4)
        labels = mesh_coordinate_labels()
        assert labels["mesh"] == "data2.model4"
        assert labels["process"] == "0"


class TestTrainServeTransition:
    def test_train_dp_reshard_serve_tp(self, mesh):
        """The tentpole composite: ONE checkpoint trains data-parallel
        under the mesh, reshards to the serving layout through the
        measured collective schedule, and serves tensor-parallel —
        streaming the same tokens as a single-chip decoder fed the
        gathered post-training params (no host round trip between the
        layouts)."""
        from veles_tpu.parallel import reshard as rs
        from veles_tpu.parallel.decode import slot_param_specs
        from veles_tpu.parallel.transformer_step import (
            build_transformer_train_step, shard_tokens)

        rng = numpy.random.RandomState(7)
        params = init_transformer_params(rng, BLOCKS, EMBED, HEADS,
                                         VOCAB)
        table = jnp.asarray(
            rng.randn(VOCAB, EMBED).astype(numpy.float32) * 0.3)
        train_mesh = build_mesh(devices=jax.devices()[:8], data=2,
                                model=4)
        step = build_transformer_train_step(HEADS, mesh=train_mesh,
                                            learning_rate=0.05)
        x = jnp.asarray(rng.randn(4, 8, EMBED).astype(numpy.float32))
        labels = jnp.asarray(rng.randint(0, VOCAB, (4, 8)))
        x, labels = shard_tokens((x, labels), train_mesh)
        for _ in range(3):
            params, (loss, _) = step(params, x, labels)
        # train layout (replicated) -> serve layout (TP on "model"):
        # the transition is the measured reshard, not a host gather
        served, stats = rs.reshard(
            params, train_mesh, slot_param_specs(params, "model"),
            label="train_to_serve")
        assert stats["bytes"] == 0  # replicated -> sharded: slices
        single = jax.tree.map(lambda a: jnp.asarray(numpy.asarray(a)),
                              params)
        prompts = [rng.randint(0, VOCAB, n) for n in (5, 8, 3)]
        dec_tp = ContinuousDecoder(served, table, HEADS, slots=2,
                                   max_len=64, n_tokens=5,
                                   mesh=train_mesh)
        dec_one = ContinuousDecoder(single, table, HEADS, slots=2,
                                    max_len=64, n_tokens=5)
        for p in prompts:
            dec_tp.submit(p)
            dec_one.submit(p)
        dec_tp.run_until_drained(chunk=4)
        dec_one.run_until_drained(chunk=4)
        assert dec_tp.results == dec_one.results
        # ...and back: serve -> train round-trips the params exactly
        back, stats_back = rs.reshard(served, train_mesh, P(),
                                      label="serve_to_train")
        assert stats_back["bytes"] > 0
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            numpy.testing.assert_array_equal(numpy.asarray(a),
                                             numpy.asarray(b))
