"""Partial fusion (parallel/segments.py): numerical identity + wiring.

The VERDICT r2 "graph-mode cliff" fix, tier 1: any chain of JitUnits —
including workflows the full fused engine declines — collapses into
per-tick composite dispatches with graph-mode numerics.
"""

import numpy

from veles_tpu.core import prng
from veles_tpu.core.distributable import TriviallyDistributable
from veles_tpu.core.units import Unit
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.parallel import segments


def _digits():
    from dataset_fixtures import digits_dataset
    return digits_dataset()


def _build(max_epochs=3):
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    X, y = _digits()
    return MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=max_epochs, fused=False,
        name="segments-test")


class HostSpy(Unit, TriviallyDistributable):
    """A custom pure-host unit spliced into the chain — the partial
    fusion engine must keep it host-side between two segments."""

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.ticks = 0
        self.seen_shapes = set()
        self.watched = None  # linked Array to observe

    def run(self):
        self.ticks += 1
        if self.watched is not None and self.watched.data is not None:
            self.seen_shapes.add(tuple(self.watched.data.shape))


def test_chain_extraction_and_partition():
    wf = _build()
    chain = segments.chain_of(wf)
    names = [type(u).__name__ for u in chain]
    assert names == ["All2AllTanh", "All2AllSoftmax", "EvaluatorSoftmax",
                     "DecisionGD", "GDSoftmax", "GDTanh"]
    parts = segments.partition(chain)
    kinds = [(kind, len(p) if kind == "segment" else type(p).__name__)
             for kind, p in parts]
    assert kinds == [("segment", 3), ("host", "DecisionGD"),
                     ("segment", 2)]


def test_segments_match_graph_mode():
    graph = _build()
    graph.initialize()
    graph.run()

    seg = _build()
    created = segments.enable(seg)
    assert len(created) == 2
    seg.initialize()
    seg.run()

    assert seg.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    assert seg.decision._epochs_done == graph.decision._epochs_done
    for fg, fs in zip(graph.forwards, seg.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(fs.weights.data),
            atol=1e-5)
        numpy.testing.assert_allclose(
            numpy.asarray(fg.bias.data), numpy.asarray(fs.bias.data),
            atol=1e-5)


def _splice_spy(wf):
    """Insert a HostSpy between fwd0 and fwd1 (control only — data links
    stay as they are)."""
    spy = HostSpy(wf, name="spy")
    spy.watched = wf.forwards[0].output
    fwd1 = wf.forwards[1]
    fwd1.unlink_from(wf.forwards[0])
    spy.link_from(wf.forwards[0])
    fwd1.link_from(spy)
    return spy


def test_custom_host_unit_splits_segments():
    graph = _build()
    graph_spy = _splice_spy(graph)
    graph.initialize()
    graph.run()

    seg = _build()
    seg_spy = _splice_spy(seg)
    created = segments.enable(seg)
    # fwd0 alone is a 1-unit run (stays per-unit); [fwd1, evaluator] and
    # [gds] fuse
    assert len(created) == 2
    seg.initialize()
    seg.run()

    assert seg_spy.ticks == graph_spy.ticks > 0
    assert seg_spy.seen_shapes == graph_spy.seen_shapes
    assert seg.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    for fg, fs in zip(graph.forwards, seg.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(fs.weights.data),
            atol=1e-5)


def test_segments_learn():
    seg = _build(max_epochs=8)
    segments.enable(seg)
    seg.initialize()
    seg.run()
    best = seg.decision.best_n_err[VALID]
    assert best is not None and best < 45


def test_mid_segment_monitor_still_fires():
    """A side unit hanging off a MID-segment member (a monitor linked
    from fwd0) must keep firing after fusion — its provider link is
    rewired to the segment."""
    graph = _build()
    gmon = HostSpy(graph, name="mon")
    gmon.watched = graph.forwards[0].output
    gmon.link_from(graph.forwards[0])
    graph.initialize()
    graph.run()

    seg = _build()
    smon = HostSpy(seg, name="mon")
    smon.watched = seg.forwards[0].output
    smon.link_from(seg.forwards[0])
    created = segments.enable(seg)
    assert len(created) == 2
    seg.initialize()
    seg.run()

    assert smon.ticks == gmon.ticks > 0
    assert seg.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]


def test_segments_with_adam_solver():
    """Partial fusion x Adam: the segment planner builds its dataflow
    plan from the GD units' EXTENDED slot tuples (second moments + step
    are instance-level INPUTS/OUTPUTS), and training still learns."""
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    X, y = _digits()
    seg = MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.01, solver="adam", max_epochs=6, fused=False,
        name="segments-adam")
    _splice_spy(seg)
    created = segments.enable(seg)
    assert created, "partial fusion did not engage"
    seg.initialize()
    seg.run()
    best = seg.decision.best_n_err[VALID]
    assert best is not None and best < 45, best
    import numpy
    assert float(numpy.asarray(seg.gds[0]._step.data)) > 0
