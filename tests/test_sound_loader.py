"""Sound loader tests (reference test_snd_file_loader.py role — fixture
WAVs generated instead of checked in)."""

import os
import wave

import numpy
import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.sound import (AutoLabelSoundFileLoader,
                                    SoundDecoderMixin)


def write_wav(path, freq, seconds=0.2, rate=8000, channels=1, width=2):
    t = numpy.arange(int(rate * seconds)) / rate
    signal = numpy.sin(2 * numpy.pi * freq * t)
    if width == 2:
        payload = (signal * 32000).astype(numpy.int16)
    else:
        payload = ((signal * 120) + 128).astype(numpy.uint8)
    if channels == 2:
        payload = numpy.repeat(payload[:, None], 2, axis=1).reshape(-1)
    with wave.open(path, "wb") as out:
        out.setnchannels(channels)
        out.setsampwidth(width)
        out.setframerate(rate)
        out.writeframes(payload.tobytes())


class TestDecoder:
    def test_decode_16bit_mono(self, tmp_path):
        path = str(tmp_path / "a.wav")
        write_wav(path, 440)
        decoded = SoundDecoderMixin.decode_file(path)
        assert decoded["sampling_rate"] == 8000
        assert decoded["channels"] == 1
        assert decoded["data"].shape == (1600, 1)
        assert -1.0 <= decoded["data"].min() < -0.9  # full-scale sine

    def test_decode_stereo_and_8bit(self, tmp_path):
        stereo = str(tmp_path / "s.wav")
        write_wav(stereo, 440, channels=2)
        decoded = SoundDecoderMixin.decode_file(stereo)
        assert decoded["channels"] == 2
        eight = str(tmp_path / "e.wav")
        write_wav(eight, 440, width=1)
        decoded = SoundDecoderMixin.decode_file(eight)
        assert abs(float(decoded["data"].max())) <= 1.0


class TestSoundLoader:
    @pytest.fixture
    def audio_tree(self, tmp_path):
        for split, count in (("train", 6), ("validation", 2)):
            for label, freq in (("low", 200), ("high", 1800)):
                d = tmp_path / split / label
                d.mkdir(parents=True)
                for i in range(count):
                    write_wav(str(d / ("%d.wav" % i)), freq + i * 7)
        return tmp_path

    def test_windows_and_labels(self, audio_tree):
        loader = AutoLabelSoundFileLoader(
            DummyWorkflow(),
            train_paths=[str(audio_tree / "train")],
            validation_paths=[str(audio_tree / "validation")],
            window_size=400, window_stride=400, minibatch_size=8)
        loader.initialize()
        # 1600 samples per clip -> 4 windows each
        assert loader.class_lengths == [0, 4 * 4, 12 * 4]
        assert loader.labels_mapping == {"high": 0, "low": 1}
        loader.run()
        assert loader.minibatch_data.shape == (8, 400)

    def test_classifier_learns_tones(self, audio_tree):
        """End-to-end: an MLP on windowed waveforms separates the two
        tones (the audio-pipeline learning smoke)."""
        from veles_tpu.models.standard import StandardWorkflow

        wf = StandardWorkflow(
            DummyLauncher(),
            loader_cls=AutoLabelSoundFileLoader,
            loader_kwargs=dict(
                train_paths=[str(audio_tree / "train")],
                validation_paths=[str(audio_tree / "validation")],
                window_size=400, window_stride=200, minibatch_size=16),
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                    {"type": "softmax", "output_sample_shape": 2}],
            learning_rate=0.2,
            decision_kwargs=dict(max_epochs=10), name="tones")
        wf.initialize()
        wf.run()
        best = wf.decision.best_n_err[1]
        total = wf.loader.class_lengths[1]
        assert best is not None and best <= total * 0.25, \
            "%s/%s validation errors" % (best, total)
