"""Fused tick mode: numerical identity with graph mode + wiring checks.

The headline design promise (SURVEY §7.1): one workflow tick = one fused
XLA computation, numerically identical to the per-unit graph dispatch.
These tests train the same topology both ways from identical seeds and
compare weights and metrics.
"""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.core import prng
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.models.standard import StandardWorkflow


def _digits_dataset():
    from sklearn.datasets import load_digits
    digits = load_digits()
    X = digits.data.astype(numpy.float32)
    y = digits.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    return X[perm], y[perm]


def _build_mlp(fused, mesh=None, max_epochs=3, sweep=True,
               pipeline=False, fail_iterations=50):
    # pipeline=False by default HERE: the identity tests compare the
    # plain engine against graph mode / explicit pipelined builds
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    X, y = _digits_dataset()
    return MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y,
                           class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=max_epochs, fused=fused, mesh=mesh,
        fused_sweep=sweep, fused_pipeline=pipeline,
        fail_iterations=fail_iterations, name="fused-identity")


def _train(wf):
    wf.initialize()
    wf.run()
    return wf


@pytest.mark.parametrize("sweep", [False, True])
def test_fused_mode_matches_graph_mode(sweep):
    """Same seeds, same data: fused (per-tick AND scanned-sweep engines)
    and graph mode must produce the same weights and per-epoch metrics."""
    graph = _train(_build_mlp(fused=False))
    fused = _train(_build_mlp(fused=True, sweep=sweep))
    assert fused.fused_tick is not None, "fused mode did not engage"
    assert fused.fused_tick.ticks > 0
    # identical epoch accounting
    assert fused.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    assert fused.decision._epochs_done == graph.decision._epochs_done
    # near-identical weights: each train tick agrees to fp reassociation
    # between the fused autodiff graph and the per-unit chain,
    # compounding over 45 ticks to ~1e-4 measured — metrics stay exact.
    # (atol was 2e-2 before round 4's gate fix: graph mode used to DROP
    # the stopping epoch's final update, and the slack masked it.)
    for fg, ff in zip(graph.forwards, fused.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(ff.weights.data),
            atol=1e-3)
        numpy.testing.assert_allclose(
            numpy.asarray(fg.bias.data), numpy.asarray(ff.bias.data),
            atol=1e-3)


def test_fused_mode_learns():
    wf = _train(_build_mlp(fused=True, max_epochs=8))
    assert wf.fused_tick is not None
    best = wf.decision.best_n_err[VALID]
    assert best is not None and best < 45, \
        "validation errors %s/297 — did not learn" % best


def test_fused_data_parallel_matches_single_device():
    """Pod mode: the shard_mapped fused tick over a 4-device data axis
    must match the single-device fused run exactly (psum-merged grads ==
    full-batch grads)."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh
    single = _train(_build_mlp(fused=True))
    mesh = build_mesh(devices=jax.devices()[:4], data=4)
    dp = _train(_build_mlp(fused=True, mesh=mesh))
    assert dp.fused_tick is not None and dp.fused_tick.mesh is mesh
    assert dp.decision.best_n_err[VALID] == single.decision.best_n_err[
        VALID]
    for fs, fd in zip(single.forwards, dp.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fs.weights.data), numpy.asarray(fd.weights.data),
            atol=1e-3)


def test_pipelined_data_parallel_matches_single_device():
    """The product default (pipelined) composed with a data-parallel
    mesh must still match the plain single-device fused run exactly."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    single = _train(_build_mlp(fused=True))
    mesh = build_mesh(devices=jax.devices()[:4], data=4)
    dp = _train(_build_mlp(fused=True, mesh=mesh, pipeline=True))
    assert dp.fused_tick is not None and dp.fused_tick.pipelined
    assert dp.decision.best_n_err[VALID] == single.decision.best_n_err[
        VALID]
    assert dp.decision._epochs_done == single.decision._epochs_done
    for fs, fd in zip(single.forwards, dp.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fs.weights.data), numpy.asarray(fd.weights.data),
            atol=1e-3)


def test_fused_convnet_matches_graph_mode():
    """Conv + pooling topologies fuse too (VERDICT round-1 item 2)."""
    from sklearn.datasets import load_digits
    d = load_digits()
    X = d.images.astype(numpy.float32)[..., None]  # (N, 8, 8, 1) NHWC
    y = d.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    X, y = X[perm][:600], y[perm][:600]
    layers = [
        {"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "softmax", "output_sample_shape": (10,)},
    ]

    def build(fused):
        prng.get("default").seed(99)
        prng.get("loader").seed(77)
        return StandardWorkflow(
            DummyLauncher(), layers=layers,
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 100, 500],
                               minibatch_size=100,
                               normalization_type="linear"),
            learning_rate=0.05, fused=fused,
            decision_kwargs=dict(max_epochs=2), name="fused-conv")

    graph = _train(build(False))
    fused = _train(build(True))
    assert fused.fused_tick is not None
    assert fused.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    for fg, ff in zip(graph.forwards, fused.forwards):
        if getattr(fg, "weights", None) is None:
            continue
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(ff.weights.data),
            atol=2e-3)


def test_fused_annealing_applies():
    """set_learning_rate() must keep working in fused mode (hypers are
    traced inputs, not baked-in constants)."""
    wf = _build_mlp(fused=True, max_epochs=1)
    wf.initialize()
    assert wf.fused_tick is not None
    for gd in wf.gds:
        gd.set_learning_rate(0.0)
    w0 = numpy.asarray(wf.forwards[0].weights.data).copy()
    wf.run()
    numpy.testing.assert_array_equal(
        w0, numpy.asarray(wf.fused_tick._params_[0]["p"]["w"]),
        "lr=0 must freeze the weights — annealing ignored by fused tick")


def test_fused_disabled_on_host_fallback(monkeypatch):
    """The loader's HBM-OOM host fallback must revert to graph mode."""
    from veles_tpu.memory import Array

    def boom(self, *a, **kw):
        raise MemoryError("synthetic HBM OOM")

    monkeypatch.setattr(Array, "to_device", boom)
    wf = _build_mlp(fused="auto", max_epochs=1)
    wf.initialize()
    assert wf.fused_tick is None, "fused mode must disengage"
    assert wf.loader.fill_data is True
    wf.run()
    assert wf.decision._epochs_done == 1  # graph mode trained fine


def test_fused_snapshot_weights_current():
    """Weights written back at epoch boundaries are the fused params (the
    Snapshotter path sees current state, not the init values)."""
    wf = _build_mlp(fused=True, max_epochs=1)
    wf.initialize()
    init_w = numpy.asarray(wf.forwards[0].weights.data).copy()
    wf.run()
    final_w = numpy.asarray(wf.forwards[0].weights.data)
    assert not numpy.allclose(init_w, final_w), \
        "epoch-boundary write-back did not happen"
    tick_w = numpy.asarray(wf.fused_tick._params_[0]["p"]["w"])
    numpy.testing.assert_array_equal(final_w, tick_w)


def test_fused_transformer_matches_graph_mode():
    """layer_norm + self_attention + softmax head fuses, with per-leaf
    update policies matching the graph-mode GD units (qkv/out decay,
    norm-shift no decay)."""
    rng = numpy.random.RandomState(0)
    n, t, e = 300, 8, 16
    X = rng.randn(n, t, e).astype(numpy.float32) * 0.1
    y = rng.randint(0, 2, n).astype(numpy.int32)
    for i in range(n):
        X[i, : t // 2 if y[i] == 0 else t, 0] += 1.0
    layers = [
        {"type": "layer_norm"},
        {"type": "self_attention", "heads": 4},
        {"type": "softmax", "output_sample_shape": (2,)},
    ]

    def build(fused):
        prng.get("default").seed(11)
        prng.get("loader").seed(12)
        return StandardWorkflow(
            DummyLauncher(), layers=layers,
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 50, 250],
                               minibatch_size=50),
            learning_rate=0.05, weights_decay=1e-4, fused=fused,
            decision_kwargs=dict(max_epochs=1), name="fused-attn")

    graph = _train(build(False))
    fused = _train(build(True))
    assert fused.fused_tick is not None
    # metrics must agree EXACTLY; weights follow the fp-reassociation
    # contract of the dense identity test (bf16 softmax/rsqrt
    # reassociation; momentum is off here so the drift does not
    # compound)
    assert fused.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    for fg, ff in zip(graph.forwards, fused.forwards):
        for attr in ("weights", "bias", "out_weights", "out_bias"):
            ag, af = getattr(fg, attr, None), getattr(ff, attr, None)
            if ag is None or ag.data is None:
                continue
            numpy.testing.assert_allclose(
                numpy.asarray(ag.data), numpy.asarray(af.data),
                atol=2e-3)


def test_fused_transformer_block_matches_graph_mode():
    """The COMPLETE pre-LN transformer block — layer_norm → residual
    self_attention → layer_norm → residual ffn → softmax head — fuses
    and matches graph mode (metrics exactly, weights to fp tolerance)."""
    rng = numpy.random.RandomState(1)
    n, t, e = 300, 8, 16
    X = rng.randn(n, t, e).astype(numpy.float32) * 0.1
    y = rng.randint(0, 2, n).astype(numpy.int32)
    for i in range(n):
        X[i, : t // 2 if y[i] == 0 else t, 0] += 1.0
    layers = [
        {"type": "layer_norm"},
        {"type": "self_attention", "heads": 4, "residual": True},
        {"type": "layer_norm"},
        {"type": "ffn", "ratio": 2},
        {"type": "softmax", "output_sample_shape": (2,)},
    ]

    def build(fused):
        prng.get("default").seed(21)
        prng.get("loader").seed(22)
        return StandardWorkflow(
            DummyLauncher(), layers=layers,
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 50, 250],
                               minibatch_size=50),
            learning_rate=0.05, weights_decay=1e-4, fused=fused,
            decision_kwargs=dict(max_epochs=1), name="fused-block")

    graph = _train(build(False))
    fused = _train(build(True))
    assert fused.fused_tick is not None
    assert fused.decision.best_n_err[VALID] == graph.decision.best_n_err[
        VALID]
    for fg, ff in zip(graph.forwards, fused.forwards):
        for attr in ("weights", "bias", "out_weights", "out_bias"):
            ag, af = getattr(fg, attr, None), getattr(ff, attr, None)
            if ag is None or ag.data is None:
                continue
            numpy.testing.assert_allclose(
                numpy.asarray(ag.data), numpy.asarray(af.data),
                atol=2e-3)


def test_pipelined_is_the_default_product_path():
    """StandardWorkflow defaults to the pipelined fused engine in
    standalone sweep mode (the path `python -m veles_tpu` executes)."""
    prng.get("default").seed(1)
    prng.get("loader").seed(1)
    X, y = _digits_dataset()
    wf = MLPWorkflow(
        DummyLauncher(), layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y,
                           class_lengths=[0, 297, 1500],
                           minibatch_size=100),
        learning_rate=0.1, max_epochs=1, name="default-pipeline")
    wf.initialize()
    assert wf.fused_tick is not None and wf.fused_tick.pipelined
    assert wf.decision.pipeline_depth == 1
    wf.run()
    assert wf.decision._epochs_done == 1


def test_pipelined_identical_on_max_epochs_stop():
    """Pipelined epoch mode (metrics one epoch late, sync overlapped)
    must produce EXACTLY the plain sweep mode's outputs when max_epochs
    stops the run — same epochs, same best error, same final weights."""
    plain = _train(_build_mlp(fused=True, max_epochs=4))
    piped = _train(_build_mlp(fused=True, max_epochs=4, pipeline=True))
    assert piped.fused_tick is not None and piped.fused_tick.pipelined
    assert piped.decision._epochs_done == plain.decision._epochs_done
    assert piped.decision.best_n_err[VALID] == plain.decision.best_n_err[
        VALID]
    assert piped.decision.best_epoch == plain.decision.best_epoch
    for fp, fq in zip(plain.forwards, piped.forwards):
        numpy.testing.assert_array_equal(
            numpy.asarray(fp.weights.data), numpy.asarray(fq.weights.data))


def test_pipelined_identical_on_no_improvement_stop():
    """A fail_iterations stop is discovered one epoch LATE in pipelined
    mode; the speculative epoch must be dropped and the params rolled
    back so outputs match the plain run exactly. lr=0 freezes learning:
    epoch 1 cannot improve on epoch 0, forcing the stop path."""
    def build(pipeline):
        wf = _build_mlp(fused=True, max_epochs=50, pipeline=pipeline,
                        fail_iterations=1)
        wf.initialize()
        for gd in wf.gds:
            gd.set_learning_rate(0.0)
        wf.run()
        return wf

    plain = build(False)
    piped = build(True)
    assert piped.fused_tick.pipelined
    assert plain.decision._epochs_done < 50, "stop path not exercised"
    assert piped.decision._epochs_done == plain.decision._epochs_done
    assert piped.decision.best_n_err[VALID] == plain.decision.best_n_err[
        VALID]
    for fp, fq in zip(plain.forwards, piped.forwards):
        numpy.testing.assert_array_equal(
            numpy.asarray(fp.weights.data), numpy.asarray(fq.weights.data))


def test_pipelined_rollback_restores_pre_speculation_weights():
    """With real learning and a tight improvement budget, the rolled-back
    weights must equal the plain run's final weights (the speculative
    epoch's training must leave no trace)."""
    plain = _train(_build_mlp(fused=True, max_epochs=50,
                              fail_iterations=2))
    piped = _train(_build_mlp(fused=True, max_epochs=50, pipeline=True,
                              fail_iterations=2))
    assert plain.decision._epochs_done < 50, "stop path not exercised"
    assert piped.decision._epochs_done == plain.decision._epochs_done
    for fp, fq in zip(plain.forwards, piped.forwards):
        numpy.testing.assert_array_equal(
            numpy.asarray(fp.weights.data), numpy.asarray(fq.weights.data))


def _attach_snapshotter(wf, directory, **kwargs):
    """Snapshot-on-improved wiring: gate_SKIP (skip still propagates the
    tick) and serialized BEFORE the end point — a parallel end point
    could race a same-tick final snapshot (see tests/test_snapshotter.py
    for the full rationale)."""
    from veles_tpu.snapshotter import Snapshotter

    snap = Snapshotter(wf, directory=str(directory), time_interval=0,
                       **kwargs)
    snap.link_from(wf.decision)
    snap.gate_skip = ~wf.decision.improved
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    return snap


@pytest.mark.parametrize("pipeline", [False, True])
def test_fused_snapshot_on_improved_holds_evaluated_weights(tmp_path,
                                                            pipeline):
    """The deferred sweep materialization fires ``improved`` on the
    epoch-end tick — the unit Arrays must still hold the weights the
    validation metric was MEASURED on (eval-tick write-back), so the
    snapshot re-evaluates to exactly the recorded best error. The
    pipelined case exercises the final max_epochs drain, where TWO
    epochs materialize on one tick (digits improves monotonically, so
    the final epoch takes 'improved' there)."""
    from veles_tpu.snapshotter import SnapshotterToFile

    wf = _build_mlp(fused=True, max_epochs=5, pipeline=pipeline)
    snap = _attach_snapshotter(wf, tmp_path, prefix="sem")
    wf.initialize()
    wf.run()
    best = wf.decision.best_n_err[VALID]
    restored = SnapshotterToFile.import_(snap.destination)
    X, y = _digits_dataset()
    w0, b0 = restored.forwards[0].weights.data, restored.forwards[0].bias.data
    w1, b1 = restored.forwards[1].weights.data, restored.forwards[1].bias.data
    Xv = jnp.asarray(X[:297])
    dmin = Xv.min(axis=1, keepdims=True)
    dmax = Xv.max(axis=1, keepdims=True)
    Xn = (Xv - dmin) * (2.0 / (dmax - dmin)) - 1.0  # linear normalizer
    h = 1.7159 * jnp.tanh(0.6666 * (Xn @ w0 + b0))  # Znicz scaled tanh
    n_err = int((jnp.argmax(h @ w1 + b1, 1) != jnp.asarray(y[:297])).sum())
    assert n_err == best, \
        "snapshot re-evaluates to %d but recorded best is %d" % (n_err, best)


def test_fused_eval_publishes_confusion():
    """Fused eval passes emit the confusion increment; the Decision
    accumulates the whole VALID sweep (MatrixPlotter feed parity with
    graph mode)."""
    wf = _train(_build_mlp(fused=True, max_epochs=2))
    assert wf.fused_tick is not None
    cm = wf.decision.last_epoch_confusion
    assert cm is not None and cm.shape == (10, 10)
    assert int(cm.sum()) == 297  # every VALID row accounted
    graph = _train(_build_mlp(fused=False, max_epochs=2))
    graph_cm = numpy.asarray(graph.decision.last_epoch_confusion)
    # the modes' weights drift ~1e-5/tick (fp reassociation), flipping a
    # few borderline argmaxes: totals must match, cells near-match
    assert int(graph_cm.sum()) == 297
    delta = numpy.abs(numpy.asarray(cm) - graph_cm).sum()
    assert delta <= 8, "confusion matrices differ by %d entries" % delta


def test_fused_confusion_per_tick_and_dp():
    """The per-tick eval path AND the shard_mapped DP path publish the
    psum-merged confusion (the sweep test above covers only the scan
    path)."""
    import jax
    from veles_tpu.parallel.mesh import build_mesh

    # per-tick engine (sweep off)
    wf = _train(_build_mlp(fused=True, max_epochs=1, sweep=False))
    cm = wf.decision.last_epoch_confusion
    assert cm is not None and int(cm.sum()) == 297

    # data-parallel engine: cm must be the psum over shards
    mesh = build_mesh(devices=jax.devices()[:4], data=4)
    dp = _train(_build_mlp(fused=True, max_epochs=1, mesh=mesh))
    cm_dp = dp.decision.last_epoch_confusion
    assert cm_dp is not None and int(cm_dp.sum()) == 297


def test_fused_confusion_disabled_flag(monkeypatch):
    """compute_confusion=False skips the fused cm publish (parity with
    the graph evaluator's opt-out)."""
    wf = _build_mlp(fused=True, max_epochs=1)
    wf.evaluator.compute_confusion = False
    wf.initialize()
    assert wf.fused_tick is not None
    wf.run()
    assert wf.decision.last_epoch_confusion is None


def test_pipelined_snapshot_resume_continues(tmp_path):
    """A snapshot taken by the PIPELINED engine (improved fires on the
    epoch-end tick) must resume and continue training: the lagged-epoch
    queue and the tick's params history are session state, rebuilt
    empty on unpickle."""
    from veles_tpu.snapshotter import SnapshotterToFile

    wf = _build_mlp(fused=True, max_epochs=3, pipeline=True)
    snap = _attach_snapshotter(wf, tmp_path, prefix="pr")
    wf.initialize()
    assert wf.fused_tick.pipelined
    wf.run()
    best_before = wf.decision.best_n_err[VALID]

    restored = SnapshotterToFile.import_(snap.destination)
    restored.workflow = DummyLauncher()
    restored.decision.max_epochs = 6
    restored.decision.complete.unset()
    restored.decision.train_ended.unset()
    restored.initialize()
    assert restored.fused_tick is not None and restored.fused_tick.pipelined
    restored.run()
    assert restored.decision._epochs_done == 6
    # STRICT improvement: the pickled best alone would satisfy <=; three
    # more epochs on digits reliably lower the error, so a broken resume
    # (e.g. garbage params after restore) fails here
    assert restored.decision.best_n_err[VALID] < best_before


class TestAdamSolver:
    """solver="adam" (additive beyond the reference's momentum-only GD):
    graph and fused modes share gd.make_updater, so they must agree."""

    def _build(self, fused, solver="adam", max_epochs=3, sweep=True):
        prng.get("default").seed(4321)
        prng.get("loader").seed(8765)
        X, y = _digits_dataset()
        return MLPWorkflow(
            DummyLauncher(), layers=(32, 10),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 297, 1500],
                               minibatch_size=100,
                               normalization_type="linear"),
            learning_rate=0.01, solver=solver, max_epochs=max_epochs,
            fused=fused, fused_sweep=sweep, fused_pipeline=False,
            fail_iterations=50, name="adam-identity")

    def test_adam_learns_graph_mode(self):
        wf = _train(self._build(fused=False))
        assert wf.decision.best_n_err[VALID] is not None
        assert wf.decision.best_n_err[VALID] < 40  # < ~13.5% on digits
        # adam state exists and evolved (graph mode really ran)
        gd = wf.gds[0]
        assert wf.fused_tick is None
        assert gd._second_w.data is not None
        assert float(gd._step.data) > 0

    @pytest.mark.parametrize("sweep", [False, True])
    def test_adam_fused_matches_graph(self, sweep):
        graph = _train(self._build(fused=False))
        fused = _train(self._build(fused=True, sweep=sweep))
        assert fused.fused_tick is not None, "fused mode did not engage"
        assert (fused.decision.best_n_err[VALID]
                == graph.decision.best_n_err[VALID])
        # weights: LOOSE tolerance by design — adam's first-step update
        # is lr*sign(g) (bias-corrected m/sqrt(s) with tiny s), which
        # amplifies fp-reassociation differences between the fused and
        # per-unit autodiff graphs on near-zero gradients into +-2*lr
        # jumps. Metric-level equality above is the parity contract;
        # this bound only catches gross update bugs (wrong lr/sign/
        # moment wiring would blow past it)
        for fg, ff in zip(graph.forwards, fused.forwards):
            numpy.testing.assert_allclose(
                numpy.asarray(fg.weights.data),
                numpy.asarray(ff.weights.data), atol=0.05)
        # step counts advance one per TRAIN tick. Known, pre-existing
        # one-tick offset: on the stopping tick graph mode's gds sit
        # BELOW the decision in the cycle and get gate-blocked by
        # `complete`, while the fused sweep trains its whole last class
        # sweep before the decision sees the metrics
        g_step = float(graph.gds[0]._step.data)
        f_step = float(fused.gds[0]._step.data)
        assert g_step > 0 and abs(g_step - f_step) <= 1

    def test_adam_adapts_fast(self):
        """Sanity: the adaptive update is live — two epochs at lr=0.01
        already put digits validation under 20% error."""
        wf = _train(self._build(fused=True, max_epochs=2))
        assert wf.decision.best_n_err[VALID] < 60

    def test_adam_snapshot_roundtrip(self, tmp_path):
        """Second moments + step survive a snapshot: resumed training
        continues from the same optimizer state."""
        import pickle

        wf = _train(self._build(fused=True, max_epochs=2))
        step_before = float(wf.gds[0]._step.data)
        blob = pickle.dumps(wf)
        wf2 = pickle.loads(blob)
        gd2 = wf2.gds[0]
        assert float(gd2._step.data) == step_before
        numpy.testing.assert_array_equal(
            numpy.asarray(gd2._second_w.data),
            numpy.asarray(wf.gds[0]._second_w.data))

    @pytest.mark.parametrize("fused", [False, True])
    def test_adagrad_learns(self, fused):
        """solver="adagrad": same stateful-slot machinery as adam (no
        first moment, no bias correction), both execution modes."""
        wf = _train(self._build(fused=fused, solver="adagrad",
                                max_epochs=4))
        assert (wf.fused_tick is not None) == fused
        assert wf.decision.best_n_err[VALID] is not None
        assert wf.decision.best_n_err[VALID] < 45
        gd = wf.gds[0]
        assert float(numpy.asarray(gd._step.data)) > 0
        assert numpy.asarray(gd._second_w.data).sum() > 0


def test_lr_decay_on_plateau():
    """decision_kwargs lr_decay/lr_decay_patience anneal every GD unit
    when validation stops improving — in fused mode (traced hypers make
    set_learning_rate effective without retrace)."""
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    X, y = _digits_dataset()
    wf = MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y,
                           class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        # lr=0: NOTHING ever improves after epoch 1, so the plateau
        # counter climbs deterministically
        learning_rate=0.0, max_epochs=7, fused=True,
        fused_pipeline=False,
        decision_kwargs=dict(max_epochs=7, lr_decay=0.5,
                             lr_decay_patience=2),
        name="lr-decay")
    wf.initialize()
    wf.run()
    # epochs 2..7 -> >=5 no-improvement epochs -> >=2 decays (at 2, 4, 6)
    lr = wf.gds[0].learning_rate
    assert lr == 0.0  # 0 * factor stays 0 — decay applied cleanly
    assert wf.decision._epochs_without_improvement >= 4
    # a REAL decay: start from a positive lr and force a plateau
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    wf2 = MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y,
                           class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=1e-7, max_epochs=6, fused=True,
        fused_pipeline=False,
        decision_kwargs=dict(max_epochs=6, lr_decay=0.5,
                             lr_decay_patience=2),
        name="lr-decay2")
    wf2.initialize()
    wf2.run()
    assert wf2.gds[0].learning_rate < 1e-7  # decayed at least once
