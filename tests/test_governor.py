"""Closed-loop serving governor (docs/serving_robustness.md, ISSUE 11):
hysteresis-banded tier transitions pinned to at most one per cooldown
window, the priced Retry-After helper replacing the hardcoded ``"1"``s,
admission resize under pool pressure, the prewarm and breaker-guard
actuators, ledger/flight/metrics visibility for every actuation — and
the chaos acceptance: under each seeded burn-inducing profile (latency
ramp, pool-exhaustion flood, compile storm) the governor converges to a
stable degraded tier with a PINNED transition count, every demoted
request's ledger row names its tier, and the system restores full
fidelity with burn < 1.0 after the fault clears, bit-identical greedy
tokens on the non-demoted path. ``make governor`` runs this module
standalone; the ramp/flood/storm acceptance rides the ``slow`` marker
so tier-1 stays inside its timeout margin."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles_tpu.observe.governor import (GovernorConfig, ServingGovernor,
                                        format_governor_transitions,
                                        parse_governor_spec,
                                        publish_governor)
from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.observe.reqledger import RequestLedger
from veles_tpu.observe.slo import SLOEngine
from veles_tpu.serving import GenerateAPI, ServingHealth
from veles_tpu.serving_chaos import ServingChaosConfig, ServingChaosMonkey

CHAOS_SEED = int(os.environ.get("VELES_TPU_CHAOS_SEED", "1"))

pytestmark = pytest.mark.governor


def post(url, payload, timeout=60):
    data = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode()), dict(
                resp.headers)
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        try:
            body = json.loads(body)
        except ValueError:
            body = {"raw": body}
        return err.code, body, dict(err.headers)


@pytest.fixture(scope="module")
def model():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads, vocab


def make_api(model, **kw):
    params, table, heads, _ = model
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("n_tokens", 5)
    kw.setdefault("chunk", 2)
    kw.setdefault("port", 0)
    kw.setdefault("rebuild_backoff", 0.02)
    kw.setdefault("ledger", RequestLedger())
    return GenerateAPI(params, table, heads, **kw)


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# -- stubs for the pure control-law tests (no HTTP, injected clock) ---------

class StubSLO:
    def __init__(self, burns):
        self.burns = list(burns)

    def summary(self):
        burn = self.burns.pop(0) if self.burns else 0.0
        if burn is None:
            return None
        return {"burn_rate": burn, "objective": "ttft_p95_ms",
                "window": "2s"}


class StubDecoder:
    def __init__(self, pool=None, quantize=None):
        self.pool = pool
        self.quantize = quantize
        self.aot = None


class StubApi:
    def __init__(self, burns, pool=None, max_queue=64):
        self.slo = StubSLO(burns)
        self.decoder = StubDecoder(pool=pool)
        self.max_queue = max_queue
        self._base_tier = "bf16"
        self.tier_requests = []
        self.trip_requests = []

    def request_tier(self, tier):
        self.tier_requests.append(tier)
        # mimic the driver's swap so reconciliation settles
        self.decoder.quantize = None if tier == "bf16" else tier

    def request_trip(self, reason):
        self.trip_requests.append(reason)


class TestGovernorConfig:
    def test_spec_parsing_and_validation_name_the_flag(self):
        config = parse_governor_spec(
            "demote_burn=3,recover_burn=0.5,cooldown_s=5,"
            "ladder=int8+int8-kv,min_admit=4,prewarm=0",
            flag="--serve-governor")
        assert config.demote_burn == 3.0
        assert config.ladder == ("int8", "int8-kv")
        assert config.min_admit == 4
        assert config.prewarm is False
        assert parse_governor_spec(None) is None
        assert parse_governor_spec("") is None
        assert parse_governor_spec("enabled=0,demote_burn=3") is None
        for bad in ("demote_burn", "nope=1", "demote_burn=x",
                    "recover_burn=5,demote_burn=2", "cooldown_s=0",
                    "ladder=bf16", "ladder=int8-kv+int8",
                    "admit_factor=1.5", "min_admit=0", "prewarm=maybe"):
            with pytest.raises(ValueError, match="--serve-governor"):
                parse_governor_spec(bad, flag="--serve-governor")

    def test_from_config_default_off_and_on(self):
        from veles_tpu.core.config import root

        assert ServingGovernor.from_config() is None  # unset -> no loop
        try:
            root.common.serve.governor = "demote_burn=4,cooldown_s=2"
            governor = ServingGovernor.from_config()
            assert governor is not None
            assert governor.config.demote_burn == 4.0
            root.common.serve.governor = "enabled=0"
            assert ServingGovernor.from_config() is None
        finally:
            root.common.serve.governor = None

    def test_base_tier_drops_unreachable_rungs(self):
        governor = ServingGovernor(GovernorConfig(
            ladder=("int8", "int8-kv")))
        governor.set_base_tier("int8")
        assert governor._ladder == ("int8-kv",)
        assert governor.tier_name() == "int8"
        governor.level = 1
        assert governor.tier_name() == "int8-kv"


class TestHysteresis:
    """Satellite: a burn rate oscillating across the demote threshold
    must produce at most ONE tier transition per cooldown window."""

    def run_governor(self, burns, cooldown=10.0, ladder=("int8",)):
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=cooldown,
            interval_s=1.0, ladder=ladder, prewarm=False,
            breaker_guard=False), clock=lambda: 0.0)
        api = StubApi(burns)
        for second, _ in enumerate(list(burns)):
            governor.tick(api, now=float(second))
        return governor, api

    def test_at_most_one_transition_per_cooldown_window(self):
        # burn flaps across the demote threshold every second; the
        # cooldown must hold the ladder to one move per window
        burns = [5.0, 0.2, 5.0, 0.2, 5.0, 0.2, 5.0, 0.2, 5.0, 0.2,
                 5.0, 0.2, 5.0, 0.2, 5.0, 0.2, 5.0, 0.2, 5.0, 0.2,
                 5.0, 0.2]
        governor, _ = self.run_governor(burns, cooldown=10.0)
        moves = [t for t in governor.transitions
                 if t["action"] in ("demote", "promote")]
        for a in moves:
            same_window = [b for b in moves
                           if a is not b
                           and abs(b["mono"] - a["mono"]) < 10.0]
            assert not same_window, (a, same_window)
        total = governor.counters["demotions"] \
            + governor.counters["promotions"]
        # 22 seconds of flapping, 10 s cooldown: at most 3 moves
        assert 1 <= total <= 3

    def test_band_holds_between_thresholds(self):
        # burn inside the (recover, demote) band must HOLD the tier
        governor, api = self.run_governor([5.0] + [1.5] * 20,
                                          cooldown=2.0)
        assert governor.counters["demotions"] == 1
        assert governor.counters["promotions"] == 0
        assert governor.demoted
        assert api.decoder.quantize == "int8"

    def test_demote_stops_at_ladder_bottom_then_recovers(self):
        burns = [9.0] * 12 + [0.0] * 12
        governor, api = self.run_governor(
            burns, cooldown=2.0, ladder=("int8", "int8-kv"))
        assert governor.counters["demotions"] == 2  # int8, int8-kv
        assert governor.counters["promotions"] == 2  # back up both
        assert not governor.demoted
        assert (api.decoder.quantize or "bf16") == "bf16"
        tiers = [t["tier"] for t in governor.transitions
                 if t["action"] in ("demote", "promote")]
        assert tiers == ["int8", "int8-kv", "int8", "bf16"]

    def test_no_slo_engine_means_no_transitions(self):
        governor = ServingGovernor(GovernorConfig(prewarm=False,
                                                  breaker_guard=False),
                                   clock=lambda: 0.0)
        api = StubApi([])
        api.slo = None
        for second in range(5):
            governor.tick(api, now=float(second))
        assert governor.counters["demotions"] == 0
        assert governor.last_burn is None


class TestDeployAwareDemotion:
    """Satellite (ISSUE 17): a demotion whose burn is attributable to
    a RAMPING green slice is suppressed — the rollout predicate owns
    the bad-deploy response (rollback); demoting the whole surface
    would punish healthy blue traffic."""

    class VersionedSLO(StubSLO):
        """A StubSLO whose surface burn comes from the green-ramp
        profile while the per-version slices tell the attribution
        story."""

        def __init__(self, burns, green_burns, blue_burns):
            super().__init__(burns)
            self.green = list(green_burns)
            self.blue = list(blue_burns)

        def version_burn(self, version, now=None):
            series = self.green if version == "green" else self.blue
            burn = series.pop(0) if series else 0.0
            if burn is None:
                return None
            return {"version": version, "burn_rate": burn}

    class ShiftingRollout:
        state = "shifting"

    def run(self, green_burns, blue_burns, rollout, deploy_aware=True):
        # the synthetic green-ramp burn profile: the surface-wide burn
        # crosses the demote bar every tick (green's regression
        # dominates the aggregate), green's slice ramps with it, blue
        # holds flat
        surface = [5.0] * len(green_burns)
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=1.0,
            interval_s=1.0, prewarm=False, breaker_guard=False,
            deploy_aware=deploy_aware), clock=lambda: 0.0)
        api = StubApi([])
        api.slo = self.VersionedSLO(surface, green_burns, blue_burns)
        api._rollout = rollout
        for second in range(len(surface)):
            governor.tick(api, now=float(second))
        return governor, api

    def test_green_ramp_burn_suppresses_demotion(self):
        ramp = [2.5, 3.5, 4.5, 5.5, 6.0]  # the ramping green slice
        governor, api = self.run(ramp, [0.2] * len(ramp),
                                 self.ShiftingRollout())
        assert governor.counters["demotions"] == 0
        assert governor.counters["demotes_suppressed_deploy"] >= 1
        assert not governor.demoted
        actions = [t["action"] for t in governor.transitions]
        assert "demote_suppressed_deploy" in actions
        note = next(t for t in governor.transitions
                    if t["action"] == "demote_suppressed_deploy")
        assert "deploy-attributable" in note["reason"]

    def test_ambient_burn_still_demotes_during_rollout(self):
        # BOTH slices burn: ambient load, not the candidate — the
        # governor must still protect the surface
        ramp = [5.0] * 5
        governor, _ = self.run(ramp, [4.0] * 5, self.ShiftingRollout())
        assert governor.counters["demotions"] == 1
        assert governor.counters["demotes_suppressed_deploy"] == 0

    def test_no_rollout_means_no_suppression(self):
        governor, _ = self.run([5.0] * 5, [0.2] * 5, None)
        assert governor.counters["demotions"] == 1

    def test_terminal_rollout_state_does_not_suppress(self):
        class Promoted:
            state = "promoted"
        governor, _ = self.run([5.0] * 5, [0.2] * 5, Promoted())
        assert governor.counters["demotions"] == 1

    def test_knob_off_restores_unconditional_demotion(self):
        governor, _ = self.run([5.0] * 5, [0.2] * 5,
                               self.ShiftingRollout(),
                               deploy_aware=False)
        assert governor.counters["demotions"] == 1
        spec = parse_governor_spec("deploy_aware=0")
        assert spec.deploy_aware is False


class TestRetryAfterPricing:
    """Satellite: the five hardcoded ``Retry-After: "1"`` headers are
    one priced helper, clamped [1, 60] like the pool gate."""

    def test_helper_clamps_and_degrades(self):
        from veles_tpu.core.httpd import retry_after_headers

        class Priced:
            def __init__(self, seconds):
                self.seconds = seconds

            def retry_after_s(self, need=1):
                return self.seconds

        assert retry_after_headers(None) == {"Retry-After": "1"}
        assert retry_after_headers(Priced(7.4)) == {"Retry-After": "7"}
        assert retry_after_headers(Priced(900)) == {"Retry-After": "60"}
        assert retry_after_headers(Priced(0.01)) == {"Retry-After": "1"}

        class Broken:
            def retry_after_s(self, need=1):
                raise RuntimeError("boom")

        assert retry_after_headers(Broken()) == {"Retry-After": "1"}

    def test_health_consults_governor_then_pool(self):
        health = ServingHealth()
        assert health.retry_after_s() == 1.0

        class PoolStub:
            def retry_after(self, need, fallback=1.0):
                return 42.0

        pool = PoolStub()
        health.attach_pool(pool)
        assert health.retry_after_s() == 42.0
        governor = ServingGovernor(GovernorConfig())
        governor.retry_price = 9.0
        health.attach_governor(governor)
        assert health.retry_after_s() == 9.0

    def test_readyz_and_429_carry_priced_headers(self, model):
        api = make_api(model, max_queue=1, deadline=60.0)
        api.start()
        gate = threading.Event()
        real = api.decoder.dispatch_chunk
        api.decoder.dispatch_chunk = lambda n: (gate.wait(20),
                                                real(n))[1]
        try:
            base = "http://127.0.0.1:%d" % api.port
            results = {}
            thread = threading.Thread(target=lambda: results.update(
                first=post(base + "/generate", {"tokens": [1, 2]})))
            thread.start()
            assert wait_until(lambda: api.health.inflight == 1, 10)
            code, _, headers = post(base + "/generate",
                                    {"tokens": [1, 2]})
            assert code == 429
            assert 1 <= int(headers["Retry-After"]) <= 60
            gate.set()
            thread.join(timeout=60)
            api.health.set_ready(False)
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=10) as resp:  # pragma: no cover
                raise AssertionError("readyz should be 503")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            assert 1 <= int(err.headers["Retry-After"]) <= 60
        finally:
            gate.set()
            api.stop()

    def test_pool_overhang_pricing(self):
        """The governor prices the time for the observed release rate
        to clear the pressure OVERHANG above the pool_high gate — the
        need it hands the pool's release-rate pricer is the pages over
        the gate, not a constant 1."""
        class PoolStub:
            def __init__(self):
                self.needs = []

            @staticmethod
            def snapshot():
                return {"pages_total": 100, "pages_used": 90,
                        "reserved_pages": 20}

            def retry_after(self, need, fallback=1.0):
                self.needs.append(need)
                return 37.0

        pool = PoolStub()
        governor = ServingGovernor(GovernorConfig(
            pool_high=0.5, prewarm=False, breaker_guard=False),
            clock=lambda: 0.0)
        api = StubApi([0.0], pool=pool, max_queue=8)
        governor.tick(api, now=0.0)
        assert pool.needs == [40]  # 90 used - 50 (the 0.5 gate)
        assert governor.retry_price == 37.0
        health = ServingHealth()
        health.attach_governor(governor)
        assert health.retry_after_s() == governor.retry_price


class TestAdmissionResize:
    def test_demotion_and_pool_pressure_shrink_the_limit(self):
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=1.0,
            interval_s=0.5, min_admit=2, admit_factor=0.5,
            pool_high=0.85, prewarm=False, breaker_guard=False),
            clock=lambda: 0.0)
        api = StubApi([5.0, 5.0, 0.0, 0.0], max_queue=64)
        governor.tick(api, now=0.0)   # demote -> limit 32
        assert governor.effective_limit == 32
        assert governor.admit_limit == 32

        class PressuredPool:
            @staticmethod
            def snapshot():
                return {"pages_total": 100, "pages_used": 10,
                        "reserved_pages": 95}

            @staticmethod
            def retry_after(need, fallback=1.0):
                return 30.0

        api.decoder.pool = PressuredPool()
        governor.tick(api, now=1.0)   # still demoted + pool pressure
        assert governor.effective_limit == 16
        api.decoder.pool = None
        governor.tick(api, now=2.0)   # promote (burn 0) -> restore
        governor.tick(api, now=3.0)
        assert governor.effective_limit == 64
        assert governor.admit_limit is None
        assert governor.counters["admit_resizes"] >= 2

    def test_disabled_bound_stays_disabled(self):
        governor = ServingGovernor(GovernorConfig(prewarm=False,
                                                  breaker_guard=False),
                                   clock=lambda: 0.0)
        api = StubApi([9.0], max_queue=0)
        governor.tick(api, now=0.0)
        assert governor.admit_limit is None
        assert governor.effective_limit is None

    def test_generate_api_effective_limit_reads_override(self, model):
        api = make_api(model, max_queue=64,
                       governor=ServingGovernor(GovernorConfig()))
        assert api.effective_max_queue == 64
        api.governor.admit_limit = 3
        assert api.effective_max_queue == 3


class TestActuationVisibility:
    def test_metrics_families_and_snapshot(self):
        governor = ServingGovernor(GovernorConfig(
            prewarm=False, breaker_guard=False), clock=lambda: 0.0)
        api = StubApi([5.0])
        governor.tick(api, now=0.0)
        registry = MetricsRegistry(enabled=True)
        publish_governor(registry, governor)
        text = registry.expose()
        assert "veles_governor_tier_level 1" in text
        assert "veles_governor_demoted 1" in text
        assert 'veles_governor_actuations_total{action="demotions"} 1' \
            in text
        assert "veles_governor_retry_after" in text
        snap = governor.snapshot()
        assert snap["tier"] == "int8" and snap["demoted"]
        assert snap["transitions"][-1]["action"] in ("demote",
                                                     "admit_resize")
        health = ServingHealth()
        health.attach_governor(governor)
        assert health.snapshot()["governor"]["tier"] == "int8"

    def test_dashboard_cell_names_the_governed_tier(self):
        from veles_tpu.web_status import format_serving_health

        cell = format_serving_health({
            "ready": True, "breaker": "closed",
            "counters": {"completed": 3},
            "governor": {"demoted": True, "tier": "int8",
                         "counters": {"demotions": 1, "promotions": 0,
                                      "guard_trips": 2}}})
        assert "tier int8 (governed)" in cell
        assert "1 tier moves" in cell
        assert "2 guard trips" in cell

    def test_autopsy_cli_replays_governor_actuations(self, tmp_path,
                                                     capsys):
        """Black-box dumps carry the governor's flight entries; the
        ``veles_tpu observe slo`` autopsy prints the actuation tail."""
        from veles_tpu.observe.flight import FlightRecorder
        from veles_tpu.observe.slo import slo_main

        recorder = FlightRecorder()
        recorder.note("governor", action="demote", tier="int8",
                      burn=12.0, reason="burn 12 >= 2")
        recorder.note("governor", action="promote", tier="bf16",
                      burn=0.4, reason="burn 0.4 <= 1")
        path = str(tmp_path / "box.json")
        recorder.dump("test", path=path)
        with open(path) as fin:
            doc = json.load(fin)
        doc["requests"] = {"slowest": [], "inflight": []}
        with open(path, "w") as fout:
            json.dump(doc, fout)
        slo_main(path)
        out = capsys.readouterr().out
        assert "governor actuations:" in out
        assert "demote" in out and "tier=int8" in out
        assert "promote" in out and "tier=bf16" in out
        assert "burn=12" in out

    def test_format_transitions(self):
        lines = format_governor_transitions([
            {"action": "guard_trip", "tier": "bf16", "burn": None,
             "reason": "recompile storm (2 total, was 1)"}])
        assert "guard_trip" in lines and "recompile storm" in lines


class TestPrewarm:
    def test_hot_bucket_prewarms_once(self):
        governor = ServingGovernor(GovernorConfig(
            prewarm=True, prewarm_hot=3, breaker_guard=False),
            clock=lambda: 0.0)
        warmed = []

        class ProgramsStub:
            def prewarm_bucket(self, bucket):
                warmed.append(bucket)
                return 1

        api = StubApi([0.0, 0.0, 0.0])
        api.decoder.aot = ProgramsStub()
        governor.observe_bucket(16)
        governor.tick(api, now=0.0)
        assert warmed == []  # 1 admission: not trending yet
        governor.observe_bucket(16)
        governor.observe_bucket(16)
        governor.tick(api, now=1.0)
        governor.drain_prewarm()
        assert warmed == [16]
        governor.observe_bucket(16)
        governor.tick(api, now=2.0)  # already warmed: no repeat
        governor.drain_prewarm()
        assert warmed == [16]
        assert governor.counters["prewarms"] == 1

    def test_aot_programs_prewarm_bucket_compiles_admit_family(self):
        from veles_tpu.aot.loader import AotPrograms

        class EntryStub:
            def __init__(self):
                self.compiled = None

            def get(self):
                self.compiled = object()
                return self.compiled

        entries = {("decode.admit", ("admit", 16, 1)): EntryStub(),
                   ("decode.admit", ("admit", 32, 1)): EntryStub(),
                   ("decode.dispatch", ("chunk", 2, 16)): EntryStub()}
        programs = AotPrograms({"geometry": None}, entries)
        assert programs.prewarm_bucket(16) == 1
        assert entries[("decode.admit", ("admit", 16, 1))].compiled \
            is not None
        assert entries[("decode.admit", ("admit", 32, 1))].compiled \
            is None
        # the step program is NOT an admit-family prewarm target
        assert entries[("decode.dispatch", ("chunk", 2, 16))].compiled \
            is None
        assert programs.prewarm_bucket(16) == 0  # idempotent


class TestChaosProfiles:
    def test_profile_validation_and_enable(self):
        with pytest.raises(ValueError, match=">= 0"):
            ServingChaosConfig(latency_ramp_ms=-1)
        with pytest.raises(ValueError, match="compile_storm_at"):
            ServingChaosConfig(compile_storm_at=-2)
        assert not ServingChaosConfig().any_profile
        assert ServingChaosConfig(latency_ramp_ms=5,
                                  latency_ramp_steps=2).any_profile
        assert ServingChaosConfig(pool_flood_pages=4).any_profile
        assert ServingChaosConfig(compile_storm_at=0).any_profile

    def test_latency_ramp_is_deterministic_and_clears(self):
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, latency_ramp_ms=1.0, latency_ramp_steps=3))
        for _ in range(5):
            monkey.before_step()
        assert monkey.counters["ramp_stalls"] == 3
        assert "ramp_start" in monkey.stamps
        assert "ramp_clear" in monkey.stamps
        assert monkey.stamps["ramp_clear"] >= monkey.stamps["ramp_start"]

    def test_pool_flood_reserves_and_releases(self):
        from veles_tpu.parallel.kv_pool import PagePool

        pool = PagePool(pages=17, page_size=4)
        decoder = StubDecoder(pool=pool)
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, pool_flood_pages=12, pool_flood_at=1,
            pool_flood_steps=2))
        monkey.before_step(decoder)          # step 0: nothing
        assert pool.snapshot()["reserved_pages"] == 0
        monkey.before_step(decoder)          # step 1: flood
        assert pool.snapshot()["reserved_pages"] == 12
        assert monkey.counters["pool_floods"] == 1
        monkey.before_step(decoder)          # step 2: held
        assert pool.snapshot()["reserved_pages"] == 12
        monkey.before_step(decoder)          # step 3: cleared
        assert pool.snapshot()["reserved_pages"] == 0
        assert "flood_clear" in monkey.stamps

    def test_compile_storm_fires_the_detector(self):
        from veles_tpu.observe.xla_stats import get_compile_tracker

        tracker = get_compile_tracker()
        was = tracker.enabled
        tracker.enable()
        before = tracker.storm_total()
        try:
            monkey = ServingChaosMonkey(ServingChaosConfig(
                seed=CHAOS_SEED, compile_storm_at=0))
            monkey.before_step()
            assert monkey.counters["compile_storms"] == 1
            assert tracker.storm_total() == before + 1
        finally:
            if not was:
                tracker.disable()


class TestChaosAcceptance:
    """THE acceptance: seeded burn-inducing profiles, convergence to a
    stable degraded tier (pinned transition counts), ledger-named
    demotions, recovery to full fidelity, bit-identical greedy tokens
    on the non-demoted path. Slow-marked: these wait out real SLO
    windows (``make governor`` runs them; tier-1 skips)."""

    pytestmark = [pytest.mark.governor, pytest.mark.slow]

    def test_latency_ramp_demotes_recovers_bit_identical(self, model):
        prompt = [1, 2, 3]
        clean_api = make_api(model)
        clean_api.start()
        try:
            code, body, _ = post(
                "http://127.0.0.1:%d/generate" % clean_api.port,
                {"tokens": prompt})
            assert code == 200
            want = body["tokens"]
        finally:
            clean_api.stop()

        engine = SLOEngine({"ttft_p95_ms": 150.0}, windows=(2.0, 8.0),
                           bucket_seconds=0.25)
        governor = ServingGovernor(GovernorConfig(
            demote_burn=2.0, recover_burn=1.0, cooldown_s=3.0,
            interval_s=0.05, ladder=("int8",), breaker_guard=False,
            prewarm=False))
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, latency_ramp_ms=400.0,
            latency_ramp_steps=10, latency_ramp_hold=1 << 30))
        ledger = RequestLedger()
        api = make_api(model, slo=engine, governor=governor,
                       chaos=monkey, ledger=ledger)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            # the ramp stalls every driver step: requests burn the ttft
            # objective until the governor demotes
            pre_demote = []
            deadline = time.time() + 60
            while not governor.demoted and time.time() < deadline:
                code, body, _ = post(url, {"tokens": prompt})
                if code == 200 and not governor.demoted \
                        and (api.decoder.quantize or "bf16") == "bf16":
                    pre_demote.append(body["tokens"])
                time.sleep(0.02)
            assert governor.demoted, governor.snapshot()
            # the fault HOLDS, so the governor stays demoted and the
            # graceful swap lands once the in-flight bf16 work drains
            # (nobody shed); keep a trickle of traffic flowing
            assert wait_until(
                lambda: (post(url, {"tokens": prompt}), )
                and api.decoder.quantize == "int8", 90), \
                api.decoder.quantize
            # a demoted request's ledger row names its tier
            code, body, _ = post(url, {"tokens": prompt})
            assert code == 200
            assert any(row.get("tier") == "int8"
                       and row.get("quant") == "int8"
                       for row in ledger.slowest(512)), \
                [(r.get("quant"), r.get("tier"))
                 for r in ledger.slowest(16)]
            # stable degraded tier under the held fault: no further
            # ladder moves while the burn persists
            assert governor.counters["demotions"] == 1
            # fault clears; a trickle of now-fast traffic shows the
            # burn decaying (the governor promotes only on OBSERVED
            # low burn — an empty window holds the tier) and full
            # fidelity restores on its own
            monkey.clear_ramp()
            assert wait_until(
                lambda: (post(url, {"tokens": prompt}), )
                and not governor.demoted
                and (api.decoder.quantize or "bf16") == "bf16", 90,
                interval=0.1), governor.snapshot()
            # pinned transition count: exactly one demote + one promote
            # — zero oscillation under the seeded ramp
            moves = [t["action"] for t in governor.transitions
                     if t["action"] in ("demote", "promote")]
            assert moves == ["demote", "promote"], moves
            # full fidelity restored: burn < 1.0 and the post-recovery
            # stream is bit-identical to the fault-free run, as is
            # every pre-demote bf16 stream
            code, body, _ = post(url, {"tokens": prompt})
            assert code == 200 and body["tokens"] == want
            for tokens in pre_demote:
                assert tokens == want
            summary = engine.summary()
            assert summary is None or summary["burn_rate"] < 1.0
            snap = api.health.snapshot()["governor"]
            assert snap["counters"]["demotions"] == 1
            assert snap["counters"]["promotions"] == 1
        finally:
            api.stop()

    def test_pool_flood_resizes_admission_and_prices_retry(self, model):
        governor = ServingGovernor(GovernorConfig(
            demote_burn=1e9, cooldown_s=0.5, interval_s=0.02,
            pool_high=0.5, min_admit=2, breaker_guard=False,
            prewarm=False))
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, pool_flood_pages=48, pool_flood_at=4,
            pool_flood_steps=1 << 30))
        api = make_api(model, paged=True, pool_pages=64, max_queue=16,
                       governor=governor, chaos=monkey)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            # traffic past the flood step: completed requests feed the
            # release-rate window, the flood reserves most of the pool
            for _ in range(4):
                code, _, _ = post(url, {"tokens": [1, 2, 3]})
                assert code == 200
            assert wait_until(lambda: "flood_start" in monkey.stamps,
                              30)
            post(url, {"tokens": [1, 2, 3]})  # tick the governor
            assert wait_until(
                lambda: api.effective_max_queue < api.max_queue, 30), \
                governor.snapshot()
            # the pool gate rejects with a PRICED Retry-After (the
            # worst-case demand cannot be reserved past the flood)
            code, body, headers = post(url, {"tokens": [1, 2, 3] * 3})
            if code == 429:
                assert 1 <= int(headers["Retry-After"]) <= 60
            assert api.health.retry_after_s() == governor.retry_price
            assert governor.counters["admit_resizes"] >= 1
            # fault clears: the reservation flood drops, the limit
            # restores to the configured bound
            monkey.release_flood()
            post(url, {"tokens": [1, 2, 3]})
            assert wait_until(
                lambda: (post(url, {"tokens": [1, 2]}),)
                and api.effective_max_queue == api.max_queue, 30), \
                governor.snapshot()
            code, body, _ = post(url, {"tokens": [1, 2, 3]})
            assert code == 200 and len(body["tokens"]) == 5
        finally:
            monkey.release_flood()
            api.stop()

    def test_compile_storm_trips_breaker_proactively(self, model):
        from veles_tpu.observe.xla_stats import get_compile_tracker

        tracker = get_compile_tracker()
        was = tracker.enabled
        governor = ServingGovernor(GovernorConfig(
            demote_burn=1e9, cooldown_s=5.0, interval_s=0.02,
            breaker_guard=True, prewarm=False))
        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=CHAOS_SEED, compile_storm_at=6))
        api = make_api(model, governor=governor, chaos=monkey)
        api.start()  # mounts metrics -> enables the compile tracker
        try:
            prompt = [1, 2, 3]
            url = "http://127.0.0.1:%d/generate" % api.port
            code, body, _ = post(url, {"tokens": prompt})
            assert code == 200
            want = body["tokens"]
            # drive steps until the injected storm fires and the guard
            # trips the breaker proactively
            deadline = time.time() + 60
            while monkey.counters["compile_storms"] == 0 \
                    and time.time() < deadline:
                post(url, {"tokens": prompt})
                time.sleep(0.02)
            assert monkey.counters["compile_storms"] == 1
            assert wait_until(
                lambda: governor.counters["guard_trips"] >= 1, 30), \
                governor.snapshot()
            # ONE guard trip per storm (cooldown-limited), the breaker
            # healed behind the probe, and the retried stream is
            # bit-identical. The trip executes at the top of the next
            # drive pass: wait for the counter BEFORE the heal.
            assert wait_until(
                lambda: api.health.counter("trips") >= 1, 30), \
                api.health.snapshot()
            assert wait_until(lambda: api.health.ready, 30), \
                api.health.snapshot()
            snap = api.health.snapshot()
            assert snap["counters"]["trips"] >= 1
            assert snap["counters"]["rebuilds"] >= 1
            assert governor.counters["guard_trips"] == 1
            assert any(t["action"] == "guard_trip"
                       and "storm" in t["reason"]
                       for t in governor.transitions)
            code, body, _ = post(url, {"tokens": prompt})
            assert code == 200 and body["tokens"] == want
        finally:
            api.stop()
            if not was:
                tracker.disable()
