"""Unit/gate/link/workflow tests, mirroring reference test_units.py and
test_workflow.py coverage."""

import pickle

import pytest

from veles_tpu.core.errors import AttributeMissingError, NoMoreJobsError
from veles_tpu.core.mutable import Bool
from veles_tpu.core.plumbing import FireStarter, Repeater
from veles_tpu.core.units import TrivialUnit, Unit
from veles_tpu.dummy import DummyLauncher, DummyWorkflow


class Recorder(Unit):
    hide_from_registry = True

    def __init__(self, workflow, log, **kwargs):
        super().__init__(workflow, **kwargs)
        self.log = log

    def run(self):
        self.log.append(self.name)


class Counter(Unit):
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.count = 0

    def run(self):
        self.count += 1


def make_chain(wf, log, names):
    units = [Recorder(wf, log, name=n) for n in names]
    prev = wf.start_point
    for u in units:
        u.link_from(prev)
        prev = u
    wf.end_point.link_from(prev)
    return units


class TestControlFlow:
    def test_linear_chain(self):
        wf = DummyWorkflow()
        log = []
        make_chain(wf, log, ["a", "b", "c"])
        wf.initialize()
        wf.run()
        assert log == ["a", "b", "c"]

    def test_and_gate_fanin(self):
        """A unit with two incoming links runs only after both fire."""
        wf = DummyWorkflow()
        log = []
        a = Recorder(wf, log, name="a")
        b = Recorder(wf, log, name="b")
        c = Recorder(wf, log, name="c")
        a.link_from(wf.start_point)
        b.link_from(wf.start_point)
        c.link_from(a, b)
        wf.end_point.link_from(c)
        wf.initialize()
        wf.run()
        assert log[-1] == "c"
        assert sorted(log[:2]) == ["a", "b"]
        assert len(log) == 3

    def test_gate_block(self):
        wf = DummyWorkflow()
        log = []
        a, b, c = make_chain(wf, log, ["a", "b", "c"])
        b.gate_block = Bool(True)
        wf.end_point.unlink_from(c)
        wf.end_point.link_from(a)  # need another path to finish
        wf.initialize()
        wf.run()
        assert "b" not in log and "c" not in log

    def test_gate_skip(self):
        wf = DummyWorkflow()
        log = []
        a, b, c = make_chain(wf, log, ["a", "b", "c"])
        b.gate_skip = Bool(True)
        wf.initialize()
        wf.run()
        assert log == ["a", "c"]

    def test_repeater_loop(self):
        """Repeater closes the epoch loop; a gate opens the exit path."""
        wf = DummyWorkflow()
        rep = Repeater(wf)
        counter = Counter(wf, name="counter")
        done = Bool(False)

        class Decider(Unit):
            hide_from_registry = True

            def run(self):
                if counter.count >= 5:
                    done.set()

        dec = Decider(wf, name="decider")
        rep.link_from(wf.start_point)
        counter.link_from(rep)
        dec.link_from(counter)
        rep.link_from(dec)          # cycle
        wf.end_point.link_from(dec)
        wf.end_point.gate_block = ~done
        rep.gate_block = done
        wf.initialize()
        wf.run()
        assert counter.count == 5

    def test_firestarter(self):
        wf = DummyWorkflow()
        c = Counter(wf, name="c")
        c.stopped = True
        fs = FireStarter(wf, units=[c])
        fs.link_from(wf.start_point)
        wf.end_point.link_from(fs)
        wf.initialize()
        wf.run()
        assert c.stopped is True  # run finished sets stopped again


class TestDataLinks:
    def test_link_attrs(self):
        wf = DummyWorkflow()
        a = TrivialUnit(wf, name="a")
        b = TrivialUnit(wf, name="b")
        a.output = 10
        b.link_attrs(a, ("input", "output"))
        assert b.input == 10
        a.output = 20
        assert b.input == 20

    def test_demand(self):
        wf = DummyWorkflow()
        u = TrivialUnit(wf, name="u")
        u.demand("needed")
        with pytest.raises(AttributeMissingError):
            wf.initialize()
        u.needed = 5
        wf.initialize()


class TestWorkflow:
    def test_error_propagates(self):
        wf = DummyWorkflow()

        class Boom(Unit):
            hide_from_registry = True

            def run(self):
                raise RuntimeError("boom")

        a = Boom(wf, name="a")
        b = Boom(wf, name="b")
        # two successors forces pool fan-out; error must surface in run()
        a.link_from(wf.start_point)
        b.link_from(wf.start_point)
        wf.end_point.link_from(a, b)
        wf.initialize()
        with pytest.raises(RuntimeError):
            wf.run()

    def test_gather_results(self):
        wf = DummyWorkflow()

        class Metric(TrivialUnit):
            hide_from_registry = True

            def get_metric_names(self):
                return ["accuracy"]

            def get_metric_values(self):
                return [0.99]

        m = Metric(wf, name="m")
        m.link_from(wf.start_point)
        wf.end_point.link_from(m)
        wf.initialize()
        wf.run()
        results = wf.gather_results()
        assert results["accuracy"] == 0.99
        assert "run_time" in results

    def test_checksum_stable(self):
        wf = DummyWorkflow()
        TrivialUnit(wf, name="x").link_from(wf.start_point)
        c1 = wf.checksum
        assert c1 == wf.checksum
        TrivialUnit(wf, name="y")
        assert wf.checksum != c1

    def test_graph_dot(self):
        wf = DummyWorkflow()
        log = []
        make_chain(wf, log, ["a", "b"])
        dot = wf.generate_graph()
        assert "digraph" in dot and '"a"' in dot.replace("\\n(Recorder)", '"')\
            or "a" in dot
        assert "->" in dot

    def test_pickle_roundtrip(self):
        wf = DummyWorkflow()
        log = []
        make_chain(wf, log, ["a", "b", "c"])
        wf.initialize()
        wf.run()
        # detach launcher before pickling (snapshotting does the same)
        launcher = wf.workflow
        wf._workflow = None
        blob = pickle.dumps(wf)
        wf._workflow = launcher
        wf2 = pickle.loads(blob)
        assert [u.name for u in wf2.units[:5]] == \
            [u.name for u in wf.units[:5]]


class TestDistributedAggregation:
    def _make(self):
        wf = DummyWorkflow()

        class Worker(Unit):
            hide_from_registry = True
            jobs = 0

            def __init__(self, workflow, **kwargs):
                super().__init__(workflow, **kwargs)
                self.applied = []
                self.updates = []

            def generate_data_for_slave(self, slave=None):
                type(self).jobs += 1
                if type(self).jobs > 3:
                    raise NoMoreJobsError()
                return {"job": type(self).jobs}

            def apply_data_from_master(self, data):
                self.applied.append(data)

            def generate_data_for_master(self):
                return {"result": len(self.applied)}

            def apply_data_from_slave(self, data, slave=None):
                self.updates.append(data)

        w = Worker(wf, name="w")
        w.link_from(wf.start_point)
        wf.end_point.link_from(w)
        wf.initialize()
        return wf, w

    def test_job_update_cycle(self):
        Worker_jobs_reset = None
        wf, w = self._make()
        type(w).jobs = 0
        job = wf.generate_data_for_slave("slave1")
        assert isinstance(job, list)
        wf.apply_data_from_master(job)
        assert w.applied == [{"job": 1}]
        update = wf.generate_data_for_master()
        wf.apply_data_from_slave(update, "slave1")
        assert w.updates == [{"result": 1}]

    def test_no_more_jobs(self):
        wf, w = self._make()
        type(w).jobs = 3
        assert wf.generate_data_for_slave("s") is None
        assert not wf.has_more_jobs()


class TestInterfaceVerification:
    """Reference verified.py role: structural interface checks at
    workflow initialize."""

    def test_valid_units_pass(self):
        from veles_tpu.core.verified import IUNIT, verify_interface
        from veles_tpu.core.units import Unit
        verify_interface(Unit(DummyWorkflow()), IUNIT, "IUnit")

    def test_missing_method_reported(self):
        from veles_tpu.core.verified import (InterfaceError, IUNIT,
                                             verify_interface)

        class Broken:
            name = "broken"
            initialize = None

        try:
            verify_interface(Broken(), IUNIT, "IUnit")
        except InterfaceError as exc:
            assert "initialize" in str(exc) and "run" in str(exc)
        else:
            raise AssertionError("no InterfaceError raised")

    def test_arity_checked(self):
        from veles_tpu.core.verified import (ILOADER, InterfaceError,
                                             verify_interface)

        class BadLoader:
            name = "bad"

            def load_data(self):
                pass

            def create_minibatch_data(self):
                pass

            def fill_minibatch(self):  # needs (indices, valid)
                pass

        try:
            verify_interface(BadLoader(), ILOADER, "ILoader")
        except InterfaceError as exc:
            assert "fill_minibatch" in str(exc)
        else:
            raise AssertionError("no InterfaceError raised")

    def test_workflow_initialize_verifies(self):
        from veles_tpu.core.verified import InterfaceError
        from veles_tpu.core.workflow import Workflow
        from veles_tpu.core.units import Unit
        from veles_tpu.dummy import DummyLauncher

        wf = Workflow(DummyLauncher(), name="verify-wf")
        unit = Unit(wf)
        unit.run = None  # sabotage
        try:
            wf.initialize()
        except InterfaceError as exc:
            assert "run" in str(exc)
        else:
            raise AssertionError("no InterfaceError raised")


class TestChangeUnit:
    def test_change_unit_swaps_control_links_and_gates(self):
        """Live graph surgery (reference workflow.py:973): replace a
        mid-chain unit; links, gates and execution move to the new
        unit."""
        from veles_tpu.core.mutable import Bool
        from veles_tpu.core.workflow import Workflow
        from veles_tpu.dummy import DummyLauncher

        wf = Workflow(DummyLauncher(), name="surgery")
        ran = []

        class Tick(TrivialUnit):
            def run(self):
                ran.append(self.name)

        a = Tick(wf, name="a")
        b = Tick(wf, name="b")
        c = Tick(wf, name="c")
        a.link_from(wf.start_point)
        b.link_from(a)
        c.link_from(b)
        wf.end_point.link_from(c)
        shared_gate = Bool(False)
        b.gate_skip = shared_gate

        b2 = Tick(wf, name="b2")
        wf.change_unit("b", b2)
        assert a in b2.links_from
        assert b2 in c.links_from and b not in c.links_from
        assert not b.links_from and not b.links_to
        assert b2.gate_skip is shared_gate
        wf.initialize()
        wf.run()
        assert ran == ["a", "b2", "c"]
