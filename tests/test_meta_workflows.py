"""Tests for the genetics gray tier, fleet task farm, and ensemble
combiner (VERDICT round-1 items 7-8)."""

import numpy
import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.genetics.config import Range
from veles_tpu.genetics.core import (GrayCodec, Population, gray_decode,
                                     gray_encode)


def genes():
    return [("root.lr", Range(0.5, 0.0, 1.0)),
            ("root.units", Range(8, 2, 30))]


class TestGrayCodec:
    def test_gray_identities(self):
        for n in range(64):
            assert gray_decode(gray_encode(n)) == n
        # adjacent integers differ by exactly one bit
        for n in range(63):
            diff = gray_encode(n) ^ gray_encode(n + 1)
            assert bin(diff).count("1") == 1

    def test_roundtrip_within_accuracy(self):
        codec = GrayCodec(genes(), accuracy=1000)
        values = [0.333, 17]
        decoded = codec.decode(codec.encode(values))
        assert abs(decoded[0] - 0.333) <= 1e-3
        assert abs(decoded[1] - 17) <= 1e-3

    def test_decode_clips_to_range(self):
        codec = GrayCodec(genes(), accuracy=10)
        bits = [1] * codec.total_bits  # max codes, possibly out of range
        decoded = codec.decode(bits)
        assert 0.0 <= decoded[0] <= 1.0
        assert 2 <= decoded[1] <= 30


class TestGrayPopulation:
    def test_evolution_stays_in_range(self):
        pop = Population(genes(), size=8, representation="gray",
                         crossover="two_point")
        assert pop.mutation_type == "binary_point"
        for _ in range(3):
            for m in pop.members:
                # fitness: prefer lr near 0.7
                m.fitness = -abs(m.values[0] - 0.7)
            pop.evolve()
            for m in pop.members:
                assert 0.0 <= m.values[0] <= 1.0
                assert 2 <= m.values[1] <= 30

    def test_gray_with_arithmetic_crossover_falls_back_to_numeric(self):
        # value-space crossovers stay usable under the gray representation
        pop = Population(genes(), size=4, representation="gray",
                         crossover="arithmetic")
        a, b = pop.members[:2]
        child = pop.cross(a, b)
        for (lo_hi, v) in zip(((0.0, 1.0), (2, 30)), child.values):
            assert lo_hi[0] <= v <= lo_hi[1]


class TestTaskFarm:
    def test_loopback_over_fleet_protocol(self, tmp_path):
        """Submit shell tasks through the REAL fleet server/client pair
        and collect results (reference optimization_workflow.py:179-279
        distribution semantics)."""
        import sys
        from veles_tpu.fleet.farm import (TaskFarmMaster, TaskFarmSlave,
                                          farm_worker)
        from veles_tpu.fleet.server import Server
        import threading

        farm = TaskFarmMaster("test")
        server = Server("127.0.0.1:0", farm).start()
        farm.on_new_tasks = server.kick
        worker = threading.Thread(
            target=farm_worker,
            args=("127.0.0.1:%d" % server.port, "test"), daemon=True)
        worker.start()
        # each task: python writes {"value": N} into its --result-file
        code = ("import json,sys;"
                "argv=sys.argv;"
                "path=argv[argv.index('--result-file')+1];"
                "json.dump({'value': int(argv[1])}, open(path,'w'))")
        for i in range(3):
            farm.submit("t%d" % i, [sys.executable, "-c", code, str(i)])
        results = farm.wait_batch(timeout=60)
        assert {k: v["results"]["value"] for k, v in results.items()} == \
            {"t0": 0, "t1": 1, "t2": 2}
        # second batch after a quiet period (the between-generations case)
        farm.take_results()
        farm.submit("t3", [sys.executable, "-c", code, "7"])
        results = farm.wait_batch(timeout=60)
        assert results["t3"]["results"]["value"] == 7
        farm.close()
        server.kick()
        worker.join(timeout=10)
        assert not worker.is_alive()
        server.stop()

    def test_drop_slave_requeues(self):
        from veles_tpu.fleet.farm import TaskFarmMaster

        class Slave:
            id = "s1"

        farm = TaskFarmMaster("x")
        farm.submit("a", ["cmd"])
        job = farm.generate_data_for_slave(Slave())
        assert job["task_id"] == "a"
        assert farm.generate_data_for_slave(Slave()) is False  # parked
        farm.drop_slave(Slave())
        job2 = farm.generate_data_for_slave(Slave())
        assert job2["task_id"] == "a"  # requeued


class TestEnsembleCombiner:
    def test_output_dumper_and_loader_roundtrip(self, tmp_path):
        from veles_tpu.ensemble import (EnsembleLoader, OutputDumper,
                                        build_combiner_file)
        from veles_tpu.loader.base import TRAIN

        rng = numpy.random.RandomState(0)
        n, dim = 30, 3
        winners = rng.randint(0, dim, n)
        entries = []
        for mid in range(2):
            wf = DummyWorkflow()
            dumper = OutputDumper(wf, model_id="m%d" % mid, klass=TRAIN)
            # simulate two epoch sweeps of minibatches
            outputs = rng.rand(n, dim).astype(numpy.float32)
            # model outputs correlate with winners: boost the true class
            outputs[numpy.arange(n), winners] += 2.0
            for start in range(0, n, 10):
                dumper.output = outputs[start:start + 10]
                dumper.minibatch_indices = numpy.arange(start, start + 10)
                dumper.minibatch_valid_size = 10
                dumper.minibatch_class = TRAIN
                dumper.run()
            entries.append(dumper.entry(labels=["a", "b", "c"]))
        path = build_combiner_file(
            entries, [["a", "b", "c"][w] for w in winners],
            str(tmp_path / "models.json"))

        loader = EnsembleLoader(DummyWorkflow(), file=path,
                                minibatch_size=10)
        loader.initialize()
        assert loader.class_lengths == [0, 0, n]
        assert loader.original_data.shape == (n, 2, dim)
        labels = numpy.asarray(loader.original_labels.mem)
        numpy.testing.assert_array_equal(labels, winners)

    def test_output_dumper_wired_into_workflow(self):
        """Regression: a leaf-linked dumper races the repeater loop and
        records rows from the WRONG class; wire() puts it in the control
        chain so every recorded row belongs to its class."""
        from veles_tpu.ensemble import OutputDumper
        from veles_tpu.loader.base import VALID
        from veles_tpu.models.mlp import MLPWorkflow

        rng = numpy.random.RandomState(0)
        X = rng.rand(300, 8).astype(numpy.float32)
        y = (X[:, 0] > 0.5).astype(numpy.int32)
        wf = MLPWorkflow(
            DummyLauncher(), layers=(8, 2),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 100, 200],
                               minibatch_size=50),
            learning_rate=0.2, max_epochs=3, fused=False, name="dump-wf")
        dumper = OutputDumper(wf, model_id="m", klass=VALID).wire(wf)
        wf.initialize()
        wf.run()
        assert sorted(dumper.rows) == list(range(100))
        entry = dumper.entry()
        assert len(entry["Output"]) == 100

    def test_combiner_model_trains_on_stack(self, tmp_path):
        """Member outputs -> EnsembleLoader -> combiner MLP learns the
        vote (the full reference combiner pipeline)."""
        from veles_tpu.ensemble import build_combiner_file
        from veles_tpu.ensemble.combiner import EnsembleLoader
        from veles_tpu.models.standard import StandardWorkflow

        rng = numpy.random.RandomState(1)
        n, dim = 120, 4
        winners = rng.randint(0, dim, n)
        entries = []
        for mid in range(3):
            outputs = rng.rand(n, dim).astype(numpy.float32) * 0.3
            good = rng.rand(n) < 0.8  # each member is 80% accurate
            outputs[numpy.arange(n)[good], winners[good]] += 1.0
            entries.append({"id": "m%d" % mid,
                            "Output": outputs.tolist(), "Labels": []})
        path = build_combiner_file(entries, winners.tolist(),
                                   str(tmp_path / "models.json"))
        wf = StandardWorkflow(
            DummyLauncher(),
            loader_cls=EnsembleLoader,
            loader_kwargs=dict(file=path, minibatch_size=20,
                               validation_ratio=0.25),
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                    {"type": "softmax", "output_sample_shape": dim}],
            learning_rate=0.1,
            decision_kwargs=dict(max_epochs=8), name="combiner")
        wf.initialize()
        wf.run()
        best = wf.decision.best_n_err[1]
        assert best is not None and best <= 10, \
            "combiner at %s/30 validation errors" % best
