"""Traffic record-replay + capacity-cliff finder tests
(docs/traffic_replay.md, ISSUE 19): trace schema round trip + the
anonymization pins (no prompt text, salted tenant-hash stability),
lossy-trace stamping from the ledger's loss tallies, deterministic
warp schedules (same trace + seed => bit-identical arrival plan), the
open-loop replayer on a scripted poster, the ``veles_reqledger_*``
metrics bridge, the capacity controller's escalate-then-backoff loop
on a scripted endpoint, the recorded-traffic chaos profile, and the
``slow`` e2e acceptance — record a mixed-tenant run off a live
GenerateAPI, escalate warp until the SLO burn breaches, and the
capacity report names the first-breaching series plus the dominant
waste cause. ``make replay`` runs this module standalone."""

import json
import threading
import time
import urllib.request

import numpy
import pytest

from veles_tpu.observe.capacity import (CapacityFinder,
                                        render_capacity_report,
                                        write_capacity_report)
from veles_tpu.observe.replay import (TRACE_ROW_FIELDS, build_trace,
                                      hash_tenant, load_trace,
                                      plan_fingerprint, record_trace,
                                      replay, tenant_mix, warp_plan,
                                      write_trace)
from veles_tpu.observe.reqledger import (RequestLedger,
                                         publish_request_ledger)

pytestmark = pytest.mark.replay


def make_ledger(n=12, chunk_cap=512, capacity=512, tenants=("acme",
                                                            "globex"),
                stagger=0.002):
    """A real ledger driven through its real hooks — rows carry true
    monotonic cadence, admit kinds and chunk stamps."""
    ledger = RequestLedger(chunk_cap=chunk_cap, capacity=capacity)
    for i in range(n):
        row = ledger.stage(api="generate-api", trace="trace-%d" % i,
                           tenant=tenants[i % len(tenants)],
                           prompt_len=4 + i % 3, budget=4, bucket=8,
                           deadline=9.0)
        ledger.note_admit(row, "dense" if i % 2 else "cold")
        for _ in range(4):
            ledger.note_tokens(row, 1)
        ledger.resolve(row, "completed")
        time.sleep(stagger)
    return ledger


class TestTraceSchema:
    def test_round_trip_preserves_rows_and_header(self, tmp_path):
        ledger = make_ledger(10)
        path = str(tmp_path / "t.jsonl")
        header = record_trace(ledger, path, salt="s1")
        loaded_header, rows = load_trace(path)
        assert loaded_header == header
        assert header["kind"] == "veles-trace"
        assert header["schema"] == 1
        assert header["count"] == len(rows) == 10
        assert header["span_s"] >= 0.0
        # arrival offsets rebased to the first arrival, ascending
        assert rows[0]["t"] == 0.0
        assert [r["t"] for r in rows] == sorted(r["t"] for r in rows)
        assert {r["admit"] for r in rows} == {"dense", "cold"}
        assert all(r["budget"] == 4 and r["deadline_s"] == 9.0
                   for r in rows)

    def test_sidecar_refuses_tampered_trace(self, tmp_path):
        ledger = make_ledger(4)
        path = str(tmp_path / "t.jsonl")
        record_trace(ledger, path)
        load_trace(path)  # intact passes
        with open(path, "a") as fout:
            fout.write(json.dumps({"t": 99.0}) + "\n")
        with pytest.raises(ValueError, match="sha256 sidecar"):
            load_trace(path)
        # an explicitly hand-cut trace (no sidecar) stays loadable
        bare = str(tmp_path / "bare.jsonl")
        header, rows = build_trace(ledger.resolved())
        write_trace(header, rows, bare)
        import os
        os.remove(bare + ".sha256")
        load_trace(bare)

    def test_newer_schema_is_refused(self, tmp_path):
        path = str(tmp_path / "future.jsonl")
        write_trace({"kind": "veles-trace", "schema": 99}, [], path)
        with pytest.raises(ValueError, match="schema"):
            load_trace(path)


class TestAnonymization:
    def test_rows_carry_only_contract_fields(self, tmp_path):
        """The whitelist pin: no trace ids, no error strings, no raw
        tenant names, no prompt text (which never existed upstream)."""
        ledger = make_ledger(6)
        path = str(tmp_path / "t.jsonl")
        record_trace(ledger, path, salt="s1")
        _, rows = load_trace(path)
        for row in rows:
            assert set(row) <= TRACE_ROW_FIELDS
        raw = open(path).read()
        assert "trace-" not in raw          # ledger trace ids
        assert "acme" not in raw            # raw tenant names
        assert "globex" not in raw

    def test_tenant_hash_stable_within_salt_distinct_across(self):
        assert hash_tenant("acme", "s1") == hash_tenant("acme", "s1")
        assert hash_tenant("acme", "s1") != hash_tenant("acme", "s2")
        assert hash_tenant("acme", "s1") != hash_tenant("globex", "s1")
        assert len(hash_tenant("acme", "s1")) == 16
        assert hash_tenant("", "s1") == ""  # anonymous stays empty

    def test_salt_never_written_only_fingerprint(self, tmp_path):
        ledger = make_ledger(3)
        path = str(tmp_path / "t.jsonl")
        header = record_trace(ledger, path, salt="super-secret")
        assert "super-secret" not in open(path).read()
        assert len(header["salt_fingerprint"]) == 8


class TestLossyStamping:
    def test_clean_ledger_stamps_not_lossy(self, tmp_path):
        ledger = make_ledger(4)
        header = record_trace(ledger, str(tmp_path / "t.jsonl"))
        assert header["lossy"] is False
        assert not any(header["loss"].values())

    def test_chunk_cap_drops_stamp_lossy_with_amount(self, tmp_path):
        ledger = make_ledger(6, chunk_cap=2)
        # 4 token chunks per request, cap 2 -> 2 dropped per request
        assert ledger.chunk_stamps_dropped_total == 12
        header = record_trace(ledger, str(tmp_path / "t.jsonl"))
        assert header["lossy"] is True
        assert header["loss"]["chunk_stamps_dropped"] == 12

    def test_ring_overflow_stamps_lossy(self, tmp_path):
        ledger = make_ledger(7, capacity=4)
        assert ledger.ring_overflow_total == 3
        header = record_trace(ledger, str(tmp_path / "t.jsonl"))
        assert header["lossy"] is True
        assert header["loss"]["resolved_ring_overflow"] == 3


class TestReqledgerMetrics:
    def test_counters_on_metrics(self):
        from veles_tpu.observe.metrics import MetricsRegistry

        ledger = make_ledger(5, chunk_cap=2, capacity=3, stagger=0.0)
        registry = MetricsRegistry(enabled=True)
        publish_request_ledger(registry, ledger)
        text = registry.expose()
        assert "veles_reqledger_staged_total 5" in text
        assert "veles_reqledger_resolved_total 5" in text
        assert "veles_reqledger_chunk_stamps_dropped_total 10" in text
        assert "veles_reqledger_ring_overflow_total 2" in text
        assert "veles_reqledger_inflight_dropped_total 0" in text


class TestWarpDeterminism:
    def _rows(self):
        ledger = make_ledger(12)
        return build_trace(ledger.resolved())[1]

    def test_same_trace_same_seed_bit_identical_plan(self, tmp_path):
        ledger = make_ledger(12)
        path = str(tmp_path / "t.jsonl")
        record_trace(ledger, path)
        _, rows = load_trace(path)
        kw = dict(warp=3.0, seed=11, burst_compress=0.4,
                  long_context_skew=0.5,
                  tenant_weights={hash_tenant("acme", "veles"): 1.7})
        one = warp_plan(rows, **kw)
        two = warp_plan(load_trace(path)[1], **kw)
        assert json.dumps(one, sort_keys=True) \
            == json.dumps(two, sort_keys=True)
        assert plan_fingerprint(one) == plan_fingerprint(two)

    def test_seed_changes_randomized_knobs(self):
        rows = self._rows()
        kw = dict(warp=2.0, burst_compress=0.3, long_context_skew=0.5)
        assert plan_fingerprint(warp_plan(rows, seed=1, **kw)) \
            != plan_fingerprint(warp_plan(rows, seed=2, **kw))

    def test_rate_warp_compresses_arrivals(self):
        rows = self._rows()
        base = warp_plan(rows, warp=1.0)
        fast = warp_plan(rows, warp=4.0)
        assert fast[-1]["at"] == pytest.approx(base[-1]["at"] / 4.0,
                                               abs=1e-6)

    def test_tenant_weight_zero_drops_and_two_doubles(self):
        rows = self._rows()
        acme = hash_tenant("acme", "veles")
        globex = hash_tenant("globex", "veles")
        plan = warp_plan(rows, tenant_weights={acme: 0.0,
                                               globex: 2.0})
        tenants = [e["tenant"] for e in plan]
        assert acme not in tenants
        assert len(tenants) == 12  # 6 globex rows, integer-doubled

    def test_burst_compress_squeezes_above_median_gaps(self):
        rows = [{"t": t, "prompt_len": 4, "budget": 2, "tokens": 2}
                for t in (0.0, 0.01, 0.02, 1.0, 1.01, 2.0)]
        plan = warp_plan(rows, burst_compress=0.5)
        assert plan[-1]["at"] < 2.0  # valleys closed up
        ats = [e["at"] for e in plan]
        assert ats == sorted(ats)  # order preserved

    def test_long_context_skew_stretches_prompts(self):
        rows = [{"t": i * 0.01, "prompt_len": 2 + (i == 9) * 18,
                 "budget": 2, "tokens": 2} for i in range(10)]
        plan = warp_plan(rows, seed=3, long_context_skew=1.0)
        assert all(e["prompt_len"] == 20 for e in plan)
        plain = warp_plan(rows, seed=3, long_context_skew=0.0)
        assert sum(e["prompt_len"] == 20 for e in plain) == 1


class TestOpenLoopReplay:
    def test_scripted_poster_full_fidelity(self):
        rows = [{"t": i * 0.005, "tenant": "aa", "prompt_len": 3,
                 "budget": 4, "tokens": 4} for i in range(10)]
        plan = warp_plan(rows)
        seen = []

        def poster(entry, payload):
            seen.append((entry["tenant"], len(payload["tokens"]),
                         payload["n_tokens"]))
            return 200, payload["n_tokens"]

        summary = replay(plan, poster=poster, workers=4)
        assert summary["requests"] == summary["completed"] == 10
        assert summary["delivered_ratio"] == 1.0
        assert summary["errors"] == 0
        assert len(seen) == 10
        assert all(t == "aa" and n == 3 and b == 4 for t, n, b in seen)

    def test_sheds_and_errors_are_booked_separately(self):
        rows = [{"t": i * 0.002, "prompt_len": 2, "budget": 2,
                 "tokens": 2} for i in range(9)]
        plan = warp_plan(rows)
        statuses = iter([200, 429, 503, 200, 400, -1, 200, 200, 200])

        def poster(entry, payload):
            status = next(statuses)
            if status == -1:
                raise OSError("connection refused")
            return status, payload["n_tokens"] if status == 200 else 0

        summary = replay(plan, poster=poster, workers=1)
        assert summary["completed"] == 5
        assert summary["shed"] == 2
        assert summary["errors"] == 2
        assert summary["availability"] == pytest.approx(5 / 9.0)

    def test_arrivals_are_open_loop_not_response_paced(self):
        """A 60ms-slow endpoint must NOT stretch a ~40ms schedule to
        ~600ms: arrivals keep releasing on the recorded cadence."""
        rows = [{"t": i * 0.004, "prompt_len": 2, "budget": 2,
                 "tokens": 2} for i in range(10)]
        plan = warp_plan(rows)
        arrivals = []
        t0 = time.monotonic()

        def poster(entry, payload):
            arrivals.append(time.monotonic() - t0)
            time.sleep(0.06)
            return 200, 2

        replay(plan, poster=poster, workers=10)
        assert max(arrivals) - min(arrivals) < 0.3


class TestCapacityController:
    def _rows(self):
        return [{"t": i * 0.01, "tenant": "aa", "prompt_len": 3,
                 "budget": 4, "tokens": 4} for i in range(8)]

    def _scripted(self, cliff):
        """An endpoint that sustains below ``cliff`` and breaches
        availability at/above it."""

        def runner(warp):
            return {"requests": 8,
                    "availability": 1.0 if warp < cliff else 0.5,
                    "tokens_per_sec": min(warp, cliff) * 100.0,
                    "schedule_skew_ms_p95": 1.5,
                    "request_wall_ms_p95": 4.0}

        return runner

    def test_escalates_until_breach_then_backs_off(self):
        finder = CapacityFinder(self._rows(), start_warp=1.0,
                                warp_step=2.0, max_warp=32.0,
                                refine_steps=2,
                                runner=self._scripted(4.0))
        doc = finder.run()
        warps = [e["warp"] for e in finder.escalation]
        phases = [e["phase"] for e in finder.escalation]
        assert warps[:3] == [1.0, 2.0, 4.0]
        assert finder.escalation[2]["breached"]
        # backoff: every post-breach probe bisects BELOW the breach
        assert phases[3:] == ["refine"] * len(phases[3:])
        assert all(2.0 < w < 4.0 for w in warps[3:])
        assert doc["breached"] is True
        assert doc["keys"]["capacity_cliff_warp_x"] <= 4.0
        assert 2.0 <= doc["keys"]["capacity_sustained_warp_x"] < 4.0
        assert doc["keys"]["capacity_sustained_tokens_per_sec"] > 200.0
        assert doc["breach"]["detail"]["objective"] == "availability"
        assert doc["breach"]["first_breaching_series"] \
            == "replay_availability"

    def test_no_breach_reports_max_warp_sustained(self):
        finder = CapacityFinder(self._rows(), start_warp=1.0,
                                warp_step=2.0, max_warp=4.0,
                                runner=self._scripted(1000.0))
        doc = finder.run()
        assert doc["breached"] is False
        assert doc["breach"] is None
        assert doc["keys"]["capacity_sustained_warp_x"] == 4.0
        text = render_capacity_report(doc)
        assert "no breach up to x4.00" in text

    def test_report_artifact_and_rendering(self, tmp_path):
        finder = CapacityFinder(self._rows(), start_warp=1.0,
                                warp_step=2.0, max_warp=16.0,
                                refine_steps=1,
                                runner=self._scripted(8.0))
        doc = finder.run()
        path = str(tmp_path / "cap.json")
        write_capacity_report(doc, path)
        saved = json.loads(open(path).read())
        assert saved["kind"] == "veles-capacity-report"
        assert saved["keys"] == doc["keys"]
        import hashlib
        digest = hashlib.sha256(open(path, "rb").read()).hexdigest()
        assert open(path + ".sha256").read().split()[0] == digest
        text = render_capacity_report(doc)
        assert "sustains" in text and "BREACH" in text
        assert "first-breaching series: replay_availability" in text

    def test_mix_rides_the_report(self):
        rows = self._rows() + [{"t": 0.09, "tenant": "bb",
                                "prompt_len": 3, "budget": 4,
                                "tokens": 4}]
        finder = CapacityFinder(rows, runner=self._scripted(2.0),
                                start_warp=1.0, warp_step=2.0,
                                refine_steps=0)
        doc = finder.run()
        assert doc["mix"]["tenants"] == tenant_mix(rows)
        assert doc["mix"]["requests"] == 9


class TestRecordedChaosProfile:
    def test_trace_becomes_deterministic_chaos_traffic(self, tmp_path):
        from veles_tpu.serving_chaos import RecordedTrafficProfile

        ledger = make_ledger(10)
        path = str(tmp_path / "t.jsonl")
        record_trace(ledger, path)
        profile = RecordedTrafficProfile(path, warp=4.0, seed=5,
                                         burst_compress=0.3)
        again = RecordedTrafficProfile(path, warp=4.0, seed=5,
                                       burst_compress=0.3)
        assert profile.fingerprint() == again.fingerprint()
        mix = profile.expected_mix()
        assert sum(mix.values()) == pytest.approx(1.0, abs=0.01)
        hits = []
        summary = profile.drive(
            poster=lambda e, p: (hits.append(e["tenant"]) or
                                 (200, p["n_tokens"])),
            workers=4)
        assert summary["completed"] == 10
        observed = {t: hits.count(t) / float(len(hits))
                    for t in set(hits)}
        assert observed == pytest.approx(mix, abs=0.01)


# -- the live-endpoint acceptance (slow tier; `make replay` runs it) --------

@pytest.fixture(scope="module")
def model():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads, vocab


@pytest.fixture
def registry():
    from veles_tpu.observe.metrics import get_metrics_registry

    reg = get_metrics_registry()
    was = reg.enabled
    reg.reset()
    reg.enable()
    yield reg
    reg.reset()
    reg.enabled = was


@pytest.fixture
def fast_history(registry, tmp_path):
    """A fast-sampling process history with ONLY the slo_burn rule, so
    the incident handoff's first-breaching series is unambiguous."""
    from veles_tpu.observe.history import (AnomalyRule,
                                           IncidentRecorder,
                                           MetricHistory,
                                           get_metric_history,
                                           set_metric_history)

    history = MetricHistory(
        registry=registry, interval_s=0.05, capacity=512,
        series_cap=128,
        rules=[AnomalyRule("slo_burn", "veles_slo_burn_rate",
                           kind="threshold", op=">=", threshold=1.0,
                           for_samples=1)],
        incidents=IncidentRecorder(cooldown_s=0.0,
                                   directory=str(tmp_path)))
    previous = get_metric_history()
    set_metric_history(history)
    try:
        yield history
    finally:
        set_metric_history(previous)


def _post(url, payload, tenant=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **({"X-Veles-Tenant": tenant} if tenant else {})))
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read().decode())


@pytest.mark.slow
class TestCapacityE2E:
    def test_record_replay_capacity_names_breaching_series(
            self, model, registry, fast_history, tmp_path):
        """The acceptance: record a mixed-tenant run off a live
        surface via the CLI, escalate warp until the (deliberately
        tight) SLO burns, and the report artifact states sustained
        tokens/sec at the recorded mix AND names the first-breaching
        series via the incident autopsy."""
        from veles_tpu.observe.history import start_history_sampler
        from veles_tpu.observe.reqledger import RequestLedger
        from veles_tpu.observe.slo import SLOEngine, parse_objectives
        from veles_tpu.observe.trace_export import main as observe_main
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model
        # a ttft objective tight enough that queueing at high warp
        # (10 arrivals compressed onto 2 slots) is certain to burn it
        slo = SLOEngine(parse_objectives("ttft_p95_ms=20"))
        ledger = RequestLedger()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0, ledger=ledger,
                          slo=slo)
        api.start()
        start_history_sampler()
        trace_path = str(tmp_path / "live.trace.jsonl")
        report_path = str(tmp_path / "live.capacity.json")
        try:
            base = "http://127.0.0.1:%d" % api.port
            url = base + "/generate"
            for i in range(10):
                _post(url, {"tokens": [1 + i % 5] * (3 + i % 3),
                            "n_tokens": 3},
                      tenant="acme" if i % 2 else "globex")
                time.sleep(0.05)
            # the CLI round trip: record --live, then capacity --live
            assert observe_main(["record", "--live", base,
                                 "-o", trace_path]) == 0
            header, rows = load_trace(trace_path)
            assert header["count"] == 10
            assert len({r["tenant"] for r in rows}) == 2
            assert observe_main([
                "capacity", trace_path, "--live", base,
                "-o", report_path, "--start-warp", "1",
                "--warp-step", "4", "--max-warp", "64",
                "--refine-steps", "0", "--workers", "8",
                "--availability", "0.999",
                "--vocab", str(vocab)]) == 0
            doc = json.loads(open(report_path).read())
            assert doc["kind"] == "veles-capacity-report"
            assert doc["escalation"], "controller never probed"
            assert set(doc["mix"]["tenants"]) \
                == {r["tenant"] for r in rows}
            # between the 20ms ttft burn and the 0.999 availability
            # floor, warp x64 onto 2 slots MUST breach something
            assert doc["breached"] is True
            breach = doc["breach"]
            assert breach["first_breaching_series"] in (
                "veles_slo_burn_rate", "replay_availability")
            if breach["first_breaching_rule"]:
                # the incident autopsy claimed the leading indicator:
                # the only rule wired into this history is slo_burn
                assert breach["first_breaching_rule"] == "slo_burn"
                assert breach["first_breaching_series"] \
                    == "veles_slo_burn_rate"
            assert doc["keys"]["capacity_cliff_warp_x"] >= 1.0
            text = render_capacity_report(doc)
            assert "first-breaching series:" in text
            assert "sustains" in text or "no breach" in text
        finally:
            api.stop()

    def test_replay_cli_against_live_endpoint(self, model, registry,
                                              tmp_path):
        """``observe replay`` at 1x against a fresh surface delivers
        full fidelity and holds its schedule."""
        from veles_tpu.observe.reqledger import RequestLedger
        from veles_tpu.observe.trace_export import main as observe_main
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model

        def serve():
            return GenerateAPI(params, table, heads, slots=2,
                               max_len=32, n_tokens=4, chunk=2,
                               port=0, ledger=RequestLedger())

        api = serve()
        api.start()
        trace_path = str(tmp_path / "t.jsonl")
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            for i in range(6):
                _post(url, {"tokens": [1, 2, 3], "n_tokens": 3},
                      tenant="acme")
                time.sleep(0.03)
            record_trace(api.ledger, trace_path)
        finally:
            api.stop()
        api = serve()
        api.start()
        try:
            base = "http://127.0.0.1:%d" % api.port
            assert observe_main(["replay", trace_path, "--live", base,
                                 "--vocab", str(vocab)]) == 0
            _, rows = load_trace(trace_path)
            plan = warp_plan(rows)
            summary = replay(plan, url=base, vocab=vocab, workers=4)
            assert summary["completed"] == 6
            assert summary["delivered_ratio"] == 1.0
            # the ledger counters the recorder depends on are live on
            # the endpoint's /metrics (the satellite contract)
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "veles_reqledger_staged_total" in text
            assert "veles_reqledger_ring_overflow_total" in text
        finally:
            api.stop()
