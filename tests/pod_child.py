"""One process of the two-process pod-parity test (NOT a pytest module).

Spawned by ``tests/test_pod_mode.py``: joins a 2-process jax.distributed
pod (1 CPU device each), runs the PRODUCT path — ``Launcher`` +
``MLPWorkflow`` with the mesh coming from ``root.common.mesh.axes`` —
and (process 0) dumps the final metrics + weights so the parent can
assert bit-for-bit parity with a single-process 2-device run.

Usage: python tests/pod_child.py PROC_ID NPROCS COORD_PORT OUT_JSON
"""

import json
import os
import sys
import tempfile

proc_id, nprocs, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                   sys.argv[3], sys.argv[4])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "xla_force_host_platform_device_count" not in f]
    + ["--xla_force_host_platform_device_count=1"])
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p)
os.environ.setdefault("VELES_TPU_HOME",
                      tempfile.mkdtemp(prefix="veles_pod_child_"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from veles_tpu.parallel.mesh import initialize_distributed  # noqa: E402

initialize_distributed("127.0.0.1:" + port, nprocs, proc_id)

import numpy  # noqa: E402

from veles_tpu.core import prng  # noqa: E402
from veles_tpu.core.config import root  # noqa: E402
from veles_tpu.launcher import Launcher  # noqa: E402
from veles_tpu.loader.base import VALID  # noqa: E402
from veles_tpu.models.mlp import MLPWorkflow  # noqa: E402

root.common.disable.plotting = True
root.common.disable.snapshotting = True
root.common.mesh.axes.data = 2  # the product pod-mode switch

prng.get("default").seed(4321)
prng.get("loader").seed(8765)

from dataset_fixtures import digits_dataset  # noqa: E402

X, y = digits_dataset()

launcher = Launcher()
wf = MLPWorkflow(
    launcher, layers=(32, 10),
    loader_kwargs=dict(data=X, labels=y,
                       class_lengths=[0, 297, 1500], minibatch_size=100,
                       normalization_type="linear"),
    learning_rate=0.1, max_epochs=3, name="pod-child")
launcher.initialize()
assert wf.fused_tick is not None and wf.fused_tick.mesh is not None, \
    "pod mode did not engage from config"
launcher.run()

if proc_id == 0:
    payload = {
        "best_n_err": int(wf.decision.best_n_err[VALID]),
        "epochs": int(wf.decision._epochs_done),
        "weights": [numpy.asarray(f.weights.data).tolist()
                    for f in wf.forwards],
    }
    with open(out_path, "w") as fout:
        json.dump(payload, fout)
jax.distributed.shutdown()
