"""Tests for the file/image loader pipeline (reference test_loader
image-loading coverage + VERDICT round-1 item 4)."""

import os

import numpy
import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.loader.file_loader import (AutoLabelMixin, FileFilter,
                                          FileListScannerMixin)
from veles_tpu.loader.image import (AutoLabelFileImageLoader,
                                    FileListImageLoader, crop_image,
                                    decode_image, scale_image)

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def write_png(path, color, size=(12, 12)):
    arr = numpy.zeros(size + (3,), numpy.uint8)
    arr[:, :] = color
    # distinguishing texture: a bright corner square
    arr[:3, :3] = 255
    Image.fromarray(arr).save(path)


@pytest.fixture
def image_tree(tmp_path):
    """<split>/<label>/<n>.png tree: red vs blue squares."""
    rng = numpy.random.RandomState(3)
    for split, count in (("train", 20), ("validation", 8)):
        for label, base in (("red", (200, 30, 30)), ("blue", (30, 30, 200))):
            d = tmp_path / split / label
            d.mkdir(parents=True)
            for i in range(count):
                jitter = rng.randint(-20, 20, 3)
                color = numpy.clip(numpy.array(base) + jitter, 0, 255)
                write_png(str(d / ("%02d.png" % i)), color)
    return tmp_path


class TestHelpers:
    def test_decode_scale_crop(self, tmp_path):
        p = str(tmp_path / "img.png")
        write_png(p, (10, 20, 30), size=(20, 10))
        arr = decode_image(p)
        assert arr.shape == (20, 10, 3)
        scaled = scale_image(arr, (8, 8))
        assert scaled.shape == (8, 8, 3)
        fitted = scale_image(arr, (8, 8), maintain_aspect_ratio=True,
                             background_color=0)
        assert fitted.shape == (8, 8, 3)
        # aspect preserved: 20x10 -> 8x4 centered, columns 0-1 background
        assert float(fitted[:, 0].max()) == 0.0
        cropped = crop_image(scaled, (4, 4), offset="center")
        assert cropped.shape == (4, 4, 3)

    def test_decode_gray(self, tmp_path):
        p = str(tmp_path / "img.png")
        write_png(p, (100, 100, 100))
        assert decode_image(p, "GRAY").shape == (12, 12, 1)

    def test_file_filter(self):
        f = FileFilter(file_type="image", file_subtypes=["png"],
                       ignored_files=[".*bad.*"])
        assert f.is_valid_filename("/data/x.png")
        assert not f.is_valid_filename("/data/x.jpg")
        assert not f.is_valid_filename("/data/bad.png")
        assert not f.is_valid_filename("/data/x.txt")

    def test_file_filter_alternatives_fully_anchored(self):
        # regression: '^a|b$' would anchor only the outer alternatives
        f = FileFilter(file_type="image", file_subtypes=["png"],
                       ignored_files=["junk.png", "bad.png"])
        assert f.is_valid_filename("junk.pngXXX.png")
        assert not f.is_valid_filename("junk.png")
        assert not f.is_valid_filename("bad.png")

    def test_fractional_crop(self, tmp_path):
        d = tmp_path / "c" / "lab"
        d.mkdir(parents=True)
        write_png(str(d / "0.png"), (90, 90, 90))
        loader = AutoLabelFileImageLoader(
            DummyWorkflow(), train_paths=[str(tmp_path / "c")],
            size=(12, 12), crop=(0.5, 0.5), minibatch_size=1)
        loader.initialize()
        assert loader.minibatch_data.shape == (1, 6, 6, 3)

    def test_auto_label(self):
        m = AutoLabelMixin()
        assert m.get_label_from_filename(
            os.path.join("data", "cats", "1.png")) == "cats"
        with pytest.raises(ValueError):
            m.get_label_from_filename("orphan.png")


class TestAutoLabelFileImageLoader:
    def make(self, tree, **kwargs):
        loader = AutoLabelFileImageLoader(
            DummyWorkflow(),
            train_paths=[str(tree / "train")],
            validation_paths=[str(tree / "validation")],
            size=(12, 12), minibatch_size=8, **kwargs)
        loader.initialize()
        return loader

    def test_scans_and_labels(self, image_tree):
        loader = self.make(image_tree)
        assert loader.class_lengths == [0, 16, 40]
        assert loader.labels_mapping == {"blue": 0, "red": 1}
        loader.run()
        assert loader.minibatch_data.shape == (8, 12, 12, 3)
        assert loader.minibatch_class == VALID

    def test_crop(self, image_tree):
        loader = self.make(image_tree, crop=(8, 8))
        assert loader.minibatch_data.shape[1:] == (8, 8, 3)

    def test_mirror_augmentation_train_only(self, image_tree):
        loader = self.make(image_tree, mirror="random")
        assert loader.has_fill_transforms
        # drain validation (not augmented)
        loader.run()
        valid_batch = numpy.asarray(loader.minibatch_data.mem)
        idx = numpy.asarray(loader.minibatch_indices.mem)
        raw = numpy.asarray(loader.original_data.mem)[idx]
        numpy.testing.assert_array_equal(valid_batch, raw)
        loader.run()
        # train minibatches: some samples mirrored
        mirrored_any = False
        for _ in range(5):
            loader.run()
            if loader.minibatch_class != TRAIN:
                continue
            got = numpy.asarray(loader.minibatch_data.mem)
            idx = numpy.asarray(loader.minibatch_indices.mem)
            raw = numpy.asarray(loader.original_data.mem)[idx]
            flipped = raw[:, :, ::-1]
            for i in range(len(got)):
                if numpy.array_equal(got[i], flipped[i]) \
                        and not numpy.array_equal(got[i], raw[i]):
                    mirrored_any = True
        assert mirrored_any


class TestFileListImageLoader:
    def test_index_file(self, image_tree, tmp_path):
        index = tmp_path / "train.txt"
        lines = []
        for label in ("red", "blue"):
            d = image_tree / "train" / label
            for name in sorted(os.listdir(d)):
                lines.append("%s %s" % (d / name, label))
        index.write_text("\n".join(lines) + "\n")
        loader = FileListImageLoader(
            DummyWorkflow(), path_to_train_text_file=str(index),
            size=(12, 12), minibatch_size=10, validation_ratio=0.2)
        loader.initialize()
        assert loader.class_lengths == [0, 8, 32]
        assert set(loader.labels_mapping) == {"red", "blue"}

    def test_json_index(self, image_tree, tmp_path):
        d = image_tree / "train" / "red"
        entries = {
            name: {"path": str(d / name), "label": ["red"]}
            for name in sorted(os.listdir(d))}
        index = tmp_path / "train.json"
        import json
        index.write_text(json.dumps(entries))
        m = FileListScannerMixin()
        m.info = lambda *a: None
        m.warning = lambda *a: None
        files = m.scan_files(str(index))
        assert len(files) == 20
        assert m.get_label_from_filename(files[0]) == "red"


class TestImageMSE:
    def _tree(self, tmp_path, n=6, labeled=False):
        rng = numpy.random.RandomState(5)
        (tmp_path / "in").mkdir()
        (tmp_path / "targets").mkdir()
        for i in range(n):
            color = tuple(int(c) for c in rng.randint(0, 255, 3))
            write_png(str(tmp_path / "in" / ("s%02d.png" % i)), color)
            write_png(str(tmp_path / "targets" / ("t%02d.png" % i)),
                      tuple(255 - c for c in color))
        return tmp_path

    def test_unlabeled_pairs_by_sorted_order(self, tmp_path):
        """i-th sample <-> i-th sorted target (reference image_mse.py
        unlabeled contract); targets ride the device gather."""
        from veles_tpu.loader.image import FileImageLoaderMSE

        tree = self._tree(tmp_path)
        wf = DummyWorkflow()
        loader = FileImageLoaderMSE(
            wf, train_paths=[str(tree / "in")],
            target_paths=[str(tree / "targets")],
            size=(12, 12), minibatch_size=3,
            target_normalization_type="none")
        loader.initialize()
        assert loader.class_lengths == [0, 0, 6]
        assert loader.original_targets.shape == (6, 12, 12, 3)
        loader.run()
        assert loader.minibatch_targets.shape == (3, 12, 12, 3)
        # the served target rows match the stored per-sample targets
        idx = numpy.asarray(loader.minibatch_indices.data)[:3]
        numpy.testing.assert_allclose(
            numpy.asarray(loader.minibatch_targets.data),
            numpy.asarray(loader.original_targets.data)[idx])

    def test_labeled_maps_by_label(self, tmp_path):
        """Labeled datasets look targets up by label (target_label_map
        role); duplicate target labels are rejected."""
        from veles_tpu.loader.image import FileImageLoaderMSE

        tree = self._tree(tmp_path, n=4)

        class Labeled(FileImageLoaderMSE):
            def get_label_from_filename(self, filename):
                # s00/t00 -> 0 ... pairs by trailing number
                return int(os.path.basename(filename)[1:3]) % 4

        wf = DummyWorkflow()
        loader = Labeled(
            wf, train_paths=[str(tree / "in")],
            target_paths=[str(tree / "targets")],
            size=(8, 8), minibatch_size=2,
            target_normalization_type="none")
        loader.initialize()
        assert loader.original_targets.shape == (4, 8, 8, 3)
        # sample i carries label i -> target row must be target t0i
        t2 = decode_image(str(tree / "targets" / "t02.png"))
        t2 = scale_image(t2, (8, 8))
        numpy.testing.assert_allclose(
            numpy.asarray(loader.original_targets.data)[2], t2)

    def test_count_mismatch_rejected(self, tmp_path):
        from veles_tpu.loader.image import FileImageLoaderMSE

        tree = self._tree(tmp_path)
        os.unlink(str(tree / "targets" / "t05.png"))
        wf = DummyWorkflow()
        loader = FileImageLoaderMSE(
            wf, train_paths=[str(tree / "in")],
            target_paths=[str(tree / "targets")],
            size=(12, 12), minibatch_size=3,
            target_normalization_type="none")
        with pytest.raises(ValueError):
            loader.initialize()


@pytest.mark.slow
class TestConvnetEndToEnd:
    def test_convnet_trains_through_image_pipeline(self, image_tree):
        """VERDICT round-1 item 4 'done' criterion: a CIFAR-style convnet
        trains end-to-end through the image pipeline."""
        from veles_tpu.models.standard import StandardWorkflow

        wf = StandardWorkflow(
            DummyLauncher(),
            loader_cls=AutoLabelFileImageLoader,
            loader_kwargs=dict(
                train_paths=[str(image_tree / "train")],
                validation_paths=[str(image_tree / "validation")],
                size=(12, 12), minibatch_size=8,
                normalization_type="internal_mean"),
            layers=[
                {"type": "conv_relu", "n_kernels": 8, "kx": 3, "ky": 3},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 2},
            ],
            learning_rate=0.02,
            decision_kwargs=dict(max_epochs=6), name="image-convnet")
        wf.initialize()
        wf.run()
        best = wf.decision.best_n_err[1]
        assert best is not None and best <= 4, \
            "convnet at %s/16 validation errors" % best


class TestFusedAugmentation:
    """In-jit mirror augmentation ON the fused path: the tick applies
    the loader's transform itself, seeded identically to graph mode."""

    def _build(self, image_tree, fused):
        from veles_tpu.core import prng
        from veles_tpu.models.standard import StandardWorkflow

        prng.get("default").seed(42)
        prng.get("loader").seed(24)
        return StandardWorkflow(
            DummyLauncher(),
            loader_cls=AutoLabelFileImageLoader,
            loader_kwargs=dict(
                train_paths=[str(image_tree / "train")],
                validation_paths=[str(image_tree / "validation")],
                size=(12, 12), minibatch_size=8, mirror="random",
                normalization_type="internal_mean"),
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 2},
            ],
            learning_rate=0.05, fused=fused,
            decision_kwargs=dict(max_epochs=3), name="aug-fused")

    def test_mirror_loader_fuses_and_matches_graph_mode(self, image_tree):
        """If the fused tick silently dropped the augmentation, the
        graph run (which DOES augment) would diverge — this identity IS
        the dead-augmentation guard."""
        graph = self._build(image_tree, fused=False)
        graph.initialize()
        assert graph.fused_tick is None, "fused=False must not splice"
        graph.run()

        fused = self._build(image_tree, fused=True)
        fused.initialize()
        assert fused.fused_tick is not None, \
            "mirror loader must fuse now (jit_transform)"
        fused.run()
        # identical seeds -> identical augmentation -> identical metrics
        assert fused.decision.best_n_err[1] == graph.decision.best_n_err[1]
        assert fused.decision.last_epoch_n_err == \
            graph.decision.last_epoch_n_err
        numpy.testing.assert_allclose(
            numpy.asarray(fused.forwards[0].weights.data),
            numpy.asarray(graph.forwards[0].weights.data), atol=2e-2)

    def test_shift_transform_fused_matches_graph(self):
        """train_transform="shift1" on a plain FullBatchLoader: the
        fused tick replicates the shift in-jit (same seeds), so both
        engines land identical metrics — the dead-augmentation guard
        for the second transform."""
        from veles_tpu.core import prng
        from veles_tpu.models.standard import StandardWorkflow

        rng = numpy.random.RandomState(3)
        data = rng.rand(120, 8, 8, 1).astype(numpy.float32)
        labels = rng.randint(0, 4, 120).astype(numpy.int32)

        def build(fused):
            prng.get("default").seed(42)
            prng.get("loader").seed(24)
            return StandardWorkflow(
                DummyLauncher(),
                loader_kwargs=dict(
                    data=data, labels=labels,
                    class_lengths=[0, 40, 80], minibatch_size=20,
                    train_transform="shift1",
                    normalization_type="none"),
                layers=[
                    {"type": "all2all_tanh", "output_sample_shape": 16},
                    {"type": "softmax", "output_sample_shape": 4},
                ],
                learning_rate=0.05, fused=fused,
                decision_kwargs=dict(max_epochs=3), name="shift-fused")

        graph = build(False)
        graph.initialize()
        assert graph.fused_tick is None
        graph.run()
        fused = build(True)
        fused.initialize()
        assert fused.fused_tick is not None, \
            "shift1 loader must fuse (jit_transform)"
        fused.run()
        assert fused.decision.best_n_err[1] == graph.decision.best_n_err[1]
        numpy.testing.assert_allclose(
            numpy.asarray(fused.forwards[0].weights.data),
            numpy.asarray(graph.forwards[0].weights.data), atol=2e-2)

    def test_shift_batch_semantics(self):
        """shift_batch: every output sample is a zero-filled integer
        translation of its input within +-max_shift."""
        from veles_tpu.ops.augment import shift_batch

        rng = numpy.random.RandomState(1)
        batch = rng.rand(12, 5, 7, 2).astype(numpy.float32) + 1.0
        out = numpy.asarray(shift_batch(batch, 11, max_shift=1))

        def shifted(img, dh, dw):
            ref = numpy.zeros_like(img)
            hs = slice(max(dh, 0), img.shape[0] + min(dh, 0))
            ws = slice(max(dw, 0), img.shape[1] + min(dw, 0))
            hsrc = slice(max(-dh, 0), img.shape[0] + min(-dh, 0))
            wsrc = slice(max(-dw, 0), img.shape[1] + min(-dw, 0))
            ref[hs, ws] = img[hsrc, wsrc]
            return ref

        matched = 0
        moved = 0
        for i in range(len(batch)):
            candidates = [(dh, dw) for dh in (-1, 0, 1)
                          for dw in (-1, 0, 1)]
            hits = [(dh, dw) for dh, dw in candidates
                    if numpy.array_equal(out[i],
                                         shifted(batch[i], dh, dw))]
            assert hits, "sample %d is not any +-1 shift" % i
            matched += 1
            if (0, 0) not in hits:
                moved += 1
        assert matched == len(batch)
        assert moved > 0, "seeded shifts must actually move samples"
        numpy.testing.assert_array_equal(
            out, numpy.asarray(shift_batch(batch, 11, max_shift=1)))

    def test_shared_mirror_math(self):
        """Both engines trace ops.augment.mirror_batch: check its
        semantics directly — per-sample flip over the W axis, seeded."""
        from veles_tpu.ops.augment import mirror_batch

        rng = numpy.random.RandomState(0)
        batch = rng.rand(16, 4, 6, 3).astype(numpy.float32)
        out = numpy.asarray(mirror_batch(batch, 7))
        flipped = batch[:, :, ::-1]
        per_sample = [numpy.array_equal(out[i], flipped[i])
                      or numpy.array_equal(out[i], batch[i])
                      for i in range(16)]
        assert all(per_sample), "samples must be kept or W-flipped"
        n_flipped = sum(numpy.array_equal(out[i], flipped[i])
                        and not numpy.array_equal(out[i], batch[i])
                        for i in range(16))
        assert 0 < n_flipped < 16, "seeded bernoulli must mix"
        # deterministic per seed, different across seeds
        numpy.testing.assert_array_equal(
            out, numpy.asarray(mirror_batch(batch, 7)))
        assert not numpy.array_equal(
            out, numpy.asarray(mirror_batch(batch, 8)))
