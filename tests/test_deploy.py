"""Zero-downtime deploys: live hot-swap, blue-green rollout, and the
persistent executable cache (veles_tpu/serving.py, veles_tpu/rollout.py,
veles_tpu/aot/exec_cache.py; docs/zero_downtime.md).

Fast tier covers the swap seam (outputs change, rollback restores
bit-identically, poisoned checkpoints are refused with the old weights
still serving, zero 5xx across the swap window), the rollback
predicate's edge cases driven as a unit with explicit clocks (zero
green traffic, blue-baseline suppression, dwell hysteresis), and the
torn-cache discipline (truncated or tampered entries refuse loudly
once, unlink, and fall back to live compilation).

The ``slow``-marked chaos tier boots real engines: a seeded bad-green
ramp must auto-roll back naming the leading indicator in the incident
artifact with zero shed requests and blue streams bit-identical, a
clean green must promote, and the poisoned-swap profile must be
refused end to end.
"""

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import zlib

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.rollout import (BURN_SERIES, SWAP_SERIES, TTFT_SERIES,
                               BlueGreenRollout, RolloutConfig)

pytestmark = pytest.mark.deploy

HEADS, EMBED, VOCAB = 4, 16, 11


def _model():
    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, 2, EMBED, HEADS, VOCAB)
    table = jnp.asarray(rng.randn(VOCAB, EMBED).astype(numpy.float32) * 0.3)
    params2 = init_transformer_params(numpy.random.RandomState(99),
                                      2, EMBED, HEADS, VOCAB)
    return params, table, params2


def _post(url, payload, timeout=60, tenant=None):
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Veles-Tenant"] = tenant
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _pt(tenant):
    return (zlib.crc32(tenant.encode()) % 10000) / 10000.0


def _tenants():
    """A tenant hashing inside the 10%% green slice and one safely
    blue at that fraction."""
    green = next("t%d" % i for i in range(1000) if _pt("t%d" % i) < 0.1)
    blue = next("t%d" % i for i in range(1000) if _pt("t%d" % i) > 0.5)
    return green, blue


def _api(params, table, chaos=None):
    from veles_tpu.serving import GenerateAPI
    return GenerateAPI(params, table, HEADS, slots=2, max_len=32,
                       n_tokens=5, chunk=2, port=0, chaos=chaos)


def _poison(params):
    leaves, tree = jax.tree.flatten(params)
    leaves[0] = jnp.full_like(leaves[0], float("nan"))
    return jax.tree.unflatten(tree, leaves)


# -- live weight hot-swap ----------------------------------------------------

class TestHotSwap:

    def test_swap_rollback_and_poison_refusal(self):
        """The full seam in one boot: a swap changes outputs, rollback
        restores the old weights bit-identically, and a NaN-poisoned
        checkpoint is refused with the old weights still serving."""
        params, table, params2 = _model()
        api = _api(params, table)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            r1 = _post(url, {"tokens": [1, 2, 3]})
            assert api.swap_params(params2, version="v2") is True
            assert api.version == "v2"
            assert api.health.counter("param_swaps") == 1
            hz = json.loads(urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % api.port,
                timeout=30).read().decode())
            assert hz["version"] == "v2"
            assert "rollout" not in hz
            r2 = _post(url, {"tokens": [1, 2, 3]})
            assert r1["tokens"] != r2["tokens"], "swap must change outputs"

            api.rollback_swap()
            r3 = _post(url, {"tokens": [1, 2, 3]})
            assert r3["tokens"] == r1["tokens"], \
                "rollback must restore the old weights bit-identically"

            with pytest.raises(RuntimeError, match="non-finite"):
                api.swap_params(_poison(params2), version="poison")
            r4 = _post(url, {"tokens": [1, 2, 3]})
            assert r4["tokens"] == r1["tokens"], \
                "old weights must keep serving after a refused swap"
            assert api.health.counter("swap_failures") == 1
        finally:
            api.stop()

    def test_zero_5xx_across_swap_window(self):
        """A client hammering /generate through the drain-then-swap
        window sees only 200s — the seam holds requests, it never
        sheds them."""
        params, table, params2 = _model()
        api = _api(params, table)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            _post(url, {"tokens": [1, 2]})  # warm the decode programs
            codes, errors, stop = [], [], threading.Event()

            def pound():
                while not stop.is_set():
                    try:
                        _post(url, {"tokens": [2, 3]}, timeout=30)
                        codes.append(200)
                    except urllib.error.HTTPError as exc:
                        codes.append(exc.code)
                    except Exception as exc:  # noqa: BLE001
                        errors.append(exc)

            thread = threading.Thread(target=pound)
            thread.start()
            try:
                time.sleep(0.2)
                assert api.swap_params(params2, version="v2") is True
            finally:
                stop.set()
                thread.join(60)
            assert not errors, errors
            assert codes and all(code == 200 for code in codes), \
                "shed requests across the swap window: %r" % (
                    [c for c in codes if c != 200],)
        finally:
            api.stop()


# -- rollback predicate edge cases (unit, explicit clock) --------------------

class _RecordingGovernor:
    def __init__(self):
        self.notes = []

    def note_deploy(self, action, api, reason="", **attrs):
        self.notes.append((action, reason, attrs))

    def actions(self):
        return [action for action, _, _ in self.notes]


class _FakeApi:
    slo = None

    def __init__(self):
        self.governor = _RecordingGovernor()


@pytest.fixture()
def no_history():
    """Detach the process metric history so predicate units neither
    read nor write ambient detector state."""
    from veles_tpu.observe.history import (get_metric_history,
                                           set_metric_history)
    previous = get_metric_history()
    set_metric_history(None)
    try:
        yield
    finally:
        set_metric_history(previous)


class TestRollbackPredicate:

    def test_zero_green_traffic_yields_no_verdict(self, no_history):
        """An idle green slice neither rolls back nor advances the
        ladder — and it resets the breach streak."""
        cfg = RolloutConfig(steps=(0.1, 1.0), hold_s=100.0,
                            cooldown_s=100.0, window_s=60.0,
                            min_requests=4, interval_s=0.01)
        rollout = BlueGreenRollout("v2", config=cfg)
        api = _FakeApi()
        rollout._breaches = 1  # stale breach from a busier rung
        for _ in range(10):
            rollout.note_resolved("blue", True, now=99.0)
        rollout.note_resolved("green", False, now=99.0)  # < min_requests
        rollout.tick(api, now=100.0)
        assert rollout.state == "shifting"
        assert rollout.step_index == 0
        assert rollout._breaches == 0
        assert "deploy_rollback" not in api.governor.actions()

    def test_blue_baseline_burning_suppresses_rollback(self, no_history):
        """When blue burns past the veto the regression is ambient:
        no rollback, a cooldown-limited suppression note instead."""
        cfg = RolloutConfig(steps=(0.1, 1.0), hold_s=100.0,
                            cooldown_s=1.0, window_s=60.0,
                            min_requests=2, burn_ratio=2.0,
                            burn_floor=0.01, blue_burn_veto=5.0,
                            breach_for=1, interval_s=0.01)
        rollout = BlueGreenRollout("v2", config=cfg)
        api = _FakeApi()
        for _ in range(10):
            rollout.note_resolved("green", False, now=100.0)
        for i in range(10):
            rollout.note_resolved("blue", i % 2 == 0, now=100.0)
        # green burn 100x, blue burn 50x: green IS worse by ratio, but
        # blue's own burn is far past the veto
        rollout.tick(api, now=100.5)
        assert rollout.state == "shifting"
        assert rollout.suppressed_total == 1
        actions = api.governor.actions()
        assert "deploy_rollback" not in actions
        assert actions.count("deploy_rollback_suppressed") == 1
        _, reason, attrs = next(
            note for note in api.governor.notes
            if note[0] == "deploy_rollback_suppressed")
        assert "blue baseline burning" in reason
        assert attrs["blue_burn"] >= cfg.blue_burn_veto
        # within the cooldown: suppression counts, but no second note
        rollout.tick(api, now=100.6)
        assert rollout.suppressed_total == 2
        assert api.governor.actions().count(
            "deploy_rollback_suppressed") == 1
        # past the cooldown the note fires again
        rollout.tick(api, now=102.0)
        assert api.governor.actions().count(
            "deploy_rollback_suppressed") == 2

    def test_breach_streak_hysteresis(self, no_history):
        """One bad window does not roll back when breach_for=2; a
        second consecutive one does, naming the plane."""
        cfg = RolloutConfig(steps=(0.1, 1.0), hold_s=100.0,
                            cooldown_s=0.1, window_s=60.0,
                            min_requests=2, burn_ratio=2.0,
                            burn_floor=0.01, blue_burn_veto=1000.0,
                            breach_for=2, interval_s=0.01)
        rollout = BlueGreenRollout("v2", config=cfg)
        api = _FakeApi()
        for _ in range(10):
            rollout.note_resolved("green", False, now=100.0)
            rollout.note_resolved("blue", True, now=100.0)
        rollout.tick(api, now=100.5)
        assert rollout.state == "shifting"
        assert rollout._breaches == 1
        rollout.tick(api, now=100.6)
        assert rollout.state == "rolling_back"
        assert "burn" in rollout.reason
        assert "deploy_rollback" in api.governor.actions()

    def test_dwell_hysteresis_prevents_oscillation(self, no_history):
        """Clean ticks advance the ladder at most once per
        max(hold_s, cooldown_s) dwell — rapid ticking cannot sprint
        to full traffic."""
        cfg = RolloutConfig(steps=(0.1, 0.5, 1.0), hold_s=10.0,
                            cooldown_s=10.0, window_s=60.0,
                            min_requests=2, interval_s=0.01)
        rollout = BlueGreenRollout("v2", config=cfg)
        api = _FakeApi()

        def feed(now):
            for _ in range(6):
                rollout.note_resolved("green", True, now=now)
                rollout.note_resolved("blue", True, now=now)

        feed(100.0)
        rollout.tick(api, now=100.0)  # anchors started_at/_last_shift
        for now in (101.0, 104.0, 109.0):
            rollout.tick(api, now=now)
        assert rollout.step_index == 0, "shifted before the dwell"
        feed(110.0)
        rollout.tick(api, now=110.5)
        assert rollout.step_index == 1
        rollout.tick(api, now=111.0)  # immediately after a shift
        assert rollout.step_index == 1, "oscillated inside the dwell"
        feed(121.0)
        rollout.tick(api, now=121.0)
        assert rollout.step_index == 2

    def test_routing_is_fixed_point_and_monotonic(self):
        """Raising the fraction only ADDS tenants to green; rollback
        sends everyone back to blue."""
        cfg = RolloutConfig(steps=(0.1, 0.5, 1.0))
        rollout = BlueGreenRollout("v2", config=cfg)
        tenants = ["t%d" % i for i in range(64)]
        greens = []
        for step in range(len(cfg.steps)):
            rollout.step_index = step
            greens.append({t for t in tenants if rollout.routes_green(t)})
        assert greens[0] <= greens[1] <= greens[2]
        assert greens[2] == set(tenants)
        rollout.state = "rolled_back"
        assert not any(rollout.routes_green(t) for t in tenants)


# -- persistent executable cache: torn-write discipline ----------------------

class TestExecCacheTornEntry:

    def _cache(self, tmp_path):
        from veles_tpu.aot.exec_cache import ExecutableCache
        return ExecutableCache(str(tmp_path / "xcache"))

    def _compiled(self):
        fn = jax.jit(lambda x: x * 2.0 + 1.0)
        return fn.lower(jnp.arange(4.0)).compile()

    def test_round_trip(self, tmp_path):
        cache = self._cache(tmp_path)
        assert cache.load("k") is None and cache.misses == 1
        assert cache.store("k", self._compiled()) is True
        loaded = cache.load("k")
        assert loaded is not None and cache.hits == 1
        expect = numpy.asarray(jnp.arange(4.0) * 2.0 + 1.0)
        numpy.testing.assert_allclose(
            numpy.asarray(loaded(jnp.arange(4.0))), expect)

    def test_torn_entry_refused_loudly_once_and_unlinked(
            self, tmp_path, caplog):
        """A truncated entry (sidecar intact) is rejected with ONE
        warning, unlinked so the next compile repairs it, and counted
        as a reject+miss — never executed."""
        from veles_tpu.serving_chaos import tear_file
        cache = self._cache(tmp_path)
        cache.store("k", self._compiled())
        path = cache._path("k")

        def _reject_records():
            return [r for r in caplog.records
                    if "refused" in r.getMessage()
                    and path in r.getMessage()]

        with caplog.at_level(logging.WARNING, logger="aot.ExecCache"):
            tear_file(path, frac=0.5)
            assert cache.load("k") is None
            assert cache.rejects == 1 and cache.misses == 1
            assert not (tmp_path / "xcache" / ("k" +
                        path.rsplit("k", 1)[-1])).exists()
            assert len(_reject_records()) == 1
            # the repaired-then-torn-again entry still refuses, but the
            # warning for this path already fired: warn-once holds
            cache.store("k", self._compiled())
            tear_file(path, frac=0.3)
            assert cache.load("k") is None
            assert cache.rejects == 2
            assert len(_reject_records()) == 1

    def test_tampered_entry_refused(self, tmp_path):
        """A bit-flip without a sidecar update fails the sha256 check."""
        cache = self._cache(tmp_path)
        cache.store("k", self._compiled())
        path = cache._path("k")
        with open(path, "rb+") as fobj:
            fobj.seek(-1, 2)
            last = fobj.read(1)
            fobj.seek(-1, 2)
            fobj.write(bytes([last[0] ^ 0xFF]))
        assert cache.load("k") is None
        assert cache.rejects == 1

    def test_missing_sidecar_refused(self, tmp_path):
        import os
        cache = self._cache(tmp_path)
        cache.store("k", self._compiled())
        os.remove(cache._path("k") + ".sha256")
        assert cache.load("k") is None
        assert cache.rejects == 1


# -- bench/regress contract --------------------------------------------------

class TestRegressDirections:

    def test_deploy_keys_are_lower_better(self):
        from veles_tpu.observe.regress import _lower_is_better
        assert _lower_is_better("coldstart_cached_to_first_token_ms")
        assert _lower_is_better("deploy_swap_shed_requests")
        assert _lower_is_better("deploy_swap_ms")

    def test_elastic_keys_directions(self):
        """The elastic bench keys (docs/elastic_serving.md): failover
        latency regresses UP; throughput, scale efficiency and the
        affinity hit rate regress DOWN (the higher-better default)."""
        from veles_tpu.observe.regress import _lower_is_better
        assert _lower_is_better("elastic_failover_ms")
        assert not _lower_is_better("elastic_tokens_per_sec_1replica")
        assert not _lower_is_better("elastic_tokens_per_sec_2replica")
        assert not _lower_is_better("elastic_scale_x")
        assert not _lower_is_better("elastic_affinity_hit_rate")


# -- the swap seam's reshard receipt (satellite: wire reshard into swap) -----

class TestSwapReshardSeam:

    def test_mesh_swap_is_slice_only_zero_wire_bytes(self):
        """The train->serve transition INSIDE the hot-swap seam: a
        host (train-layout) checkpoint swapped onto a live serve mesh
        must move 0 bytes on the wire — replicated -> sharded lowers
        to local slices, never a collective — and the swapped engine
        must stream bit-identically to a cold single-chip boot on the
        same checkpoint."""
        from veles_tpu.parallel.mesh import build_mesh
        from veles_tpu.serving import ContinuousDecoder
        # a mesh-divisible vocab (the tensor-parallel axis shards
        # heads/ffn/vocab; the module default VOCAB=11 cannot)
        vocab = 16
        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, 2, EMBED, HEADS, vocab)
        table = jnp.asarray(
            rng.randn(vocab, EMBED).astype(numpy.float32) * 0.3)
        params2 = init_transformer_params(numpy.random.RandomState(99),
                                          2, EMBED, HEADS, vocab)
        mesh = build_mesh(devices=jax.devices()[:4], data=1, model=4)
        dec = ContinuousDecoder(params, table, HEADS, slots=2,
                                max_len=32, n_tokens=5, mesh=mesh)
        assert dec.last_swap_stats is None
        dec.swap_params(params2)
        stats = dec.last_swap_stats
        assert stats is not None, \
            "a mesh swap must leave its reshard receipt"
        assert stats["bytes"] == 0, \
            "host checkpoint -> serve mesh must be slice-only " \
            "(0 wire bytes), got %r" % (stats,)
        assert set(stats["counts"]) <= {"slice", "keep"}, \
            stats["counts"]
        # bit-identity across the seam: the hot-swapped mesh engine
        # equals a cold single-chip engine on the same checkpoint
        single = ContinuousDecoder(params2, table, HEADS, slots=2,
                                   max_len=32, n_tokens=5)
        prompts = [[1, 2, 3], [4, 5, 6, 7], [2, 2]]
        for p in prompts:
            dec.submit(p)
            single.submit(p)
        dec.run_until_drained(chunk=2)
        single.run_until_drained(chunk=2)
        assert dec.results == single.results

    def test_single_chip_swap_leaves_no_receipt(self):
        params, table, params2 = _model()
        from veles_tpu.serving import ContinuousDecoder
        dec = ContinuousDecoder(params, table, HEADS, slots=2,
                                max_len=32, n_tokens=5)
        dec.swap_params(params2)
        assert dec.last_swap_stats is None


# -- the deploy rollout CLI verb (satellite: fetch+verify+begin_rollout) -----

class _RolloutRecorder:
    """The injectable ``api`` seam: a live-enough GenerateAPI stand-in
    whose decoder carries the real tree structure."""

    class _Decoder:
        def __init__(self, params, table):
            self.params = params
            self.embed_table = table

    def __init__(self, params, table, refuse=None):
        self.decoder = self._Decoder(params, table)
        self.calls = []
        self._refuse = refuse

    def begin_rollout(self, new_params, new_embed_table=None,
                      version="green", timeout=120.0):
        if self._refuse is not None:
            raise self._refuse
        self.calls.append({"version": version, "timeout": timeout,
                           "params": new_params,
                           "table": new_embed_table})


class TestDeployRolloutCLI:

    def _package(self, tmp_path, params, table, tamper=False,
                 weights=True):
        """A real packed package: manifest + sha-sidecar'd serving
        checkpoint (forge/package.py conventions)."""
        import hashlib
        import veles_tpu.forge.package as pkg
        from veles_tpu.deploy_cli import save_serving_checkpoint
        d = tmp_path / ("pkg_tampered" if tamper else "pkg")
        d.mkdir()
        (d / "wf.py").write_text("# serving checkpoint carrier\n")
        artifacts = []
        if weights:
            with open(d / "weights.npz", "wb") as fout:
                save_serving_checkpoint(fout, params, table)
            digest = hashlib.sha256(
                (d / "weights.npz").read_bytes()).hexdigest()
            if tamper:
                digest = "0" * 64
            (d / "weights.npz.sha256").write_text(
                "%s  weights.npz\n" % digest)
            artifacts = ["weights.npz"]
        (d / "manifest.json").write_text(json.dumps({
            "name": "toy-serve", "version": "2.0", "workflow": "wf.py",
            "artifacts": artifacts}))
        path, _ = pkg.pack(str(d))
        return path

    def test_exit_code_matrix(self, tmp_path, monkeypatch):
        import veles_tpu.serving as serving
        from veles_tpu.deploy_cli import (EXIT_OK, EXIT_PACKAGE,
                                          EXIT_ROLLOUT, EXIT_TAMPERED,
                                          main, rollout_package)
        import io as _io
        params, table, params2 = _model()
        path = self._package(tmp_path, params2, table)
        sink = _io.StringIO()

        # 0: resolve + verify + begin_rollout, stamped name@version
        api = _RolloutRecorder(params, table)
        assert rollout_package(path, api=api, out=sink) == EXIT_OK
        assert len(api.calls) == 1
        assert api.calls[0]["version"] == "toy-serve@2.0"
        got = jax.tree.leaves((api.calls[0]["params"],
                               api.calls[0]["table"]))
        want = jax.tree.leaves((params2, table))
        for a, b in zip(got, want):
            numpy.testing.assert_array_equal(numpy.asarray(a),
                                             numpy.asarray(b))

        # 2: unresolvable / malformed / missing-weights packages
        assert rollout_package(str(tmp_path / "absent.tar.gz"),
                               api=api, out=sink) == EXIT_PACKAGE
        garbage = tmp_path / "garbage.tar.gz"
        garbage.write_bytes(b"not a tarball")
        assert rollout_package(str(garbage), api=api,
                               out=sink) == EXIT_PACKAGE
        nw_dir = tmp_path / "nw"
        nw_dir.mkdir()
        no_weights = self._package(nw_dir, params2, table,
                                   weights=False)
        assert rollout_package(no_weights, api=api,
                               out=sink) == EXIT_PACKAGE

        # 2: checkpoint that cannot assemble against the live tree
        mismatched = _RolloutRecorder({"only": table}, table)
        assert rollout_package(path, api=mismatched,
                               out=sink) == EXIT_PACKAGE
        assert mismatched.calls == []

        # 3: tampered artifact refused before any weight byte parses
        bad = self._package(tmp_path, params2, table, tamper=True)
        assert rollout_package(bad, api=api, out=sink) == EXIT_TAMPERED

        # 4: no live serving api in this process
        monkeypatch.setattr(serving, "_CURRENT_API", None)
        assert rollout_package(path, api=None, out=sink) == EXIT_ROLLOUT

        # 4: the live api refuses the rollout (one already in flight)
        busy = _RolloutRecorder(
            params, table, refuse=RuntimeError("already in flight"))
        assert rollout_package(path, api=busy, out=sink) == EXIT_ROLLOUT

        # the CLI surface maps straight through
        assert main(["rollout", path, "--timeout", "5"],
                    api=_RolloutRecorder(params, table)) == EXIT_OK

    def test_checkpoint_roundtrip(self, tmp_path):
        import io as _io
        from veles_tpu.deploy_cli import (load_serving_checkpoint,
                                          save_serving_checkpoint)
        params, table, _ = _model()
        buf = _io.BytesIO()
        save_serving_checkpoint(buf, params, table)
        got_params, got_table = load_serving_checkpoint(
            buf.getvalue(), params, table)
        for a, b in zip(jax.tree.leaves((params, table)),
                        jax.tree.leaves((got_params, got_table))):
            numpy.testing.assert_array_equal(numpy.asarray(a),
                                             numpy.asarray(b))
        with pytest.raises(ValueError, match="leaves"):
            load_serving_checkpoint(buf.getvalue(), {"one": table},
                                    table)


# -- chaos deploy proof (slow tier) ------------------------------------------

@pytest.fixture()
def isolated_history(tmp_path, monkeypatch):
    """A private MetricHistory + incident recorder so the deploy
    detector rules and artifacts are observable without ambient serve
    rules claiming the leading indicator."""
    import veles_tpu.observe.servescope as servescope
    from veles_tpu.observe.history import (IncidentRecorder,
                                           MetricHistory,
                                           get_metric_history,
                                           set_metric_history)
    from veles_tpu.observe.metrics import MetricsRegistry
    monkeypatch.setattr(servescope, "MIN_EVAL_TOKENS", 10 ** 9)
    history = MetricHistory(
        registry=MetricsRegistry(enabled=True), interval_s=0.01,
        capacity=256, series_cap=64, rules=[],
        incidents=IncidentRecorder(cooldown_s=0.0,
                                   directory=str(tmp_path)))
    previous = get_metric_history()
    set_metric_history(history)
    try:
        yield history
    finally:
        set_metric_history(previous)


@pytest.mark.slow
class TestDeployChaos:

    def test_clean_green_promotes_with_blue_bit_identical(self):
        params, table, params2 = _model()
        green_t, blue_t = _tenants()
        api = _api(params, table)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            base_blue = _post(url, {"tokens": [1, 2, 3]}, tenant=blue_t)
            base_green = _post(url, {"tokens": [1, 2, 3]}, tenant=green_t)
            cfg = RolloutConfig(steps=(0.1, 1.0), hold_s=0.3,
                                cooldown_s=0.3, window_s=5.0,
                                min_requests=2, interval_s=0.05)
            rollout = api.begin_rollout(params2, version="v2", config=cfg)
            hz = json.loads(urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % api.port,
                timeout=30).read().decode())
            assert hz["rollout"]["version"] == "v2"
            assert hz["rollout"]["state"] == "shifting"
            g1 = _post(url, {"tokens": [1, 2, 3]}, tenant=green_t)
            b1 = _post(url, {"tokens": [1, 2, 3]}, tenant=blue_t)
            assert b1["tokens"] == base_blue["tokens"], \
                "blue streams must stay bit-identical during the ramp"
            assert g1["tokens"] != base_green["tokens"], \
                "green tenant should be on the new weights"
            deadline = time.time() + 120
            while rollout.state not in ("promoted", "rolled_back") \
                    and time.time() < deadline:
                _post(url, {"tokens": [2, 3]}, tenant=green_t)
                _post(url, {"tokens": [2, 3]}, tenant=blue_t)
                time.sleep(0.05)
            assert rollout.state == "promoted", rollout.snapshot()
            assert api.version == "v2"
            assert api.health.counter("promotes") == 1
            after = _post(url, {"tokens": [1, 2, 3]}, tenant=blue_t)
            assert after["tokens"] == g1["tokens"], \
                "after promote everyone serves v2"
        finally:
            api.stop()

    def test_bad_green_auto_rolls_back_naming_leading_indicator(
            self, isolated_history):
        """The seeded green-ramp chaos profile must trip the TTFT
        plane: auto-rollback with zero shed, blue bit-identical, and
        an incident artifact whose leading indicator names the green
        TTFT series."""
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)
        history = isolated_history
        params, table, params2 = _model()
        green_t, blue_t = _tenants()
        chaos = ServingChaosMonkey(ServingChaosConfig(
            deploy_green_ramp_ms=80.0, deploy_green_ramp_steps=3))
        api = _api(params, table, chaos=chaos)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            base_blue = _post(url, {"tokens": [1, 2, 3]}, tenant=blue_t)
            cfg = RolloutConfig(steps=(0.1, 1.0), hold_s=30.0,
                                cooldown_s=0.5, window_s=10.0,
                                min_requests=2, interval_s=0.05,
                                ttft_ratio=1.5, ttft_floor_s=0.01,
                                breach_for=2)
            rollout = api.begin_rollout(params2, version="v2", config=cfg)
            deadline = time.time() + 120
            shed = 0
            while rollout.state not in ("promoted", "rolled_back") \
                    and time.time() < deadline:
                for tenant in (green_t, blue_t):
                    try:
                        _post(url, {"tokens": [2, 3]}, tenant=tenant)
                    except urllib.error.HTTPError:
                        shed += 1
            assert rollout.state == "rolled_back", rollout.snapshot()
            assert "ttft" in (rollout.reason or ""), rollout.reason
            assert shed == 0, "zero-shed contract violated: %d" % shed
            assert api.health.counter("rollbacks") == 1
            assert chaos.counters.get("green_ramp_stalls", 0) > 0
            after = _post(url, {"tokens": [1, 2, 3]}, tenant=blue_t)
            assert after["tokens"] == base_blue["tokens"], \
                "blue streams must stay bit-identical across the rollback"
            doc = history.incidents.last_doc
            assert doc is not None, "rollback must cut an incident artifact"
            leading = doc["leading_indicator"]
            assert leading["series"] == TTFT_SERIES, leading
            assert history.incidents.last_path is not None
        finally:
            api.stop()

    def test_poisoned_swap_profile_refused_with_artifact(
            self, isolated_history):
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)
        history = isolated_history
        params, table, params2 = _model()
        chaos = ServingChaosMonkey(ServingChaosConfig(
            deploy_poison_nan=True))
        api = _api(params, table, chaos=chaos)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            r1 = _post(url, {"tokens": [1, 2, 3]})
            with pytest.raises(RuntimeError, match="non-finite"):
                api.swap_params(params2, version="v2")
            assert chaos.counters.get("poisoned_swaps") == 1
            assert api.health.counter("swap_failures") == 1
            r2 = _post(url, {"tokens": [1, 2, 3]})
            assert r2["tokens"] == r1["tokens"], \
                "old weights must keep serving after the refusal"
            doc = history.incidents.last_doc
            assert doc is not None
            assert doc["leading_indicator"]["series"] == SWAP_SERIES
        finally:
            api.stop()
