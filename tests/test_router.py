"""Elastic replicated serving: the fault-tolerant router front + the
replica control plane (veles_tpu/router.py, veles_tpu/fleet/
serve_plane.py; docs/elastic_serving.md).

Fast tier drives the router against a SCRIPTED transport (no real
replicas): consistent-hash affinity stability under replica churn,
pressure spill, the per-request lease's exactly-once fence
(half-stream failover, hedged double-delivery discard), Retry-After-
priced backoff, the honest all-down 503, and the real ``_http_post``
transport's half-stream EOF verdict against a socket that lies about
Content-Length. The control plane's leave-one-out collapse detector,
lifecycle actuations (drain/retire/dead/adopt, min_active
suppression), and the incident artifact NAMING the replica run as
units with explicit clocks and synthetic /healthz snapshots.

The ``slow``-marked chaos acceptance boots N real ``GenerateAPI``
subprocess replicas from one seed and kill -9s one mid-traffic: every
request must complete through failover with bit-identical greedy
tokens vs the fault-free run, zero non-retryable 5xx, and the
detector must name the dead replica in the ledger and the incident
artifact.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from veles_tpu.fleet.serve_plane import (COLLAPSE_RULE,
                                         FLEET_PRESSURE_SERIES,
                                         REPLICA_GOODPUT_SERIES,
                                         ServePlane, ServePlaneConfig)
from veles_tpu.router import (ElasticRouter, HashRing, RouterConfig,
                              _http_post, build_router, prefix_key)

pytestmark = pytest.mark.elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- harness -----------------------------------------------------------------

#: a healthy replica's /healthz, as the plane's fetch sees it
def healthy_snap(goodput=1.0, inflight=0, limit=8, pages_used=0,
                 pages_total=64):
    return {"servescope": {"goodput_fraction": goodput},
            "inflight": inflight,
            "governor": {"effective_limit": limit},
            "pool": {"pages_used": pages_used,
                     "pages_total": pages_total},
            "counters": {"completed": 0}}


class ScriptedTransport:
    """Attempt transport keyed by replica URL prefix: each behavior is
    ``fn(body, headers, timeout) -> (status, headers, payload)`` or
    raises (a transport failure, exactly like a dead socket)."""

    def __init__(self):
        self.behavior = {}
        self._lock = threading.Lock()
        self.calls = []

    def set(self, url, fn):
        self.behavior[url.rstrip("/")] = fn

    def __call__(self, url, body, headers, timeout):
        with self._lock:
            self.calls.append(url)
        for prefix, fn in self.behavior.items():
            if url.startswith(prefix):
                return fn(body, headers, timeout)
        raise ConnectionRefusedError("no behavior for %s" % url)


def ok_behavior(name):
    """Deterministic tokens from the prompt — IDENTICAL across
    replicas, like same-seed weights (the bit-identity contract)."""
    def fn(body, headers, timeout):
        tokens = json.loads(body.decode())["tokens"]
        out = [(sum(tokens) + i) % 97 for i in range(3)]
        return 200, {}, json.dumps({"tokens": out,
                                    "served_by": name}).encode()
    return fn


def busy_behavior(price):
    def fn(body, headers, timeout):
        return 429, {"Retry-After": str(price)}, b'{"error":"full"}'
    return fn


def dead_behavior(body, headers, timeout):
    raise ConnectionResetError("kill -9")


def make_plane(n=2, standby=0, fetch=None, **over):
    cfg = ServePlaneConfig(**dict({"poll_interval_s": 0.01,
                                   "cooldown_s": 0.0}, **over))
    replicas = ["http://127.0.0.1:%d" % (9000 + i) for i in range(n)]
    sb = ["http://127.0.0.1:%d" % (9500 + i) for i in range(standby)]
    return ServePlane(replicas, standby=sb, config=cfg,
                      fetch=fetch if fetch is not None
                      else (lambda url: healthy_snap()))


def make_router(plane, transport, **over):
    cfg = RouterConfig(**dict({"port": 0, "hedge_after_s": 5.0,
                               "backoff_s": 0.0, "page_size": 4},
                              **over))
    return ElasticRouter(plane, config=cfg, transport=transport)


def body_for(tokens):
    return json.dumps({"tokens": list(tokens)}).encode()


def wait_until(predicate, timeout=10.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(tick)
    return False


@pytest.fixture
def no_history():
    from veles_tpu.observe.history import (get_metric_history,
                                           set_metric_history)
    previous = get_metric_history()
    set_metric_history(None)
    try:
        yield
    finally:
        set_metric_history(previous)


@pytest.fixture
def isolated_history(tmp_path, monkeypatch):
    """A private MetricHistory + incident recorder so the collapse
    detector's rule and artifact are observable without ambient serve
    rules claiming the leading indicator."""
    import veles_tpu.observe.servescope as servescope
    from veles_tpu.observe.history import (IncidentRecorder,
                                           MetricHistory,
                                           get_metric_history,
                                           set_metric_history)
    from veles_tpu.observe.metrics import MetricsRegistry
    monkeypatch.setattr(servescope, "MIN_EVAL_TOKENS", 10 ** 9)
    history = MetricHistory(
        registry=MetricsRegistry(enabled=True), interval_s=0.01,
        capacity=256, series_cap=64, rules=[],
        incidents=IncidentRecorder(cooldown_s=0.0,
                                   directory=str(tmp_path)))
    previous = get_metric_history()
    set_metric_history(history)
    try:
        yield history
    finally:
        set_metric_history(previous)


# -- affinity: the consistent-hash ring + prefix key -------------------------

class TestAffinity:

    def keys(self, n=256):
        return [("key-%d" % i).encode() for i in range(n)]

    def test_ring_stable_under_replica_join(self):
        """Adding one replica must remap ONLY the keys the newcomer
        takes — every other prefix keeps its owner (the whole reason
        the cache hit rate survives churn)."""
        before = HashRing(["r-a", "r-b", "r-c"])
        after = HashRing(["r-a", "r-b", "r-c", "r-d"])
        moved = [k for k in self.keys()
                 if before.owners(k)[0] != after.owners(k)[0]
                 and after.owners(k)[0] != "r-d"]
        assert moved == []

    def test_ring_stable_under_replica_leave(self):
        """Removing a replica remaps only ITS keys; survivors' keys
        stay put."""
        before = HashRing(["r-a", "r-b", "r-c"])
        after = HashRing(["r-a", "r-b"])
        moved = [k for k in self.keys()
                 if before.owners(k)[0] != "r-c"
                 and before.owners(k)[0] != after.owners(k)[0]]
        assert moved == []

    def test_owners_order_distinct_and_complete(self):
        ring = HashRing(["r-a", "r-b", "r-c"])
        order = ring.owners(b"some-key")
        assert sorted(order) == ["r-a", "r-b", "r-c"]

    def test_empty_ring_owns_nothing(self):
        assert HashRing([]).owners(b"k") == []

    def test_prefix_key_page_aligned(self):
        """Only WHOLE pages are reusable: the key ignores the partial
        tail, and a sub-page prompt has no key at all (chase load, not
        affinity)."""
        assert prefix_key([1, 2, 3], page_size=4) is None
        base = prefix_key([1, 2, 3, 4], page_size=4)
        assert base is not None
        assert prefix_key([1, 2, 3, 4, 9], page_size=4) == base
        assert prefix_key([1, 2, 3, 4, 9, 9, 9], page_size=4) == base
        assert prefix_key([1, 2, 3, 5], page_size=4) != base

    def test_pick_prefers_affinity_primary(self, no_history):
        plane = make_plane(n=3)
        router = make_router(plane, ScriptedTransport())
        for rep in plane.replicas:
            rep.pressure = 0.0
        key = prefix_key([7, 7, 7, 7], page_size=4)
        ring = router._ring_for(r.name for r in plane.replicas)
        primary = ring.owners(key)[0]
        rep, is_primary = router._pick(key, set())
        assert rep.name == primary
        assert is_primary is True

    def test_pick_spills_over_pressure(self, no_history):
        """A primary owner above spill_pressure yields to the next
        ring owner — affinity is a preference, not a hot spot."""
        plane = make_plane(n=3)
        router = make_router(plane, ScriptedTransport(),
                             spill_pressure=0.9)
        key = prefix_key([7, 7, 7, 7], page_size=4)
        ring = router._ring_for(r.name for r in plane.replicas)
        order = ring.owners(key)
        for rep in plane.replicas:
            rep.pressure = 0.95 if rep.name == order[0] else 0.1
        rep, is_primary = router._pick(key, set())
        assert rep.name == order[1]
        assert is_primary is False

    def test_pick_without_key_chases_least_pressure(self, no_history):
        plane = make_plane(n=3)
        router = make_router(plane, ScriptedTransport())
        for rep, p in zip(plane.replicas, (0.8, 0.2, 0.5)):
            rep.pressure = p
        rep, is_primary = router._pick(None, set())
        assert rep is plane.replicas[1]
        assert is_primary is False

    def test_pick_skips_excluded_and_unroutable(self, no_history):
        plane = make_plane(n=3)
        router = make_router(plane, ScriptedTransport())
        plane.replicas[0].state = "draining"
        rep, _ = router._pick(None, {plane.replicas[1].name})
        assert rep is plane.replicas[2]
        rep, _ = router._pick(None, {plane.replicas[1].name,
                                     plane.replicas[2].name})
        assert rep is None


# -- the lease fence + failover machinery ------------------------------------

class TestLeaseFailover:

    def test_transport_death_fails_over_transparently(self, no_history):
        """A replica that dies mid-attempt (connection reset = the
        kill -9 verdict) fails its lease attempt; the next replica
        completes the SAME request."""
        plane = make_plane(n=2)
        transport = ScriptedTransport()
        transport.set(plane.replicas[0].url, dead_behavior)
        transport.set(plane.replicas[1].url, ok_behavior("r1"))
        router = make_router(plane, transport)
        # a sub-page prompt has no affinity key: the pick is by
        # (pressure, leases, name), so the DEAD replica goes first
        tokens = [1, 2, 3]
        lease = router.dispatch(tokens, body_for(tokens), {},
                                time.monotonic() + 30)
        assert lease.outcome is not None
        status, payload, replica = lease.outcome
        assert status == 200
        assert replica == plane.replicas[1].name
        assert json.loads(payload.decode())["served_by"] == "r1"
        assert router.counter("failovers") == 1
        assert lease.failure_count() == 1
        rep_name, kind, price = lease.failures[0]
        assert rep_name == plane.replicas[0].name
        assert kind.startswith("transport:")
        assert price is None
        assert wait_until(lambda: len(router.failover_ms_samples()) == 1)
        assert plane.replicas[0].failures == 1
        assert plane.replicas[1].failures == 0

    def test_busy_replica_prices_the_backoff(self, no_history):
        """A 429's Retry-After is the failed replica's own price: the
        retry backoff uses IT, not the blind base, and the busy
        verdict never trips the failure counter."""
        plane = make_plane(n=2)
        transport = ScriptedTransport()
        transport.set(plane.replicas[0].url, busy_behavior(3.5))
        transport.set(plane.replicas[1].url, busy_behavior(1.5))
        sleeps = []
        router = make_router(plane, transport, max_attempts=2)
        router._sleep = sleeps.append
        tokens = [5, 6, 7, 8]
        lease = router.dispatch(tokens, body_for(tokens), {},
                                time.monotonic() + 30)
        assert lease.outcome is None
        assert router.counter("retries") == 2
        assert router.counter("failovers") == 0
        # the backoff before attempt 2 uses attempt 1's OWN price
        assert sleeps and sleeps[0] == lease.failures[0][2]
        assert lease.last_price() == lease.failures[1][2]
        assert {f[2] for f in lease.failures} == {3.5, 1.5}
        assert plane.replicas[0].failures == 0, \
            "busy is not broken: 429 must not advance the death count"

    def test_hedged_double_delivery_is_fence_discarded(self,
                                                      no_history):
        """The exactly-once fence: a slow replica hedged past
        hedge_after_s loses the race; when it finally answers, its
        verdict is counted and DROPPED — never double-delivered."""
        plane = make_plane(n=2)
        release = threading.Event()
        slow_name = []

        def slow(body, headers, timeout):
            release.wait(10)
            return 200, {}, b'{"served_by": "slow", "tokens": [9]}'

        transport = ScriptedTransport()
        key = prefix_key([1, 2, 3, 4], page_size=4)
        ring = HashRing([r.name for r in plane.replicas])
        primary = ring.owners(key)[0]
        for rep in plane.replicas:
            if rep.name == primary:
                slow_name.append(rep.name)
                transport.set(rep.url, slow)
            else:
                transport.set(rep.url, ok_behavior("fast"))
        router = make_router(plane, transport, hedge_after_s=0.05)
        tokens = [1, 2, 3, 4]
        lease = router.dispatch(tokens, body_for(tokens), {},
                                time.monotonic() + 30)
        assert lease.outcome is not None
        assert lease.outcome[2] != slow_name[0], \
            "the hedge must win while the primary hangs"
        release.set()
        assert wait_until(lambda: router.counter("late_discards") == 1)
        assert lease.late == 1
        assert lease.outcome[2] != slow_name[0], \
            "the late answer must not overwrite the winner"

    def test_exhausted_replica_set_leaves_no_outcome(self, no_history):
        plane = make_plane(n=2)
        transport = ScriptedTransport()
        transport.set(plane.replicas[0].url, dead_behavior)
        transport.set(plane.replicas[1].url, dead_behavior)
        router = make_router(plane, transport)
        tokens = [1, 2, 3, 4]
        lease = router.dispatch(tokens, body_for(tokens), {},
                                time.monotonic() + 30)
        assert lease.outcome is None
        assert lease.failure_count() == 2
        assert {name for name, _, _ in lease.failures} == \
            {r.name for r in plane.replicas}

    def test_non_retryable_verdict_passes_through(self, no_history):
        """A replica 400 is a verdict about the REQUEST: no failover
        tour, the status relays as-is."""
        plane = make_plane(n=2)
        transport = ScriptedTransport()

        def reject(body, headers, timeout):
            return 400, {}, b'{"error":"bad tokens"}'

        transport.set(plane.replicas[0].url, reject)
        transport.set(plane.replicas[1].url, reject)
        router = make_router(plane, transport)
        tokens = [1, 2, 3, 4]
        lease = router.dispatch(tokens, body_for(tokens), {},
                                time.monotonic() + 30)
        assert lease.outcome is not None
        assert lease.outcome[0] == 400
        assert router.counter("failovers") == 0
        assert len(transport.calls) == 1


class TestHttpTransport:
    """The REAL attempt transport against sockets that misbehave."""

    def _serve_once(self, conn_script):
        """One-shot TCP server running ``conn_script(conn)`` on the
        first connection; returns the URL."""
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind(("127.0.0.1", 0))
        server.listen(1)
        port = server.getsockname()[1]

        def run():
            conn, _ = server.accept()
            try:
                conn.recv(65536)
                conn_script(conn)
            finally:
                conn.close()
                server.close()

        threading.Thread(target=run, daemon=True).start()
        return "http://127.0.0.1:%d" % port

    def test_half_stream_eof_raises(self):
        """A replica that dies mid-body (headers promised 1000 bytes,
        the socket delivered 10 and closed — the kill -9 shape) must
        RAISE, so the attempt fails over instead of delivering a
        truncated stream."""
        def half(conn):
            conn.sendall(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Length: 1000\r\n\r\n"
                         b"0123456789")

        url = self._serve_once(half)
        with pytest.raises(Exception):
            _http_post(url, b"{}", {}, timeout=10)

    def test_error_status_returns_as_verdict(self):
        """HTTP error statuses are replica VERDICTS, not transport
        failures: they return normally with headers intact."""
        def busy(conn):
            conn.sendall(b"HTTP/1.1 429 Too Many Requests\r\n"
                         b"Retry-After: 7\r\n"
                         b"Content-Length: 2\r\n\r\n{}")

        url = self._serve_once(busy)
        status, headers, payload = _http_post(url, b"{}", {},
                                              timeout=10)
        assert status == 429
        assert headers.get("Retry-After") == "7"
        assert payload == b"{}"


# -- the HTTP front ----------------------------------------------------------

def post_router(url, payload, headers=None):
    """POST returning (status, body_dict, headers) — error statuses
    included."""
    data = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        url + "/generate", data=data,
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode()), \
                dict(resp.headers)
    except urllib.error.HTTPError as err:
        with err:
            return err.code, json.loads(err.read().decode() or "{}"), \
                dict(err.headers or {})


class TestRouterFront:

    def _start(self, transport, n=2, plane_over=None, **cfg_over):
        plane = make_plane(n=n, **dict({"poll_interval_s": 30.0},
                                       **(plane_over or {})))
        router = make_router(plane, transport, **cfg_over)
        router.start()
        return plane, router, "http://127.0.0.1:%d" % router.port

    def test_routes_and_relays_with_replica_header(self, no_history):
        transport = ScriptedTransport()
        plane, router, url = self._start(transport)
        try:
            for rep in plane.replicas:
                transport.set(rep.url, ok_behavior(rep.name))
            status, body, headers = post_router(
                url, {"tokens": [1, 2, 3, 4]},
                headers={"X-Veles-Trace": "t-42"})
            assert status == 200
            assert body["tokens"] == [(10 + i) % 97 for i in range(3)]
            names = {r.name for r in plane.replicas}
            assert headers.get("X-Veles-Replica") in names
            assert body["served_by"] == headers["X-Veles-Replica"]
            assert headers.get("X-Veles-Trace") == "t-42"
            assert router.health.counter("completed") == 1
        finally:
            router.stop()

    def test_bad_request_is_400_without_a_replica_call(self,
                                                       no_history):
        transport = ScriptedTransport()
        plane, router, url = self._start(transport)
        try:
            for payload in (b"not json", b"{}",
                            json.dumps({"tokens": []}).encode(),
                            json.dumps({"tokens": [1, True]}).encode(),
                            json.dumps({"tokens": "abc"}).encode()):
                status, body, _ = post_router(url, payload)
                assert status == 400, payload
                assert "error" in body
            assert transport.calls == [], \
                "a bad request does not deserve a failover tour"
        finally:
            router.stop()

    def test_all_replicas_down_is_honest_503(self, no_history):
        """Every replica dead -> 503 with an integer Retry-After >= 1
        (the control plane's detection horizon) and the per-replica
        failure list — never a hang, never a bare 500."""
        transport = ScriptedTransport()
        plane, router, url = self._start(
            transport, plane_over={"fail_threshold": 3})
        try:
            for rep in plane.replicas:
                transport.set(rep.url, dead_behavior)
            status, body, headers = post_router(
                url, {"tokens": [1, 2, 3, 4]})
            assert status == 503
            retry_after = int(headers["Retry-After"])
            assert retry_after >= 1
            assert {f["replica"] for f in body["failures"]} == \
                {r.name for r in plane.replicas}
            assert all(f["kind"].startswith("transport:")
                       for f in body["failures"])
            assert router.counter("all_down") == 1
            assert router.health.counter("shed") == 1
            assert router.health.snapshot()["inflight"] == 0
        finally:
            router.stop()

    def test_no_routable_replica_rejects_unready(self, no_history):
        transport = ScriptedTransport()
        plane, router, url = self._start(transport)
        try:
            for rep in plane.replicas:
                rep.state = "dead"
            assert router.health.ready is False
            status, _, headers = post_router(
                url, {"tokens": [1, 2, 3, 4]})
            assert status == 503
            assert "Retry-After" in headers
            ready = urllib.request.Request(url + "/readyz")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(ready, timeout=10)
            assert err.value.code == 503
        finally:
            router.stop()

    def test_debug_and_metrics_surfaces(self, no_history):
        transport = ScriptedTransport()
        plane, router, url = self._start(transport)
        try:
            for rep in plane.replicas:
                transport.set(rep.url, ok_behavior(rep.name))
                rep.goodput, rep.pressure = 1.0, 0.25
            post_router(url, {"tokens": [1, 2, 3, 4]})
            with urllib.request.urlopen(url + "/debug/router",
                                        timeout=10) as resp:
                snap = json.loads(resp.read().decode())
            assert snap["counters"]["requests"] == 1
            assert snap["plane"]["active"] == 2
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=10) as resp:
                scrape = resp.read().decode()
            assert "veles_router_requests_total" in scrape
            assert "veles_router_replica_goodput" in scrape
            assert "veles_router_replica_pressure" in scrape
        finally:
            router.stop()


# -- the control plane: detector + lifecycle ---------------------------------

class TestServePlane:

    def run_polls(self, plane, snaps_by_name, polls, start=0.0):
        """Drive ``poll`` with an explicit clock; ``snaps_by_name``
        maps replica name -> snapshot | None (poll failure) |
        callable(poll_index)."""
        poll_index = [0]

        def fetch(url):
            rep = next(r for r in plane.replicas if r.url == url)
            snap = snaps_by_name.get(rep.name, healthy_snap())
            if callable(snap):
                snap = snap(poll_index[0])
            if snap is None:
                raise ConnectionRefusedError("down")
            return snap

        plane._fetch = fetch
        for i in range(polls):
            poll_index[0] = i
            plane.poll(now=start + float(i))

    def test_leave_one_out_drains_then_retires(self, no_history):
        """One replica's goodput collapses below retire_ratio x the
        rest-median for retire_polls -> drain; with no leases it
        retires the same pass."""
        plane = make_plane(n=3, retire_polls=2, retire_ratio=0.5)
        victim = plane.replicas[0].name
        self.run_polls(plane, {victim: healthy_snap(goodput=0.05)}, 3)
        assert plane.replicas[0].state == "retired"
        assert plane.counters["replica_drain"] == 1
        assert plane.counters["replica_retire"] == 1
        actions = [(t["action"], t["replica"])
                   for t in plane.transitions]
        assert ("replica_drain", victim) in actions
        assert ("replica_retire", victim) in actions

    def test_fleet_wide_brownout_names_nobody(self, no_history):
        """Every replica equally slow is a capacity problem, not a
        straggler: relative scoring must not scapegoat one replica."""
        plane = make_plane(n=3, retire_polls=2)
        snaps = {r.name: healthy_snap(goodput=0.1)
                 for r in plane.replicas}
        self.run_polls(plane, snaps, 5)
        assert plane.counters["replica_drain"] == 0
        assert all(r.state == "active" for r in plane.replicas)

    def test_draining_replica_waits_for_leases(self, no_history):
        plane = make_plane(n=3, retire_polls=1)
        victim = plane.replicas[0]
        victim.note_dispatch()  # one live lease
        self.run_polls(plane, {victim.name: healthy_snap(goodput=0.0)},
                       2)
        assert victim.state == "draining", \
            "retire must wait for the lease to finish"
        victim.note_done(True)
        plane.poll(now=10.0)
        assert victim.state == "retired"

    def test_dead_after_fail_threshold_with_standby_backfill(
            self, no_history):
        """A replica whose /healthz stops answering crosses
        fail_threshold -> DEAD, and a standby backfills to hold
        min_active."""
        plane = make_plane(n=1, standby=1, fail_threshold=3)
        victim = plane.replicas[0].name
        self.run_polls(plane, {victim: None}, 3)
        assert plane.find(victim).state == "dead"
        assert plane.counters["replica_dead"] == 1
        assert plane.counters["replica_adopt"] == 1
        assert len(plane.active()) == 1
        actions = [t["action"] for t in plane.transitions]
        assert actions.index("replica_dead") \
            < actions.index("replica_adopt")

    def test_min_active_suppression_is_ledger_visible(self,
                                                      no_history):
        """A retire that would empty the fleet below min_active with
        no standby is SUPPRESSED — and the ledger says so."""
        plane = make_plane(n=2, retire_polls=2, min_active=2)
        victim = plane.replicas[0].name
        self.run_polls(plane, {victim: healthy_snap(goodput=0.0)}, 4)
        assert plane.find(victim).state == "active"
        assert plane.counters["replica_drain"] == 0
        assert plane.counters["replica_retire_suppressed"] >= 1
        note = next(t for t in plane.transitions
                    if t["action"] == "replica_retire_suppressed")
        assert note["replica"] == victim
        assert "min_active" in note["reason"]

    def test_adopt_under_sustained_pressure_only(self, no_history):
        """Mean fleet pressure >= adopt_pressure for adopt_polls
        consecutive polls adopts ONE standby; a single spike does
        not."""
        plane = make_plane(n=2, standby=1, adopt_pressure=0.8,
                           adopt_polls=3)
        hot = {r.name: healthy_snap(inflight=8, limit=8)
               for r in plane.active()}
        cool = {r.name: healthy_snap(inflight=1, limit=8)
                for r in plane.active()}
        self.run_polls(plane, hot, 2)
        self.run_polls(plane, cool, 1, start=2.0)
        assert plane.counters["replica_adopt"] == 0, \
            "a spike shorter than adopt_polls must not adopt"
        self.run_polls(plane, hot, 3, start=3.0)
        assert plane.counters["replica_adopt"] == 1
        assert len(plane.active()) == 3

    def test_cooldown_bounds_actuation_rate(self, no_history):
        """Hysteresis + cooldown: two simultaneous collapses actuate
        ONE drain per cooldown window — a flapping fleet cannot
        thrash."""
        plane = make_plane(n=4, retire_polls=1, cooldown_s=100.0)
        bad = {plane.replicas[0].name: healthy_snap(goodput=0.0),
               plane.replicas[1].name: healthy_snap(goodput=0.0)}
        self.run_polls(plane, bad, 3)
        assert plane.counters["replica_drain"] == 1

    def test_collapse_cuts_incident_naming_the_replica(
            self, isolated_history):
        """The acceptance's artifact contract: a drain fires the
        detector-owned rule and the incident's leading indicator NAMES
        the replica on the per-replica goodput series."""
        history = isolated_history
        plane = make_plane(n=3, retire_polls=2)
        victim = plane.replicas[0].name
        self.run_polls(plane, {victim: healthy_snap(goodput=0.0)}, 3)
        rule = next(r for r in history.rules
                    if r.name == COLLAPSE_RULE)
        assert rule.external is True, \
            "the sampler must never evaluate the detector-owned rule"
        doc = history.incidents.last_doc
        assert doc is not None, "a drain must cut an incident artifact"
        leading = doc["leading_indicator"]
        assert leading["series"] == REPLICA_GOODPUT_SERIES
        assert ["replica", victim] in leading["labels"]
        assert history.incidents.last_path is not None

    def test_control_series_recorded(self, isolated_history):
        """The plane's sensor readings ride the metric-history plane:
        per-replica goodput (labelled) and fleet pressure are control
        series the incident autopsy can replay."""
        history = isolated_history
        plane = make_plane(n=2)
        self.run_polls(plane, {}, 2)
        snap = history.debug_snapshot(window=60.0, now=2.0)
        rows = {(r["name"], tuple(sorted(r["labels"].items())))
                for r in snap["series"]}
        names = {name for name, _ in rows}
        assert REPLICA_GOODPUT_SERIES in names
        assert FLEET_PRESSURE_SERIES in names
        for rep in plane.replicas:
            assert (REPLICA_GOODPUT_SERIES,
                    (("replica", rep.name),)) in rows

    def test_registry_rejects_duplicates_and_drops_departed(
            self, no_history):
        plane = make_plane(n=2, standby=0)
        with pytest.raises(ValueError, match="already registered"):
            plane.add_standby(plane.replicas[0].url)
        fresh = plane.add_standby("http://127.0.0.1:9900")
        assert fresh.state == "standby"
        assert plane.drop_replica(fresh.name) is fresh
        assert plane.find(fresh.name) is None


# -- configuration -----------------------------------------------------------

class TestConfig:

    def test_shared_subtree_splits_by_key_set(self):
        """Both configs read the ONE router subtree, each skipping the
        other's keys."""
        spec = ("hedge_after_s=1.5,retire_polls=5,max_attempts=2,"
                "adopt_pressure=0.7")
        router_cfg = RouterConfig.from_spec(spec)
        plane_cfg = ServePlaneConfig.from_spec(spec)
        assert router_cfg.hedge_after_s == 1.5
        assert router_cfg.max_attempts == 2
        assert plane_cfg.retire_polls == 5
        assert plane_cfg.adopt_pressure == 0.7

    def test_unknown_key_raises_naming_the_flag(self):
        with pytest.raises(ValueError, match="root.common.serve.router"):
            RouterConfig.from_spec("no_such_knob=1")
        with pytest.raises(ValueError, match="no_such_knob"):
            ServePlaneConfig.from_spec("no_such_knob=1")

    @pytest.mark.parametrize("kwargs", [
        {"max_inflight": -1}, {"hedge_after_s": 0},
        {"max_attempts": 0}, {"backoff_s": -0.1},
        {"page_size": 0}, {"spill_pressure": 1.5}])
    def test_router_validation(self, kwargs):
        with pytest.raises(ValueError):
            RouterConfig(**kwargs)

    @pytest.mark.parametrize("kwargs", [
        {"poll_interval_s": 0}, {"fail_threshold": 0},
        {"retire_ratio": 1.0}, {"retire_polls": 0},
        {"goodput_floor": 0}, {"adopt_pressure": 0},
        {"cooldown_s": -1}, {"min_active": 0}])
    def test_plane_validation(self, kwargs):
        with pytest.raises(ValueError):
            ServePlaneConfig(**kwargs)

    def test_unbounded_admission_spelling(self):
        assert RouterConfig(max_inflight=0).max_inflight is None
        assert RouterConfig(max_inflight="").max_inflight is None
        assert RouterConfig(max_inflight=8).max_inflight == 8

    def test_build_router_wires_both_halves(self, no_history):
        plane, router = build_router(
            ["http://127.0.0.1:9000", "127.0.0.1:9001"],
            standby=["127.0.0.1:9100"],
            spec="vnodes=16,retire_polls=4")
        assert router.plane is plane
        assert router.config.vnodes == 16
        assert plane.config.retire_polls == 4
        assert len(plane.active()) == 2
        assert len(plane.standby()) == 1
        assert plane.replicas[1].url == "http://127.0.0.1:9001"

    def test_duplicate_replica_names_refused(self):
        with pytest.raises(ValueError, match="duplicate"):
            ServePlane(["http://127.0.0.1:9000",
                        "127.0.0.1:9000"])


# -- the replica chaos planner -----------------------------------------------

class TestReplicaChaos:

    def test_deterministic_schedule(self):
        from veles_tpu.serving_chaos import (ReplicaChaosConfig,
                                             ReplicaChaosMonkey)
        cfg = ReplicaChaosConfig(kill_at=2, kill_index=1, slow_at=1,
                                 slow_ticks=2, slow_index=0,
                                 poison_healthz_at=4, poison_index=2)
        monkey = ReplicaChaosMonkey(cfg)
        schedule = {tick: monkey.actions(tick) for tick in range(6)}
        assert schedule[0] == []
        assert schedule[1] == [("pause", 0)]
        assert schedule[2] == [("kill", 1)]
        assert schedule[3] == [("resume", 0)]
        assert schedule[4] == [("poison_healthz", 2)]
        assert schedule[5] == []
        assert monkey.counters == {"kills": 1, "pauses": 1,
                                   "resumes": 1, "healthz_poisons": 1}
        assert "kill_at" in monkey.stamps

    def test_flap_toggles_on_period(self):
        from veles_tpu.serving_chaos import (ReplicaChaosConfig,
                                             ReplicaChaosMonkey)
        monkey = ReplicaChaosMonkey(ReplicaChaosConfig(flap_period=2,
                                                       flap_index=1))
        acts = [monkey.actions(t) for t in range(7)]
        assert acts[2] == [("pause", 1)]
        assert acts[4] == [("resume", 1)]
        assert acts[6] == [("pause", 1)]
        assert acts[1] == acts[3] == acts[5] == []

    def test_every_profile_leads_on_replica_goodput(self):
        from veles_tpu.serving_chaos import (REPLICA_PROFILES,
                                             ReplicaChaosConfig)
        cfg = ReplicaChaosConfig(kill_at=1, slow_at=1, slow_ticks=1,
                                 flap_period=2, poison_healthz_at=1)
        leading = cfg.expected_leading_series()
        assert set(leading) == set(REPLICA_PROFILES)
        assert set(leading.values()) == {REPLICA_GOODPUT_SERIES}

    def test_validation(self):
        from veles_tpu.serving_chaos import ReplicaChaosConfig
        with pytest.raises(ValueError):
            ReplicaChaosConfig(kill_at=-1)
        with pytest.raises(ValueError):
            ReplicaChaosConfig(slow_at=1, slow_ticks=-1)
        with pytest.raises(ValueError):
            ReplicaChaosConfig(flap_period=-2)
        assert ReplicaChaosConfig().any_profile is False
        assert ReplicaChaosConfig(kill_at=0).any_profile is True


# -- the kill -9 chaos acceptance --------------------------------------------

CHILD = r"""
import json, sys, time
import numpy
import jax.numpy as jnp
from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.serving import GenerateAPI

rng = numpy.random.RandomState(0)
params = init_transformer_params(rng, 2, 16, 4, 11)
table = jnp.asarray(rng.randn(11, 16).astype(numpy.float32) * 0.3)
api = GenerateAPI(params, table, 4, slots=2, max_len=32, n_tokens=5,
                  chunk=2, port=0)
api.start()
print(json.dumps({"port": api.port}), flush=True)
while True:
    time.sleep(3600)
"""


@pytest.mark.slow
class TestElasticChaosAcceptance:
    """The ISSUE's acceptance: N same-seed subprocess replicas, kill
    -9 one mid-traffic — every request completes through failover
    bit-identically, zero non-retryable 5xx, and the control plane
    names the dead replica."""

    def _spawn_replicas(self, n):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        procs, urls = [], []
        try:
            for _ in range(n):
                proc = subprocess.Popen(
                    [sys.executable, "-c", CHILD], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, cwd=REPO)
                procs.append(proc)
            for proc in procs:
                line = proc.stdout.readline()
                assert line, proc.stderr.read()[-2000:]
                port = json.loads(line)["port"]
                urls.append("http://127.0.0.1:%d" % port)
        except Exception:
            for proc in procs:
                proc.kill()
            raise
        return procs, urls

    def test_kill9_failover_is_bit_identical_and_named(
            self, isolated_history):
        from veles_tpu.serving_chaos import (ReplicaChaosConfig,
                                             ReplicaChaosMonkey)
        history = isolated_history
        procs, urls = self._spawn_replicas(3)
        router = None
        try:
            plane, router = build_router(
                urls, spec="poll_interval_s=0.2,fail_threshold=2,"
                           "cooldown_s=0.0,hedge_after_s=2.0,"
                           "backoff_s=0.01,page_size=4")
            router.start()
            front = "http://127.0.0.1:%d" % router.port
            prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [2, 4, 6, 8],
                       [9, 1, 9, 1], [3, 3, 3, 3], [1, 2, 3, 4, 5]]
            # warm every replica's decode program first (each prompt
            # rides affinity to one replica; hit them all directly)
            for url in urls:
                status, body, _ = post_router(url, {"tokens": [1, 2, 3]})
                assert status == 200, body

            # the fault-free baseline THROUGH the router
            baseline = {}
            for prompt in prompts:
                status, body, _ = post_router(front, {"tokens": prompt})
                assert status == 200, body
                baseline[tuple(prompt)] = body["tokens"]

            # chaos: sustained traffic, kill -9 replica 0 at tick 1
            monkey = ReplicaChaosMonkey(ReplicaChaosConfig(kill_at=1,
                                                           kill_index=0))
            results, errors = [], []
            lock = threading.Lock()

            def pound(prompt, rounds=6):
                for _ in range(rounds):
                    try:
                        status, body, _ = post_router(
                            front, {"tokens": prompt})
                    except Exception as exc:
                        with lock:
                            errors.append(("transport", repr(exc)))
                        continue
                    with lock:
                        results.append((tuple(prompt), status, body))

            threads = [threading.Thread(target=pound, args=(p,))
                       for p in prompts]
            for t in threads:
                t.start()
            for tick in range(2):
                for action, index in monkey.actions(tick):
                    assert action == "kill"
                    procs[index].send_signal(signal.SIGKILL)
                time.sleep(0.05)
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads)

            # zero-shed failover: every request completed with the
            # fault-free greedy tokens, zero non-retryable 5xx
            assert errors == []
            assert len(results) == len(prompts) * 6
            for prompt, status, body in results:
                assert status == 200, (status, body)
                assert body["tokens"] == baseline[prompt], \
                    "failover must stay bit-identical"
            assert monkey.counters["kills"] == 1

            # the detector names the dead replica in the ledger...
            dead_name = plane.replicas[0].name
            assert wait_until(
                lambda: plane.find(dead_name).state == "dead",
                timeout=30)
            entry = next(t for t in plane.transitions
                         if t["action"] == "replica_dead")
            assert entry["replica"] == dead_name
            # ...and in the incident artifact
            assert wait_until(
                lambda: history.incidents.last_doc is not None,
                timeout=10)
            doc = history.incidents.last_doc
            leading = doc["leading_indicator"]
            assert leading["series"] == REPLICA_GOODPUT_SERIES
            assert ["replica", dead_name] in leading["labels"]

            # the fleet keeps serving after the death
            status, body, _ = post_router(front,
                                          {"tokens": [1, 2, 3, 4]})
            assert status == 200
            assert body["tokens"] == baseline[(1, 2, 3, 4)]
        finally:
            if router is not None:
                router.stop()
            for proc in procs:
                proc.kill()
            for proc in procs:
                proc.wait(timeout=30)
