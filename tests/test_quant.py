"""Int8 weight-only quantization tier (ops/quant.py + the decode
serving path): quantization error bounds, Pallas kernel == XLA
formulation, and end-to-end generate() wiring."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops.quant import (int8_matmul, matmul_any, quantize_int8)


def test_quantize_roundtrip_error_bound():
    """|w - q*scale| <= scale/2 per element (symmetric absmax)."""
    rng = numpy.random.RandomState(0)
    w = rng.randn(64, 128).astype(numpy.float32)
    q, scale = quantize_int8(w)
    assert q.dtype == jnp.int8 and scale.shape == (128,)
    err = numpy.abs(numpy.asarray(q, numpy.float32) *
                    numpy.asarray(scale) - w)
    assert (err <= numpy.asarray(scale) / 2 + 1e-7).all()
    # absmax elements hit +-127 exactly
    assert int(numpy.abs(numpy.asarray(q)).max()) == 127


def test_quantize_zero_column_safe():
    w = numpy.zeros((32, 128), numpy.float32)
    q, scale = quantize_int8(w)
    assert (numpy.asarray(q) == 0).all()
    assert (numpy.asarray(scale) == 1.0).all()


def test_xla_path_matches_manual_dequant():
    rng = numpy.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 64).astype(numpy.float32))
    w = rng.randn(64, 128).astype(numpy.float32)
    q, scale = quantize_int8(w)
    got = int8_matmul(x, q, scale, use_pallas=False)
    want = x @ (numpy.asarray(q, numpy.float32) * numpy.asarray(scale))
    numpy.testing.assert_allclose(numpy.asarray(got),
                                  numpy.asarray(want), rtol=2e-5,
                                  atol=1e-5)


def test_pallas_kernel_matches_xla_exactly_on_integers():
    """Integer x, scale folded to 1: both paths accumulate exact f32
    integers -> bitwise-equal results (pins the kernel's indexing)."""
    rng = numpy.random.RandomState(2)
    x = jnp.asarray(rng.randint(-8, 8, (8, 64)).astype(numpy.float32))
    q = jnp.asarray(rng.randint(-127, 127, (64, 512)), jnp.int8)
    scale = jnp.ones(512, jnp.float32)
    got = int8_matmul(x, q, scale, use_pallas=True, interpret=True)
    want = int8_matmul(x, q, scale, use_pallas=False)
    numpy.testing.assert_array_equal(numpy.asarray(got),
                                     numpy.asarray(want))


def test_pallas_kernel_matches_xla_float_and_grid():
    """Float x over a multi-step grid (N = 2 blocks)."""
    rng = numpy.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 96).astype(numpy.float32))
    w = rng.randn(96, 1024).astype(numpy.float32)
    q, scale = quantize_int8(w)
    got = int8_matmul(x, q, scale, use_pallas=True, interpret=True)
    want = int8_matmul(x, q, scale, use_pallas=False)
    numpy.testing.assert_allclose(numpy.asarray(got),
                                  numpy.asarray(want), rtol=2e-5,
                                  atol=1e-4)


def test_matmul_any_dispatch():
    rng = numpy.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 3, 64).astype(numpy.float32))
    w = rng.randn(64, 128).astype(numpy.float32)
    dense = matmul_any(x, jnp.asarray(w))
    q, scale = quantize_int8(w)
    quant = matmul_any(x, {"q8": q, "scale": scale})
    assert quant.shape == dense.shape == (2, 3, 128)
    # int8 weights: ~1% relative error on a randn product
    err = numpy.abs(numpy.asarray(quant) - numpy.asarray(dense))
    assert err.mean() < 0.05 * numpy.abs(numpy.asarray(dense)).mean()


def test_generate_int8_matches_quantized_reference_loop():
    """generate(quantize='int8') tokens == a naive recompute loop over
    the SAME quantized weights (the wiring, not the rounding, is under
    test; the XLA path runs on CPU where the auto-gate declines)."""
    from veles_tpu.parallel.decode import generate, quantize_params
    from veles_tpu.parallel.transformer_step import (
        _forward, init_transformer_params)

    heads, embed, vocab = 4, 16, 11
    rng = numpy.random.RandomState(5)
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(rng.randn(vocab, embed).astype(numpy.float32)
                        * 0.3)
    prompt = jnp.asarray(rng.randint(0, vocab, (2, 5)))

    toks, _ = generate(params, table, prompt, heads, n_tokens=6,
                       quantize="int8")
    assert toks.shape == (2, 6)

    qparams = quantize_params(params)
    seq = table[prompt]
    ref = []
    for _ in range(6):
        logits = _forward(qparams, seq, heads, 1, "ulysses")[:, -1]
        tok = jnp.argmax(logits, axis=-1)
        ref.append(tok)
        seq = jnp.concatenate([seq, table[tok][:, None, :]], axis=1)
    numpy.testing.assert_array_equal(
        numpy.asarray(toks), numpy.asarray(jnp.stack(ref, axis=1)))


def test_generate_int8_accepts_prequantized():
    from veles_tpu.parallel.decode import generate, quantize_params
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)

    heads, embed, vocab = 4, 16, 11
    rng = numpy.random.RandomState(6)
    params = init_transformer_params(rng, 1, embed, heads, vocab)
    table = jnp.asarray(rng.randn(vocab, embed).astype(numpy.float32)
                        * 0.3)
    prompt = jnp.asarray(rng.randint(0, vocab, (1, 4)))
    qparams = quantize_params(params)
    t1, _ = generate(params, table, prompt, heads, n_tokens=3,
                     quantize="int8")
    t2, _ = generate(qparams, table, prompt, heads, n_tokens=3,
                     quantize="int8")
    numpy.testing.assert_array_equal(numpy.asarray(t1),
                                     numpy.asarray(t2))


def _attend_fixture(batch=2, length=7, heads=3, dim=8, seed=8):
    """(q, head-major int8 K/V + scales, equivalent fp K/V, mask)."""
    from veles_tpu.parallel.decode import _quantize_kv

    rng = numpy.random.RandomState(seed)
    q = jnp.asarray(rng.randn(batch, 1, heads, dim).astype(
        numpy.float32))
    k = jnp.asarray(rng.randn(batch, length, heads, dim).astype(
        numpy.float32))
    v = jnp.asarray(rng.randn(batch, length, heads, dim).astype(
        numpy.float32))
    kq, ks = _quantize_kv(k)
    vq, vs = _quantize_kv(v)
    # (B,T,H,D) -> head-major (B,H,D,T); scales (B,T,H) -> (B,H,T)
    to_hm = lambda a: jnp.transpose(a, (0, 2, 3, 1))  # noqa: E731
    return (q, to_hm(kq), jnp.transpose(ks, (0, 2, 1)), to_hm(vq),
            jnp.transpose(vs, (0, 2, 1)), k, v, kq, ks, vq, vs)


def test_cache_attend_scale_folding_matches_explicit_dequant():
    """int8_cache_attend (XLA formulation, head-major layout) folds
    k_scale into the score row and v_scale into the softmax weights;
    it must equal attending against explicitly dequantized fp K/V
    through the plain _cache_attend (pure reassociation + layout)."""
    from veles_tpu.parallel.decode import _cache_attend
    from veles_tpu.ops.quant import int8_cache_attend

    (q, khm, kshm, vhm, vshm, _, _, kq, ks, vq, vs) = _attend_fixture()
    length, dim = kq.shape[1], q.shape[-1]
    inv = 1.0 / numpy.sqrt(dim)
    mask_addend = jnp.zeros(length, jnp.float32)
    got = int8_cache_attend(q * inv, khm, kshm, vhm, vshm, mask_addend,
                            use_pallas=False)
    deq_k = kq.astype(jnp.float32) * ks[..., None]
    deq_v = vq.astype(jnp.float32) * vs[..., None]
    mask = jnp.ones((1, 1, 1, length), bool)
    want = _cache_attend(q, deq_k, deq_v, mask)
    numpy.testing.assert_allclose(numpy.asarray(got),
                                  numpy.asarray(want), rtol=1e-5,
                                  atol=1e-6)


def test_cache_attend_kernel_matches_xla_formulation():
    """The Pallas dequant-fused attend (interpret mode off-TPU) ==
    the XLA formulation of the same math, mask included, at a
    tile-friendly shape."""
    from veles_tpu.ops.quant import int8_cache_attend

    (q, khm, kshm, vhm, vshm, *_) = _attend_fixture(
        batch=2, length=128, heads=2, dim=32, seed=11)
    inv = 1.0 / numpy.sqrt(q.shape[-1])
    mask_addend = jnp.where(jnp.arange(128) <= 50, 0.0,
                            -1e30).astype(jnp.float32)
    want = int8_cache_attend(q * inv, khm, kshm, vhm, vshm,
                             mask_addend, use_pallas=False)
    got = int8_cache_attend(q * inv, khm, kshm, vhm, vshm, mask_addend,
                            use_pallas=True, interpret=True)
    numpy.testing.assert_allclose(numpy.asarray(got),
                                  numpy.asarray(want), rtol=2e-5,
                                  atol=2e-5)


def test_cache_attend_per_row_masks_match_per_row_calls():
    """The (B, T) per-row mask form (the slot engine's per-slot
    lengths): each row must equal a separate call with that row's
    1-D mask — on the XLA formulation and the kernel (interpret)."""
    from veles_tpu.ops.quant import int8_cache_attend

    (q, khm, kshm, vhm, vshm, *_) = _attend_fixture(
        batch=2, length=128, heads=2, dim=32, seed=12)
    inv = 1.0 / numpy.sqrt(q.shape[-1])
    lengths = (50, 97)
    masks = jnp.stack([
        jnp.where(jnp.arange(128) <= n, 0.0, -1e30).astype(jnp.float32)
        for n in lengths])
    for pallas in (False, True):
        got = int8_cache_attend(q * inv, khm, kshm, vhm, vshm, masks,
                                use_pallas=pallas, interpret=True)
        for row in range(2):
            want = int8_cache_attend(
                q[row:row + 1] * inv, khm[row:row + 1],
                kshm[row:row + 1], vhm[row:row + 1], vshm[row:row + 1],
                masks[row], use_pallas=pallas, interpret=True)
            numpy.testing.assert_allclose(
                numpy.asarray(got[row:row + 1]), numpy.asarray(want),
                rtol=2e-5, atol=2e-5)


def test_quantize_kv_roundtrip_bound():
    from veles_tpu.parallel.decode import _quantize_kv

    rng = numpy.random.RandomState(9)
    x = rng.randn(2, 5, 3, 16).astype(numpy.float32)
    q, scale = _quantize_kv(jnp.asarray(x))
    assert q.dtype == jnp.int8 and scale.shape == (2, 5, 3)
    err = numpy.abs(numpy.asarray(q, numpy.float32)
                    * numpy.asarray(scale)[..., None] - x)
    assert (err <= numpy.asarray(scale)[..., None] / 2 + 1e-7).all()


def test_generate_int8_kv_runs_and_tracks_fp():
    """int8-kv serving: the fully-quantized loop must stay close to the
    fp32 decode — same first token (clean logit margins at this scale)
    and highly-correlated logits throughout."""
    from veles_tpu.parallel.decode import (decode_step, generate,
                                           init_kv_cache, prefill)
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)

    heads, embed, vocab = 4, 32, 13
    rng = numpy.random.RandomState(10)
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(rng.randn(vocab, embed).astype(numpy.float32)
                        * 0.3)
    prompt = jnp.asarray(rng.randint(0, vocab, (2, 6)))

    toks, cache = generate(params, table, prompt, heads, n_tokens=5,
                           quantize="int8-kv")
    assert toks.shape == (2, 5)
    assert cache["k"].dtype == jnp.int8
    assert int(cache["length"]) == 11

    # logits comparison at the first decode step: quantized cache vs fp
    x = table[prompt]
    fp_logits, fp_cache = prefill(
        params, x, heads, init_kv_cache(2, 2, 11, heads, embed // heads))
    q_logits, q_cache = prefill(
        params, x, heads,
        init_kv_cache(2, 2, 11, heads, embed // heads, quantized=True))
    # prefill attends the exact K/V: logits identical
    numpy.testing.assert_allclose(numpy.asarray(q_logits),
                                  numpy.asarray(fp_logits), rtol=1e-5,
                                  atol=1e-5)
    tok = jnp.argmax(fp_logits, axis=-1)
    x_tok = table[tok][:, None, :]
    fp_step, _ = decode_step(params, x_tok, heads, fp_cache)
    q_step, _ = decode_step(params, x_tok, heads, q_cache)
    fp_np = numpy.asarray(fp_step, numpy.float64)
    q_np = numpy.asarray(q_step, numpy.float64)
    cos = (fp_np * q_np).sum() / (numpy.linalg.norm(fp_np)
                                  * numpy.linalg.norm(q_np))
    assert cos > 0.999
    numpy.testing.assert_array_equal(fp_np.argmax(-1), q_np.argmax(-1))


def test_tp_decode_rejects_quantized_params():
    from veles_tpu.parallel.decode import (make_tp_generate,
                                           quantize_params)
    from veles_tpu.parallel.mesh import build_mesh
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)

    rng = numpy.random.RandomState(7)
    params = quantize_params(
        init_transformer_params(rng, 1, 16, 2, 8))
    table = jnp.asarray(rng.randn(8, 16).astype(numpy.float32))
    mesh = build_mesh(devices=jax.devices()[:2], data=1, model=2)
    run = make_tp_generate(mesh, 2, n_tokens=2)
    with pytest.raises(ValueError):
        run(params, table, jnp.zeros((1, 3), jnp.int32))
