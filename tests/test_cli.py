"""CLI end-to-end tests (mirror reference test_velescli.py)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, timeout=300, cwd=REPO):
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               VELES_TPU_HOME=os.environ.get("VELES_TPU_HOME",
                                             "/tmp/veles_cli_test"),
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + list(args),
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_sample_workflow_end_to_end(tmp_path):
    result_file = str(tmp_path / "results.json")
    proc = run_cli("samples/digits_mlp.py", "samples/digits_config.py",
                   "root.digits.max_epochs=2", "--seed", "7",
                   "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = json.load(open(result_file))
    assert results["epochs"] == 2
    assert results["best_validation_errors"] < 297


@pytest.mark.slow
def test_transformer_sample_end_to_end(tmp_path):
    """The transformer sample trains, exports, and the native runtime
    loads the package (attention tier of the C++ op library)."""
    result_file = str(tmp_path / "results.json")
    package = str(tmp_path / "tx.tar")
    proc = run_cli("samples/transformer_digits.py", "-",
                   "root.transformer.epochs=2",
                   "root.transformer.export=%s" % package,
                   "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert json.load(open(result_file))["epochs"] == 2
    from veles_tpu.inference import NativeWorkflow
    # 6 units: the full pre-LN block (LN, residual attention, LN, ffn)
    # + dense + softmax head
    assert NativeWorkflow(package).unit_count == 6


def test_dry_run_init():
    proc = run_cli("samples/digits_mlp.py", "-", "--dry-run", "init")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_dump_config():
    proc = run_cli("samples/digits_mlp.py", "samples/digits_config.py",
                   "--dump-config")
    assert proc.returncode == 0
    assert "learning_rate" in proc.stdout


def test_bad_override_rejected():
    proc = run_cli("samples/digits_mlp.py", "-", "bogus.path=1")
    assert proc.returncode != 0


TINY_WF = """
import numpy
from veles_tpu.core.config import root
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(120, 6).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(int(root.tiny.hidden), 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 40, 80],
                            minibatch_size=20),
         learning_rate=float(root.tiny.lr), max_epochs=2)
    main()
"""

TINY_CFG = """
from veles_tpu.genetics.config import Range
root.tiny.update({"hidden": Range(6, 2, 12), "lr": Range(0.3, 0.05, 1.0)})
"""


@pytest.mark.slow
def test_optimize_cli_end_to_end(tmp_path):
    """--optimize runs subprocess GA evaluations and prints the winner
    (reference --optimize contract)."""
    wf = tmp_path / "wf.py"
    wf.write_text(TINY_WF)
    cfg = tmp_path / "cfg.py"
    cfg.write_text(TINY_CFG)
    proc = run_cli(str(wf), str(cfg), "--optimize", "3:2",
                   "--optimize-representation", "gray", timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "{" in proc.stdout, proc.stderr[-2000:]
    payload = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert "best_fitness" in payload
    assert 2 <= payload["best_values"]["root.tiny.hidden"] <= 12


@pytest.mark.slow
def test_ensemble_train_and_test_cli(tmp_path):
    """--ensemble-train N:r then --ensemble-test round-trip (reference
    --ensemble-* contract)."""
    wf = tmp_path / "wf.py"
    wf.write_text(TINY_WF.replace("root.tiny.hidden", "6").replace(
        "root.tiny.lr", "0.3"))
    # the CLI writes ensemble.json into ITS cwd: run the subprocess in
    # tmp_path so no artifact touches the repository tree
    proc = run_cli(str(wf), "-", "--ensemble-train", "2:0.8",
                   timeout=600, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    ensemble_file = tmp_path / "ensemble.json"
    assert ensemble_file.is_file()
    payload = json.load(open(ensemble_file))
    assert len(payload["instances"]) == 2
    assert all(e["returncode"] == 0 for e in payload["instances"])
    # --ensemble-test re-evaluates the stored snapshots
    proc = run_cli(str(wf), "-", "--ensemble-test", str(ensemble_file),
                   timeout=600, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "{" in proc.stdout, proc.stderr[-2000:]
    tested = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert "tests" in tested


@pytest.mark.slow
def test_snapshot_resume_from_url(tmp_path):
    """-w http://... downloads the snapshot first (reference
    __main__.py:572-581)."""
    import http.server
    import threading

    wf = tmp_path / "wf.py"
    wf.write_text(TINY_WF.replace("root.tiny.hidden", "6").replace(
        "root.tiny.lr", "0.3"))
    # train + snapshot locally first
    from veles_tpu.core import prng
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mlp import MLPWorkflow
    from veles_tpu.snapshotter import Snapshotter
    import numpy
    prng.get("default").seed(3)
    rng = numpy.random.RandomState(0)
    X = rng.rand(120, 6).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    wf_obj = MLPWorkflow(
        DummyLauncher(), layers=(6, 2),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 40, 80],
                           minibatch_size=20),
        learning_rate=0.3, max_epochs=1, name="url-snap")
    snap = Snapshotter(wf_obj, prefix="url", directory=str(tmp_path),
                       interval=1, time_interval=0)
    wf_obj.initialize()
    snap.initialize()
    wf_obj.run()
    snap.run()
    name = os.path.basename(snap.destination)

    import functools
    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=str(tmp_path))
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        url = "http://127.0.0.1:%d/%s" % (httpd.server_address[1], name)
        proc = run_cli(str(wf), "-", "-w", url, "--dry-run", "init")
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "resuming from" in proc.stderr + proc.stdout
    finally:
        httpd.shutdown()
        httpd.server_close()


@pytest.mark.slow
def test_profile_flag_writes_trace(tmp_path):
    """--profile captures a jax profiler trace of the run (the timeline
    role of the reference's Mongo event spans, done the TPU way)."""
    trace_dir = str(tmp_path / "trace")
    proc = run_cli("samples/digits_mlp.py", "samples/digits_config.py",
                   "root.digits.max_epochs=1", "--profile", trace_dir)
    assert proc.returncode == 0, proc.stderr[-2000:]
    found = []
    for base, _, files in os.walk(trace_dir):
        found.extend(f for f in files
                     if f.endswith((".xplane.pb", ".json.gz")))
    assert found, "no trace artifacts under %s" % trace_dir
