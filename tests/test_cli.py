"""CLI end-to-end tests (mirror reference test_velescli.py)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, timeout=300):
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               VELES_TPU_HOME=os.environ.get("VELES_TPU_HOME",
                                             "/tmp/veles_cli_test"))
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + list(args),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_sample_workflow_end_to_end(tmp_path):
    result_file = str(tmp_path / "results.json")
    proc = run_cli("samples/digits_mlp.py", "samples/digits_config.py",
                   "root.digits.max_epochs=2", "--seed", "7",
                   "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = json.load(open(result_file))
    assert results["epochs"] == 2
    assert results["best_validation_errors"] < 297


def test_dry_run_init():
    proc = run_cli("samples/digits_mlp.py", "-", "--dry-run", "init")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_dump_config():
    proc = run_cli("samples/digits_mlp.py", "samples/digits_config.py",
                   "--dump-config")
    assert proc.returncode == 0
    assert "learning_rate" in proc.stdout


def test_bad_override_rejected():
    proc = run_cli("samples/digits_mlp.py", "-", "bogus.path=1")
    assert proc.returncode != 0
