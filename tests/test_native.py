"""Native runtime tests: export a trained workflow, build the C++ runtime,
and check its inference matches the JAX forward pass bit-for-bit-ish
(the reference's libVeles/tests tier, driven from Python)."""

import io
import os
import subprocess
import tarfile

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.dummy import DummyLauncher
from veles_tpu.export import package_export
from veles_tpu.inference import BUILD_DIR, NativeWorkflow, build_native
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.models.standard import StandardWorkflow


def _digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    X = d.data.astype(numpy.float32)
    y = d.target.astype(numpy.int32)
    return X, y


@pytest.fixture(scope="module")
def native_lib():
    try:
        return build_native()
    except subprocess.CalledProcessError as e:
        pytest.fail("native build failed:\n%s" % e.stderr.decode()[-3000:])


@pytest.fixture(scope="module")
def trained_mlp():
    X, y = _digits()
    wf = MLPWorkflow(
        DummyLauncher(), layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=300,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=2, name="export-test")
    wf.initialize()
    wf.run()
    return wf


def test_cpp_unit_tests(native_lib, trained_mlp, tmp_path_factory):
    """Run the C++ test binary against generated fixtures."""
    fixture_dir = str(tmp_path_factory.mktemp("fixtures"))
    # npy fixture
    buf = io.BytesIO()
    numpy.save(buf, numpy.arange(6, dtype=numpy.float32).reshape(2, 3))
    with tarfile.open(os.path.join(fixture_dir, "npy_fixture.tar"),
                      "w") as tar:
        info = tarfile.TarInfo("m.npy")
        blob = buf.getvalue()
        info.size = len(blob)
        tar.addfile(info, io.BytesIO(blob))
    package_export(trained_mlp,
                   os.path.join(fixture_dir, "mlp_package.tar"))
    proc = subprocess.run(
        [os.path.join(BUILD_DIR, "veles_rt_tests"), fixture_dir],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_native_matches_jax_forward(native_lib, trained_mlp, tmp_path):
    package = str(tmp_path / "mlp.tar")
    package_export(trained_mlp, package)
    rt = NativeWorkflow(package)
    assert rt.unit_count == 2
    assert rt.input_size == 64
    assert rt.output_size == 10

    X, _ = _digits()
    batch = X[:32] / numpy.abs(X).max()  # loader-normalized scale
    native_out = rt.run(batch)

    # jax forward with the same weights (softmax applied to the logits)
    w0 = trained_mlp.forwards[0].weights.data
    b0 = trained_mlp.forwards[0].bias.data
    w1 = trained_mlp.forwards[1].weights.data
    b1 = trained_mlp.forwards[1].bias.data
    h = 1.7159 * jnp.tanh(0.6666 * (jnp.asarray(batch) @ w0 + b0))
    logits = h @ w1 + b1
    jax_out = numpy.asarray(jnp.exp(logits) /
                            jnp.sum(jnp.exp(logits), -1, keepdims=True))
    numpy.testing.assert_allclose(native_out, jax_out, rtol=2e-3,
                                  atol=1e-5)
    # agreement on predictions
    numpy.testing.assert_array_equal(native_out.argmax(-1),
                                     jax_out.argmax(-1))


def test_native_convnet(native_lib, tmp_path):
    """Conv + pooling + dense export path."""
    from sklearn.datasets import load_digits
    d = load_digits()
    X = (d.images.astype(numpy.float32) / 16.0)[..., None]
    y = d.target.astype(numpy.int32)
    wf = StandardWorkflow(
        DummyLauncher(),
        layers=[
            {"type": "conv_strict_relu", "n_kernels": 4, "kx": 3, "ky": 3},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": (10,)},
        ],
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=300),
        learning_rate=0.1, decision_kwargs=dict(max_epochs=1),
        name="conv-export")
    wf.initialize()
    wf.run()
    package = str(tmp_path / "conv.tar")
    from veles_tpu.export import package_export as export
    export(wf, package)
    rt = NativeWorkflow(package)
    assert rt.unit_count == 3

    batch = X[:8]
    native_out = rt.run(batch)
    # compare against the python units' own forward
    wf.loader.minibatch_data.data = jnp.asarray(batch)
    for fwd in wf.forwards:
        fwd.run()
    jax_logits = numpy.asarray(wf.forwards[-1].output.mem)[:8]
    jax_probs = numpy.exp(jax_logits) / numpy.exp(jax_logits).sum(
        -1, keepdims=True)
    numpy.testing.assert_allclose(native_out, jax_probs, rtol=2e-2,
                                  atol=2e-4)


def test_native_transformer(native_lib, tmp_path):
    """The complete pre-LN transformer block — layer_norm → residual
    self_attention → layer_norm → residual ffn → softmax head — through
    export: the C++ runtime's transformer tier must match the JAX
    units' forward."""
    rng = numpy.random.RandomState(0)
    n, t, e = 400, 6, 16
    X = rng.randn(n, t, e).astype(numpy.float32) * 0.2
    y = rng.randint(0, 2, n).astype(numpy.int32)
    wf = StandardWorkflow(
        DummyLauncher(),
        layers=[
            {"type": "layer_norm"},
            {"type": "self_attention", "heads": 4, "residual": True},
            {"type": "layer_norm"},
            {"type": "ffn", "ratio": 2},
            {"type": "softmax", "output_sample_shape": (2,)},
        ],
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 100, 300],
                           minibatch_size=100),
        learning_rate=0.05, decision_kwargs=dict(max_epochs=1),
        name="attn-export")
    wf.initialize()
    wf.run()
    package = str(tmp_path / "attn.tar")
    package_export(wf, package)
    rt = NativeWorkflow(package)
    assert rt.unit_count == 5

    batch = X[:8]
    native_out = rt.run(batch)
    wf.loader.minibatch_data.data = jnp.asarray(batch)
    for fwd in wf.forwards:
        fwd.run()
    jax_logits = numpy.asarray(wf.forwards[-1].output.mem)[:8]
    jax_probs = numpy.exp(jax_logits) / numpy.exp(jax_logits).sum(
        -1, keepdims=True)
    numpy.testing.assert_allclose(native_out, jax_probs, rtol=2e-2,
                                  atol=2e-4)


def test_native_causal_attention(native_lib, tmp_path):
    """The causal mask must match (build an untrained causal stack and
    compare raw forwards)."""
    rng = numpy.random.RandomState(1)
    n, t, e = 300, 5, 8
    X = rng.randn(n, t, e).astype(numpy.float32) * 0.3
    y = rng.randint(0, 2, n).astype(numpy.int32)
    wf = StandardWorkflow(
        DummyLauncher(),
        layers=[
            {"type": "self_attention", "heads": 2, "causal": True},
            {"type": "softmax", "output_sample_shape": (2,)},
        ],
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 100, 200],
                           minibatch_size=100),
        learning_rate=0.0, decision_kwargs=dict(max_epochs=1),
        name="causal-export")
    wf.initialize()
    wf.run()
    package = str(tmp_path / "causal.tar")
    package_export(wf, package)
    rt = NativeWorkflow(package)
    batch = X[:4]
    native_out = rt.run(batch)
    wf.loader.minibatch_data.data = jnp.asarray(batch)
    for fwd in wf.forwards:
        fwd.run()
    jax_logits = numpy.asarray(wf.forwards[-1].output.mem)[:4]
    jax_probs = numpy.exp(jax_logits) / numpy.exp(jax_logits).sum(
        -1, keepdims=True)
    numpy.testing.assert_allclose(native_out, jax_probs, rtol=2e-2,
                                  atol=2e-4)


class TestMalformedPackages:
    """The runtime consumes arbitrary packages: malformed input must
    produce a clean Python error (the C API catches std::exception),
    never a crash or an out-of-bounds read."""

    def _load(self, path):
        from veles_tpu.inference import NativeWorkflow
        return NativeWorkflow(path)

    def _tar_with(self, tmp_path, members):
        path = str(tmp_path / "pkg.tar")
        with tarfile.open(path, "w") as tar:
            for name, payload in members.items():
                info = tarfile.TarInfo(name)
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
        return path

    @staticmethod
    def _all2all_contents():
        """One shared minimal all2all package manifest — the schema under
        test lives in one place."""
        import json
        return json.dumps({
            "workflow": "x", "input_shape": [4],
            "units": [{"name": "u0", "type": "all2all",
                       "config": {"activation": "tanh",
                                  "out_features": 2},
                       "arrays": {"weights": "@w.npy",
                                  "bias": "@b.npy"}}]}).encode()

    def test_not_a_tar(self, native_lib, tmp_path):
        bad = tmp_path / "junk.tar"
        bad.write_bytes(os.urandom(512))
        with pytest.raises(RuntimeError):
            self._load(str(bad))

    def test_missing_contents(self, native_lib, tmp_path):
        path = self._tar_with(tmp_path, {"other.npy": b"\x00" * 16})
        with pytest.raises(RuntimeError):
            self._load(path)

    def test_broken_json(self, native_lib, tmp_path):
        path = self._tar_with(tmp_path, {"contents.json": b"{unclosed"})
        with pytest.raises(RuntimeError):
            self._load(path)

    def test_unknown_unit_type(self, native_lib, tmp_path):
        import json
        contents = json.dumps({
            "workflow": "x", "input_shape": [4],
            "units": [{"name": "u0", "type": "quantum_flux",
                       "config": {}, "arrays": {}}]}).encode()
        path = self._tar_with(tmp_path, {"contents.json": contents})
        with pytest.raises(RuntimeError, match="quantum_flux"):
            self._load(path)

    def test_missing_array_member(self, native_lib, tmp_path):
        path = self._tar_with(
            tmp_path, {"contents.json": self._all2all_contents()})
        with pytest.raises(RuntimeError):
            self._load(path)

    def test_truncated_npy(self, native_lib, tmp_path):
        path = self._tar_with(tmp_path, {
            "contents.json": self._all2all_contents(),
            "w.npy": b"\x93NUMPY garbage",
            "b.npy": b"\x00" * 8})
        with pytest.raises(RuntimeError):
            self._load(path)

    def test_shape_mismatch_rejected(self, native_lib, tmp_path):
        """weights rows != input size must throw at load/infer time."""
        def npy(arr):
            buf = io.BytesIO()
            numpy.save(buf, arr)
            return buf.getvalue()

        path = self._tar_with(tmp_path, {
            "contents.json": self._all2all_contents(),
            "w.npy": npy(numpy.zeros((7, 2), numpy.float32)),  # 7 != 4
            "b.npy": npy(numpy.zeros(2, numpy.float32))})
        with pytest.raises(RuntimeError):
            self._load(path)

    def test_f16_export_half_size_and_parity(self, native_lib,
                                             tmp_path):
        """``precision=16`` (the reference workflow.py:864-975 API):
        float16 weights, ~half the package size, and the native
        runtime's f2->f32 widening keeps inference within the f16
        quantization tolerance of the f32 package."""
        from sklearn.datasets import load_digits
        d = load_digits()
        X = d.data.astype(numpy.float32)
        y = d.target.astype(numpy.int32)
        wf = MLPWorkflow(
            DummyLauncher(), layers=(16, 10),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 297, 1500],
                               minibatch_size=300,
                               normalization_type="linear"),
            learning_rate=0.1, max_epochs=2, name="f16-export")
        wf.initialize()
        wf.run()
        p32 = str(tmp_path / "w32.tar")
        p16 = str(tmp_path / "w16.tar")
        package_export(wf, p32, precision=32)
        package_export(wf, p16, precision=16)
        # the .npy members dominate the tar: halving the dtype must
        # show up in the file size (tar rounds members to 512B blocks)
        assert os.path.getsize(p16) < 0.65 * os.path.getsize(p32)
        with tarfile.open(p16) as tar:
            blob = tar.extractfile("fwd0_weights.npy").read()
            assert numpy.load(io.BytesIO(blob)).dtype == numpy.float16
        batch = X[:64] / numpy.abs(X).max()
        out32 = self._load(p32).run(batch)
        out16 = self._load(p16).run(batch)
        numpy.testing.assert_allclose(out16, out32, atol=5e-3)
        # and the predictions agree
        numpy.testing.assert_array_equal(out16.argmax(-1),
                                         out32.argmax(-1))
        with pytest.raises(ValueError):
            package_export(wf, str(tmp_path / "bad.tar"), precision=8)

    def test_random_mutations_never_crash(self, native_lib, tmp_path):
        """Byte-flip fuzzing of a VALID package: every mutation loads
        or errors cleanly (no SIGSEGV/SIGFPE would mean pytest dies)."""
        from sklearn.datasets import load_digits
        d = load_digits()
        X = d.data.astype(numpy.float32)[:60]
        y = d.target.astype(numpy.int32)[:60]
        wf = MLPWorkflow(
            DummyLauncher(), layers=(4, 10),
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 10, 50],
                               minibatch_size=10),
            learning_rate=0.1, max_epochs=1, name="fuzz-base")
        wf.initialize()
        wf.run()
        base = str(tmp_path / "base.tar")
        package_export(wf, base)
        assert self._load(base).unit_count == 2  # the base itself loads
        blob = bytearray(open(base, "rb").read())
        rng = numpy.random.RandomState(0)
        outcomes = {"loaded": 0, "rejected": 0}
        for trial in range(40):
            mutated = bytearray(blob)
            for _ in range(rng.randint(1, 8)):
                mutated[rng.randint(0, len(mutated))] = rng.randint(256)
            path = str(tmp_path / "mut.tar")
            open(path, "wb").write(bytes(mutated))
            try:
                # a mutant that loads must also RUN cleanly: payload
                # flips that dodge the shape checks exercise inference
                rt = self._load(path)
                rt.run(X[:2])
                outcomes["loaded"] += 1  # harmless flip (padding bytes)
            except (RuntimeError, ValueError):
                outcomes["rejected"] += 1
        # reaching here alive is the crash-free property; every mutation
        # must have resolved to exactly one clean outcome
        assert outcomes["loaded"] + outcomes["rejected"] == 40
