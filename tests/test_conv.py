"""Conv/pooling unit tests + convnet functional regression."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.memory import Array
from veles_tpu.models.standard import StandardWorkflow
from veles_tpu.nn.conv import Conv, GDConv
from veles_tpu.nn.pooling import AvgPooling, GDPooling, MaxPooling


def test_conv_forward_shape_and_math():
    wf = DummyWorkflow()
    unit = Conv(wf, n_kernels=4, kx=3, ky=3, padding="SAME")
    x = numpy.random.RandomState(0).rand(2, 8, 8, 1).astype(numpy.float32)
    unit.input = Array(x)
    unit.initialize()
    unit.run()
    assert unit.output.shape == (2, 8, 8, 4)
    # identity-kernel check: 1x1 conv with unit weight reproduces input
    unit2 = Conv(wf, n_kernels=1, kx=1, ky=1, padding="SAME")
    unit2.input = Array(x)
    unit2.initialize()
    unit2.weights.data = jnp.ones((1, 1, 1, 1), jnp.float32)
    unit2.bias.data = jnp.zeros(1, jnp.float32)
    unit2.run()
    numpy.testing.assert_allclose(
        numpy.asarray(unit2.output.mem), x, rtol=1e-2, atol=1e-3)


def test_gdconv_matches_autodiff():
    rng = numpy.random.RandomState(1)
    x = rng.rand(2, 6, 6, 2).astype(numpy.float32)
    wf = DummyWorkflow()
    fwd = Conv(wf, n_kernels=3, kx=3, ky=3, padding="SAME")
    fwd.input = Array(x)
    fwd.initialize()
    w0 = numpy.asarray(fwd.weights.mem).copy()
    fwd.run()
    err = rng.rand(2, 6, 6, 3).astype(numpy.float32)

    gd = GDConv(wf, learning_rate=1.0)
    gd.link_conv(fwd, type("E", (), {"err_output": Array(err)})())
    gd.initialize()
    gd.run()

    def loss(w):
        out = fwd._pre_activation(jnp.asarray(x), w,
                                  jnp.zeros(3, jnp.float32))
        return jnp.sum(out * jnp.asarray(err))

    grad_w = jax.grad(loss)(jnp.asarray(w0))
    numpy.testing.assert_allclose(
        numpy.asarray(fwd.weights.mem), w0 - numpy.asarray(grad_w),
        rtol=1e-2, atol=1e-3)
    assert gd.err_input.shape == x.shape


def test_pooling_forward_and_backward():
    x = numpy.arange(16, dtype=numpy.float32).reshape(1, 4, 4, 1)
    wf = DummyWorkflow()
    pool = MaxPooling(wf, kx=2, ky=2)
    pool.input = Array(x)
    pool.initialize()
    pool.run()
    numpy.testing.assert_array_equal(
        numpy.asarray(pool.output.mem).reshape(2, 2),
        [[5, 7], [13, 15]])
    gd = GDPooling(wf)
    gd.link_pooling(pool, type("E", (), {
        "err_output": Array(numpy.ones((1, 2, 2, 1), numpy.float32))})())
    gd.run()
    err_in = numpy.asarray(gd.err_input.mem).reshape(4, 4)
    assert err_in.sum() == 4.0  # gradient routed only to the 4 winners
    assert err_in[1, 1] == 1.0 and err_in[0, 0] == 0.0


def test_avg_pooling():
    x = numpy.ones((1, 4, 4, 1), numpy.float32)
    wf = DummyWorkflow()
    pool = AvgPooling(wf, kx=2, ky=2)
    pool.input = Array(x)
    pool.initialize()
    pool.run()
    numpy.testing.assert_allclose(numpy.asarray(pool.output.mem),
                                  numpy.ones((1, 2, 2, 1)), rtol=1e-6)


@pytest.mark.slow
def test_convnet_learns_digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    X = (d.images.astype(numpy.float32) / 16.0)[..., None]
    y = d.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    X, y = X[perm], y[perm]
    wf = StandardWorkflow(
        DummyLauncher(),
        layers=[
            {"type": "conv_strict_relu", "n_kernels": 8, "kx": 3, "ky": 3},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_tanh", "output_sample_shape": 32},
            {"type": "softmax", "output_sample_shape": 10},
        ],
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=100),
        learning_rate=0.1, gradient_moment=0.9,
        decision_kwargs=dict(max_epochs=6), name="digits-conv-test")
    wf.initialize()
    wf.run()
    best = wf.decision.best_n_err[1]
    assert best is not None and best < 45, \
        "convnet at %s/297 validation errors" % best
