"""parallel/reshard.py: portable collective resharding (arxiv
2112.01075 translation) — schedules, bit-exact round trips, byte
accounting, metrics. Runs on the suite's 8-device virtual CPU mesh
(`make mesh` mirrors `make chaos` for this file + test_mesh_serving)."""

import numpy
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.parallel import reshard as rs
from veles_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(devices=jax.devices()[:8], data=2, model=4)


def _plan_one(shape, src, dst, mesh, dtype=numpy.float32):
    plan = rs.plan_reshard(jnp.zeros(shape, dtype), mesh, dst, src)
    return plan.leaves[0], plan


class TestSchedule:
    def test_transpose_resharding_is_one_all_to_all(self, mesh):
        """The paper's headline case: an axis moving between tensor
        dims must plan ONE all_to_all — never gather + slice (which
        materializes the full array and moves n-1x the bytes)."""
        leaf, _ = _plan_one((16, 32), P(None, "model"),
                            P("model", None), mesh)
        assert [s[0] for s in leaf.steps] == ["all_to_all"]
        # each device exchanges (n-1)/n of its shard: 3/4 of the bytes
        # the data-replicated model sharding leaves per device, x8 devs
        assert leaf.bytes == 8 * (16 * 32 * 4 // 4) * 3 // 4

    def test_slice_only_transition_is_free(self, mesh):
        leaf, _ = _plan_one((16, 32), P(), P(None, "model"), mesh)
        assert [s[0] for s in leaf.steps] == ["slice"]
        assert leaf.bytes == 0

    def test_gather_books_bytes(self, mesh):
        leaf, _ = _plan_one((16, 32), P("data", None), P(), mesh)
        assert [s[0] for s in leaf.steps] == ["all_gather"]
        assert leaf.bytes == 8 * (16 * 32 * 4 // 2) * (2 - 1)

    def test_nested_tuple_gathers_minor_first(self, mesh):
        """A ("data","model") nested dim must gather model (the minor
        axis) before data, or the blocks reassemble out of order."""
        leaf, _ = _plan_one((16, 32), P(("data", "model"), None), P(),
                            mesh)
        assert [(s[0], s[1]) for s in leaf.steps] == \
            [("all_gather", "model"), ("all_gather", "data")]

    def test_same_spec_is_keep(self, mesh):
        leaf, _ = _plan_one((16, 32), P("data", None), P("data", None),
                            mesh)
        assert [s[0] for s in leaf.steps] == ["keep"]
        assert leaf.bytes == 0

    @pytest.mark.parametrize("src,dst", [
        (P("model"), P("model", None)),
        (P(), P(None)),
        (P(("model",), None), P("model")),
    ])
    def test_equal_layouts_spelled_differently_are_keep(self, mesh,
                                                        src, dst):
        """jax reports a live array's spec in any of several equal
        spellings (trailing Nones, 1-tuple entries); the planner must
        compare LAYOUTS — a spelling change is a keep, never an empty
        schedule (which used to crash reshard())."""
        leaf, _ = _plan_one((16, 32), src, dst, mesh)
        assert [s[0] for s in leaf.steps] == ["keep"]
        assert leaf.bytes == 0

    def test_indivisible_spec_raises_named_error(self, mesh):
        """An indivisible dst spec must fail with an error naming the
        shape and spec — never as an opaque partitioner frame."""
        with pytest.raises(ValueError, match="cannot shard"):
            _plan_one((15, 32), P(), P("data", None), mesh)

    def test_entangled_swap_lowers_to_gather_slice(self, mesh):
        """An axis swap inside one dim pair cannot ride a tiled
        all_to_all (the nesting scrambles); it must lower to the
        provable gather+slice form."""
        leaf, _ = _plan_one((16, 32), P("model", "data"),
                            P("data", "model"), mesh)
        kinds = [s[0] for s in leaf.steps]
        assert "all_to_all" not in kinds
        assert kinds.count("all_gather") == 2
        assert kinds.count("slice") == 2


class TestReshard:
    CASES = [
        (P(), P(None, "model")),
        (P(None, "model"), P("model", None)),
        (P("data", None), P(None, "model")),
        (P(("data", "model"), None), P()),
        (P("model", "data"), P("data", "model")),
    ]

    @pytest.mark.parametrize("src,dst", CASES)
    def test_round_trip_bit_exact(self, mesh, src, dst):
        """Any spec change round-trips to the exact original values —
        the schedule moves data, it never computes."""
        rng = numpy.random.RandomState(0)
        w = rng.randn(16, 32).astype(numpy.float32)
        arr = jax.device_put(jnp.asarray(w), NamedSharding(mesh, src))
        there, stats = rs.reshard(arr, mesh, dst, src)
        assert not numpy.isnan(stats["seconds"])
        back, _ = rs.reshard(there, mesh, src)
        numpy.testing.assert_array_equal(numpy.asarray(there), w)
        numpy.testing.assert_array_equal(numpy.asarray(back), w)

    def test_tree_transition_train_to_serve_and_back(self, mesh):
        """The product transition: a transformer checkpoint moves from
        the replicated train layout to the tensor-parallel serving
        layout and back, every leaf exact (the acceptance contract)."""
        from veles_tpu.parallel.decode import slot_param_specs
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)

        rng = numpy.random.RandomState(1)
        params = init_transformer_params(rng, 2, 32, 8, 16)
        serve_specs = slot_param_specs(params)
        served, stats = rs.reshard(params, mesh, serve_specs,
                                   label="train_to_serve")
        # replicated -> sharded is slice-only: zero interconnect bytes
        assert stats["bytes"] == 0
        assert stats["counts"].get("slice")
        wqkv = served["blocks"][0]["wqkv"]
        assert not wqkv.sharding.is_fully_replicated
        back, stats_back = rs.reshard(served, mesh, P(),
                                      label="serve_to_train")
        assert stats_back["bytes"] > 0  # gathers pay real bytes
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            numpy.testing.assert_array_equal(numpy.asarray(a),
                                             numpy.asarray(b))

    def test_respelled_dst_spec_is_a_no_op(self, mesh):
        """A dst spec spelling the array's CURRENT layout differently
        (P('model') vs P('model', None)) must pass the leaf through —
        the raw tuple comparison used to plan an empty schedule and
        crash."""
        w = numpy.arange(64, dtype=numpy.float32).reshape(8, 8)
        arr = jax.device_put(jnp.asarray(w),
                             NamedSharding(mesh, P("model")))
        out, stats = rs.reshard(arr, mesh, P("model", None))
        assert stats["bytes"] == 0
        assert stats["counts"] == {"keep": 1}
        numpy.testing.assert_array_equal(numpy.asarray(out), w)

    def test_unplaced_host_leaves_are_placed_first(self, mesh):
        w = numpy.arange(64, dtype=numpy.float32).reshape(8, 8)
        out, _ = rs.reshard(jnp.asarray(w), mesh, P("data", None))
        numpy.testing.assert_array_equal(numpy.asarray(out), w)
        assert not out.sharding.is_fully_replicated

    def test_indivisible_leaf_raises(self, mesh):
        w = numpy.arange(15 * 4, dtype=numpy.float32).reshape(15, 4)
        with pytest.raises(ValueError, match="cannot shard"):
            rs.reshard(jnp.asarray(w), mesh, P("data", None))

    def test_metrics_surface(self, mesh):
        """Every transition books veles_reshard_bytes_total and a
        veles_reshard_seconds observation under its label."""
        registry = MetricsRegistry(enabled=True)
        arr = jax.device_put(
            jnp.zeros((16, 32), jnp.float32),
            NamedSharding(mesh, P("data", None)))
        rs.reshard(arr, mesh, P(), label="t2s-test",
                   registry=registry)
        text = registry.expose()
        assert 'veles_reshard_bytes_total{transition="t2s-test"}' in text
        assert "veles_reshard_seconds_bucket" in text
        assert 'transition="t2s-test"' in text
