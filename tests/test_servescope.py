"""Serving goodput observatory: occupancy timelines, token-waste
decomposition, padding autopsy.

The tentpole suite (docs/observability.md "Serving goodput + slot
timeline"): unit tests for the accounting ring / record-path
discipline / the exact per-cause waste math against the real dense AND
paged engines, the wall decomposition, the slot occupancy timeline,
the detector-owned anomaly rules + incident artifacts naming the
dominant waste cause, the metrics/healthz/web-status surfaces, the
``observe serve-trace`` CLI (saved payload and --live), and the chaos
acceptance — a seeded waste profile must deterministically land an
incident naming EXACTLY the injected cause.

``make servescope`` runs this module standalone; the chaos end-to-end
rides the ``slow`` marker so tier-1 keeps its timeout margin.
"""

import json
import time
import urllib.request

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.observe.history import (IncidentRecorder, MetricHistory,
                                       set_metric_history)
from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.observe.servescope import (
    DISPATCH_RING_CAPACITY, OCCUPANCY_BREACH, OPEN_SLOT_CAP,
    SLOT_RING_CAPACITY, WASTE_CAUSES, WASTE_SHARE_BREACH, ServeScope,
    assemble_serve_trace, ensure_serve_registered, ensure_serve_rules,
    get_serve_scope, load_serve_payload, publish_serve_scope,
    serve_trace_main)
from veles_tpu.observe.trace_export import span_tree
from veles_tpu.parallel.decode import (admit_waste,
                                       page_overshoot_tokens,
                                       span_overshoot_tokens)

pytestmark = pytest.mark.servescope


@pytest.fixture(autouse=True)
def _fresh_scope():
    scope = get_serve_scope()
    scope.reset()
    scope.enabled = True
    yield scope
    scope.reset()


def _tiny(blocks=1, embed=32, heads=4, vocab=64):
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)

    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, blocks, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.02)
    return params, table, heads


def _history(tmp_path, cooldown=0.0):
    return MetricHistory(
        registry=MetricsRegistry(enabled=False),
        incidents=IncidentRecorder(cooldown_s=cooldown,
                                   directory=str(tmp_path)))


# -- record-path discipline -------------------------------------------------

class TestRecordPath:
    def test_no_lock_attribute_anywhere(self):
        """The flight-recorder discipline: the scope may not hold a
        lock (the analyze gate's lock.record-path rule is the static
        twin of this runtime check)."""
        scope = ServeScope()
        for name, value in vars(scope).items():
            assert not hasattr(value, "acquire"), name
            assert "lock" not in name and "mutex" not in name

    def test_rings_bounded(self):
        scope = ServeScope()
        for index in range(DISPATCH_RING_CAPACITY + 500):
            scope.note_dispatch(2, 4, 2, 1, 0.0)
        assert len(scope._ring) == DISPATCH_RING_CAPACITY
        for rid in range(OPEN_SLOT_CAP + 100):
            scope.note_slot_admit(rid % 4, rid, "dense")
        assert len(scope._open) <= OPEN_SLOT_CAP
        for rid in range(SLOT_RING_CAPACITY + 200):
            scope.note_slot_admit(rid % 4, rid, "dense")
            scope.note_slot_retire(rid)
        assert len(scope._slots) == SLOT_RING_CAPACITY

    def test_disabled_is_noop(self):
        scope = ServeScope()
        scope.enabled = False
        scope.note_admit("dense", 16, 2, 2, 14, 18, 0, 0.001)
        scope.note_dispatch(2, 4, 2, 1, 0.0)
        scope.note_collect(4, 4, 0.0)
        scope.note_idle(0.1)
        scope.note_slot_admit(0, 0, "dense")
        scope.inject_waste("dead_slot", 100)
        assert scope.summary() is None
        assert sum(scope.waste.values()) == 0
        assert scope.seconds["idle"] == 0.0


# -- the waste math, helper-level then engine-level -------------------------

class TestWasteMath:
    def test_admit_waste_decomposition(self):
        assert admit_waste(16, [5, 9], 2) == (14, 18, 0)
        # 3 live rows padded to 4 -> one duplicate row of bucket size
        assert admit_waste(32, [17, 20, 30], 4) == (67, 29, 32)
        # a hit admission dispatches zero tokens
        assert admit_waste(0, [], 2) == (0, 0, 0)

    def test_span_overshoot_matches_brute_force(self):
        for lens, span, chunk in [([5, 9], 24, 2), ([5], 8, 4),
                                  ([7, 7, 7], 16, 8), ([15], 16, 4),
                                  ([3], 64, 1), ([63], 64, 8)]:
            expected = sum(
                max(0, span - (n + i))
                for n in lens for i in range(1, chunk + 1))
            assert span_overshoot_tokens(lens, span, chunk) \
                == expected, (lens, span, chunk)

    def test_page_overshoot_is_the_span_form(self):
        assert page_overshoot_tokens([5], 2, 8, 1) \
            == span_overshoot_tokens([5], 16, 1)

    def test_dense_engine_exact_accounting(self, _fresh_scope):
        """Two prompts (lens 5 and 9, one bucket-16 group), budget 4,
        4 slots, tile 8, unpipelined chunk=1 drain: every cause is
        hand-computable."""
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=4,
                                max_len=64, n_tokens=4, tile=8)
        dec.submit([1, 2, 3, 4, 5])
        dec.submit(list(range(1, 10)))
        dec.run_until_drained(chunk=1)
        assert scope.useful == {"prefill": 14, "decode": 8}
        assert scope.waste["bucket_pad"] == 18     # (16-5) + (16-9)
        assert scope.waste["group_dup"] == 0       # 2 rows is pow2
        assert scope.waste["dead_slot"] == 8       # 2 idle lanes x 4
        assert scope.waste["discard"] == 0         # chunk=1, no tails
        assert scope.waste["page_overshoot"] == 0
        expected = 0
        lens = [5, 9]
        for _ in range(4):
            span = -(-(max(lens) + 1) // 8) * 8
            expected += sum(span - (n + 1) for n in lens)
            lens = [n + 1 for n in lens]
        assert scope.waste["span_overshoot"] == expected
        occupancy = scope.occupancy()
        assert occupancy["fraction"] == 0.5        # 2 of 4 lanes live
        assert occupancy["total_lane_steps"] == 16

    def test_group_duplicate_rows_counted(self, _fresh_scope):
        """Three same-bucket prompts pad to a 4-row group: one
        duplicate row of bucket positions books as group_dup."""
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=4,
                                max_len=64, n_tokens=1, tile=8)
        for _ in range(3):
            dec.submit([1, 2, 3])
        dec.run_until_drained(chunk=1)
        assert scope.waste["group_dup"] == 16
        assert scope.useful["prefill"] == 9
        assert scope.waste["bucket_pad"] == 3 * (16 - 3)

    def test_paged_engine_exact_accounting(self, _fresh_scope):
        """The paged twin: PB-page gathers overshoot the live length,
        dead lanes' scratch appends book as dead_slot."""
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=3, tile=8,
                                paged=True, page_size=8)
        dec.submit([1, 2, 3])
        dec.run_until_drained(chunk=1)
        assert scope.useful == {"prefill": 3, "decode": 3}
        assert scope.waste["bucket_pad"] == 13     # bucket 16 - 3
        assert scope.waste["dead_slot"] == 3       # 1 idle lane x 3
        # steps gather 1 page (8 positions) at lens 3/4/5 ->
        # overshoot 4 + 3 + 2
        assert scope.waste["page_overshoot"] == 9
        assert scope.waste["span_overshoot"] == 0
        rows = scope.slot_rows()
        assert [row["kind"] for row in rows] == ["cold"]

    def test_lag_tail_books_discard(self, _fresh_scope):
        """The pipelined drain's lag-1 retirement tail: tokens
        computed for a finished slot are discarded, never delivered —
        and the useful tally still equals exactly what was
        delivered."""
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=5, tile=8)
        dec.submit([1, 2, 3])
        dec.submit([4, 5, 6])
        results = dec.drain_pipelined(chunk=2)
        delivered = sum(len(tokens) for tokens in results.values())
        assert delivered == 10
        assert scope.useful["decode"] == delivered
        assert scope.waste["discard"] > 0

    def test_cancel_retires_slot_as_cancelled(self, _fresh_scope):
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=8, tile=8)
        rid = dec.submit([1, 2, 3])
        dec.step()
        assert dec.cancel(rid)
        rows = [row for row in scope.slot_rows()
                if row["rid"] == rid]
        assert rows and rows[0]["reason"] == "cancelled"
        assert rows[0]["retire"] is not None

    def test_injected_waste_books_named_cause(self, _fresh_scope):
        scope = _fresh_scope
        scope.inject_waste("span_overshoot", 123)
        scope.inject_waste("not-a-cause", 999)  # silently ignored
        assert scope.waste["span_overshoot"] == 123
        assert sum(scope.waste.values()) == 123
        assert scope.dominant_cause() == "span_overshoot"


# -- wall decomposition + the slot occupancy timeline -----------------------

class TestWallAndTimeline:
    def test_wall_components_accumulate(self):
        scope = ServeScope()
        base = time.monotonic()
        scope.note_admit("dense", 16, 1, 1, 5, 11, 0, 0.010,
                         now=base + 0.010)
        scope.note_dispatch(2, 4, 1, 0, 0.020, now=base + 0.040)
        scope.note_collect(2, 2, 0.005, now=base + 0.050)
        scope.note_idle(0.030, now=base + 0.080)
        seconds = scope.seconds
        assert seconds["prefill_compute"] == pytest.approx(0.010)
        assert seconds["decode_compute"] == pytest.approx(0.025)
        # dispatch started 10ms after the admit mark, collect started
        # 5ms after the dispatch mark -> 15ms of host bookkeeping
        assert seconds["host"] == pytest.approx(0.015)
        assert seconds["idle"] == pytest.approx(0.030)

    def test_slot_timeline_ordering(self, _fresh_scope):
        from veles_tpu.serving import ContinuousDecoder

        scope = _fresh_scope
        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=3, tile=8)
        first = dec.submit([1, 2, 3])
        second = dec.submit([4, 5, 6, 7, 8])
        dec.run_until_drained(chunk=1)
        rows = {row["rid"]: row for row in scope.slot_rows()}
        assert set(rows) == {first, second}
        for row in rows.values():
            assert row["kind"] == "dense"
            assert row["reason"] == "done"
            assert row["admit"] <= row["first"] <= row["retire"]
            assert row["slot"] in (0, 1)


# -- detector-owned anomaly rules + incident artifacts ----------------------

class TestAutopsy:
    def test_waste_incident_names_dominant_cause(self, tmp_path):
        scope = ServeScope()
        history = _history(tmp_path)
        path = None
        for _ in range(4):
            scope.note_collect(8, 8, 0.0)
            scope.inject_waste("group_dup", 5000)
            scope.inject_waste("bucket_pad", 7)
            path = scope.autopsy_tick(history) or path
        assert path is not None
        doc = json.load(open(path))
        assert doc["reason"] == "serve_waste"
        assert doc["trigger"]["dominant_cause"] == "group_dup"
        assert doc["trigger"]["value"] >= WASTE_SHARE_BREACH
        assert ["cause", "group_dup"] in doc["trigger"]["labels"]
        # the breach-window per-cause decomposition rides the artifact
        assert doc["trigger"]["waste_window"]["group_dup"] > 0

    def test_occupancy_collapse_incident(self, tmp_path):
        scope = ServeScope()
        history = _history(tmp_path)
        path = None
        for _ in range(5):
            scope.note_dispatch(4, 8, 1, 0, 0.0)  # 1/8 occupancy
            scope.note_collect(4, 4, 0.0)
            # keep the waste share healthy so only occupancy breaches
            scope.useful["decode"] += 1000
            path = scope.autopsy_tick(history) or path
        assert path is not None and "serve_occupancy" in path
        doc = json.load(open(path))
        assert doc["trigger"]["value"] <= OCCUPANCY_BREACH

    def test_rules_are_external_and_idempotent(self, tmp_path):
        history = _history(tmp_path)
        waste, occupancy = ensure_serve_rules(history)
        assert waste.external and occupancy.external
        assert ensure_serve_rules(history) == (waste, occupancy)
        # the sampler-side evaluator must skip detector-owned rules
        history.sample(rows=[("veles_serve_waste_share", "gauge", (),
                              0.99)])
        assert waste.streak == 0 and waste.fired_total == 0

    def test_healthy_window_resets_streak(self, tmp_path):
        scope = ServeScope()
        history = _history(tmp_path)
        waste, _ = ensure_serve_rules(history)
        scope.inject_waste("dead_slot", 1000)
        scope.autopsy_tick(history)
        assert waste.streak == 1
        scope.useful["decode"] += 10000
        scope.autopsy_tick(history)
        assert waste.streak == 0 and waste.breach_since is None

    def test_toy_trickle_below_floor_never_pages(self, tmp_path):
        """The verify-drive regression: a lightly-loaded server's
        organic dead-slot/overshoot waste on a handful of tokens must
        not land incidents — sub-floor windows accumulate instead of
        judging."""
        from veles_tpu.observe.servescope import MIN_EVAL_TOKENS

        scope = ServeScope()
        history = _history(tmp_path)
        waste, _ = ensure_serve_rules(history)
        for _ in range(20):
            scope.note_dispatch(2, 4, 1, 3, 0.0)   # mostly waste
            scope.note_collect(2, 2, 0.0)
            assert scope.autopsy_tick(history) is None
        assert waste.fired_total == 0
        # ... but the accumulated trickle IS judged once it crosses
        # the floor (anchors were never consumed)
        scope.inject_waste("dead_slot", MIN_EVAL_TOKENS)
        scope.autopsy_tick(history)
        assert waste.streak >= 1

    def test_dispatch_free_window_with_stale_streak(self, tmp_path):
        """Review regression: an admit-only evaluation window
        (occupancy None) meeting a COMPLETED occupancy streak from
        earlier windows must not fire (or crash formatting None) —
        the streak simply holds until decode traffic returns."""
        scope = ServeScope()
        history = _history(tmp_path)
        waste_rule, occupancy_rule = ensure_serve_rules(history)
        # build the occupancy streak while the waste rule (which
        # fires first) burns its cooldown
        for _ in range(3):
            scope.note_dispatch(4, 8, 1, 0, 0.0)
            scope.note_collect(4, 4, 0.0)
            scope.useful["decode"] += 1000
            scope.autopsy_tick(history)
        assert occupancy_rule.streak >= occupancy_rule.for_samples
        occupancy_rule.last_fired = None  # armed to fire next breach
        # a dispatch-free window: prefill tokens only, occupancy None
        scope.note_admit("dense", 512, 1, 1, 400, 112, 0, 0.0)
        assert scope.autopsy_tick(history) is None
        # the armed rule did NOT fire on the None window
        assert occupancy_rule.last_fired is None

    def test_no_traffic_is_a_noop(self, tmp_path):
        scope = ServeScope()
        history = _history(tmp_path)
        assert scope.autopsy_tick(history) is None
        assert scope.autopsy_tick(None) is None

    def test_cooldown_limits_artifacts(self, tmp_path):
        scope = ServeScope()
        history = MetricHistory(
            registry=MetricsRegistry(enabled=False),
            incidents=IncidentRecorder(cooldown_s=3600.0,
                                       directory=str(tmp_path)))
        paths = []
        for _ in range(6):
            scope.note_collect(2, 2, 0.0)
            scope.inject_waste("dead_slot", 500)
            result = scope.autopsy_tick(history)
            if result:
                paths.append(result)
        assert len(paths) == 1


# -- metrics + health surfaces ----------------------------------------------

class TestMetricsAndHealth:
    def test_collector_publishes_families(self, _fresh_scope):
        scope = _fresh_scope
        scope.note_admit("dense", 16, 2, 2, 14, 18, 0, 0.001)
        scope.note_dispatch(2, 4, 2, 3, 0.001)
        scope.note_collect(4, 4, 0.0)
        registry = MetricsRegistry(enabled=True)
        ensure_serve_registered(registry)
        ensure_serve_registered(registry)  # idempotent
        text = registry.expose()
        for token in ("veles_serve_goodput_fraction",
                      'veles_serve_goodput_seconds_total{'
                      'component="prefill_compute"}',
                      'veles_serve_token_waste_total{'
                      'cause="bucket_pad"}',
                      'veles_serve_tokens_useful_total{'
                      'phase="decode"}',
                      "veles_serve_slot_occupancy",
                      "veles_serve_waste_share"):
            assert token in text, token

    def test_trafficless_scope_publishes_nothing(self):
        registry = MetricsRegistry(enabled=True)
        publish_serve_scope(registry, ServeScope())
        assert "veles_serve_" not in registry.expose()

    def test_health_snapshot_and_dashboard_cell(self, _fresh_scope):
        from veles_tpu.serving import ServingHealth
        from veles_tpu.web_status import format_serving_health

        scope = _fresh_scope
        scope.note_dispatch(4, 4, 2, 0, 0.0)
        scope.note_collect(8, 8, 0.0)
        health = ServingHealth()
        health.attach_servescope(scope)
        snap = health.snapshot()
        # 8 live of 16 lane-steps; 8 useful tokens vs 8 dead-slot
        assert snap["servescope"]["occupancy"] == 0.5
        assert snap["servescope"]["goodput"] == 0.5
        assert snap["servescope"]["dominant_cause"] == "dead_slot"
        cell = format_serving_health(snap)
        assert "occupancy 50%" in cell
        assert "goodput 50%" in cell
        assert "waste 50% (dead_slot)" in cell

    def test_waste_causes_cover_the_catalog(self):
        assert set(WASTE_CAUSES) == {
            "bucket_pad", "group_dup", "span_overshoot",
            "page_overshoot", "tile_pad", "dead_slot", "discard"}


# -- trace assembly + the serve-trace CLI -----------------------------------

def _payload():
    return {
        "kind": "servescope", "schema": 1, "pid": 7,
        "goodput": {"fraction": 0.5, "useful_tokens": 10,
                    "waste_tokens": 10, "seconds": {}},
        "waste": {"dead_slot": 10}, "dominant_cause": "dead_slot",
        "occupancy": {"fraction": 0.5, "live_lane_steps": 1,
                      "total_lane_steps": 2},
        "slots": [
            {"slot": 0, "rid": 7, "kind": "dense", "admit": 1.0,
             "first": 1.1, "retire": 1.5, "reason": "done",
             "trace": None, "span": None},
            {"slot": 1, "rid": 8, "kind": "hit", "admit": 1.2,
             "first": None, "retire": None, "reason": None,
             "trace": "abc", "span": "s1"}],
        "requests": {"inflight": [], "slowest": [
            {"rid": 7, "id": 3, "trace": "t7",
             "outcome": "completed",
             "stages": [["staged", 0.9], ["admitted", 1.0],
                        ["resolved", 1.5]]}]},
    }


class TestServeTrace:
    def test_one_row_per_slot_and_connected_chains(self):
        trace = assemble_serve_trace(_payload())
        events = trace["traceEvents"]
        slots_pid = next(
            e["pid"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"
            and e["args"]["name"].startswith("slots"))
        slot_tids = {e["tid"] for e in events
                     if e.get("ph") == "M"
                     and e["name"] == "thread_name"
                     and e["pid"] == slots_pid}
        assert slot_tids == {0, 1}
        trees = span_tree(trace)
        # the occupancy span parents to the ledger-row span: one
        # connected chain per request, linked by the trace id
        assert trees["t7"]["occ-7"] == "req-7"
        assert "req-7" in trees["t7"]
        assert trees["t7"]["first-7"] == "occ-7"
        # the still-open slot renders (no retire -> a B event)
        assert any(e.get("ph") == "B" for e in events)

    def test_cli_round_trip_saved_payload(self, tmp_path, capsys):
        saved = tmp_path / "serve.json"
        saved.write_text(json.dumps(_payload()))
        assert serve_trace_main(str(saved)) == 0
        out = capsys.readouterr().out
        assert "dominant waste cause: dead_slot" in out
        trace_path = tmp_path / "serve.trace.json"
        assert trace_path.exists()
        trace = json.loads(trace_path.read_text())
        assert trace["traceEvents"]

    def test_cli_rejects_garbage(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"kind": "nope"}))
        assert serve_trace_main(str(bad)) == 1
        missing = tmp_path / "missing.json"
        assert serve_trace_main(str(missing)) == 1

    def test_load_payload_unwraps_embedding(self, tmp_path):
        wrapped = tmp_path / "wrapped.json"
        wrapped.write_text(json.dumps({"servescope": _payload()}))
        assert load_serve_payload(str(wrapped))["kind"] == "servescope"


# -- HTTP surfaces (GenerateAPI end to end) ---------------------------------

class TestHTTPSurfaces:
    def test_debug_serve_index_metrics_and_live_trace(
            self, _fresh_scope, tmp_path):
        from veles_tpu.serving import GenerateAPI

        params, table, heads = _tiny()
        api = GenerateAPI(params, table, heads, slots=2, max_len=64,
                          n_tokens=3, chunk=2, chaos=None).start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            request = urllib.request.Request(
                url + "/generate",
                json.dumps({"tokens": [1, 2, 3]}).encode(),
                {"Content-Type": "application/json"})
            reply = json.load(urllib.request.urlopen(request,
                                                     timeout=30))
            assert len(reply["tokens"]) == 3
            debug = json.load(urllib.request.urlopen(
                url + "/debug/serve", timeout=10))
            assert debug["kind"] == "servescope"
            assert debug["goodput"]["useful_tokens"] > 0
            assert any(row["reason"] == "done"
                       for row in debug["slots"])
            assert "requests" in debug
            index = json.load(urllib.request.urlopen(
                url + "/debug/", timeout=10))
            assert set(index["surfaces"]) == {
                "/debug/requests", "/debug/history", "/debug/serve",
                "/debug/memory"}
            healthz = json.load(urllib.request.urlopen(
                url + "/healthz", timeout=10))
            assert 0.0 <= healthz["servescope"]["goodput"] <= 1.0
            assert "occupancy" in healthz["servescope"]
            metrics = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            assert "veles_serve_goodput_fraction" in metrics
            assert 'veles_serve_token_waste_total{cause="dead_slot"}' \
                in metrics
            out = tmp_path / "live.trace.json"
            assert serve_trace_main(live=url, output=str(out)) == 0
            trace = json.loads(out.read_text())
            assert trace["traceEvents"]
        finally:
            api.stop()

    def test_restful_api_mounts_index(self):
        from veles_tpu.core.httpd import DEBUG_SURFACES
        assert set(DEBUG_SURFACES) == {
            "/debug/requests", "/debug/history", "/debug/serve",
            "/debug/memory"}


# -- the chaos waste profile ------------------------------------------------

class TestChaosWasteProfile:
    def test_config_validation(self):
        from veles_tpu.serving_chaos import ServingChaosConfig

        with pytest.raises(ValueError, match="waste_cause"):
            ServingChaosConfig(waste_cause="nope", waste_tokens=10,
                               waste_steps=2)
        with pytest.raises(ValueError):
            ServingChaosConfig(waste_cause="dead_slot",
                               waste_tokens=-1)
        config = ServingChaosConfig(waste_cause="group_dup",
                                    waste_tokens=1000, waste_at=1,
                                    waste_steps=4)
        assert config.any_profile
        assert config.expected_leading_cause() == "group_dup"
        assert config.expected_leading_series()["waste_profile"] \
            == "veles_serve_waste_share"
        assert ServingChaosConfig().expected_leading_cause() is None

    @pytest.mark.slow
    def test_injected_cause_names_itself(self, _fresh_scope,
                                         tmp_path):
        """The acceptance: a seeded chaos waste profile deterministically
        yields an incident artifact naming the injected dominant
        cause."""
        from veles_tpu.serving import GenerateAPI
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        config = ServingChaosConfig(waste_cause="group_dup",
                                    waste_tokens=5000, waste_at=1,
                                    waste_steps=6)
        monkey = ServingChaosMonkey(config)
        history = _history(tmp_path)
        set_metric_history(history)
        params, table, heads = _tiny()
        api = GenerateAPI(params, table, heads, slots=4, max_len=64,
                          n_tokens=4, chunk=2, chaos=monkey).start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            for prompt in ([1, 2, 3], list(range(1, 10))):
                request = urllib.request.Request(
                    url + "/generate",
                    json.dumps({"tokens": prompt}).encode(),
                    {"Content-Type": "application/json"})
                json.load(urllib.request.urlopen(request, timeout=30))
            def waste_incidents():
                return sorted(tmp_path.glob(
                    "incident-*-serve_waste-*.json"))

            deadline = time.monotonic() + 20
            while not waste_incidents() \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            api.stop()
            set_metric_history(None)
        assert monkey.counters["waste_injections"] > 0
        # the synthetic injection also craters occupancy, so a
        # serve_occupancy incident may land too — the acceptance is
        # the WASTE incident naming the injected cause
        paths = waste_incidents()
        assert paths
        doc = json.load(open(paths[0]))
        assert doc["reason"] == "serve_waste"
        assert doc["trigger"]["dominant_cause"] \
            == config.expected_leading_cause()
        # the scope's own decomposition agrees (the injected cause
        # dominates the organic padding/overshoot waste)
        assert _fresh_scope.dominant_cause() == "group_dup"
