"""Seeded violation for ``shared.rmw`` — the test registry declares
``SharedCounters`` reachable from handler AND driver threads; the
unlocked ``+= 1`` interleaves load/op/store across threads and drops
updates (the locked dict update below is the sanctioned shape)."""

import threading


class SharedCounters:
    def __init__(self):
        self._lock = threading.Lock()
        self.served = 0
        self.by_kind = {}

    def book(self, kind):
        self.served += 1  # analyze-expect: shared.rmw
        with self._lock:
            self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
