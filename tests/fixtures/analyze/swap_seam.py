"""Seeded ``deploy.swap-seam`` violation: a handler-side hot patch
clobbers the live decoder's weights directly instead of routing the
swap through the drive loop's drained seam."""


class ToyDecoder:
    def __init__(self, params, embed_table):
        self.params = params            # sanctioned: pre-publication
        self.embed_table = embed_table

    def swap_params(self, new_params):
        old = self.params
        self.params = new_params        # sanctioned: the seam itself
        return old


class ToyHandler:
    def __init__(self, decoder):
        self.decoder = decoder

    def hot_patch(self, new_params):
        self.decoder.params = new_params  # analyze-expect: deploy.swap-seam
