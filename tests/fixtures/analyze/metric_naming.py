"""Seeded violation for ``metric.naming`` — a counter without the
``_total`` suffix (PR 5's Prometheus grammar). ``help=`` is present so
only the naming rule fires on this file."""


def publish(registry):
    registry.incr("veles_fixture_requests", help="seeded bad counter")  # analyze-expect: metric.naming
