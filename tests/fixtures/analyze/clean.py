"""The negative control: clean under EVERY rule, even though the test
registry declares ``CleanLedger.record`` record-path and
``CleanShared`` thread-shared — each construct below is the sanctioned
shape of a pattern the sibling fixtures violate."""

import threading

import jax

_FN_CACHE = {}


def _step(x):
    return x


def cached_dispatch(key, x):
    # the sanctioned miss-branch shape: one jit object per key
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_step)
        _FN_CACHE[key] = fn
    return fn(x)


class CleanLedger:
    def __init__(self):
        self.rows = []
        self.dropped = 0

    def record(self, stamp):
        # GIL-atomic container append: the flight-recorder discipline
        self.rows.append(stamp)


class CleanShared:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def book(self):
        with self._lock:
            self.count += 1


def publish(registry):
    registry.incr("veles_clean_total", help="clean fixture counter")
