"""Seeded violation for ``lock.ordering`` — ``forward`` nests
alpha->beta, ``backward`` nests beta->alpha: the classic two-thread
deadlock, reported where the second ordering completes."""

import threading


class TwoLocks:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()
        self.balance = 0

    def forward(self):
        with self._alpha_lock:
            with self._beta_lock:
                self.balance = 1

    def backward(self):
        with self._beta_lock:
            with self._alpha_lock:  # analyze-expect: lock.ordering
                self.balance = 2
