"""Seeded violation for ``retrace.unhashable-static`` — passing a
list for a declared static argname: statics key the jit cache, so an
unhashable one raises (and a call-varying one re-traces per call)."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("cfg",))
def program(x, cfg):
    return x


def run(x):
    return program(x, cfg=["a", "b"])  # analyze-expect: retrace.unhashable-static
