"""Seeded violation for ``metric.help`` — a validly named gauge whose
family never passes ``help=`` at any call site (a bare ``# HELP`` line
dashboards cannot explain)."""


def publish(registry):
    registry.set("veles_fixture_depth", 3)  # analyze-expect: metric.help
