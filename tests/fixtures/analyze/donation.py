"""Seeded violation for ``donation.read-after-dispatch`` — ``state``
is donated to the jitted step, then read again: XLA may already have
reused its buffer (PR 9's donated-buffer doctrine)."""

import jax


def _train(state, batch):
    return state


step = jax.jit(_train, donate_argnums=(0,))


def tick(state, batch):
    out = step(state, batch)
    return state, out  # analyze-expect: donation.read-after-dispatch
