"""Seeded violation for ``retrace.shape-key`` — a program cache keyed
on a list: shape keys must be canonical hashable tuples (one compiled
program per canonical key is the dispatch-economy invariant)."""

_PROGRAM_CACHE = {}


def remember(bucket, group, fn):
    _PROGRAM_CACHE[[bucket, group]] = fn  # analyze-expect: retrace.shape-key
