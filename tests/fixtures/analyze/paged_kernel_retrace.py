"""Seeded violations for the fused paged-attention kernel's jit
surface (ops/paged_attention.py): the kernel wrapper carries static
``page_size``/``block_h`` arguments, so a careless integration could
(a) rebuild the jit inside the per-request serve loop — a fresh
traced callable per admitted request, the per-request retrace the
capability-probe doctrine exists to prevent — or (b) key the statics
on an unhashable block-shape list. The SHIPPED module does neither
(tests/test_analyze.py asserts the real kernel surface is
retrace-clean); this fixture proves the rules would catch both
regressions at the exact line."""

import functools

import jax


def _paged_attend(q, page_table, lengths, *, page_size, block_h):
    return q


def serve_requests(requests, page_size):
    outs = []
    for q, page_table, lengths in requests:
        attend = functools.partial(_paged_attend, page_size=page_size,
                                   block_h=8)
        step = jax.jit(attend)  # analyze-expect: retrace.jit-in-loop
        outs.append(step(q, page_table, lengths))
    return outs


@functools.partial(jax.jit, static_argnames=("block_shape",))
def tuned_attend(q, block_shape):
    return q


def admit(q):
    return tuned_attend(q, block_shape=[8, 128])  # analyze-expect: retrace.unhashable-static
