"""Seeded violation for ``retrace.jit-in-loop`` — constructing the
jit inside the loop body builds a fresh traced callable per iteration
(nothing cached across iterations)."""

import jax


def _step(x):
    return x + 1


def sweep(batches):
    outs = []
    for batch in batches:
        fn = jax.jit(_step)  # analyze-expect: retrace.jit-in-loop
        outs.append(fn(batch))
    return outs
