"""Seeded violation for ``retrace.unpinned-out-shardings`` — a mesh
jit that pins in_shardings but lets the output layout float (the PR 6
retrace-storm signature)."""

import jax

SPECS = object()


def build_step(fn):
    return jax.jit(fn, in_shardings=SPECS)  # analyze-expect: retrace.unpinned-out-shardings
