"""Seeded violation for ``lock.record-path`` — the test registry
declares ``ToyLedger.record`` a record-path function; the sleep is the
one violation (the append is the sanctioned GIL-atomic op)."""

import time


class ToyLedger:
    def __init__(self):
        self.marks = []

    def record(self, stamp):
        time.sleep(0.001)  # analyze-expect: lock.record-path
        self.marks.append(stamp)
