"""Seeded violation for ``retrace.local-jit-dispatch`` — jitting a
fresh shard_map wrapper and dispatching it in the same scope: the jit
cache keys on the wrapper's identity, so every ``run_once`` call
re-traces."""

import jax


def shard_map(fn, mesh=None):
    return fn


def run_once(xs, mesh):
    fn = jax.jit(shard_map(_double, mesh=mesh))
    return fn(xs)  # analyze-expect: retrace.local-jit-dispatch


def _double(x):
    return x * 2
