"""Fleet-mode tests: master+slave in one process over loopback (the
reference's key distributed-test pattern, ``test_network.py:111-137`` /
``test_launcher.py:91-118``)."""

import asyncio
import os
import threading

import numpy
import pytest

from veles_tpu.core import prng
from veles_tpu.fleet.protocol import encode_frame, machine_id
from veles_tpu.launcher import Launcher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow


def _digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    return (d.data.astype(numpy.float32),
            d.target.astype(numpy.int32))


def _kw(max_epochs=2, minibatch=300):
    X, y = _digits()
    return dict(
        layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=minibatch,
                           normalization_type="linear"),
        learning_rate=0.5, max_epochs=max_epochs)


def _seed():
    prng.get("default").seed(42)
    prng.get("loader").seed(43)


def _run_master(kw):
    _seed()
    master = Launcher(listen_address="127.0.0.1:0")
    wf = MLPWorkflow(master, name="fleet-t", **kw)
    master.initialize()
    thread = threading.Thread(target=master.run, daemon=True)
    thread.start()
    return master, wf, thread


def _run_slave(port, kw, **slave_kw):
    _seed()
    slave = Launcher(master_address="127.0.0.1:%d" % port, **slave_kw)
    MLPWorkflow(slave, name="fleet-t", **kw)
    slave.initialize()
    return slave


class FakeReader:
    def __init__(self, data):
        import io
        self.buf = io.BytesIO(data)

    async def readexactly(self, n):
        data = self.buf.read(n)
        if len(data) < n:
            raise asyncio.IncompleteReadError(data, n)
        return data


KEY = b"test-secret"


class TestProtocol:
    def test_frame_roundtrip(self):
        msg = {"type": "job", "job": [numpy.arange(5), {"a": 1}]}
        frame = encode_frame(msg, KEY)
        from veles_tpu.fleet.protocol import read_frame
        out = asyncio.run(
            read_frame(FakeReader(frame), KEY))
        assert out["type"] == "job"
        numpy.testing.assert_array_equal(out["job"][0], numpy.arange(5))

    def test_big_frame_compressed(self):
        big = {"data": numpy.zeros(1024 * 1024, numpy.float32)}
        frame = encode_frame(big, KEY)
        assert len(frame) < 1024 * 1024  # gzip kicked in

    def test_unauthenticated_frame_rejected(self):
        """A frame MAC'd with the wrong key must never reach
        pickle.loads (pre-handshake RCE hardening)."""
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        frame = encode_frame({"type": "hello"}, b"attacker-key")
        with pytest.raises(ProtocolError):
            asyncio.run(
                read_frame(FakeReader(frame), KEY))

    def test_tampered_frame_rejected(self):
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        frame = bytearray(encode_frame({"type": "hello"}, KEY))
        frame[-1] ^= 0xFF
        with pytest.raises(ProtocolError):
            asyncio.run(
                read_frame(FakeReader(bytes(frame)), KEY))

    def test_secret_defaults_to_workflow_checksum(self, monkeypatch):
        from veles_tpu.core.config import root
        from veles_tpu.fleet.protocol import resolve_secret

        monkeypatch.delenv("VELES_TPU_FLEET_SECRET", raising=False)
        # root is a process-global singleton: force the unset state rather
        # than assuming no earlier test configured a secret
        monkeypatch.setattr(root.common.fleet, "secret", None, raising=False)

        class WF:
            checksum = "abc123"

        secret, source = resolve_secret(WF(), with_source=True)
        assert secret == b"abc123" and source == "checksum"

    def test_machine_id_stable(self):
        assert machine_id() == machine_id()

    @staticmethod
    def _raw_frame(codec, payload):
        """Build a frame with an arbitrary codec byte and a VALID MAC, so
        the test exercises the post-authentication rejection path."""
        import struct
        from veles_tpu.fleet.protocol import _mac
        return (struct.pack(">IB", len(payload), codec)
                + _mac(KEY, codec, payload) + payload)

    def test_gzip_bomb_rejected(self):
        """An authenticated peer must not be able to detonate a gzip bomb:
        the frame limit applies to the DECOMPRESSED size too."""
        import gzip
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        bomb = gzip.compress(b"\0" * (4 * 1024 * 1024), compresslevel=9)
        assert len(bomb) < 1024 * 1024  # fits the wire-length check
        frame = self._raw_frame(1, bomb)
        with pytest.raises(ProtocolError, match="exceeds limit"):
            asyncio.run(read_frame(FakeReader(frame), KEY,
                                   max_frame=1024 * 1024))

    def test_truncated_gzip_member_rejected(self):
        """A truncated gzip member is a protocol violation, never
        silently-partial data."""
        import gzip
        import pickle
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        member = gzip.compress(pickle.dumps({"type": "job"}))
        frame = self._raw_frame(1, member[:-6])
        with pytest.raises(ProtocolError,
                           match="gzip"):
            asyncio.run(read_frame(FakeReader(frame), KEY))

    def test_unknown_codec_byte_rejected(self):
        """An authenticated frame with an unassigned codec byte must be
        rejected before any deserialization."""
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        frame = self._raw_frame(7, b"payload")
        with pytest.raises(ProtocolError, match="unknown frame codec"):
            asyncio.run(read_frame(FakeReader(frame), KEY))

    def test_oversized_preauth_hello_rejected(self):
        """The server reads the pre-auth hello with a 64 KiB cap: an
        unauthenticated peer cannot make it buffer a giant payload."""
        from veles_tpu.fleet.protocol import ProtocolError, read_frame
        # incompressible padding: the frame must exceed the cap on the
        # wire, exercising the pre-buffer length check (a compressible
        # payload would instead trip the decompressed-size guard)
        big = encode_frame({"type": "hello",
                            "pad": os.urandom(1 << 17)}, KEY)
        with pytest.raises(ProtocolError, match="exceeds limit"):
            asyncio.run(read_frame(FakeReader(big), KEY,
                                   max_frame=1 << 16))


class TestSharedIO:
    """Same-host shared-memory data plane (reference txzmq SharedIO)."""

    def _read(self, frame):
        from veles_tpu.fleet.protocol import read_frame
        return asyncio.run(
            read_frame(FakeReader(frame), KEY))

    @staticmethod
    def _segments():
        from veles_tpu.fleet import sharedio
        return {n for n in os.listdir(sharedio.shm_dir())
                if n.startswith(sharedio._PREFIX)}

    def test_shm_frame_roundtrip(self):
        msg = {"type": "job", "job": numpy.arange(50000)}
        before = self._segments()
        frame = encode_frame(msg, KEY, shm_threshold=0)
        # only the descriptor rode the wire
        assert len(frame) < 1024
        created = self._segments() - before
        assert len(created) == 1, "no segment created"
        out = self._read(frame)
        numpy.testing.assert_array_equal(out["job"], numpy.arange(50000))
        assert not created & self._segments(), "segment not unlinked"

    def test_shm_tamper_rejected(self):
        from veles_tpu.fleet import sharedio
        from veles_tpu.fleet.protocol import ProtocolError
        before = self._segments()
        frame = encode_frame({"x": numpy.zeros(9000)}, KEY,
                             shm_threshold=0)
        name = (self._segments() - before).pop()
        path = os.path.join(sharedio.shm_dir(), name)
        with open(path, "r+b") as f:
            f.write(b"\xff")
        with pytest.raises(ProtocolError):
            self._read(frame)
        # left in place on failed verification
        assert name in self._segments()
        os.unlink(path)

    def test_shm_path_containment(self):
        """A descriptor must not be able to point outside the segment
        namespace (authenticated-peer unlink/read primitive)."""
        import pickle
        from veles_tpu.fleet.protocol import ProtocolError
        for name in ("../../etc/passwd", "/etc/passwd", "evil"):
            bad = {"__shm__": {"name": name, "size": 1, "mac": "0"}}
            frame = encode_frame(bad, KEY)
            with pytest.raises(ProtocolError):
                self._read(frame)

    def test_negotiated_on_loopback_fleet(self):
        """Same machine id -> the welcome negotiates shm; a big job
        payload moves via a segment end-to-end."""
        from veles_tpu.fleet import sharedio
        from veles_tpu.fleet.server import Server

        class BigJobWorkflow:
            checksum = "shm-test"
            applied = []

            def generate_initial_data_for_slave(self, slave):
                return None

            def generate_data_for_slave(self, slave):
                if self.applied:
                    return None
                return numpy.ones(200000, numpy.float32)  # 800KB

            def apply_data_from_slave(self, update, slave):
                self.applied.append(numpy.asarray(update).sum())

            def apply_initial_data_from_master(self, initial):
                pass

            def do_job(self, job, callback):
                callback(numpy.asarray(job) * 2)

            def drop_slave(self, slave):
                pass

            def has_more_jobs(self):
                return not self.applied

        from veles_tpu.fleet.client import Client
        wf = BigJobWorkflow()
        server = Server("127.0.0.1:0", wf, secret="shm-test").start()
        done = threading.Event()
        server.on_finished = done.set
        client = Client(server.address, BigJobWorkflow(),
                        secret="shm-test").start()
        try:
            assert done.wait(timeout=20), "fleet job did not complete"
            assert wf.applied and wf.applied[0] == 400000.0
            slave = next(iter(server.slaves.values()), None)
            assert slave is None or slave.shm_threshold is not None
        finally:
            client.stop()
            server.stop()


@pytest.mark.slow
class TestLoopback:
    def test_sync_training_and_parity(self):
        """One master + one sync slave must produce the SAME result as a
        standalone run (sequential SGD equivalence)."""
        kw = _kw()
        _seed()
        lau = Launcher()
        wf_sa = MLPWorkflow(lau, name="fleet-t", **kw)
        lau.initialize()
        lau.run()
        expected = wf_sa.decision.best_n_err[VALID]

        master, wf_m, thread = _run_master(kw)
        slave = _run_slave(master.agent.port, kw)
        slave.run()
        thread.join(60)
        assert not thread.is_alive(), "master did not finish"
        assert wf_m.decision.best_n_err[VALID] == expected
        assert slave.agent.jobs_done == 12  # 2 epochs x (1 valid + 5 train)
        master.stop()
        slave.stop()

    def test_two_slaves_share_the_epoch(self):
        kw = _kw(max_epochs=2)
        master, wf_m, thread = _run_master(kw)
        s1 = _run_slave(master.agent.port, kw)
        s2 = _run_slave(master.agent.port, kw)
        t1 = threading.Thread(target=s1.run, daemon=True)
        t1.start()
        s2.run()
        t1.join(60)
        thread.join(60)
        assert not thread.is_alive()
        total = s1.agent.jobs_done + s2.agent.jobs_done
        # the job stream is asynchronous: with 2 slaves the master may hand
        # out a couple of next-epoch jobs before the stop decision lands,
        # so the total can overshoot the 12-minibatch epoch slightly
        assert total >= 12, "jobs split %d+%d < 12" % (
            s1.agent.jobs_done, s2.agent.jobs_done)
        assert s1.agent.jobs_done > 0 and s2.agent.jobs_done > 0
        assert wf_m.decision.best_n_err[VALID] is not None
        master.stop()
        s1.stop()
        s2.stop()

    def test_n_slave_convergence_parity(self):
        """VERDICT round-1 weak #7: prove N-slave training converges like
        1-slave training on a real dataset (digits, 4 epochs): both must
        reach the same accuracy class."""
        kw = _kw(max_epochs=4, minibatch=300)
        results = {}
        for n_slaves in (1, 2):
            master, wf_m, thread = _run_master(kw)
            slaves = [_run_slave(master.agent.port, kw)
                      for _ in range(n_slaves)]
            threads = [threading.Thread(target=s.run, daemon=True)
                       for s in slaves]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            thread.join(120)
            assert not thread.is_alive(), "master did not finish"
            results[n_slaves] = wf_m.decision.best_n_err[VALID]
            master.stop()
            for s in slaves:
                s.stop()
        # same accuracy class: both clearly learned (digits: 297 valid
        # rows; an untrained model sits near 267 errors). The 2-slave
        # bound is intentionally loose: async stale-update overwrites
        # make the interleaving nondeterministic (observed 40-60 across
        # runs at 4 epochs); sync numerics are pinned EXACTLY by
        # test_sync_training_and_parity instead
        assert results[1] <= 40, results
        assert results[2] <= 80, results
        assert abs(results[1] - results[2]) <= 45, results

    def test_average_merge_convergence_tight(self, monkeypatch):
        """VERDICT r3 #6a: under ``merge="average"`` the blended updates
        make N-slave convergence deterministic-ish, so the bounds can be
        TIGHT (the async ``overwrite`` test above stays loose — that is
        its nature)."""
        from veles_tpu.core.config import root
        monkeypatch.setattr(root.common.fleet, "merge", "average",
                            raising=False)
        kw = _kw(max_epochs=6, minibatch=300)
        results = {}
        for n_slaves in (1, 2):
            master, wf_m, thread = _run_master(kw)
            slaves = [_run_slave(master.agent.port, kw)
                      for _ in range(n_slaves)]
            threads = [threading.Thread(target=s.run, daemon=True)
                       for s in slaves]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            thread.join(180)
            assert not thread.is_alive(), "master did not finish"
            results[n_slaves] = wf_m.decision.best_n_err[VALID]
            master.stop()
            for s in slaves:
                s.stop()
        # both clearly learned (random ~267/297; absolute error trails
        # overwrite-mode because averaging against the stale master
        # state damps each step — the EASGD tradeoff) and, the point:
        # averaging makes the outcome near-independent of slave count
        # and scheduling — measured {1: 42, 2: 46-47} across repeated
        # 6-epoch runs, vs the 40-80 swing that forced the overwrite
        # test's wide bounds
        assert results[1] <= 50, results
        assert results[2] <= 60, results
        assert abs(results[1] - results[2]) <= 12, results

    def test_fleet_payload_covers_all_leaves_and_solver_state(self):
        """VERDICT r3 #6b: (1) GD payloads derive from the unit's slot
        contract — GDSelfAttention's out projection rides them (it
        silently desynchronized before); (2) stateful solvers ship
        moments + step both ways; momentum stays weights-only
        (reference wire parity)."""
        import jax.numpy as jnp

        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.memory import Array
        from veles_tpu.nn.attention import GDSelfAttention
        from veles_tpu.nn.gd import GradientDescent

        wf = DummyWorkflow()
        attn = GDSelfAttention(wf)
        for attr, shape in (("weights", (4, 12)), ("bias", (12,)),
                            ("out_weights", (4, 4)), ("out_bias", (4,))):
            setattr(attn, attr, Array(numpy.ones(shape, numpy.float32)))
        job = attn.generate_data_for_slave()
        assert {"weights", "bias", "out_weights", "out_bias",
                "lr", "lr_bias"} <= set(job)
        momentum = GradientDescent(wf)
        momentum.weights = Array(numpy.ones((3, 2), numpy.float32))
        momentum.bias = Array(numpy.ones(2, numpy.float32))
        assert momentum._solver_state_attrs() == []
        adam = GradientDescent(wf, solver="adam")
        adam.weights = Array(numpy.ones((3, 2), numpy.float32))
        adam.bias = Array(numpy.ones(2, numpy.float32))
        adam.weights.to_device()
        adam.bias.to_device()
        adam.initialize()
        adam._velocity_w.data = jnp.full((3, 2), 0.5)
        adam._second_w.data = jnp.full((3, 2), 0.25)
        adam._step.data = jnp.asarray(7.0)
        update = adam.generate_data_for_master()
        assert {"_velocity_w", "_velocity_b", "_second_w", "_second_b",
                "_step"} <= set(update)
        # master applies the moments (overwrite, regardless of merge)
        master = GradientDescent(wf, solver="adam")
        master.weights = Array(numpy.zeros((3, 2), numpy.float32))
        master.bias = Array(numpy.zeros(2, numpy.float32))
        master.weights.to_device()
        master.bias.to_device()
        master.initialize()
        master.apply_data_from_slave(update)
        numpy.testing.assert_allclose(
            numpy.asarray(master._second_w.data), 0.25)
        assert float(master._step.data) == 7.0
        # and the next job ships them back down (respawned slave
        # resumes its estimates)
        job = master.generate_data_for_slave()
        assert "_second_w" in job and "_step" in job
        slave = GradientDescent(wf, solver="adam")
        slave.weights = Array(numpy.zeros((3, 2), numpy.float32))
        slave.bias = Array(numpy.zeros(2, numpy.float32))
        slave.weights.to_device()
        slave.bias.to_device()
        slave.initialize()
        slave.apply_data_from_master(job)
        numpy.testing.assert_allclose(
            numpy.asarray(slave._velocity_w.data), 0.5)
        assert float(slave._step.data) == 7.0

    def test_average_merge_mode(self, monkeypatch):
        from veles_tpu.core.config import root
        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.nn.gd import GradientDescent

        monkeypatch.setattr(root.common.fleet, "merge", "average",
                            raising=False)
        from veles_tpu.memory import Array
        gd = GradientDescent(DummyWorkflow())
        gd.weights = Array(numpy.full((2, 2), 4.0, numpy.float32))
        gd.bias = Array(numpy.full(2, 4.0, numpy.float32))
        gd.weights.to_device()
        gd.bias.to_device()
        gd.apply_data_from_slave(
            {"weights": numpy.zeros((2, 2), numpy.float32),
             "bias": numpy.zeros(2, numpy.float32)})
        numpy.testing.assert_allclose(numpy.asarray(gd.weights.mem), 2.0)
        numpy.testing.assert_allclose(numpy.asarray(gd.bias.mem), 2.0)
        # unknown mode rejected
        monkeypatch.setattr(root.common.fleet, "merge", "bogus",
                            raising=False)
        with pytest.raises(ValueError):
            gd.apply_data_from_slave(
                {"weights": numpy.zeros((2, 2), numpy.float32),
                 "bias": numpy.zeros(2, numpy.float32)})

    def test_async_slave_mode(self):
        kw = _kw(max_epochs=2)
        master, wf_m, thread = _run_master(kw)
        slave = _run_slave(master.agent.port, kw, async_slave=True)
        slave.run()
        thread.join(60)
        assert not thread.is_alive()
        assert wf_m.decision.best_n_err[VALID] is not None
        master.stop()
        slave.stop()

    def test_drop_slave_requeues_minibatches(self):
        """A disconnected slave's pending work must be requeued and the
        epoch still complete exactly (reference drop_slave semantics)."""
        kw = _kw(max_epochs=1)
        master, wf_m, thread = _run_master(kw)
        loader = wf_m.loader
        # simulate: serve a job to a fake slave, then drop it
        class FakeSlave:
            id = "fake-1"
        job = loader.generate_data_for_slave(FakeSlave())
        assert loader.pending_minibatches_["fake-1"]
        loader.drop_slave(FakeSlave())
        assert len(loader.failed_minibatches) == 1
        # a real slave now runs everything, including the requeued batch
        slave = _run_slave(master.agent.port, kw)
        slave.run()
        thread.join(60)
        assert not thread.is_alive()
        # requeued minibatch was re-served: total samples == 1 full epoch
        # + the duplicated minibatch
        assert wf_m.decision.best_n_err[VALID] is not None
        master.stop()
        slave.stop()


class TestRespawn:
    def test_manager_backoff_and_budget(self):
        from veles_tpu.fleet.respawn import RespawnManager

        spawned = []
        mgr = RespawnManager(
            spawner=lambda host, cmd, cwd=None, env=None:
            spawned.append((host, cmd, cwd, env)),
            max_attempts=2, base_delay=0.01)
        recipe = {"executable": "/usr/bin/python3",
                  "argv": ["wf.py", "-m", "h:1"],
                  "cwd": "/work", "pythonpath": "/lib"}
        assert mgr.schedule("10.0.0.5", recipe, key="mid-1")
        assert mgr.schedule("10.0.0.5", recipe, key="mid-1")
        # budget exhausted
        assert not mgr.schedule("10.0.0.5", recipe, key="mid-1")
        import time as _t
        deadline = _t.time() + 5
        while len(spawned) < 2 and _t.time() < deadline:
            _t.sleep(0.01)
        assert len(spawned) == 2
        host, cmd, cwd, env = spawned[0]
        assert host == "10.0.0.5" and cwd == "/work"
        assert env == {"PYTHONPATH": "/lib"}
        assert "-b" in cmd and "wf.py" in cmd  # daemonized relaunch
        # a self-reconnect resets the budget
        mgr.notify_reconnected("mid-1")
        assert mgr.schedule("10.0.0.5", recipe, key="mid-1")
        mgr.stop()

    def test_incomplete_recipe_rejected(self):
        from veles_tpu.fleet.respawn import RespawnManager

        mgr = RespawnManager(spawner=lambda *a, **k: None)
        assert not mgr.schedule("h", {})
        assert not mgr.schedule("h", {"executable": "python"})

    def test_server_respawns_dropped_slave(self):
        """Loopback: a dying slave with a recipe triggers the master's
        respawn schedule (reference server.py:637-655 semantics)."""
        spawned = []
        kw = _kw(max_epochs=2)
        _seed()
        master = Launcher(listen_address="127.0.0.1:0", respawn=True)
        wf_m = MLPWorkflow(master, name="fleet-t", **kw)
        master.initialize()
        master.agent.respawn_manager.spawner = \
            lambda host, cmd, cwd=None, env=None: spawned.append(
                (host, cmd))
        master.agent.respawn_manager.base_delay = 0.01
        mthread = threading.Thread(target=master.run, daemon=True)
        mthread.start()
        slave = _run_slave(master.agent.port, kw, respawn=True)
        sthread = threading.Thread(target=slave.run, daemon=True)
        sthread.start()
        import time as _t
        deadline = _t.time() + 10
        while not master.agent.slaves and _t.time() < deadline:
            _t.sleep(0.05)
        assert master.agent.slaves, "slave never connected"
        # abrupt death: close the transport with no 'bye' (the in-process
        # stand-in for the fault injection's os._exit)
        slave.agent.stop()
        deadline = _t.time() + 10
        while not spawned and _t.time() < deadline:
            _t.sleep(0.05)
        master.stop()
        slave.stop()
        assert spawned, "master never scheduled a respawn"
        host, cmd = spawned[0]
        assert host in ("127.0.0.1", "::1")
        assert "-b" in cmd


class TestChecksum:
    def test_checksum_mismatch_rejected(self):
        import types

        kw = _kw(max_epochs=1)
        master, wf_m, thread = _run_master(kw)
        slave = _run_slave(master.agent.port, kw)
        # a class-level checksum patch would hit the master too (same class
        # in-process), so swap the CLIENT's workflow for a bogus-checksum
        # stand-in instead
        slave.agent.workflow = types.SimpleNamespace(checksum="bogus")
        try:
            slave.run()
            assert slave.agent.jobs_done == 0
        finally:
            master.stop()
            slave.stop()
            thread.join(1)


class TestSafeCodec:
    """fleet/safecodec.py + the codec="safe" wire mode: a leaked secret
    must not be remote code execution (VERDICT r2 weak #6)."""

    @pytest.fixture
    def safe_wire(self):
        from veles_tpu.core.config import root
        saved = root.common.fleet.get("codec", "pickle")
        root.common.fleet.codec = "safe"
        yield
        root.common.fleet.codec = saved

    def test_roundtrip_structures(self):
        from veles_tpu.fleet import safecodec
        import jax.numpy as jnp

        msg = {
            "type": "job",
            "n": 7, "f": 1.5, "flag": True, "none": None,
            "name": "unit", "raw": b"\x00\xffbytes",
            "list": [1, [2.5, "x"], {"k": (1, 2)}],
            "tuple": (3, "y"),
            5: "int-key", (1, "t"): "tuple-key",
            "arr": numpy.arange(12, dtype=numpy.float32).reshape(3, 4),
            "i64": numpy.arange(3, dtype=numpy.int64),
            "jax": jnp.ones((2, 2), jnp.bfloat16),
            "scalar": numpy.float32(2.25),
        }
        out = safecodec.loads(safecodec.dumps(msg))
        assert out["type"] == "job" and out["n"] == 7
        assert out["f"] == 1.5 and out["flag"] is True
        assert out["none"] is None and out["raw"] == b"\x00\xffbytes"
        assert out["list"] == [1, [2.5, "x"], {"k": (1, 2)}]
        assert out["tuple"] == (3, "y")
        assert out[5] == "int-key" and out[(1, "t")] == "tuple-key"
        numpy.testing.assert_array_equal(out["arr"], msg["arr"])
        assert out["arr"].dtype == numpy.float32
        assert out["i64"].dtype == numpy.int64
        assert out["jax"].dtype == numpy.dtype("bfloat16")
        numpy.testing.assert_array_equal(
            out["jax"].astype(numpy.float32), numpy.ones((2, 2)))
        assert out["scalar"] == numpy.float32(2.25)
        assert type(out["scalar"]) is numpy.float32  # not a 0-d array

    def test_numpy_keys_coerced_at_encode(self):
        """Numpy-scalar dict keys (bare or inside tuple keys) must
        round-trip as working lookups, not explode at the receiver."""
        from veles_tpu.fleet import safecodec

        msg = {numpy.int64(3): "a", (numpy.int32(1), "t"): "b"}
        out = safecodec.loads(safecodec.dumps(msg))
        assert out[3] == "a" and out[(1, "t")] == "b"
        with pytest.raises(safecodec.UnsupportedType, match="dict key"):
            safecodec.dumps({frozenset((1,)): "x"})

    def test_malformed_safe_frame_is_protocol_error(self, safe_wire):
        """A malformed-but-authenticated safe frame must surface as
        ProtocolError (peer dropped), never a raw KeyError/ValueError
        that would kill the fleet session loop."""
        import gzip as gzip_lib
        import json
        import struct as struct_lib

        from veles_tpu.fleet.protocol import (
            ProtocolError, _mac, read_frame)

        deep = b"[" * 50000 + b"1" + b"]" * 50000  # RecursionError bait
        for header in ({"x": 1},                       # missing 't'
                       {"t": "a", "d": "<f4",
                        "s": [5, 5], "o": 0, "n": 4},  # bad reshape
                       {"t": "zz"},                    # unknown node
                       deep):
            head = (header if isinstance(header, bytes)
                    else json.dumps(header).encode())
            payload = struct_lib.pack(">I", len(head)) + head + b"\0" * 4
            if len(payload) >= 64 * 1024:
                payload = gzip_lib.compress(payload)
            frame = (struct_lib.pack(">IB", len(payload), 2)
                     + _mac(KEY, 2, payload) + payload)
            with pytest.raises(ProtocolError, match="bad safe frame"):
                asyncio.run(read_frame(FakeReader(frame), KEY))

    def test_unsupported_type_fails_at_encode(self):
        from veles_tpu.fleet import safecodec

        class Payload:
            pass

        with pytest.raises(safecodec.UnsupportedType,
                           match="Payload"):
            safecodec.dumps({"job": Payload()})
        with pytest.raises(safecodec.UnsupportedType):
            safecodec.dumps(numpy.array([object()], dtype=object))

    def test_safe_receiver_rejects_pickle_frames(self, safe_wire):
        """THE security property: a safe-configured host never reaches
        pickle.loads, even for a correctly authenticated frame."""
        from veles_tpu.core.config import root
        from veles_tpu.fleet.protocol import ProtocolError, read_frame

        root.common.fleet.codec = "pickle"
        pickle_frame = encode_frame({"type": "hello"}, KEY)
        root.common.fleet.codec = "safe"
        with pytest.raises(ProtocolError, match="safe fleet codec"):
            asyncio.run(read_frame(FakeReader(pickle_frame), KEY))

    def test_safe_frame_roundtrip_and_compression(self, safe_wire):
        from veles_tpu.fleet.protocol import read_frame

        msg = {"type": "job",
               "job": [numpy.zeros(1024 * 1024, numpy.float32),
                       {"lr": 0.5}]}
        frame = encode_frame(msg, KEY)
        assert len(frame) < 1024 * 1024  # gzip applies to safe frames too
        out = asyncio.run(read_frame(FakeReader(frame), KEY))
        numpy.testing.assert_array_equal(out["job"][0], msg["job"][0])
        assert out["job"][1] == {"lr": 0.5}

    def test_fleet_trains_on_safe_codec(self, safe_wire):
        """The PRODUCT path: master + slave converge identically to the
        standalone run with zero pickle on the wire."""
        kw = _kw()
        _seed()
        lau = Launcher()
        wf_sa = MLPWorkflow(lau, name="fleet-t", **kw)
        lau.initialize()
        lau.run()
        expected = wf_sa.decision.best_n_err[VALID]

        master, wf_m, thread = _run_master(kw)
        slave = _run_slave(master.agent.port, kw)
        slave.run()
        thread.join(60)
        assert not thread.is_alive(), "master did not finish"
        assert wf_m.decision.best_n_err[VALID] == expected
        master.stop()
        slave.stop()


def test_lr_decay_reaches_slaves():
    """Master-side plateau annealing must propagate: the decayed rates
    ride the job payloads, so the slave that executes the GD ticks
    anneals too."""
    kw = _kw(max_epochs=6, minibatch=300)
    kw["learning_rate"] = 1e-7  # guaranteed plateau after epoch 1
    master, wf_m, thread = _run_master(kw)
    wf_m.decision.lr_decay = 0.5
    wf_m.decision.lr_decay_patience = 2
    slave = _run_slave(master.agent.port, kw)
    wf_s = slave.workflow
    slave.run()
    thread.join(120)
    assert not thread.is_alive(), "master did not finish"
    assert wf_m.gds[0].learning_rate < 1e-7  # master decayed
    assert wf_s.gds[0].learning_rate < 1e-7  # ...and the slave followed
    master.stop()
    slave.stop()
