"""Tests for the loader layer (mirrors reference test_loader.py)."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader


def make_loader(minibatch_size=10, lengths=(0, 20, 50), **kwargs):
    n = sum(lengths)
    data = numpy.arange(n * 3, dtype=numpy.float32).reshape(n, 3)
    labels = numpy.arange(n, dtype=numpy.int32) % 7
    loader = FullBatchLoader(
        DummyWorkflow(), data=data, labels=labels,
        class_lengths=list(lengths), minibatch_size=minibatch_size,
        **kwargs)
    loader.initialize()
    return loader


class TestServing:
    def test_class_order_and_epoch_flags(self):
        loader = make_loader()
        classes, ends = [], []
        for _ in range(7):  # 2 valid + 5 train minibatches
            loader.run()
            classes.append(loader.minibatch_class)
            ends.append(bool(loader.epoch_ended))
        assert classes == [VALID] * 2 + [TRAIN] * 5
        assert ends == [False] * 6 + [True]
        assert loader.epoch_number == 0
        loader.run()  # first minibatch of next epoch
        assert loader.epoch_number == 1
        assert loader.minibatch_class == VALID

    def test_short_final_minibatch_mask(self):
        loader = make_loader(minibatch_size=8, lengths=(0, 0, 20))
        for _ in range(3):
            loader.run()
        # 20 = 8 + 8 + 4: final minibatch half-valid
        assert loader.minibatch_valid_size == 4
        mask = numpy.asarray(loader.sample_mask.mem)
        numpy.testing.assert_array_equal(mask, [1, 1, 1, 1, 0, 0, 0, 0])
        assert loader.minibatch_data.shape == (8, 3)  # static shape

    def test_minibatch_contents_match_indices(self):
        loader = make_loader()
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        valid = loader.minibatch_valid_size
        expected = numpy.arange(150, dtype=numpy.float32).reshape(50, 3)[idx]
        numpy.testing.assert_array_equal(
            numpy.asarray(loader.minibatch_data.mem)[:valid],
            expected[:valid])

    def test_train_shuffled_between_epochs(self):
        loader = make_loader(lengths=(0, 0, 50), minibatch_size=50)
        loader.run()
        first = numpy.asarray(loader.minibatch_indices.mem).copy()
        loader.run()
        second = numpy.asarray(loader.minibatch_indices.mem)
        assert not numpy.array_equal(first, second)
        assert set(first) == set(second) == set(range(50))

    def test_validation_not_shuffled(self):
        loader = make_loader()
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        numpy.testing.assert_array_equal(
            idx[:loader.minibatch_valid_size], numpy.arange(10))

    def test_train_ratio(self):
        loader = make_loader(train_ratio=0.5, lengths=(0, 0, 40),
                             minibatch_size=10)
        served = 0
        loader.run()
        while not loader.epoch_ended:
            served += loader.minibatch_valid_size
            loader.run()
        served += loader.minibatch_valid_size
        assert served == 20  # half of train

    def test_normalization_linear(self):
        loader = make_loader(normalization_type="linear")
        loader.run()
        assert float(numpy.abs(loader.minibatch_data.mem).max()) <= 1.0


class TestDistribution:
    def test_master_serves_indices_slave_fills(self):
        master = make_loader()
        slave = make_loader()
        job = master.generate_data_for_slave("slave-1")
        slave.apply_data_from_master(job)
        assert slave.minibatch_class == job[0]
        assert master.pending_minibatches_["slave-1"]
        master.apply_data_from_slave({}, "slave-1")
        assert not master.pending_minibatches_["slave-1"]

    def test_drop_slave_requeues(self):
        master = make_loader()
        job = master.generate_data_for_slave("slave-1")
        master.drop_slave("slave-1")
        assert len(master.failed_minibatches) == 1
        # requeued minibatch served again, to another slave
        job2 = master.generate_data_for_slave("slave-2")
        numpy.testing.assert_array_equal(job[1], job2[1])


class TestResplit:
    def test_validation_ratio(self):
        loader = make_loader(lengths=(0, 0, 50), validation_ratio=0.2)
        assert loader.class_lengths == [0, 10, 40]
        assert loader.total_samples == 50
