"""Tests for Kohonen SOM, the AlexNet topology, and the autotune CLI
(SURVEY §7 item 10 + BASELINE conv anchor + VERDICT item 10)."""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.dummy import DummyLauncher, DummyWorkflow


def two_blobs(n=200, dim=6, seed=0):
    rng = numpy.random.RandomState(seed)
    a = rng.normal(-2.0, 0.3, (n // 2, dim))
    b = rng.normal(+2.0, 0.3, (n // 2, dim))
    X = numpy.concatenate([a, b]).astype(numpy.float32)
    labels = numpy.array([0] * (n // 2) + [1] * (n // 2))
    perm = rng.permutation(n)
    return X[perm], labels[perm]


class TestKohonen:
    def test_trainer_reduces_quantization_error(self):
        from veles_tpu.nn.kohonen import KohonenTrainer

        X, _ = two_blobs()
        trainer = KohonenTrainer(DummyWorkflow(), shape=(4, 4),
                                 learning_rate=0.5)
        trainer.input = X
        trainer.initialize()
        errors = []
        for _ in range(15):
            trainer.run()
            errors.append(float(trainer.quantization_error))
        assert errors[-1] < errors[0] * 0.5, errors

    def test_bmu_separates_clusters(self):
        from veles_tpu.nn.kohonen import KohonenForward, KohonenTrainer

        X, labels = two_blobs()
        trainer = KohonenTrainer(DummyWorkflow(), shape=(4, 4),
                                 learning_rate=0.5)
        trainer.input = X
        trainer.initialize()
        for _ in range(20):
            trainer.run()
        fwd = KohonenForward(DummyWorkflow())
        fwd.input = jnp.asarray(X)
        fwd.weights = trainer.weights.data
        fwd.run()
        winners = numpy.asarray(fwd.output.mem)
        # the two blobs must map to disjoint BMU sets
        set_a = set(winners[labels == 0].tolist())
        set_b = set(winners[labels == 1].tolist())
        assert not (set_a & set_b)

    def test_workflow_end_to_end(self):
        from veles_tpu.models.kohonen import KohonenWorkflow

        X, _ = two_blobs()
        wf = KohonenWorkflow(
            DummyLauncher(), shape=(4, 4),
            loader_kwargs=dict(data=X, class_lengths=[0, 0, len(X)],
                               minibatch_size=50),
            max_epochs=5, name="som")
        wf.initialize()
        wf.run()
        results = wf.gather_results()
        assert results["epochs"] == 5
        assert results["quantization_error"] < 1.0


class TestAlexNet:
    @pytest.mark.slow
    def test_scaled_alexnet_trains(self):
        """The AlexNet spec compiles + trains on synthetic 64x64 images
        (scale=0.05 shrinks widths; geometry/stride structure intact)."""
        from veles_tpu.core import prng
        from veles_tpu.models.alexnet import AlexNetWorkflow

        # weight init draws from the process-global named streams: seed
        # them so this test does not depend on what ran before it
        prng.get("default").seed(7)
        prng.get("loader").seed(8)
        rng = numpy.random.RandomState(0)
        n = 64
        y = rng.randint(0, 4, n).astype(numpy.int32)
        X = rng.rand(n, 64, 64, 3).astype(numpy.float32) * 0.1
        for i in range(n):  # class = bright quadrant (spatial pattern)
            y0, x0 = (y[i] // 2) * 32, (y[i] % 2) * 32
            X[i, y0:y0 + 32, x0:x0 + 32, :] += 0.8
        wf = AlexNetWorkflow(
            DummyLauncher(), n_classes=4, scale=0.05,
            loader_kwargs=dict(data=X, labels=y,
                               class_lengths=[0, 16, 48],
                               minibatch_size=16,
                               normalization_type="mean_disp"),
            learning_rate=0.1,
            decision_kwargs=dict(max_epochs=10), name="mini-alexnet")
        wf.initialize()
        losses = []
        orig = wf.decision._epoch_summary

        def capture(stats, epoch):
            losses.append(stats[2][2] / max(stats[2][1], 1))
            return orig(stats, epoch)

        wf.decision._epoch_summary = capture
        wf.run()
        # smoke criterion: the full 5-conv geometry compiles and the
        # optimizer makes progress (48 samples can't prove accuracy;
        # conv accuracy is covered by the digits convnet test)
        assert wf.decision.epochs_done == 10
        assert len(losses) == 10
        assert losses[-1] < losses[0] * 0.95, losses

    def test_full_size_spec_shapes(self):
        from veles_tpu.models.alexnet import alexnet_layers

        layers = alexnet_layers()
        assert layers[0]["n_kernels"] == 96
        assert layers[0]["sliding"] == (4, 4)
        assert layers[-3]["output_sample_shape"] == 4096
        assert layers[-1]["output_sample_shape"] == 1000
        assert sum(1 for l in layers if l["type"].startswith("conv")) == 5


class TestAutotuneCLI:
    def test_cache_roundtrip(self, tmp_path, monkeypatch):
        """VERDICT item 10: --autotune persists winners and _tuned_blocks
        reads them back (devices/device_infos.json semantics)."""
        from veles_tpu.core.config import root
        from veles_tpu.ops import gemm

        cache_file = str(tmp_path / "tuning.json")
        monkeypatch.setattr(root.common.engine, "pallas_autotune_cache",
                            cache_file, raising=False)
        monkeypatch.setattr(gemm, "_tuning_cache", None, raising=False)
        calls = []

        def fake_matmul(a, b, out_dtype=None, bm=None, bn=None, bk=None):
            calls.append((bm, bn, bk))
            return jnp.zeros((a.shape[0], b.shape[1]), jnp.float32)

        monkeypatch.setattr(gemm, "pallas_matmul", fake_matmul)

        # deterministic positive timings: the fake kernel is a no-op,
        # so the real two-length slope would measure pure noise — and
        # the cache-hygiene gate (rightly) refuses to persist a
        # noise-negative "measurement"
        def fake_scan_time(product, a, lengths=(50, 350), repeats=4):
            product(a)  # exercise the candidate (records its blocks)
            return 1e-4

        monkeypatch.setattr(gemm, "_matmul_scan_time", fake_scan_time)
        blocks = gemm.autotune_matmul(512, 512, 1024, iters=1)
        assert calls, "no candidates benchmarked"
        assert blocks in [c for c in calls]
        # cache round-trips through a fresh load
        monkeypatch.setattr(gemm, "_tuning_cache", None, raising=False)
        assert gemm._tuned_blocks(512, 512, 1024, "bfloat16") == blocks

    def test_cli_entry(self, tmp_path, monkeypatch, capsys):
        from veles_tpu.core.config import root
        from veles_tpu.ops import gemm

        monkeypatch.setattr(root.common.engine, "pallas_autotune_cache",
                            str(tmp_path / "t.json"), raising=False)
        monkeypatch.setattr(gemm, "_tuning_cache", None, raising=False)
        monkeypatch.setattr(
            gemm, "pallas_matmul",
            lambda a, b, **kw: jnp.zeros((a.shape[0], b.shape[1]),
                                         jnp.float32))
        # positive stub timing: see test_cache_roundtrip — a no-op
        # kernel's measured slope is noise the hygiene gate rejects
        monkeypatch.setattr(
            gemm, "_matmul_scan_time",
            lambda product, a, lengths=(50, 350), repeats=4:
            (product(a), 1e-4)[1])
        assert gemm.autotune_main(["512x512x1024"]) == 0
        out = capsys.readouterr().out
        assert '"shape": [512, 512, 1024]' in out
