"""Snapshot/resume tests (mirror reference test_workflow.py:69-278
snapshot-restore coverage)."""

import glob
import os

import numpy
import pytest

from veles_tpu.core.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.snapshotter import Snapshotter, SnapshotterToFile


def _digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    X = d.data.astype(numpy.float32)
    y = d.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    return X[perm], y[perm]


def make_wf(max_epochs):
    X, y = _digits()
    return MLPWorkflow(
        DummyLauncher(), layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=300,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=max_epochs, name="snap-test")


@pytest.mark.slow
def test_snapshot_resume_roundtrip(tmp_path):
    wf = make_wf(max_epochs=2)
    snap = Snapshotter(wf, directory=str(tmp_path), prefix="digits",
                       interval=1, time_interval=0)
    snap.link_from(wf.decision)
    # gate_SKIP (not block): a skipped unit still propagates the tick,
    # which the serialized end point depends on
    snap.gate_skip = ~wf.decision.improved
    # serialize the snapshotter BEFORE the end point (the reference
    # samples' wiring): decision dependents run concurrently, so a
    # parallel end point could finish the workflow before a same-tick
    # snapshot starts — pipelined mode always materializes the last
    # improvement on the final tick, making that race deterministic
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    wf.initialize()
    wf.run()
    files = glob.glob(os.path.join(str(tmp_path), "digits_*.pickle*"))
    files = [f for f in files if not f.endswith(".lnk")]
    assert files, "no snapshot written"
    err_before = wf.decision.best_n_err[VALID]

    restored = SnapshotterToFile.import_(snap.destination)
    assert restored.restored_from_snapshot
    # re-parent onto a fresh launcher (the snapshot never carries one)
    restored.workflow = DummyLauncher()
    # links survived: evaluator still reads the last forward's output slot
    assert restored.evaluator.input is restored.forwards[-1].output
    w_a = numpy.asarray(restored.forwards[0].weights.mem)
    w_b = numpy.asarray(wf.forwards[0].weights.mem)
    # restored weights are a *snapshot* of some improved epoch
    assert w_a.shape == w_b.shape

    # resume training for more epochs: must run and not regress wildly
    restored.decision.max_epochs = 4
    restored.decision.complete.unset()
    restored.decision.train_ended.unset()
    restored.initialize()
    restored.run()
    err_after = restored.decision.best_n_err[VALID]
    assert err_after is not None and err_before is not None
    assert err_after <= err_before * 2 + 10


def test_weights_export(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="w")
    path = snap.export_weights()
    arrays = numpy.load(path)
    assert "fwd0_weights" in arrays and "fwd1_bias" in arrays
    assert arrays["fwd0_weights"].shape == (64, 16)


def test_interval_and_time_gating(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="gate",
                             interval=3, time_interval=0)
    snap.initialize()
    snap.run()
    snap.run()
    assert snap.destination is None  # interval not reached
    snap.run()
    assert snap.destination is not None


def test_skip_bool(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="skip",
                             interval=1, time_interval=0)
    snap.initialize()
    snap.skip.set()
    snap.run()
    assert snap.destination is None


def test_snapshotter_to_db_roundtrip(tmp_path):
    """DB-backed snapshot store (reference SnapshotterToDB role over
    sqlite3): export rows, import newest by prefix, exact by suffix."""
    from veles_tpu.core import prng
    from veles_tpu.snapshotter import SnapshotterToDB

    db = str(tmp_path / "snaps.sqlite3")
    prng.get("default").seed(7)
    prng.get("loader").seed(7)
    wf = make_wf(max_epochs=1)
    snap = Snapshotter(wf, database=db, prefix="dbtest",
                       interval=1, time_interval=0)
    assert isinstance(snap, SnapshotterToDB)
    snap.link_from(wf.decision)
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    wf.initialize()
    wf.run()
    assert snap.destination.startswith("sqlite://")
    restored = SnapshotterToDB.import_(snap.destination)
    assert numpy.asarray(restored.forwards[0].weights.data).shape \
        == numpy.asarray(wf.forwards[0].weights.data).shape
    assert restored.decision._epochs_done == wf.decision._epochs_done
    assert restored._restored_from_snapshot_
    # exact-suffix addressing
    suffix = snap.suffix or "current"
    again = SnapshotterToDB.import_(
        "sqlite://%s#dbtest/%s" % (db, suffix))
    assert again.decision._epochs_done == restored.decision._epochs_done
    # missing prefix -> clear error
    with pytest.raises(FileNotFoundError):
        SnapshotterToDB.import_("sqlite://%s#nope" % db)
