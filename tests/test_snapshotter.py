"""Snapshot/resume tests (mirror reference test_workflow.py:69-278
snapshot-restore coverage)."""

import glob
import os

import numpy
import pytest

from veles_tpu.core.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.snapshotter import Snapshotter, SnapshotterToFile


def _digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    X = d.data.astype(numpy.float32)
    y = d.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    return X[perm], y[perm]


def make_wf(max_epochs):
    X, y = _digits()
    return MLPWorkflow(
        DummyLauncher(), layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=300,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=max_epochs, name="snap-test")


@pytest.mark.slow
def test_snapshot_resume_roundtrip(tmp_path):
    wf = make_wf(max_epochs=2)
    snap = Snapshotter(wf, directory=str(tmp_path), prefix="digits",
                       interval=1, time_interval=0)
    snap.link_from(wf.decision)
    # gate_SKIP (not block): a skipped unit still propagates the tick,
    # which the serialized end point depends on
    snap.gate_skip = ~wf.decision.improved
    # serialize the snapshotter BEFORE the end point (the reference
    # samples' wiring): decision dependents run concurrently, so a
    # parallel end point could finish the workflow before a same-tick
    # snapshot starts — pipelined mode always materializes the last
    # improvement on the final tick, making that race deterministic
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    wf.initialize()
    wf.run()
    files = glob.glob(os.path.join(str(tmp_path), "digits_*.pickle*"))
    files = [f for f in files if not f.endswith(".lnk")]
    assert files, "no snapshot written"
    err_before = wf.decision.best_n_err[VALID]

    restored = SnapshotterToFile.import_(snap.destination)
    assert restored.restored_from_snapshot
    # re-parent onto a fresh launcher (the snapshot never carries one)
    restored.workflow = DummyLauncher()
    # links survived: evaluator still reads the last forward's output slot
    assert restored.evaluator.input is restored.forwards[-1].output
    w_a = numpy.asarray(restored.forwards[0].weights.mem)
    w_b = numpy.asarray(wf.forwards[0].weights.mem)
    # restored weights are a *snapshot* of some improved epoch
    assert w_a.shape == w_b.shape

    # resume training for more epochs: must run and not regress wildly
    restored.decision.max_epochs = 4
    restored.decision.complete.unset()
    restored.decision.train_ended.unset()
    restored.initialize()
    restored.run()
    err_after = restored.decision.best_n_err[VALID]
    assert err_after is not None and err_before is not None
    assert err_after <= err_before * 2 + 10


def test_weights_export(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="w")
    path = snap.export_weights()
    arrays = numpy.load(path)
    assert "fwd0_weights" in arrays and "fwd1_bias" in arrays
    assert arrays["fwd0_weights"].shape == (64, 16)


def test_interval_and_time_gating(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="gate",
                             interval=3, time_interval=0)
    snap.initialize()
    snap.run()
    snap.run()
    assert snap.destination is None  # interval not reached
    snap.run()
    assert snap.destination is not None


def test_skip_bool(tmp_path):
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="skip",
                             interval=1, time_interval=0)
    snap.initialize()
    snap.skip.set()
    snap.run()
    assert snap.destination is None


def test_current_link_updated_atomically(tmp_path):
    """The `_current` resume pointer is replaced via temp-link +
    os.replace — never removed-then-recreated — so a crash can no
    longer leave NO pointer at all; and re-exports repoint it."""
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="atom",
                             interval=1, time_interval=0)
    snap.suffix = "first"
    snap.export()
    link = os.path.join(str(tmp_path), "atom_current.lnk")
    assert os.path.islink(link)
    assert "first" in os.readlink(link)
    snap.suffix = "second"
    snap.export()
    assert os.path.islink(link)
    assert "second" in os.readlink(link)
    # no temp links left behind
    assert not glob.glob(os.path.join(str(tmp_path), "*.lnk.tmp*"))
    # the link resolves through import_
    restored = SnapshotterToFile.import_(link)
    assert restored.restored_from_snapshot


def test_checksum_sidecar_and_corruption_fallback(tmp_path):
    """Every export writes a SHA-256 sidecar; import_ verifies it and
    falls back to the newest intact sibling — with a warning, not a
    crash — when the snapshot is truncated or tampered with."""
    from veles_tpu.snapshotter import SnapshotCorruptError

    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="sha",
                             interval=1, time_interval=0)
    snap.suffix = "old"
    snap.export()
    intact = snap.destination
    assert os.path.isfile(intact + ".sha256")
    # the sidecar's first line is shasum-formatted
    # ("<hexdigest>  <filename>"); a comment records the exact prefix
    # so the corruption fallback never crosses experiments
    lines = open(intact + ".sha256").read().splitlines()
    digest, name = lines[0].split()
    assert len(digest) == 64 and name == os.path.basename(intact)
    assert "# prefix: sha" in lines[1]
    snap.suffix = "new"
    snap.export()
    newest = snap.destination
    os.utime(intact, (os.path.getmtime(newest) - 60,) * 2)
    # tamper with the newest snapshot: flip bytes, keep the length
    with open(newest, "r+b") as fout:
        fout.seek(0)
        fout.write(b"\x00\x01\x02\x03")
    with pytest.raises(SnapshotCorruptError):
        SnapshotterToFile._load_verified(newest)
    # import_ falls back to the intact previous version
    restored = SnapshotterToFile.import_(newest)
    assert restored.restored_from_snapshot
    # truncation (a crashed writer) is also survived
    with open(newest, "wb") as fout:
        fout.write(b"\x1f\x8b")  # gzip magic, then nothing
    restored = SnapshotterToFile.import_(newest)
    assert restored.restored_from_snapshot
    # the fallback NEVER crosses into another experiment's prefix in a
    # shared directory — even one that shares a leading "_" segment
    # (the sidecar records the exact prefix; "sha_twin_current..."
    # cannot be told apart from prefix "sha" + suffix "twin_current"
    # by filename alone): with every same-prefix sibling corrupt,
    # import_ raises despite the intact foreign snapshot sitting there
    other = SnapshotterToFile(wf, directory=str(tmp_path),
                              prefix="sha_twin", interval=1,
                              time_interval=0)
    other.export()
    with open(intact, "r+b") as fout:
        fout.write(b"\x00\x01\x02\x03")
    with pytest.raises(Exception):
        SnapshotterToFile.import_(newest)
    # the foreign snapshot itself still imports fine
    assert SnapshotterToFile.import_(
        other.destination).restored_from_snapshot
    # with NO intact sibling the corruption surfaces loudly
    lonely = str(tmp_path / "lonely")
    os.makedirs(lonely)
    bad = os.path.join(lonely, "x_current.0.pickle")
    with open(bad, "wb") as fout:
        fout.write(b"garbage")
    with pytest.raises(Exception):
        SnapshotterToFile.import_(bad)


def test_crash_between_sidecar_and_data_rename_tolerated(tmp_path):
    """The export's two renames cannot be atomic together; the sidecar
    lands first and vouches for the PREVIOUS generation too, so a crash
    between the renames (new sidecar + old data bytes) must still
    resume — not reject the intact old snapshot as corrupt."""
    import shutil

    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="win",
                             interval=1, time_interval=0)
    snap.export()
    path = snap.destination
    gen1 = str(tmp_path / "gen1.bak")
    shutil.copy(path, gen1)
    snap.export()  # same path (default suffix): overwrites generation 1
    # emulate the crash window: sidecar is generation 2, data rolled
    # back to generation 1 (the payload timestamp makes digests differ)
    sidecar_lines = open(path + ".sha256").read().splitlines()
    assert len([l for l in sidecar_lines
                if l and not l.startswith("#")]) == 2
    shutil.copy(gen1, path)
    restored = SnapshotterToFile.import_(path)
    assert restored.restored_from_snapshot
    # an actually-corrupt file still fails both digests
    with open(path, "r+b") as fout:
        fout.write(b"\x00\x01\x02\x03")
    from veles_tpu.snapshotter import SnapshotCorruptError
    with pytest.raises(SnapshotCorruptError):
        SnapshotterToFile._load_verified(path)


def test_restful_api_unit_snapshots_cleanly():
    """Regression: RESTfulAPI's health registry holds a Lock; it must
    ride the volatile (trailing-underscore) contract so snapshotting a
    workflow containing a serving unit keeps working."""
    import pickle

    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.serving import RESTfulAPI

    api = RESTfulAPI(DummyWorkflow(), port=0)
    api.health.set_ready(True)
    restored = pickle.loads(pickle.dumps(api))
    # the health registry is rebuilt fresh on unpickle
    assert restored.health is not None
    assert not restored.health.ready


@pytest.mark.parametrize("codec", ["", "bz2", "xz"])
def test_compression_codecs_roundtrip(tmp_path, codec):
    """Every codec exports through the hashing tee and imports back
    (regression: lzma.open refused the preset kwarg on READ, so xz
    snapshots could never be resumed)."""
    wf = make_wf(max_epochs=1)
    wf.initialize()
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="c",
                             compression=codec, interval=1,
                             time_interval=0)
    snap.export()
    restored = SnapshotterToFile.import_(snap.destination)
    assert restored.restored_from_snapshot


def test_snapshotter_to_db_roundtrip(tmp_path):
    """DB-backed snapshot store (reference SnapshotterToDB role over
    sqlite3): export rows, import newest by prefix, exact by suffix."""
    from veles_tpu.core import prng
    from veles_tpu.snapshotter import SnapshotterToDB

    db = str(tmp_path / "snaps.sqlite3")
    prng.get("default").seed(7)
    prng.get("loader").seed(7)
    wf = make_wf(max_epochs=1)
    snap = Snapshotter(wf, database=db, prefix="dbtest",
                       interval=1, time_interval=0)
    assert isinstance(snap, SnapshotterToDB)
    snap.link_from(wf.decision)
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    wf.initialize()
    wf.run()
    assert snap.destination.startswith("sqlite://")
    restored = SnapshotterToDB.import_(snap.destination)
    assert numpy.asarray(restored.forwards[0].weights.data).shape \
        == numpy.asarray(wf.forwards[0].weights.data).shape
    assert restored.decision._epochs_done == wf.decision._epochs_done
    assert restored._restored_from_snapshot_
    # exact-suffix addressing
    suffix = snap.suffix or "current"
    again = SnapshotterToDB.import_(
        "sqlite://%s#dbtest/%s" % (db, suffix))
    assert again.decision._epochs_done == restored.decision._epochs_done
    # missing prefix -> clear error
    with pytest.raises(FileNotFoundError):
        SnapshotterToDB.import_("sqlite://%s#nope" % db)
