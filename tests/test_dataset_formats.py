"""Tests for pickles/HDF5 loaders and the minibatch saver/replay pair
(reference test_pickles / test_minibatches_saver_loader coverage)."""

import pickle

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.pickles import PicklesLoader
from veles_tpu.loader.saver import MinibatchesLoader, MinibatchesSaver


def dataset(n=48, dim=5, seed=0):
    rng = numpy.random.RandomState(seed)
    return (rng.uniform(-1, 1, (n, dim)).astype(numpy.float32),
            (rng.randint(0, 3, n)).astype(numpy.int32))


class TestPicklesLoader:
    def test_tuple_payloads(self, tmp_path):
        X, y = dataset()
        paths = []
        for i, sl in enumerate((slice(0, 16), slice(16, 48))):
            p = str(tmp_path / ("part%d.pickle" % i))
            with open(p, "wb") as f:
                pickle.dump((X[sl], y[sl]), f)
            paths.append(p)
        loader = PicklesLoader(
            DummyWorkflow(), validation_pickles=[paths[0]],
            train_pickles=[paths[1]], minibatch_size=8)
        loader.initialize()
        assert loader.class_lengths == [0, 16, 32]
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        numpy.testing.assert_allclose(
            numpy.asarray(loader.minibatch_data.mem), X[idx], rtol=1e-6)

    def test_dict_payload_and_shape_mismatch(self, tmp_path):
        X, y = dataset()
        good = str(tmp_path / "good.pickle")
        with open(good, "wb") as f:
            pickle.dump({"data": X, "labels": y}, f)
        bad = str(tmp_path / "bad.pickle")
        with open(bad, "wb") as f:
            pickle.dump({"data": numpy.zeros((4, 9), numpy.float32),
                         "labels": numpy.zeros(4, numpy.int32)}, f)
        loader = PicklesLoader(DummyWorkflow(), train_pickles=[good],
                               validation_pickles=[bad])
        with pytest.raises(ValueError, match="sample shapes differ"):
            loader.initialize()


class TestHDF5Loaders:
    @pytest.fixture
    def h5_files(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        X, y = dataset()
        paths = {}
        for name, sl in (("validation", slice(0, 16)),
                         ("train", slice(16, 48))):
            p = str(tmp_path / (name + ".h5"))
            with h5py.File(p, "w") as f:
                f["data"] = X[sl]
                f["label"] = y[sl]
            paths[name] = p
        return paths, X, y

    def test_fullbatch(self, h5_files):
        from veles_tpu.loader.hdf5 import FullBatchHDF5Loader
        paths, X, y = h5_files
        loader = FullBatchHDF5Loader(
            DummyWorkflow(), validation_path=paths["validation"],
            train_path=paths["train"], minibatch_size=8)
        loader.initialize()
        assert loader.class_lengths == [0, 16, 32]
        loader.run()
        idx = numpy.asarray(loader.minibatch_indices.mem)
        numpy.testing.assert_allclose(
            numpy.asarray(loader.minibatch_data.mem), X[idx], rtol=1e-6)

    def test_streaming(self, h5_files):
        from veles_tpu.loader.hdf5 import HDF5Loader
        paths, X, y = h5_files
        loader = HDF5Loader(
            DummyWorkflow(), validation_path=paths["validation"],
            train_path=paths["train"], minibatch_size=8,
            normalization_type="mean_disp")
        loader.initialize()
        served = 0
        loader.run()
        while True:
            idx = numpy.asarray(loader.minibatch_indices.mem)
            valid = loader.minibatch_valid_size
            got = numpy.asarray(loader.minibatch_data.mem)[:valid]
            expected = loader.normalizer.apply_batch(numpy, X[idx[:valid]])
            numpy.testing.assert_allclose(got, expected, rtol=1e-4,
                                          atol=1e-5)
            lab = numpy.asarray(loader.minibatch_labels.mem)[:valid]
            numpy.testing.assert_array_equal(lab, y[idx[:valid]])
            served += valid
            if loader.epoch_ended:
                break
            loader.run()
        assert served == 48


class TestSaverReplay:
    def test_roundtrip(self, tmp_path):
        X, y = dataset()
        wf = DummyWorkflow()
        loader = FullBatchLoader(
            wf, data=X, labels=y, class_lengths=[0, 16, 32],
            minibatch_size=8, shuffle_limit=0)
        wf.loader = loader
        saver = MinibatchesSaver(
            wf, file_name=str(tmp_path / "stream.dat"), compression="gz")
        saver.link_attrs(loader, "minibatch_data", "minibatch_labels",
                         "minibatch_class", "minibatch_valid_size",
                         "class_lengths", "max_minibatch_size")
        loader.initialize()
        saver.initialize()
        for _ in range(6):  # one full epoch: 2 valid + 4 train
            loader.run()
            saver.run()
        saver.stop()

        replay = MinibatchesLoader(
            DummyWorkflow(), file_name=str(tmp_path / "stream.dat"),
            minibatch_size=8)
        replay.initialize()
        assert replay.class_lengths == [0, 16, 32]
        assert replay.labels_mapping == {0: 0, 1: 1, 2: 2}
        replay.run()
        idx = numpy.asarray(replay.minibatch_indices.mem)
        got = numpy.asarray(replay.minibatch_data.mem)
        numpy.testing.assert_allclose(got, X[idx], rtol=1e-6)
        lab = numpy.asarray(replay.minibatch_labels.mem)
        numpy.testing.assert_array_equal(lab, y[idx])

    def test_saver_requires_no_shuffle(self, tmp_path):
        X, y = dataset()
        wf = DummyWorkflow()
        loader = FullBatchLoader(wf, data=X, labels=y,
                                 class_lengths=[0, 16, 32])
        wf.loader = loader
        saver = MinibatchesSaver(wf, file_name=str(tmp_path / "s.dat"))
        saver.link_attrs(loader, "minibatch_data", "minibatch_labels",
                         "minibatch_class", "minibatch_valid_size",
                         "class_lengths", "max_minibatch_size")
        loader.initialize()
        with pytest.raises(ValueError, match="shuffle"):
            saver.initialize()


class TestHDFSTextLoader:
    """HDFSTextLoader against an in-process fake WebHDFS namenode
    (reference hdfs_loader.py:48-77 contract: chunked line streaming,
    finished Bool at EOF)."""

    @pytest.fixture
    def webhdfs(self):
        import http.server
        import json
        import threading

        lines = ["line %d" % i for i in range(25)]
        payload = ("\n".join(lines) + "\n").encode()

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if "op=GETFILESTATUS" in self.path:
                    body = json.dumps({"FileStatus": {
                        "length": len(payload), "type": "FILE"}}).encode()
                elif "op=OPEN" in self.path:
                    body = payload
                else:
                    self.send_error(400)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        yield "127.0.0.1:%d" % server.server_port, lines
        server.shutdown()

    def test_chunked_streaming(self, webhdfs):
        from veles_tpu.loader.hdfs import HDFSTextLoader

        address, lines = webhdfs
        wf = DummyWorkflow()
        loader = HDFSTextLoader(wf, file="/data/corpus.txt",
                                address=address, chunk=10)
        assert loader.stat()["type"] == "FILE"
        loader.initialize()
        got = []
        while not loader.finished:
            loader.run()
            got.append(list(loader.output))
        assert got[0] == lines[:10]
        assert got[1] == lines[10:20]
        # final short chunk: output truncated to the valid lines (no
        # stale tail from the previous chunk), finished set
        assert got[2] == lines[20:25]
        assert bool(loader.finished)
