"""Regression-sentinel tests (docs/observability.md): the incremental
atomic BENCH artifact writer, the any-format loader (including the
VERDICT r5 truncated-tail recovery against the REAL committed
artifact), the spread-aware comparator, and the CLI exit codes `make
regress` gates CI on — the seeded-regression fixture here is the proof
the gate actually exits nonzero."""

import json
import os

import pytest

from veles_tpu.observe.regress import (BenchArtifact, compare,
                                       compare_main, load_bench,
                                       recover_keys, regressions,
                                       sha256_of, verify_sidecar)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R05 = os.path.join(REPO, "BENCH_r05.json")


class TestBenchArtifact:
    def test_incremental_updates_always_parseable(self, tmp_path):
        """Every update leaves a complete, loadable JSON on disk — the
        whole point: a kill between sections loses nothing already
        measured."""
        path = str(tmp_path / "bench.json")
        artifact = BenchArtifact(path)
        artifact.update({"a_tokens_per_sec": 100.0})
        first = json.load(open(path))
        assert first["schema"] == 1
        assert first["keys"] == {"a_tokens_per_sec": 100.0}
        artifact.update({"b_step_ms": 2.5})
        doc = json.load(open(path))
        assert doc["keys"] == {"a_tokens_per_sec": 100.0,
                               "b_step_ms": 2.5}
        # no torn temp files left behind
        leftovers = [n for n in os.listdir(tmp_path) if ".tmp" in n]
        assert leftovers == []

    def test_sidecar_verifies_and_detects_tamper(self, tmp_path):
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"x": 1.0})
        assert verify_sidecar(path) is True
        assert sha256_of(path) == open(path + ".sha256").read().split()[0]
        with open(path, "a") as fout:
            fout.write(" ")
        assert verify_sidecar(path) is False
        os.unlink(path + ".sha256")
        assert verify_sidecar(path) is None

    def test_artifact_carries_fingerprint_and_sha(self, tmp_path):
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"x": 1.0})
        doc = json.load(open(path))
        assert "device" in doc and "git_sha" in doc
        # in a git checkout the sha resolves; either way the KEY exists
        assert doc["git_sha"] is None or len(doc["git_sha"]) == 40


class TestLoader:
    def test_recovers_real_r05_truncated_tail(self):
        """The committed round artifact lost its headline to tail
        truncation (VERDICT r5); the loader must still salvage every
        complete key so the round stays comparable."""
        keys, info = load_bench(R05)
        assert info["recovered"] is True
        assert info["format"] == "driver-wrapper"
        # the keys AFTER the truncation point are all there
        for key in ("decode_tokens_per_sec", "decode_int8_step_ms",
                    "transformer_mfu", "longctx_pallas_speedup",
                    "decode_continuous_tokens_per_sec"):
            assert key in keys, key
        assert keys["decode_tokens_per_sec"] == 7506.3

    def test_sentinel_schema_roundtrip(self, tmp_path):
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"a_ms": 1.0, "b": "cfg"})
        keys, info = load_bench(path)
        assert keys == {"a_ms": 1.0, "b": "cfg"}
        assert info["format"] == "sentinel-v1"
        assert info["sidecar"] is True
        assert info["recovered"] is False

    def test_flat_and_wrapper_parsed_formats(self, tmp_path):
        flat = tmp_path / "flat.json"
        flat.write_text(json.dumps({"metric": "x", "value": 3.0}))
        keys, info = load_bench(str(flat))
        assert keys["value"] == 3.0 and info["format"] == "flat"
        wrapper = tmp_path / "wrap.json"
        wrapper.write_text(json.dumps(
            {"rc": 0, "tail": "garbage", "parsed": {"value": 5.0}}))
        keys, info = load_bench(str(wrapper))
        assert keys == {"value": 5.0}
        assert info["format"] == "driver-wrapper"

    def test_torn_file_salvaged(self, tmp_path):
        torn = tmp_path / "torn.json"
        torn.write_text('{"a_tokens_per_sec": 12.5, "b_step_ms": 3.0, '
                        '"trunca')
        keys, info = load_bench(str(torn))
        assert keys == {"a_tokens_per_sec": 12.5, "b_step_ms": 3.0}
        assert info["recovered"] is True

    def test_recover_keys_parses_value_kinds(self):
        text = ('"f": 1.5, "i": -3, "e": 1.2e-4, "t": true, '
                '"n": null, "s": "cfg", "torn": 12')
        out = recover_keys(text)
        assert out["f"] == 1.5 and out["i"] == -3
        assert out["e"] == pytest.approx(1.2e-4)
        assert out["t"] is True and out["n"] is None and out["s"] == "cfg"


class TestCompare:
    OLD = {"decode_tokens_per_sec": 1000.0, "decode_spread": 0.01,
           "decode_step_ms": 1.0,
           "noisy_tokens_per_sec": 1000.0, "noisy_spread": 0.4,
           "run_config": "b8", "ok_flag": True}

    def test_identical_runs_clean(self):
        assert regressions(compare(self.OLD, dict(self.OLD))) == []

    def test_throughput_drop_regresses(self):
        new = dict(self.OLD, decode_tokens_per_sec=500.0)
        bad = regressions(compare(self.OLD, new))
        assert [f["key"] for f in bad] == ["decode_tokens_per_sec"]
        assert bad[0]["verdict"] == "regressed"

    def test_time_increase_regresses(self):
        new = dict(self.OLD, decode_step_ms=2.0)
        assert [f["key"] for f in regressions(compare(self.OLD, new))] \
            == ["decode_step_ms"]

    def test_improvements_never_regress(self):
        new = dict(self.OLD, decode_tokens_per_sec=5000.0,
                   decode_step_ms=0.2)
        assert regressions(compare(self.OLD, new)) == []

    def test_spread_aware_tolerance(self):
        """A noisy key (spread 0.4 both sides) tolerates a 30% wobble
        that would fail a tight key — and the tight key still fails."""
        new = dict(self.OLD, noisy_tokens_per_sec=700.0,
                   decode_tokens_per_sec=700.0)
        bad = [f["key"] for f in regressions(compare(self.OLD, new))]
        assert bad == ["decode_tokens_per_sec"]

    def test_missing_key_is_a_regression(self):
        """Tail truncation deletes keys — a missing key must FAIL, not
        silently shrink the comparison (the r5 failure mode)."""
        new = dict(self.OLD)
        del new["decode_tokens_per_sec"]
        bad = regressions(compare(self.OLD, new))
        assert [f["key"] for f in bad] == ["decode_tokens_per_sec"]
        assert bad[0]["verdict"] == "missing"

    def test_new_keys_and_metadata_are_not_regressions(self):
        new = dict(self.OLD, extra_tokens_per_sec=1.0,
                   run_config="b16")
        findings = compare(self.OLD, new)
        assert regressions(findings) == []
        assert any(f["verdict"] == "new"
                   and f["key"] == "extra_tokens_per_sec"
                   for f in findings)

    def test_fleet_mapreduce_key_directions(self):
        """The fleet section's keys (bench.py fleet_section /
        docs/compiler_fleet.md) compare with the right better-
        directions: reduce/baseline/step times and wire bytes regress
        UP, MFU and the in-program speedup regress DOWN."""
        old = {"fleet_reduce_ms": 10.0, "fleet_reduce_bytes": 1000,
               "fleet_reduce_int8_bytes": 250,
               "fleet_host_baseline_ms": 100.0,
               "fleet_step_ms": 50.0, "fleet_step_mfu": 0.5,
               "fleet_inprogram_speedup": 10.0}
        worse = {"fleet_reduce_ms": 20.0, "fleet_reduce_bytes": 2000,
                 "fleet_reduce_int8_bytes": 500,
                 "fleet_host_baseline_ms": 200.0,
                 "fleet_step_ms": 100.0, "fleet_step_mfu": 0.25,
                 "fleet_inprogram_speedup": 5.0}
        bad = {f["key"] for f in regressions(compare(old, worse))}
        assert bad == set(old)
        better = {"fleet_reduce_ms": 5.0, "fleet_reduce_bytes": 500,
                  "fleet_reduce_int8_bytes": 100,
                  "fleet_host_baseline_ms": 100.0,
                  "fleet_step_ms": 25.0, "fleet_step_mfu": 0.9,
                  "fleet_inprogram_speedup": 20.0}
        assert regressions(compare(old, better)) == []

    def test_request_latency_and_burn_rate_directions(self):
        """The request-truth observability keys (ISSUE 10):
        per-request latency percentiles (decode_continuous_ttft_*/
        tpot_*_ms) and SLO burn rates are LOWER-better — a slower p99
        or a hotter error-budget burn regresses even while tokens/sec
        holds."""
        old = {"decode_continuous_ttft_p50_ms": 10.0,
               "decode_continuous_ttft_p95_ms": 25.0,
               "decode_continuous_ttft_p99_ms": 40.0,
               "decode_continuous_tpot_p95_ms": 2.0,
               "serve_slo_burn_rate": 0.5,
               "decode_continuous_tokens_per_sec": 1000.0}
        worse = {"decode_continuous_ttft_p50_ms": 20.0,
                 "decode_continuous_ttft_p95_ms": 50.0,
                 "decode_continuous_ttft_p99_ms": 80.0,
                 "decode_continuous_tpot_p95_ms": 4.0,
                 "serve_slo_burn_rate": 2.0,
                 "decode_continuous_tokens_per_sec": 1000.0}
        bad = {f["key"] for f in regressions(compare(old, worse))}
        assert bad == set(old) - {"decode_continuous_tokens_per_sec"}
        better = {key: value / 2 if key !=
                  "decode_continuous_tokens_per_sec" else value
                  for key, value in old.items()}
        assert regressions(compare(old, better)) == []

    def test_history_key_directions(self):
        """The metric-history keys (ISSUE 12, bench history_section):
        incident_mttd_ms rides the _ms rule (a slower detector
        regressed), the sampler-overhead _ns keys and the
        _anomaly_rate key are LOWER-better too (a pricier or noisier
        embedded recorder regresses even while throughput holds)."""
        old = {"incident_mttd_ms": 400.0,
               "history_sample_on_ns": 50000.0,
               "history_sample_off_ns": 20000.0,
               "history_anomaly_rate": 0.01}
        worse = {"incident_mttd_ms": 900.0,
                 "history_sample_on_ns": 150000.0,
                 "history_sample_off_ns": 60000.0,
                 "history_anomaly_rate": 0.2}
        bad = {f["key"] for f in regressions(compare(old, worse))}
        assert bad == set(old)
        better = {key: value / 2 for key, value in old.items()}
        assert regressions(compare(old, better)) == []

    def test_servescope_key_directions(self):
        """The serving goodput-observatory keys (bench
        servescope_section / observe/servescope.py):
        serve_goodput_fraction and the occupancy fraction are
        HIGHER-better (less useful work is a regression), every
        *_waste_share key — aggregate and per-cause — regresses UP,
        and the record-path overhead rides the _ns rule."""
        old = {"serve_goodput_fraction": 0.8,
               "serve_slot_occupancy_fraction": 0.7,
               "serve_waste_share": 0.2,
               "serve_dead_slot_waste_share": 0.1,
               "serve_group_dup_waste_share": 0.05,
               "serve_scope_note_ns": 500.0}
        worse = {"serve_goodput_fraction": 0.4,
                 "serve_slot_occupancy_fraction": 0.3,
                 "serve_waste_share": 0.6,
                 "serve_dead_slot_waste_share": 0.3,
                 "serve_group_dup_waste_share": 0.15,
                 "serve_scope_note_ns": 1500.0}
        bad = {f["key"] for f in regressions(compare(old, worse))}
        assert bad == set(old)
        better = {"serve_goodput_fraction": 0.95,
                  "serve_slot_occupancy_fraction": 0.9,
                  "serve_waste_share": 0.05,
                  "serve_dead_slot_waste_share": 0.02,
                  "serve_group_dup_waste_share": 0.01,
                  "serve_scope_note_ns": 250.0}
        assert regressions(compare(old, better)) == []

    def test_capacity_and_replay_key_directions(self):
        """The traffic record-replay + capacity keys (observe/
        replay.py, observe/capacity.py, bench replay_section —
        docs/traffic_replay.md): sustained tokens/sec, the cliff warp
        and round-trip fidelity are HIGHER-better (a config that
        sustains less, cliffs earlier or loses replayed tokens
        regressed); the replayer's schedule skew rides the _ms rule."""
        old = {"capacity_sustained_tokens_per_sec": 1000.0,
               "capacity_cliff_warp_x": 8.0,
               "replay_fidelity_delivered_ratio": 1.0,
               "replay_schedule_skew_ms": 5.0}
        worse = {"capacity_sustained_tokens_per_sec": 600.0,
                 "capacity_cliff_warp_x": 3.0,
                 "replay_fidelity_delivered_ratio": 0.6,
                 "replay_schedule_skew_ms": 50.0}
        bad = {f["key"] for f in regressions(compare(old, worse))}
        assert bad == set(old)
        better = {"capacity_sustained_tokens_per_sec": 1500.0,
                  "capacity_cliff_warp_x": 12.0,
                  "replay_fidelity_delivered_ratio": 1.0,
                  "replay_schedule_skew_ms": 1.0}
        assert regressions(compare(old, better)) == []

    def test_fifteen_percent_capacity_loss_regresses(self):
        """The ISSUE-19 contract: a PR that silently costs 15% of peak
        throughput must fail the gate (base tolerance is 10%)."""
        old = {"capacity_sustained_tokens_per_sec": 1000.0}
        new = {"capacity_sustained_tokens_per_sec": 850.0}
        bad = regressions(compare(old, new))
        assert [f["key"] for f in bad] \
            == ["capacity_sustained_tokens_per_sec"]
        assert bad[0]["verdict"] == "regressed"

    def test_type_change_is_a_regression(self):
        new = dict(self.OLD, decode_step_ms="fast")
        assert regressions(compare(self.OLD, new))[0]["verdict"] \
            == "type-changed"


class TestSentinelCLI:
    def test_real_r05_self_comparison_exits_zero(self, capsys):
        """The `make regress` acceptance path: the committed r05
        artifact against itself through the full loader (exercising
        truncation recovery) is clean."""
        assert compare_main(R05, R05) == 0
        out = capsys.readouterr().out
        assert "0 regression(s)" in out
        assert "recovered from a truncated artifact" in out

    def test_seeded_regression_fixture_exits_nonzero(self, tmp_path,
                                                     capsys):
        """The other half of `make regress`: prove the gate actually
        FAILS on a regression — a gate that can't fail proves
        nothing."""
        keys, _ = load_bench(R05)
        seeded = dict(keys)
        seeded["decode_tokens_per_sec"] = \
            keys["decode_tokens_per_sec"] * 0.5
        new_path = str(tmp_path / "seeded.json")
        BenchArtifact(new_path).update(seeded)
        assert compare_main(R05, new_path) == 1
        assert "REGRESSED" in capsys.readouterr().out

    def test_seeded_capacity_loss_fixture_exits_one(self, tmp_path,
                                                    capsys):
        """The ISSUE-19 acceptance fixture: two artifacts identical
        but for a 15% capacity_sustained_tokens_per_sec loss — the
        full CLI path (artifact load, direction lookup, tolerance)
        exits 1 and names the key."""
        base = {"capacity_sustained_tokens_per_sec": 1200.0,
                "capacity_cliff_warp_x": 6.0,
                "replay_schedule_skew_ms": 4.0,
                "replay_fidelity_delivered_ratio": 1.0}
        old_path = str(tmp_path / "main.json")
        new_path = str(tmp_path / "pr.json")
        BenchArtifact(old_path).update(base)
        BenchArtifact(new_path).update(
            dict(base, capacity_sustained_tokens_per_sec=1020.0))
        assert compare_main(old_path, new_path) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out
        assert "capacity_sustained_tokens_per_sec" in out

    def test_unreadable_artifact_exits_two(self, tmp_path):
        missing = str(tmp_path / "nope.json")
        assert compare_main(missing, R05) == 2

    def test_tampered_keys_exit_two(self, tmp_path, capsys):
        """Edited measurements fail the embedded keys hash — exit 2."""
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"a_tokens_per_sec": 1.0})
        doc = json.load(open(path))
        doc["keys"]["a_tokens_per_sec"] = 99.0  # forge the number
        with open(path, "w") as fout:
            json.dump(doc, fout)
        assert compare_main(path, path) == 2
        assert "INTEGRITY FAILURE" in capsys.readouterr().out

    def test_stale_sidecar_with_intact_keys_proceeds(self, tmp_path,
                                                     capsys):
        """The crash-window case: a kill between the artifact and
        sidecar writes leaves a stale sidecar beside an INTACT
        artifact — the embedded keys hash (atomic with the payload)
        vouches for it and the comparison proceeds with a warning
        instead of discarding a real measurement."""
        path = str(tmp_path / "bench.json")
        artifact = BenchArtifact(path)
        artifact.update({"a_tokens_per_sec": 1.0})
        stale = open(path + ".sha256").read()
        artifact.update({"b_step_ms": 2.0})
        with open(path + ".sha256", "w") as fout:
            fout.write(stale)  # the pre-crash sidecar
        assert verify_sidecar(path) is False
        assert compare_main(path, path) == 0
        assert "sidecar is stale" in capsys.readouterr().out

    def test_empty_sidecar_is_a_mismatch_not_a_crash(self, tmp_path):
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"a_tokens_per_sec": 1.0})
        open(path + ".sha256", "w").close()  # zero-byte sidecar
        assert verify_sidecar(path) is False

    def test_json_output(self, tmp_path, capsys):
        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"a_tokens_per_sec": 1.0})
        assert compare_main(path, path, as_json=True) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == 0

    def test_observe_cli_routes_regress(self, tmp_path, capsys):
        from veles_tpu.observe.trace_export import main as observe_main

        path = str(tmp_path / "bench.json")
        BenchArtifact(path).update({"a_tokens_per_sec": 1.0})
        assert observe_main(["regress", path, path]) == 0


class TestBenchHooks:
    def test_spread_warn_flags(self):
        import bench

        out = {"decode_spread": 0.42, "tight_spread": 0.004,
               "other_key": 1.0, "flagless_spread_warn": True}
        warns = bench._spread_warns(out)
        assert warns == {"decode_spread_warn": True}

    def test_two_length_times_runs_warmup_passes(self):
        import bench

        calls = {"a": 0, "b": 0}

        def runner(name):
            def fn():
                calls[name] += 1
            return fn

        fns = {("v", 1): runner("a"), ("v", 3): runner("b")}
        bench._two_length_times(fns, (1, 3), repeats=3, warmup=2)
        # 2 warmup + 3 timed visits each
        assert calls == {"a": 5, "b": 5}
