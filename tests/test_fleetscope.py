"""Fleet goodput observatory: cross-process trace assembly, clock
alignment, goodput decomposition, straggler autopsy.

The tentpole suite (docs/observability.md "Fleet timeline + goodput"):
unit tests for the span ring / ingestion validation / NTP-style clock
estimator / goodput math / straggler detector, the multi-process
Chrome-exporter satellite, the ``observe fleet-trace`` CLI, and the
chaos acceptance — a real loopback fleet with a seeded slow-slave
chaos profile must deterministically name the injected straggler, land
a fleet incident artifact, and export a Perfetto-loadable merged trace
with clock-aligned issue → do_job → apply chains.

``make fleetscope`` runs this module standalone; the chaos end-to-end
rides the ``slow`` marker so tier-1 keeps its timeout margin.
"""

import json
import math
import os
import random
import threading
import time

import pytest

from veles_tpu.fleet.ledger import JobLedger
from veles_tpu.observe.fleetscope import (
    CLOCK_UNCERTAINTY_FLOOR_S, ClockEstimate, FleetScope, SpanRing,
    SPAN_SHIP_MAX_ROWS, STRAGGLER_RATIO, STRAGGLER_WINDOWS, StepWindow,
    assemble_fleet_trace, ensure_fleet_rules, fleet_trace_main,
    get_span_ring, valid_span_rows)

pytestmark = pytest.mark.fleetscope


class _FakeSlave:
    def __init__(self, sid, mid="m", pid=1):
        self.id = sid
        self.mid = mid
        self.pid = pid


# -- span ring (the slave-side record path) ---------------------------------

class TestSpanRing:
    def test_bounded_drop_oldest(self):
        ring = SpanRing(capacity=8).enable()
        for index in range(50):
            ring.note_span("s%d" % index, "t", "sp%d" % index, None,
                           0.0, 1.0, 0)
        assert len(ring) == 8
        rows = ring.drain()
        assert [row[0] for row in rows] == \
            ["s%d" % i for i in range(42, 50)]
        assert len(ring) == 0
        assert ring.noted_total == 50 and ring.shipped_total == 8

    def test_disabled_is_noop(self):
        ring = SpanRing(capacity=8)
        ring.note_span("a", "t", "sp", None, 0.0, 1.0, 0)
        assert len(ring) == 0 and ring.noted_total == 0

    def test_drain_cap_per_frame(self):
        ring = SpanRing(capacity=512).enable()
        for index in range(300):
            ring.note_span("s", "t", "sp%d" % index, None, 0.0, 1.0, 0)
        first = ring.drain()
        assert len(first) == SPAN_SHIP_MAX_ROWS
        assert len(ring) == 300 - SPAN_SHIP_MAX_ROWS

    def test_record_path_has_no_lock_and_truncates_names(self):
        """The flight-recorder overhead contract: no lock attribute
        anywhere on the ring, bounded memory, names truncated at note
        time — the analyze lock.record-path rule gates the source."""
        ring = SpanRing(capacity=4).enable()
        assert not any("lock" in name or "mutex" in name
                       for name in vars(ring))
        ring.note_span("x" * 500, "t", "sp", None, 0.0, 1.0, 0)
        assert len(ring.drain()[0][0]) <= 120


class TestSpanShipping:
    def test_tracer_feeds_completed_spans(self):
        from veles_tpu.observe.tracing import Tracer, get_tracer

        ring = get_span_ring()
        was_enabled = ring.enabled
        ring.drain(10 ** 6)
        ring.enable()
        tracer = get_tracer()
        tracer_was = tracer.enabled
        tracer.enable()
        try:
            with tracer.span("fleet.do_job", job_id=7) as span:
                time.sleep(0.002)
            tracer.event("fleet.issue", job_id=7)
            rows = ring.drain()
        finally:
            tracer.enabled = tracer_was
            ring.enabled = was_enabled
        by_name = {row[0]: row for row in rows}
        assert "fleet.do_job" in by_name and "fleet.issue" in by_name
        do_job = by_name["fleet.do_job"]
        assert do_job[1] == span.trace_id
        assert do_job[2] == span.span_id
        assert do_job[5] >= 2.0  # dur_ms covers the sleep
        assert by_name["fleet.issue"][5] == 0.0  # events are instants

    def test_disabled_ring_untouched_by_tracer(self):
        from veles_tpu.observe.tracing import Tracer

        ring = get_span_ring()
        was_enabled = ring.enabled
        ring.disable()
        ring.drain(10 ** 6)
        tracer = Tracer(enabled=True)
        try:
            with tracer.span("quiet"):
                pass
            assert len(ring) == 0
        finally:
            ring.enabled = was_enabled


# -- clock alignment --------------------------------------------------------

def _exchange(est, theta_true, d1, d2, residence, t0):
    """Simulate one job->update exchange: master sends at t0 (master
    clock), wire delays d1/d2, slave residence; feeds the estimator
    the same theta/delta the server derives from the stamps."""
    t1 = t0 + d1 + theta_true           # slave receive (slave clock)
    t2 = t1 + residence                 # slave send (slave clock)
    t3 = t0 + d1 + residence + d2       # master receive (master clock)
    theta = ((t1 - t0) + (t2 - t3)) / 2.0
    delta = (t3 - t0) - (t2 - t1)
    est.observe(theta, delta)
    return t3


class TestClockEstimate:
    def test_symmetric_delay_recovers_offset_exactly(self):
        est = ClockEstimate()
        _exchange(est, theta_true=5.0, d1=0.01, d2=0.01,
                  residence=0.05, t0=100.0)
        assert abs(est.offset_s - 5.0) < 1e-9
        assert est.uncertainty_s == pytest.approx(
            0.01 + CLOCK_UNCERTAINTY_FLOOR_S)

    def test_asymmetric_delay_within_reported_uncertainty(self):
        """The NTP error bound: |estimate - truth| <= delta/2 holds for
        ANY delay asymmetry — the uncertainty the estimator reports is
        a true bound, not a vibe."""
        est = ClockEstimate()
        _exchange(est, theta_true=-3.0, d1=0.002, d2=0.038,
                  residence=0.1, t0=50.0)
        assert abs(est.offset_s - (-3.0)) <= est.uncertainty_s

    def test_chaos_frame_delay_profile_stays_within_bound(self):
        """The chaos frame-delay satellite: seeded random delays (the
        fleet/chaos.py delay profile shape) on every exchange — the
        min-round-trip filter keeps the estimate within its own
        reported bound, and the bound itself stays below the worst
        injected delay."""
        rng = random.Random(1)
        theta_true = 2.5
        est = ClockEstimate()
        t = 10.0
        for _ in range(40):
            d1 = rng.uniform(0.0005, 0.02)
            d2 = rng.uniform(0.0005, 0.02)
            if rng.random() < 0.5:  # the injected frame delay
                d1 += 0.02
            if rng.random() < 0.5:
                d2 += 0.02
            t = _exchange(est, theta_true, d1, d2,
                          residence=rng.uniform(0.01, 0.05), t0=t) + 0.1
        assert abs(est.offset_s - theta_true) <= est.uncertainty_s
        assert est.uncertainty_s <= 0.041  # never worse than max delay
        assert est.samples == 40

    def test_filter_prefers_min_round_trip(self):
        est = ClockEstimate()
        _exchange(est, theta_true=1.0, d1=0.04, d2=0.001,
                  residence=0.02, t0=0.0)
        loose = est.uncertainty_s
        _exchange(est, theta_true=1.0, d1=0.001, d2=0.001,
                  residence=0.02, t0=1.0)
        assert est.uncertainty_s < loose
        assert abs(est.offset_s - 1.0) < 1e-6

    def test_to_master_mapping(self):
        est = ClockEstimate()
        _exchange(est, theta_true=7.0, d1=0.001, d2=0.001,
                  residence=0.01, t0=0.0)
        assert est.to_master(107.0) == pytest.approx(100.0, abs=1e-6)


# -- ingestion validation ---------------------------------------------------

class TestSpanValidation:
    GOOD = ["fleet.do_job", "t" * 16, "s" * 16, "p" * 16, 12.5, 3.25, 7]

    def test_good_row_passes(self):
        assert len(valid_span_rows([list(self.GOOD)])) == 1

    def test_hostile_rows_dropped(self):
        bad = [
            None, "string", 42, [],                       # not rows
            ["n", "t", "s", "p", 1.0],                    # short
            [1, "t", "s", "p", 1.0, 1.0, 0],              # name not str
            ["n", "t", "", "p", 1.0, 1.0, 0],             # empty span id
            ["n", "t", "s" * 200, "p", 1.0, 1.0, 0],      # oversized id
            ["n", 5, "s", "p", 1.0, 1.0, 0],              # trace not str
            ["n", "t", "s", 5, 1.0, 1.0, 0],              # parent not str
            ["n", "t", "s", "p", float("nan"), 1.0, 0],   # t0 nan
            ["n", "t", "s", "p", 1.0, -1.0, 0],           # negative dur
            ["n", "t", "s", "p", 1.0, float("inf"), 0],   # inf dur
            ["n", "t", "s", "p", True, 1.0, 0],           # bool t0
        ]
        assert valid_span_rows(bad) == []

    def test_row_volume_capped_and_name_truncated(self):
        rows = [["x" * 500, None, "sp%d" % i, None, 0.0, 1.0, 0]
                for i in range(1000)]
        out = valid_span_rows(rows)
        assert len(out) == SPAN_SHIP_MAX_ROWS
        assert all(len(row[0]) <= 120 for row in out)

    def test_bad_tid_degrades_to_zero(self):
        row = list(self.GOOD)
        row[6] = "boom"
        assert valid_span_rows([row])[0][6] == 0


class TestFleetScopeIngestion:
    def test_round_trip_builds_clock_and_pair(self):
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        scope.note_issue(1, slave, now=100.0)
        msg = {"job_id": 1, "mono": [205.01, 205.06], "job_ms": 40.0,
               "spans": [list(TestSpanValidation.GOOD)]}
        pair = scope.note_update(slave, msg, now=100.07)
        assert pair is not None
        assert pair["rtt"] == pytest.approx(0.07)
        assert pair["residence"] == pytest.approx(0.05)
        assert pair["compute"] == pytest.approx(0.04)
        clocks = scope.clock_summary()
        assert clocks["m:1"]["slave"] == "slave-1"
        # true offset 105s, symmetric 10ms wire legs -> exact
        assert clocks["m:1"]["offset_ms"] == pytest.approx(105000.0,
                                                           abs=1.0)
        assert len(scope.spans) == 1

    def test_duplicate_replay_deduped(self):
        """A chaos duplicate-update replay ships the same span rows
        twice and re-echoes the same job_id: spans must not double,
        and the second frame has no pending stamp to pair."""
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        scope.note_issue(1, slave, now=0.0)
        msg = {"job_id": 1, "mono": [10.0, 10.01], "job_ms": 5.0,
               "spans": [list(TestSpanValidation.GOOD)]}
        assert scope.note_update(slave, msg, now=0.05) is not None
        assert scope.note_update(slave, dict(msg), now=0.09) is None
        assert len(scope.spans) == 1
        assert scope.spans_ingested["slave-1"] == 1

    def test_garbage_stamps_ignored(self):
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        for bad in ({"job_id": "x"}, {"job_id": 2},
                    {"job_id": 1, "mono": "zzz"},
                    {"job_id": 1, "mono": [1.0]},
                    {"job_id": 1, "mono": [float("nan"), 2.0]},
                    {"job_id": 1, "mono": [5.0, 1.0]}):
            scope.note_issue(1, slave, now=0.0)
            assert scope.note_update(slave, bad, now=1.0) is None
        assert scope.clock_summary() == {}

    def test_zombie_update_cannot_consume_reissued_stamp(self):
        """A requeued lease's job_id gets re-issued to another slave:
        the zombie's late (fenced) update must not consume the
        re-issued slave's pending stamp pair — its mixed-origin
        stamps would poison the clock and orphan the real booking."""
        scope = FleetScope()
        zombie = _FakeSlave("slave-1", pid=1)
        healthy = _FakeSlave("slave-2", pid=2)
        scope.note_issue(1, zombie, now=0.0)
        # the lease expires and the job re-issues to slave-2
        scope.note_issue(1, healthy, now=1.0)
        late = {"job_id": 1, "mono": [9.0, 9.01], "job_ms": 5.0}
        assert scope.note_update(zombie, late, now=1.1) is None
        assert scope.clock_summary() == {}
        # the genuine update still pairs against ITS issue stamp
        real = {"job_id": 1, "mono": [50.0, 50.02], "job_ms": 15.0}
        pair = scope.note_update(healthy, real, now=1.2)
        assert pair is not None
        assert pair["rtt"] == pytest.approx(0.2)
        assert "m:2" in scope.clock_summary()

    def test_rollback_report_last_wins(self):
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        scope.note_update(slave, {"rollback_ms": 100.0}, now=1.0)
        scope.note_update(slave, {"rollback_ms": 250.0}, now=2.0)
        assert scope.goodput_summary()["wasted_s"] == \
            pytest.approx(0.25)


# -- goodput decomposition --------------------------------------------------

class TestGoodput:
    def test_decomposition_adds_up(self):
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        scope.note_issue(1, slave, now=0.0)
        msg = {"job_id": 1, "mono": [50.01, 50.07], "job_ms": 40.0}
        pair = scope.note_update(slave, msg, now=0.08)
        scope.book_update("slave-1", pair, now=0.08)
        summary = scope.goodput_summary()
        assert summary["jobs"] == 1
        assert summary["compute_s"] == pytest.approx(0.04)
        assert summary["host_s"] == pytest.approx(0.02)   # 60ms - 40ms
        assert summary["wire_s"] == pytest.approx(0.02)   # 80ms - 60ms
        assert summary["idle_s"] == pytest.approx(0.0)
        assert summary["fraction"] == pytest.approx(0.5)

    def test_idle_gap_between_jobs(self):
        scope = FleetScope()
        slave = _FakeSlave("slave-1")
        scope.note_issue(1, slave, now=0.0)
        pair = scope.note_update(
            slave, {"job_id": 1, "mono": [10.0, 10.05], "job_ms": 50.0},
            now=0.05)
        scope.book_update("slave-1", pair, now=0.05)
        # 0.95s gap before the next job's round trip starts
        scope.note_issue(2, slave, now=1.0)
        pair = scope.note_update(
            slave, {"job_id": 2, "mono": [20.0, 20.05], "job_ms": 50.0},
            now=1.05)
        scope.book_update("slave-1", pair, now=1.05)
        summary = scope.goodput_summary()
        assert summary["idle_s"] == pytest.approx(0.95)
        assert summary["compute_s"] == pytest.approx(0.1)

    def test_ledger_requeue_books_wasted_seconds(self):
        """Requeued-after-death work: the lease's in-flight seconds
        land in the ledger's wasted tally, which the server feeds into
        the goodput summary."""
        ledger = JobLedger()
        job = ledger.issue("slave-1", timeout=60.0, now=1000.0)
        ledger.requeue_for_slave("slave-1", now=1002.5)
        snap = ledger.snapshot()
        assert snap["wasted_s"] == pytest.approx(2.5)
        expired = ledger.issue("slave-1", timeout=10.0, now=2000.0)
        assert ledger.expire_if_outstanding(expired, now=2011.0)
        assert ledger.snapshot()["wasted_s"] == pytest.approx(13.5)
        # DONE leases never count as waste
        done = ledger.issue("slave-1", timeout=60.0, now=3000.0)
        assert ledger.settle(done, "slave-1") is None
        assert ledger.snapshot()["wasted_s"] == pytest.approx(13.5)
        scope = FleetScope()
        summary = scope.goodput_summary(wasted_s=snap["wasted_s"])
        assert summary["wasted_s"] == pytest.approx(2.5)


# -- straggler detection ----------------------------------------------------

def _feed(scope, sid, times):
    window = scope.windows.setdefault(sid, StepWindow())
    for value in times:
        window.push(value)


class TestStraggler:
    def test_names_the_slow_slave_after_k_windows(self):
        scope = FleetScope()
        _feed(scope, "slave-1", [0.01] * 5)
        _feed(scope, "slave-2", [0.05] * 5)
        events = []
        for step in range(STRAGGLER_WINDOWS):
            event = scope.evaluate_straggler("slave-2", now=float(step))
            events.append(event)
        assert events[:-1] == [None] * (STRAGGLER_WINDOWS - 1)
        assert events[-1]["slave"] == "slave-2"
        assert events[-1]["score"] == pytest.approx(5.0)
        assert events[-1]["windows"] == STRAGGLER_WINDOWS
        assert scope.straggler_summary()["slave"] == "slave-2"
        # the fast slave never breaches
        assert scope.scores["slave-1"] < 1.0

    def test_single_slave_fleet_has_no_straggler(self):
        scope = FleetScope()
        _feed(scope, "slave-1", [0.5] * 10)
        assert scope.evaluate_straggler("slave-1", now=0.0) is None

    def test_recovery_clears_the_verdict(self):
        scope = FleetScope()
        _feed(scope, "slave-1", [0.01] * 10)
        _feed(scope, "slave-2", [0.05] * 10)
        for step in range(STRAGGLER_WINDOWS):
            scope.evaluate_straggler("slave-2", now=float(step))
        assert scope.straggler_summary() is not None
        # the slave recovers: fresh fast samples pull its median down
        _feed(scope, "slave-2", [0.01] * 100)
        assert scope.evaluate_straggler("slave-2", now=99.0) is None
        assert scope.straggler_summary() is None

    def test_ratio_threshold_respected(self):
        scope = FleetScope()
        _feed(scope, "slave-1", [0.010] * 5)
        below = 0.010 * (STRAGGLER_RATIO - 0.1)
        _feed(scope, "slave-2", [below] * 5)
        for step in range(STRAGGLER_WINDOWS + 2):
            assert scope.evaluate_straggler("slave-2",
                                            now=float(step)) is None

    def test_dropped_slave_leaves_the_scoring_pool(self):
        """A departed slave's frozen window must not skew the
        rest-of-fleet median, and a straggler verdict naming a dead
        slave is flagged departed (kept visible), never pinned as a
        live breach forever."""
        scope = FleetScope()
        _feed(scope, "slave-1", [0.01] * 5)
        _feed(scope, "slave-2", [0.05] * 5)
        _feed(scope, "slave-3", [0.011] * 5)
        for step in range(STRAGGLER_WINDOWS):
            scope.evaluate_straggler("slave-2", now=float(step))
        assert scope.straggler_summary()["slave"] == "slave-2"
        scope.drop_slave("slave-2")
        verdict = scope.straggler_summary()
        assert verdict["slave"] == "slave-2" and verdict["departed"]
        # the survivors now score against each other only: the dead
        # slave's 50ms median no longer inflates slave-3's score
        scope.evaluate_straggler("slave-3", now=10.0)
        assert scope.scores["slave-3"] == pytest.approx(1.1)
        assert "slave-2" not in scope._streaks
        # a re-tracked sid rejoins the pool
        scope.track_window("slave-2", scope.windows["slave-2"])
        assert "slave-2" not in scope._departed

    def test_fleet_rules_not_evaluated_by_the_sampler(self):
        """The fleet rules are detector-owned (external=True): the
        history sampler's rule pass must skip them — sampler-cadence
        evaluation would race autopsy_tick's state writes and fire
        without the detector's per-job window semantics."""
        from veles_tpu.observe.history import MetricHistory
        from veles_tpu.observe.metrics import MetricsRegistry

        history = MetricHistory(registry=MetricsRegistry())
        straggler_rule, _ = ensure_fleet_rules(history)
        assert straggler_rule.external
        rows = [("veles_fleet_straggler_score", "gauge",
                 (("slave", "slave-2"),), 99.0)]
        for step in range(STRAGGLER_WINDOWS + 2):
            history.sample(now=float(step), rows=list(rows))
        assert straggler_rule.streak == 0
        assert straggler_rule.fired_total == 0
        assert history.anomalies_total == 0

    def test_hang_timeout_reads_the_same_window(self):
        """Satellite: SlaveDescription's mean+3σ hang threshold and
        the straggler detector read ONE StepWindow implementation."""
        from veles_tpu.fleet.server import SlaveDescription

        slave = SlaveDescription("slave-1", {})
        assert slave.job_times == []
        for value in (1.0, 2.0, 3.0, 4.0):
            slave.record_job_time(value)
        assert slave.job_times == [1.0, 2.0, 3.0, 4.0]
        mean = 2.5
        sigma = (sum((t - mean) ** 2 for t in (1, 2, 3, 4)) / 4) ** 0.5
        assert slave.timeout(0.0) == pytest.approx(mean + 3 * sigma)
        assert slave.timeout(1000.0) == 1000.0  # floor kept
        assert slave.window.hang_timeout(0.0) == slave.timeout(0.0)
        # the cap still holds (the old job_times bound)
        for _ in range(300):
            slave.record_job_time(1.0)
        assert len(slave.job_times) == SlaveDescription.JOB_TIMES_KEEP


class TestFleetRules:
    def _history(self, tmp_path):
        from veles_tpu.observe.history import (IncidentRecorder,
                                               MetricHistory)
        from veles_tpu.observe.metrics import MetricsRegistry

        return MetricHistory(
            registry=MetricsRegistry(enabled=False),
            incidents=IncidentRecorder(directory=str(tmp_path),
                                       cooldown_s=0.0))

    def test_rules_booked_idempotently(self, tmp_path):
        history = self._history(tmp_path)
        first = ensure_fleet_rules(history)
        second = ensure_fleet_rules(history)
        assert first == second
        names = [rule.name for rule in history.rules]
        assert names.count("fleet_straggler") == 1
        assert names.count("fleet_goodput") == 1

    def test_autopsy_fires_incident_naming_straggler(self, tmp_path):
        """The acceptance core, synthetically: a persistent straggler
        lands a fleet incident artifact whose trigger names the slave,
        with the goodput breach as the lead reference."""
        history = self._history(tmp_path)
        scope = FleetScope()
        slave = _FakeSlave("slave-2")
        _feed(scope, "slave-1", [0.01] * 6)
        # feed goodput so the fraction breaches (mostly host time)
        scope.note_issue(1, slave, now=0.0)
        pair = scope.note_update(
            slave, {"job_id": 1, "mono": [5.0, 5.1], "job_ms": 10.0},
            now=0.1)
        scope.book_update("slave-2", pair, now=0.1)
        path = None
        # one sample per tick: the detector needs MIN_SAMPLES history
        # before scoring, then STRAGGLER_WINDOWS breaching windows
        for step in range(STRAGGLER_WINDOWS * 2 + 2):
            _feed(scope, "slave-2", [0.05])
            path = path or scope.autopsy_tick(
                "slave-2", history, now=float(step + 1))
        assert path is not None and os.path.exists(path)
        with open(path) as fin:
            doc = json.load(fin)
        assert doc["reason"] == "fleet_straggler"
        assert doc["trigger"]["labels"] == [["slave", "slave-2"]]
        assert doc["trigger"]["straggler"]["slave"] == "slave-2"
        breaching = {row["name"] for row in doc["breaching"]}
        assert "fleet_straggler" in breaching
        assert "fleet_goodput" in breaching  # fraction 0.1 <= 0.5
        lead = doc["leading_indicator"]
        assert lead["reference"] == "fleet_goodput"
        # trend series recorded for the timeline
        assert history.get("veles_fleet_straggler_score",
                           {"slave": "slave-2"}) is not None
        assert history.get("veles_fleet_goodput_fraction") is not None
        # cooldown: an immediate second firing is suppressed
        rule = next(r for r in history.rules
                    if r.name == "fleet_straggler")
        assert rule.fired_total == 1

    def test_autopsy_without_history_still_detects(self):
        scope = FleetScope()
        _feed(scope, "slave-1", [0.01] * 6)
        _feed(scope, "slave-2", [0.05] * 6)
        for step in range(STRAGGLER_WINDOWS):
            scope.autopsy_tick("slave-2", None, now=float(step))
        assert scope.straggler_summary()["slave"] == "slave-2"


# -- the Chrome exporter satellite ------------------------------------------

def _span_events(pid, trace_id, name, span_id, parent, t0, dur,
                 tid=1):
    base = {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent, "pid": pid, "tid": tid}
    return [dict(base, etype="begin", mono=t0),
            dict(base, etype="end", mono=t0 + dur)]


class TestChromeMultiprocess:
    def test_process_rows_do_not_collapse(self):
        from veles_tpu.observe.trace_export import chrome_trace

        events = (_span_events(111, "tr", "a", "s1", None, 1.0, 0.5)
                  + _span_events(222, "tr", "b", "s2", "s1", 1.2, 0.1))
        trace = chrome_trace(events)
        metadata = [e for e in trace["traceEvents"]
                    if e.get("ph") == "M"]
        names = {e["name"] for e in metadata}
        assert "process_name" in names and "thread_name" in names
        process_rows = [e for e in metadata
                        if e["name"] == "process_name"]
        assert len(process_rows) == 2
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert len({e["pid"] for e in spans}) == 2
        # stable small pids, first-appearance order
        assert sorted(e["pid"] for e in spans) == [1, 2]

    def test_process_names_metadata(self):
        from veles_tpu.observe.trace_export import chrome_trace

        events = _span_events("m:1", "tr", "a", "s1", None, 0.0, 1.0)
        trace = chrome_trace(events,
                             process_names={"m:1": "slave slave-1"})
        row = next(e for e in trace["traceEvents"]
                   if e.get("ph") == "M"
                   and e["name"] == "process_name")
        assert row["args"]["name"] == "slave slave-1"

    def test_span_tree_still_connects(self):
        from veles_tpu.observe.trace_export import chrome_trace, \
            span_tree

        events = (_span_events(1, "tr", "root", "s1", None, 0.0, 1.0)
                  + _span_events(2, "tr", "child", "s2", "s1", 0.1,
                                 0.5))
        trees = span_tree(chrome_trace(events))
        assert trees == {"tr": {"s1": None, "s2": "s1"}}


class TestAssembleFleetTrace:
    def _payload(self, offset_s=4.0):
        # master issues at mono 10.0, applies at 10.2; the slave (clock
        # ahead by offset_s) ran do_job in between at its own stamps
        master_spans = []
        for event in (
                {"name": "fleet.issue", "etype": "single", "mono": 10.0,
                 "trace_id": "tr1", "span_id": "i1",
                 "parent_id": None, "tid": 5, "pid": 999},
                {"name": "fleet.apply", "etype": "begin", "mono": 10.2,
                 "trace_id": "tr1", "span_id": "a1",
                 "parent_id": "d1", "tid": 5, "pid": 999},
                {"name": "fleet.apply", "etype": "end", "mono": 10.25,
                 "trace_id": "tr1", "span_id": "a1",
                 "parent_id": "d1", "tid": 5, "pid": 999},
                # a master copy of a span the slave ALSO shipped (the
                # same-host shared-ring case): must dedupe
                {"name": "fleet.do_job", "etype": "begin",
                 "mono": 10.05, "trace_id": "tr1", "span_id": "d1",
                 "parent_id": "i1", "tid": 6, "pid": 999}):
            master_spans.append(dict(event, kind="span"))
        slave_t0 = 10.05 + offset_s
        return {
            "kind": "fleetscope", "schema": 1, "master_pid": 999,
            "master_mid": "mid0",
            "status": {"goodput": {"jobs": 2, "fraction": 0.8,
                                   "compute_s": 1.0, "host_s": 0.1,
                                   "wire_s": 0.1, "idle_s": 0.05,
                                   "wasted_s": 0.0}},
            "clocks": {"mid0:7": {"slave": "slave-1",
                                  "offset_ms": offset_s * 1e3,
                                  "uncertainty_ms": 1.0,
                                  "samples": 4}},
            "slave_spans": [
                {"proc": "mid0:7", "slave": "slave-1",
                 "name": "fleet.do_job", "trace_id": "tr1",
                 "span_id": "d1", "parent_id": "i1", "tid": 9,
                 "t0": slave_t0, "dur_ms": 100.0,
                 "t0_master": slave_t0 - offset_s}],
            "master_spans": master_spans,
        }

    def test_one_row_per_process_and_aligned_chain(self):
        payload = self._payload()
        trace = assemble_fleet_trace(payload)
        events = trace["traceEvents"]
        process_rows = {e["args"]["name"] for e in events
                        if e.get("ph") == "M"
                        and e["name"] == "process_name"}
        assert process_rows == {"master (mid0 pid 999)",
                                "slave slave-1 (mid0:7)"}
        spans = {e["name"]: e for e in events if e.get("ph") != "M"}
        issue, do_job, apply_ = (spans["fleet.issue"],
                                 spans["fleet.do_job"],
                                 spans["fleet.apply"])
        # per-process rows: do_job renders on the slave's row
        assert do_job["pid"] != issue["pid"]
        assert issue["pid"] == apply_["pid"]
        # the master's duplicate do_job copy was deduped
        assert sum(1 for e in events
                   if e.get("ph") != "M"
                   and e["name"] == "fleet.do_job") == 1
        # clock-aligned: issue (0) < do_job (50ms) < apply (200ms)
        assert issue["ts"] <= do_job["ts"] <= apply_["ts"]
        assert do_job["ts"] == pytest.approx(50e3, abs=1e3)
        assert do_job["dur"] == pytest.approx(100e3, abs=1.0)
        # the one-trace chain survives assembly
        assert do_job["args"]["parent_id"] == "i1"
        assert apply_["args"]["parent_id"] == "d1"

    def test_cli_round_trip(self, tmp_path, capsys):
        saved = tmp_path / "fleet_debug.json"
        saved.write_text(json.dumps(self._payload()))
        out = tmp_path / "fleet.trace.json"
        assert fleet_trace_main(str(saved), output=str(out)) == 0
        trace = json.loads(out.read_text())
        assert any(e.get("ph") == "M" for e in trace["traceEvents"])
        text = capsys.readouterr().out
        assert "process row" in text
        assert "goodput 80.0%" in text
        assert "ui.perfetto.dev" in text

    def test_cli_rejects_garbage(self, tmp_path, capsys):
        bad = tmp_path / "nope.json"
        bad.write_text("{\"kind\": \"other\"}")
        assert fleet_trace_main(str(bad)) == 1
        assert fleet_trace_main(str(tmp_path / "missing.json")) == 1

    def test_observe_subcommand_dispatch(self, tmp_path, capsys):
        from veles_tpu.observe.trace_export import main as observe_main

        saved = tmp_path / "fleet_debug.json"
        saved.write_text(json.dumps(self._payload()))
        out = tmp_path / "cli.trace.json"
        assert observe_main(["fleet-trace", str(saved),
                             "-o", str(out)]) == 0
        assert json.loads(out.read_text())["traceEvents"]


class TestDashboardCell:
    def test_fleet_cell_renders_goodput_and_straggler(self):
        from veles_tpu.web_status import format_fleet_health

        cell = format_fleet_health({
            "plane": "data",
            "ledger": {"done": 10, "issued": 12, "requeued": 2},
            "goodput": {"jobs": 10, "fraction": 0.62, "wasted_s": 1.5},
            "straggler": {"slave": "slave-2", "score": 4.1}})
        assert "goodput 62%" in cell
        assert "1.5s wasted" in cell
        assert "straggler slave-2 (4.1x median)" in cell

    def test_fleet_cell_quiet_without_fleetscope_data(self):
        from veles_tpu.web_status import format_fleet_health

        cell = format_fleet_health({"ledger": {"done": 1, "issued": 1}})
        assert "goodput" not in cell and "straggler" not in cell


# -- bench key directions (satellite) ---------------------------------------

class TestDirections:
    def test_fleetscope_bench_directions(self):
        from veles_tpu.observe.regress import _lower_is_better

        assert not _lower_is_better("fleet_goodput_fraction")
        assert _lower_is_better("fleet_straggler_detect_ms")
        assert _lower_is_better("fleet_span_ship_overhead_ns")


# -- the chaos slow-slave acceptance (real loopback fleet) ------------------

class _ScriptedWorkflow:
    """Minimal fleet-protocol workflow: the master side serves job
    integers, the slave side sleeps a fixed per-job wall."""

    checksum = "fleetscope-e2e"

    def __init__(self, jobs=(), job_sleep_s=0.0):
        self._jobs = list(jobs)
        self.job_sleep_s = job_sleep_s
        self.applied = []

    def generate_initial_data_for_slave(self, slave):
        return None

    def generate_data_for_slave(self, slave):
        return self._jobs.pop(0) if self._jobs else None

    def apply_data_from_slave(self, update, slave):
        self.applied.append(update)

    def apply_initial_data_from_master(self, initial):
        pass

    def do_job(self, job, callback):
        time.sleep(self.job_sleep_s)
        callback({"job": job})

    def drop_slave(self, slave):
        pass

    def has_more_jobs(self):
        return bool(self._jobs)


@pytest.mark.slow
class TestChaosSlowSlaveE2E:
    def test_chaos_straggler_named_and_trace_assembled(self, tmp_path):
        """The acceptance criterion: a loopback fleet with the seeded
        slow-slave chaos profile on one slave (and frame-delay jitter
        on the other) deterministically names the injected straggler
        in fleet_status(), lands a fleet incident artifact, keeps the
        clock aligned within its own bound, and `observe fleet-trace`
        emits a Perfetto-loadable merged trace (saved payload AND
        --live) with connected, clock-ordered issue→do_job→apply
        chains."""
        import urllib.request

        from veles_tpu.fleet.chaos import ChaosConfig, ChaosMonkey
        from veles_tpu.fleet.client import Client
        from veles_tpu.fleet.server import Server
        from veles_tpu.observe.history import (IncidentRecorder,
                                               MetricHistory,
                                               get_metric_history,
                                               set_metric_history)
        from veles_tpu.observe.metrics import MetricsRegistry
        from veles_tpu.observe.tracing import get_tracer

        tracer = get_tracer()
        tracer_was = tracer.enabled
        tracer.enable()
        previous_history = get_metric_history()
        history = MetricHistory(
            registry=MetricsRegistry(enabled=False),
            incidents=IncidentRecorder(directory=str(tmp_path),
                                       cooldown_s=0.0))
        set_metric_history(history)
        get_span_ring().drain(10 ** 6)
        master = Server("127.0.0.1:0",
                        _ScriptedWorkflow(jobs=range(80)),
                        secret="fleetscope-e2e", metrics_port=0)
        done = threading.Event()
        master.on_finished = done.set
        clients = []
        try:
            master.start()
            # slave A: frame-delay jitter only (alignment stressor)
            delay = ChaosMonkey(ChaosConfig(
                seed=1, frame_delay=0.5, frame_delay_ms=10.0))
            fast = Client("127.0.0.1:%d" % master.port,
                          _ScriptedWorkflow(job_sleep_s=0.003),
                          secret="fleetscope-e2e", chaos=delay)
            # slave B: the injected straggler — every job stretched
            slow_chaos = ChaosMonkey(ChaosConfig(
                seed=1, slow_job=1.0, slow_job_ms=40.0))
            slow = Client("127.0.0.1:%d" % master.port,
                          _ScriptedWorkflow(job_sleep_s=0.003),
                          secret="fleetscope-e2e", chaos=slow_chaos)
            clients = [fast.start(), slow.start()]
            assert done.wait(60.0), "fleet did not finish"
            master.drain(timeout=10.0)
            status = master.fleet_status()
            # every configured fault actually fired
            assert slow_chaos.counters["jobs_slowed"] >= \
                STRAGGLER_WINDOWS + STRAGGLER_WINDOWS
            assert delay.counters["frames_delayed"] > 0
            # --- straggler named deterministically -------------------
            straggler = status.get("straggler")
            assert straggler is not None
            assert straggler["slave"] == slow.sid
            assert straggler["score"] >= STRAGGLER_RATIO
            # per-slave stats persist on the scope even after the
            # slaves disconnect at end-of-stream
            slow_stats = master.scope.slave_stats(slow.sid)
            fast_stats = master.scope.slave_stats(fast.sid)
            assert slow_stats["straggler_score"] >= STRAGGLER_RATIO
            assert fast_stats["step_ms"] < slow_stats["step_ms"]
            # --- goodput decomposition -------------------------------
            goodput = status["goodput"]
            assert goodput["jobs"] >= 60
            # the stretch is injected residence, not workflow compute:
            # it must land in HOST time and drag the fraction down
            assert goodput["host_s"] > 0.15  # >= 6 jobs x 40ms stretch
            assert 0.0 < goodput["fraction"] < 0.6
            # --- clock alignment within its own bound ----------------
            clocks = status["clock"]
            assert clocks, "no clock estimates"
            for row in clocks.values():
                # same physical clock: the truth is offset 0, so the
                # estimate must sit within its own uncertainty
                assert abs(row["offset_ms"]) <= \
                    row["uncertainty_ms"] + 1.0
                assert row["uncertainty_ms"] < 500.0
            # --- fleet incident artifact names the straggler ---------
            incidents = [name for name in os.listdir(str(tmp_path))
                         if name.startswith("incident-")
                         and "fleet_straggler" in name]
            assert incidents, "no fleet incident artifact"
            with open(os.path.join(str(tmp_path),
                                   sorted(incidents)[-1])) as fin:
                doc = json.load(fin)
            assert doc["reason"] == "fleet_straggler"
            assert doc["trigger"]["labels"] == [["slave", slow.sid]]
            assert doc["leading_indicator"]["reference"] in (
                "fleet_goodput", "fleet_straggler")
            # --- span shipping actually happened ---------------------
            assert sum(master.scope.spans_ingested.values()) > 0
            # --- fleet-trace: saved payload + --live -----------------
            payload = master.fleet_debug()
            saved = tmp_path / "fleet_debug.json"
            saved.write_text(json.dumps(payload))
            out = tmp_path / "merged.trace.json"
            assert fleet_trace_main(str(saved),
                                    output=str(out)) == 0
            trace = json.loads(out.read_text())
            self._check_trace(trace)
            live_out = tmp_path / "live.trace.json"
            url = "http://127.0.0.1:%d" % master.metrics_port
            with urllib.request.urlopen("%s/debug/fleet" % url,
                                        timeout=10) as resp:
                assert json.loads(
                    resp.read().decode())["kind"] == "fleetscope"
            assert fleet_trace_main(live=url,
                                    output=str(live_out)) == 0
            assert json.loads(live_out.read_text())["traceEvents"]
        finally:
            for client in clients:
                client.stop()
            master.stop()
            set_metric_history(previous_history)
            tracer.enabled = tracer_was
            get_span_ring().drain(10 ** 6)

    def _check_trace(self, trace):
        events = trace["traceEvents"]
        process_rows = [e for e in events if e.get("ph") == "M"
                        and e["name"] == "process_name"]
        # at least the master row and the slave-process row (both
        # loopback slaves share one OS process, hence one row)
        assert len(process_rows) >= 2
        by_trace = {}
        for event in events:
            if event.get("ph") == "M":
                continue
            trace_id = event.get("args", {}).get("trace_id")
            if trace_id:
                by_trace.setdefault(trace_id, []).append(event)
        chains = [evs for evs in by_trace.values()
                  if {"fleet.issue", "fleet.do_job", "fleet.apply"}
                  <= {ev["name"] for ev in evs}]
        assert chains, "no connected issue->do_job->apply chain"
        checked = 0
        for evs in chains:
            by_name = {ev["name"]: ev for ev in evs}
            issue = by_name["fleet.issue"]
            do_job = by_name["fleet.do_job"]
            apply_ = by_name["fleet.apply"]
            # one trace, connected across the wire
            assert do_job["args"]["parent_id"] == \
                issue["args"]["span_id"]
            assert apply_["args"]["parent_id"] == \
                do_job["args"]["span_id"]
            # clock-aligned ordering (50ms slack >> the uncertainty)
            slack_us = 50e3
            assert issue["ts"] <= do_job["ts"] + slack_us
            assert do_job["ts"] <= apply_["ts"] + slack_us
            checked += 1
        assert checked >= 3
