"""ISSUE 20: memscope — per-owner HBM attribution, leak forensics
and headroom forecasting (docs/memscope.md).

Pins the attribution plane (weakref'd accountants, GC-as-unregister,
multi-instance stacking, scratch tags), the reconciliation contract
(sum of exported owners covers the device total with ``untagged``
exported, never hidden), the lifecycle-edge leak verdicts with their
flight-recorder incident artifacts + the LEAK_EXEMPT carve-outs, the
headroom-forecast slope math, the governor guard inputs (the
memory-frac CPU fallback and ``headroom_guard_s``), the
``veles_hbm_*`` / ``veles_device_memory_limit_bytes`` metric
families, the ``/debug/memory`` surface, the real serving engine's
owner registrations, and the acceptance: the ``serving_chaos``
retained-pool leak injection must yield an incident artifact naming
``kv_pool``.
"""

import gc
import io
import json
import time
import urllib.request

import numpy
import pytest

from veles_tpu.observe.memscope import (MemScope, get_memscope,
                                        pytree_nbytes, set_memscope)
from veles_tpu.observe.metrics import MetricsRegistry

pytestmark = pytest.mark.memscope


@pytest.fixture
def fresh_scope():
    """Install an isolated process scope (restored at teardown) so the
    serving engine's registrations land where the test can see them."""
    scope = MemScope(leak_min_bytes=1024, limit_bytes=None)
    previous = set_memscope(scope)
    try:
        yield scope
    finally:
        set_memscope(previous)


@pytest.fixture
def run_dir(tmp_path, monkeypatch):
    """Redirect flight-recorder black boxes under tmp_path."""
    from veles_tpu.core.config import root
    monkeypatch.setattr(root.common.dirs, "run", str(tmp_path / "run"))
    return tmp_path / "run"


def _tiny():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, 1, 8, 2, 7)
    table = jnp.asarray(rng.randn(7, 8).astype(numpy.float32))
    return params, table, 2


class _Box:
    """A registrable owner instance (weakref needs a non-builtin)."""

    def __init__(self, nbytes):
        self.nbytes = nbytes


# -- sizing + attribution ----------------------------------------------------

class TestAttribution:
    def test_pytree_nbytes(self):
        tree = {"w": numpy.zeros((4, 4), numpy.float32),
                "b": numpy.zeros(4, numpy.float32),
                "meta": "not-an-array", "none": None}
        assert pytree_nbytes(tree) == 64 + 16
        assert pytree_nbytes(None) == 0
        assert pytree_nbytes("scalar") == 0

    def test_register_sums_live_instances(self):
        scope = MemScope(leak_min_bytes=1024)
        a, b = _Box(100), _Box(200)
        scope.register("kv_pool", a, lambda box: box.nbytes)
        scope.register("kv_pool", b, lambda box: box.nbytes)
        assert scope.attribute()["kv_pool"] == 300
        # re-registering the SAME instance replaces, never stacks
        scope.register("kv_pool", a, lambda box: box.nbytes * 2)
        assert scope.attribute()["kv_pool"] == 400

    def test_gc_is_the_unregister(self):
        scope = MemScope(leak_min_bytes=1024)
        a = _Box(100)
        scope.register("params", a, lambda box: box.nbytes)
        assert scope.attribute()["params"] == 100
        del a
        gc.collect()
        assert scope.attribute()["params"] == 0

    def test_raising_accountant_contributes_nothing(self):
        scope = MemScope(leak_min_bytes=1024)
        a, b = _Box(100), _Box(50)
        scope.register("params", a,
                       lambda box: 1 / 0)  # must not take us down
        scope.register("params", b, lambda box: box.nbytes)
        assert scope.attribute()["params"] == 50

    def test_scratch_tags_note_and_drop_exactly_once(self):
        scope = MemScope(leak_min_bytes=1024)
        scope.scratch_note("r1", 4096)
        scope.scratch_note("r2", 1000)
        assert scope.attribute()["admission_scratch"] == 5096
        scope.scratch_drop("r1")
        scope.scratch_drop("r1")  # second drop is a no-op
        scope.scratch_drop(None)  # None key tolerated (resolve path)
        assert scope.attribute()["admission_scratch"] == 1000

    def test_snapshot_reconciles_and_exports_untagged(self):
        """The acceptance contract: the exported owner rows cover the
        device total — owners sum to >= device_bytes because the
        residue is PUBLISHED as owner="untagged", not hidden."""
        scope = MemScope(leak_min_bytes=1024)
        scope.register("params", _keepalive(scope, _Box(1 << 10)),
                       lambda box: box.nbytes)
        snap = scope.snapshot()
        owners = snap["owners"]
        assert "untagged" in owners
        assert owners["untagged"] == max(
            0, snap["device_bytes"] - snap["tagged_bytes"])
        assert sum(owners.values()) >= snap["device_bytes"]
        assert 0.0 <= snap["untagged_fraction"] <= 1.0

    def test_device_totals_shape(self):
        used, limit = MemScope.device_totals()
        assert isinstance(used, int) and used >= 0
        assert limit is None or (isinstance(limit, int) and limit > 0)


def _keepalive(scope, box):
    """Park a strong ref on the scope so the box outlives the caller's
    frame (the weakref must stay live for the snapshot)."""
    refs = getattr(scope, "_test_refs", None)
    if refs is None:
        refs = scope._test_refs = []
    refs.append(box)
    return box


# -- lifecycle-edge leak forensics -------------------------------------------

class TestLeakForensics:
    def test_edge_diff_names_the_grown_owner(self, run_dir):
        scope = MemScope(leak_min_bytes=1024)
        pool = _Box(10_000)
        scope.register("kv_pool", pool, lambda box: box.nbytes)
        scope.edge_begin("breaker_rebuild")
        zombie = _Box(50_000)  # the retained old pool coexists
        scope.register("kv_pool", zombie, lambda box: box.nbytes)
        verdict = scope.edge_end("breaker_rebuild")
        assert verdict["leak"] is True
        assert verdict["owner"] == "kv_pool"
        assert verdict["grew_bytes"] == 50_000
        assert verdict["edge"] == "breaker_rebuild"
        assert scope.leaks_total == 1 and scope.edges_total == 1
        # the incident artifact names the owner in reason AND payload
        wrote = scope.flush_incidents()
        assert len(wrote) == 1
        assert "memscope_leak_kv_pool" in wrote[0]
        doc = json.load(open(wrote[0]))
        assert doc["extra"]["memscope_leak"]["owner"] == "kv_pool"
        assert doc["extra"]["memscope_leak"]["grew_bytes"] == 50_000
        # flushed verdicts move to incidents with their path
        assert scope.incidents[-1]["artifact"] == wrote[0]
        assert scope.flush_incidents() == []  # drained

    def test_growth_below_threshold_is_no_leak(self):
        scope = MemScope(leak_min_bytes=1 << 20)
        pool = _Box(10_000)
        scope.register("kv_pool", pool, lambda box: box.nbytes)
        scope.edge_begin("swap_params")
        pool.nbytes += 4096  # < leak_min_bytes
        verdict = scope.edge_end("swap_params")
        assert verdict["leak"] is False and verdict["owner"] is None
        assert scope.leaks_total == 0

    def test_leak_exempt_owners_never_verdict(self):
        """param_stash grows by DESIGN on every successful hot-swap
        (the rollback stash); admission scratch tracks the staged
        queue. Both are exempt — but still visible in ``grown``."""
        scope = MemScope(leak_min_bytes=1024)
        stash = _Box(0)
        scope.register("param_stash", stash, lambda box: box.nbytes)
        scope.edge_begin("swap_params")
        stash.nbytes = 1 << 20
        scope.scratch_note("r1", 1 << 20)
        verdict = scope.edge_end("swap_params")
        assert verdict["leak"] is False and verdict["owner"] is None
        assert verdict["grown"]["param_stash"] == 1 << 20
        assert scope.leaks_total == 0

    def test_edge_end_without_begin_is_none(self):
        scope = MemScope(leak_min_bytes=1024)
        assert scope.edge_end("breaker_rebuild") is None
        assert scope.edges_total == 0

    def test_retrying_edges_pair_with_the_newest_begin(self):
        scope = MemScope(leak_min_bytes=1024)
        pool = _Box(1000)
        scope.register("kv_pool", pool, lambda box: box.nbytes)
        scope.edge_begin("breaker_rebuild")   # failed attempt's begin
        pool.nbytes = 5000
        scope.edge_begin("breaker_rebuild")   # the retry
        verdict = scope.edge_end("breaker_rebuild")
        # diffed against the RETRY's 5000 baseline, not the stale 1000
        assert verdict["grown"] == {} and verdict["leak"] is False
        # the stale begin is still open; a second end drains it
        assert scope.edge_end("breaker_rebuild") is not None
        assert scope.edge_end("breaker_rebuild") is None


# -- headroom forecasting ----------------------------------------------------

class TestHeadroomForecast:
    def _ramp(self, scope, now, slope=2, points=6, free_last=10):
        for i in range(points):
            used = slope * i
            scope._pool_points.append(
                (now - (points - 1 - i) * 1.0, used,
                 free_last + slope * (points - 1 - i)))

    def test_slope_math(self):
        scope = MemScope(leak_min_bytes=1024)
        now = time.monotonic()
        self._ramp(scope, now)  # 2 pages/s net, 10 free at the end
        assert scope.headroom_forecast_s(now=now) == pytest.approx(5.0)

    def test_flat_or_shrinking_usage_forecasts_none(self):
        scope = MemScope(leak_min_bytes=1024)
        now = time.monotonic()
        for i in range(4):
            scope._pool_points.append((now - (3 - i), 8, 8))
        assert scope.headroom_forecast_s(now=now) is None
        scope._pool_points.clear()
        for i in range(4):
            scope._pool_points.append((now - (3 - i), 8 - i, 8 + i))
        assert scope.headroom_forecast_s(now=now) is None

    def test_needs_two_points_inside_the_window(self):
        scope = MemScope(leak_min_bytes=1024)
        now = time.monotonic()
        assert scope.headroom_forecast_s(now=now) is None
        scope._pool_points.append((now - 120.0, 0, 20))
        scope._pool_points.append((now, 10, 10))
        # the 120s-old point falls outside the 60s window -> 1 point
        assert scope.headroom_forecast_s(now=now) is None
        scope._pool_points.clear()
        scope._pool_points.append((now - 5.0, 0, 20))
        scope._pool_points.append((now, 10, 10))
        assert scope.headroom_forecast_s(now=now) == pytest.approx(5.0)

    def test_note_pool_reads_pool_counters(self):
        class _Pool:
            used_pages = 3
            free_pages = 5

        scope = MemScope(leak_min_bytes=1024)
        scope.note_pool(_Pool())
        scope.note_pool(None)  # tolerated
        assert len(scope._pool_points) == 1
        assert scope._pool_points[0][1:] == (3, 5)


# -- publication + governor inputs -------------------------------------------

class TestPublication:
    def test_publish_hbm_families_and_headroom(self):
        scope = MemScope(leak_min_bytes=1024)
        box = _keepalive(scope, _Box(1 << 12))
        scope.register("params", box, lambda b: b.nbytes)
        now = time.monotonic()
        for i in range(4):
            scope._pool_points.append((now - (3 - i), 2 * i, 12 - 2 * i))
        registry = MetricsRegistry(enabled=True)
        scope.publish(registry)
        text = registry.expose()
        assert 'veles_hbm_bytes{owner="params"} 4096' in text
        assert 'veles_hbm_bytes{owner="untagged"}' in text
        assert "veles_headroom_forecast_s" in text
        if scope.device_totals()[0]:
            assert 'veles_hbm_fraction{owner="untagged"}' in text

    def test_gauge_family_retires_dead_owners(self):
        scope = MemScope(leak_min_bytes=1024)
        box = _Box(1 << 12)
        scope.register("aot_executables", box, lambda b: b.nbytes)
        registry = MetricsRegistry(enabled=True)
        scope.publish(registry)
        assert 'owner="aot_executables"' in registry.expose()
        del box
        gc.collect()
        scope.publish(registry)
        # dead instance -> 0 bytes row (still exported, value 0)
        assert 'veles_hbm_bytes{owner="aot_executables"} 0' \
            in registry.expose()

    def test_device_memory_limit_gauge(self, monkeypatch):
        """Satellite: allocator budgets export as their own gauge."""
        import veles_tpu.observe.xla_stats as xla_stats

        monkeypatch.setattr(
            xla_stats, "_sample_device_memory",
            lambda: {0: {"bytes_in_use": 60, "bytes_limit": 100},
                     1: {"live_bytes": 30}})
        registry = MetricsRegistry(enabled=True)
        xla_stats.publish_device_stats(registry)
        text = registry.expose()
        assert 'veles_device_memory_limit_bytes{device="0"} 100' \
            in text
        # the CPU-fallback device has no limit -> no phantom row
        assert 'veles_device_memory_limit_bytes{device="1"}' \
            not in text

    def test_governor_memory_frac_allocator_path(self, monkeypatch):
        from veles_tpu.observe.governor import ServingGovernor
        import veles_tpu.observe.xla_stats as xla_stats

        monkeypatch.setattr(
            xla_stats, "_sample_device_memory",
            lambda: {0: {"bytes_in_use": 60, "bytes_limit": 100},
                     1: {"bytes_in_use": 90, "bytes_limit": 100}})
        assert ServingGovernor._device_memory_frac() \
            == pytest.approx(0.9)

    def test_governor_memory_frac_cpu_fallback(self, monkeypatch,
                                               fresh_scope):
        """Satellite: the old raw memory_stats() read silently no-op'd
        on CPU; the guard now falls back to memscope's reconciled
        total over the configured byte budget."""
        from veles_tpu.observe.governor import ServingGovernor
        import veles_tpu.observe.xla_stats as xla_stats

        monkeypatch.setattr(xla_stats, "_sample_device_memory",
                            lambda: {0: {"live_bytes": 30}})
        fresh_scope.limit_bytes = None
        assert ServingGovernor._device_memory_frac() is None
        fresh_scope.limit_bytes = 120
        assert ServingGovernor._device_memory_frac() \
            == pytest.approx(0.25)
        assert fresh_scope.device_fraction() == pytest.approx(0.25)

    def test_governor_headroom_guard_trips_breaker(self, fresh_scope):
        from veles_tpu.observe.governor import (GovernorConfig,
                                                ServingGovernor)

        class _Api:
            tripped = None

            def request_trip(self, reason):
                self.tripped = reason

        config = GovernorConfig(headroom_guard_s=30.0)
        governor = ServingGovernor(config)
        api = _Api()
        now = time.monotonic()
        # 2 pages/s against 10 free -> ~5s, under the 30s guard
        for i in range(6):
            fresh_scope._pool_points.append(
                (now - (5 - i) * 1.0, 2 * i, 20 - 2 * i))
        governor._guard_breaker(api, now)
        assert api.tripped is not None
        assert "pool exhausts" in api.tripped
        assert governor.counters["guard_trips"] == 1
        # guard cooldown: an immediate second pass holds fire
        api.tripped = None
        governor._guard_breaker(api, now + 0.01)
        assert api.tripped is None

    def test_headroom_guard_disabled_by_default(self, fresh_scope):
        from veles_tpu.observe.governor import (GovernorConfig,
                                                parse_governor_spec)

        assert GovernorConfig().headroom_guard_s == 0.0
        spec = parse_governor_spec("headroom_guard_s=12")
        assert spec.headroom_guard_s == 12.0
        with pytest.raises(ValueError):
            GovernorConfig(headroom_guard_s=-1)


# -- the /debug/memory surface ----------------------------------------------

class _Handler:
    """Just enough of BaseHTTPRequestHandler for httpd.reply()."""

    def __init__(self, path):
        self.path = path
        self.wfile = io.BytesIO()

    def send_response(self, code):
        self.code = code

    def send_header(self, key, value):
        pass

    def end_headers(self):
        pass

    def body(self):
        return json.loads(self.wfile.getvalue().decode())


class TestDebugMemory:
    def test_route_matches_and_replies(self):
        from veles_tpu.core.httpd import serve_debug_memory

        scope = MemScope(leak_min_bytes=1024)
        box = _keepalive(scope, _Box(2048))
        scope.register("params", box, lambda b: b.nbytes)
        scope.edge_begin("swap_params")
        scope.edge_end("swap_params")
        handler = _Handler("/debug/memory")
        assert serve_debug_memory(handler, scope=scope) is True
        doc = handler.body()
        assert doc["memscope"]["owners"]["params"] == 2048
        assert "untagged" in doc["memscope"]["owners"]
        assert doc["edges_total"] == 1 and len(doc["edges"]) == 1
        assert serve_debug_memory(_Handler("/debug/serve"),
                                  scope=scope) is False

    def test_edges_query_param_clamped(self):
        from veles_tpu.core.httpd import serve_debug_memory

        scope = MemScope(leak_min_bytes=1024)
        for i in range(20):
            scope.edge_begin("e%d" % i)
            scope.edge_end("e%d" % i)
        handler = _Handler("/debug/memory?edges=4")
        assert serve_debug_memory(handler, scope=scope)
        assert len(handler.body()["edges"]) == 4
        handler = _Handler("/debug/memory?edges=garbage")
        assert serve_debug_memory(handler, scope=scope)
        assert len(handler.body()["edges"]) == 16  # default kept

    def test_debug_index_lists_memory(self):
        from veles_tpu.core.httpd import DEBUG_SURFACES
        assert "/debug/memory" in DEBUG_SURFACES


# -- the serving engine's registrations --------------------------------------

class TestServingWiring:
    def test_decoder_registers_owner_taxonomy(self, fresh_scope):
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=2, paged=True,
                                page_size=8)
        owners = fresh_scope.attribute()
        assert owners["params"] > 0
        assert owners["kv_pool"] > 0
        assert owners["decode_state"] >= 0
        # the pool's geometry was stamped at construction
        assert dec.pool.page_bytes > 0
        assert dec.pool.hbm_bytes() \
            == dec.pool.pages * dec.pool.page_bytes
        # no double counting: kv_pool bytes come OUT of slot state
        from veles_tpu.parallel.decode import slot_state_bytes
        assert owners["decode_state"] \
            == max(0, slot_state_bytes(dec.state)
                   - dec.pool.hbm_bytes())
        del dec
        gc.collect()
        owners = fresh_scope.attribute()
        assert owners["params"] == 0 and owners["kv_pool"] == 0

    def test_dense_decoder_reports_full_slot_state(self, fresh_scope):
        from veles_tpu.parallel.decode import slot_state_bytes
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=32, n_tokens=2)
        owners = fresh_scope.attribute()
        assert owners["decode_state"] == slot_state_bytes(dec.state)
        assert "kv_pool" not in owners

    def test_paged_kv_bytes_and_pool_sizers(self, fresh_scope):
        from veles_tpu.parallel.kv_pool import paged_kv_bytes
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads = _tiny()
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=2, paged=True,
                                page_size=8)
        total = paged_kv_bytes(dec.state)
        assert total > 0
        # stamped page_bytes re-assembles to within one page of the
        # true paged-KV footprint (integer division remainder)
        assert 0 <= total - dec.pool.hbm_bytes() < dec.pool.pages
        assert dec.pool.shadow_bytes() >= 0


# -- the chaos leak-injection acceptance -------------------------------------

class TestChaosLeakInjection:
    def test_config_validation_and_leading_series(self):
        from veles_tpu.serving_chaos import ServingChaosConfig

        config = ServingChaosConfig(seed=1, leak_retain_pool_at=2)
        assert config.any_profile
        assert config.expected_leading_series()["pool_leak"] \
            == "veles_hbm_bytes"
        with pytest.raises(ValueError):
            ServingChaosConfig(leak_retain_pool_at=-1)
        assert not ServingChaosConfig().any_profile

    @pytest.mark.slow
    def test_retained_pool_names_kv_pool(self, fresh_scope, run_dir):
        """The acceptance (ISSUE 20): a seeded chaos run that retains
        a dead decoder's KV pool across a breaker rebuild must produce
        an incident artifact naming kv_pool as the grown owner."""
        from veles_tpu.serving import GenerateAPI
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        monkey = ServingChaosMonkey(ServingChaosConfig(
            seed=1, leak_retain_pool_at=1))
        params, table, heads = _tiny()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=3, chunk=2, port=0, paged=True,
                          page_size=8, rebuild_backoff=0.02,
                          chaos=monkey)
        api.start()
        url = "http://127.0.0.1:%d" % api.port
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline \
                    and not fresh_scope.incidents:
                request = urllib.request.Request(
                    url + "/generate",
                    json.dumps({"tokens": [1, 2, 3]}).encode(),
                    {"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(request,
                                                timeout=30) as resp:
                        resp.read()
                except Exception:
                    time.sleep(0.05)  # breaker open mid-rebuild
            assert monkey.counters["pool_leaks"] == 1
            assert fresh_scope.leaks_total >= 1
            verdict = fresh_scope.incidents[-1]
            assert verdict["owner"] == "kv_pool"
            assert verdict["edge"] == "breaker_rebuild"
            assert verdict["grew_bytes"] >= fresh_scope.leak_min_bytes
            path = verdict["artifact"]
            assert path and "memscope_leak_kv_pool" in path
            doc = json.load(open(path))
            leak = doc["extra"]["memscope_leak"]
            assert leak["owner"] == "kv_pool"
            # the serving surfaces carry the attribution too
            metrics = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            assert 'veles_hbm_bytes{owner="kv_pool"}' in metrics
            assert 'veles_hbm_bytes{owner="untagged"}' in metrics
            debug = json.load(urllib.request.urlopen(
                url + "/debug/memory", timeout=10))
            assert debug["leaks_total"] >= 1
            assert debug["incidents"]
            healthz = json.load(urllib.request.urlopen(
                url + "/healthz", timeout=10))
            assert healthz["memscope"]["leaks"] >= 1
            assert healthz["memscope"]["last_leak_owner"] == "kv_pool"
        finally:
            monkey.release_leak()
            api.stop()

    @pytest.mark.slow
    def test_clean_rebuild_is_no_leak(self, fresh_scope, run_dir):
        """The negative control: the same trip WITHOUT the retention
        closes its edge with no leak verdict (the rebuilt pool
        replaces the collected old one rather than stacking)."""
        from veles_tpu.serving import GenerateAPI

        params, table, heads = _tiny()
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=3, chunk=2, port=0, paged=True,
                          page_size=8, rebuild_backoff=0.02)
        api.start()
        url = "http://127.0.0.1:%d" % api.port
        try:
            request = urllib.request.Request(
                url + "/generate",
                json.dumps({"tokens": [1, 2, 3]}).encode(),
                {"Content-Type": "application/json"})
            json.load(urllib.request.urlopen(request, timeout=30))
            api.request_trip("test: clean trip")
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline \
                    and not fresh_scope.edges:
                time.sleep(0.05)
            assert fresh_scope.edges, "rebuild edge never closed"
            verdict = fresh_scope.edges[-1]
            assert verdict["edge"] == "breaker_rebuild"
            assert verdict["leak"] is False
            assert fresh_scope.leaks_total == 0
        finally:
            api.stop()
