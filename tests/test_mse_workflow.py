"""End-to-end MSE (regression) workflows: the Znicz EvaluatorMSE +
DecisionMSE model family, and their ride on the partial-fusion tier
(the full fused engine recognizes softmax chains only — MSE used to be
one of the VERDICT r2 graph-mode-cliff casualties)."""

import numpy

from veles_tpu.core import prng
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import VALID
from veles_tpu.models.standard import StandardWorkflow
from veles_tpu.parallel.segments import FusedSegment


def _dataset(n=1200, din=16, dout=4):
    rng = numpy.random.RandomState(3)
    X = rng.rand(n, din).astype(numpy.float32)
    W = rng.randn(din, dout).astype(numpy.float32) * 0.4
    Y = numpy.tanh(X @ W) + 0.01 * rng.randn(n, dout).astype(
        numpy.float32)
    return X, Y.astype(numpy.float32)


def _build(fused="auto", max_epochs=6):
    prng.get("default").seed(1111)
    prng.get("loader").seed(2222)
    X, Y = _dataset()
    return StandardWorkflow(
        DummyLauncher(),
        layers=[{"type": "all2all_tanh", "output_sample_shape": (24,)},
                {"type": "all2all", "output_sample_shape": (4,)}],
        evaluator="mse",
        loader_kwargs=dict(data=X, targets=Y,
                           class_lengths=[0, 200, 1000],
                           minibatch_size=100,
                           normalization_type="linear",
                           target_normalization_type="none"),
        learning_rate=0.1, gradient_moment=0.9,
        decision_kwargs=dict(max_epochs=max_epochs),
        fused=fused, name="mse-wf")


def test_mse_workflow_learns_graph_mode():
    wf = _build(fused=False, max_epochs=15)
    wf.initialize()
    wf.run()
    best = wf.decision.best_n_err[VALID]
    # target variance is ~0.4 — well below it proves the regression
    # actually fits, not just centers
    assert best is not None and best < 0.08, \
        "validation mse %s did not drop" % best
    assert wf.decision._epochs_done == 15


def test_mse_workflow_rides_fused_engine():
    """The FULL fused engine (sweep dispatch) now handles regression:
    targets gathered in-jit, grads of masked MSE — numerically matching
    the graph-mode GD chain."""
    graph = _build(fused=False)
    graph.initialize()
    graph.run()

    fused = _build(fused="auto")
    fused.initialize()
    assert fused.fused_tick is not None, \
        "fused engine declined the MSE chain"
    assert fused.fused_tick._loss_kind_ == "mse"
    fused.run()

    assert abs(fused.decision.best_n_err[VALID]
               - graph.decision.best_n_err[VALID]) < 1e-4
    assert fused.decision._epochs_done == graph.decision._epochs_done
    # float reassociation between the fused autodiff graph and the
    # per-unit chain compounds over 15 momentum epochs (same bound family
    # as tests/test_fused.py)
    for fg, ff in zip(graph.forwards, fused.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(ff.weights.data),
            atol=1e-2)


def test_mse_with_host_unit_rides_partial_fusion():
    """An MSE chain with a custom host unit: the full engine declines
    (unrecognized unit in the chain) and partial fusion takes over."""
    from veles_tpu.core.distributable import TriviallyDistributable
    from veles_tpu.core.units import Unit

    class Spy(Unit, TriviallyDistributable):
        ticks = 0

        def run(self):
            type(self).ticks += 1

    def splice(wf):
        spy = Spy(wf, name="spy")
        fwd1 = wf.forwards[1]
        fwd1.unlink_from(wf.forwards[0])
        spy.link_from(wf.forwards[0])
        fwd1.link_from(spy)
        return spy

    graph = _build(fused=False)
    splice(graph)
    graph.initialize()
    graph.run()

    seg = _build(fused="auto")
    splice(seg)
    seg.initialize()
    assert seg.fused_tick is None, \
        "full engine must decline a chain with a host unit"
    segments = [u for u in seg.units if isinstance(u, FusedSegment)]
    assert len(segments) == 2
    seg.run()
    assert abs(seg.decision.best_n_err[VALID]
               - graph.decision.best_n_err[VALID]) < 1e-6
    for fg, fs in zip(graph.forwards, seg.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(fs.weights.data),
            atol=1e-5)


def test_mse_snapshot_suffix_and_metrics():
    wf = _build(fused=False, max_epochs=2)
    wf.initialize()
    wf.run()
    assert wf.decision.snapshot_suffix.startswith("validation_mse_")
    assert wf.decision.get_metric_names()[0] == "best_validation_mse"
    assert wf.decision.best_mse[VALID] == wf.decision.best_n_err[VALID]
