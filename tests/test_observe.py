"""Observability-layer tests (docs/observability.md): MetricsRegistry
semantics + Prometheus exposition, the disabled-path overhead guard,
EventRecorder buffering, dashboard event tailing, Chrome trace export,
and end-to-end trace propagation through a real GenerateAPI request and
a real fleet round trip. ``make metrics`` runs this module standalone."""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy
import pytest

from veles_tpu.core.logger import EventRecorder
from veles_tpu.observe.metrics import MetricsRegistry, bridge
from veles_tpu.observe.tracing import (NULL_SPAN, Tracer,
                                       parse_trace_header)
from veles_tpu.observe.trace_export import (chrome_trace,
                                            export_chrome_trace,
                                            load_events, span_tree)


def get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def post(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode()), dict(resp.headers)


class TestMetricsRegistry:
    def test_concurrent_counters_exact(self):
        """N threads hammering the same counter (and a labeled series)
        must land on the exact total — the registry's one lock is the
        whole consistency story."""
        registry = MetricsRegistry(enabled=True)
        threads_n, per_thread = 8, 2000

        def work(i):
            for _ in range(per_thread):
                registry.incr("veles_test_total")
                registry.incr("veles_test_labeled_total", 2,
                              labels={"worker": str(i % 2)})
                registry.observe("veles_test_seconds", 0.01,
                                 buckets=(0.005, 0.05))

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(threads_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        text = registry.expose()
        assert "veles_test_total %d" % (threads_n * per_thread) in text
        for worker in ("0", "1"):
            assert ('veles_test_labeled_total{worker="%s"} %d'
                    % (worker, threads_n // 2 * per_thread * 2)) in text
        assert ("veles_test_seconds_count %d"
                % (threads_n * per_thread)) in text

    def test_exposition_format(self):
        registry = MetricsRegistry(enabled=True)
        registry.incr("veles_req_total", 3,
                      labels={"path": 'a"b\\c\nd'},
                      help="requests\nby path")
        registry.set("veles_up", 1, help="liveness")
        registry.observe("veles_lat_seconds", 0.03,
                         buckets=(0.01, 0.1, 1.0))
        registry.observe("veles_lat_seconds", 5.0,
                         buckets=(0.01, 0.1, 1.0))
        text = registry.expose()
        lines = text.splitlines()
        # HELP escaping: newline survives as \n, backslash doubled
        assert "# HELP veles_req_total requests\\nby path" in lines
        assert "# TYPE veles_req_total counter" in lines
        assert "# TYPE veles_up gauge" in lines
        assert "# TYPE veles_lat_seconds histogram" in lines
        # label value escaping: quote, backslash and newline
        assert ('veles_req_total{path="a\\"b\\\\c\\nd"} 3') in lines
        # histogram: cumulative monotone buckets, +Inf == count, sum
        buckets = [line for line in lines
                   if line.startswith("veles_lat_seconds_bucket")]
        counts = [int(line.rsplit(" ", 1)[1]) for line in buckets]
        assert counts == sorted(counts), buckets
        assert buckets[-1].startswith(
            'veles_lat_seconds_bucket{le="+Inf"}')
        assert counts[-1] == 2
        assert "veles_lat_seconds_count 2" in lines
        assert "veles_lat_seconds_sum 5.03" in lines

    def test_bridge_unregisters_dead_source(self):
        registry = MetricsRegistry(enabled=True)

        class Source:
            pass

        source = Source()
        bridge(registry, source,
               lambda reg, live: reg.set("veles_src_up", 1))
        assert "veles_src_up 1" in registry.expose()
        assert len(registry._collectors) == 1
        del source
        import gc
        gc.collect()
        registry.expose()  # the dead collector unregisters itself
        assert registry._collectors == []

    def test_broken_collector_never_breaks_exposition(self):
        registry = MetricsRegistry(enabled=True)
        registry.add_collector(lambda: 1 / 0)
        registry.incr("veles_ok_total")
        assert "veles_ok_total 1" in registry.expose()

    def test_kind_collision_drops_the_write(self):
        """A scalar sample aimed at a histogram family (e.g. a skewed
        fleet slave re-using a histogram name) must be DROPPED, not
        poison every later expose()."""
        registry = MetricsRegistry(enabled=True)
        registry.observe("veles_h_seconds", 0.1, buckets=(1.0,))
        registry.counter_set("veles_h_seconds", 7)
        registry.incr("veles_h_seconds")
        registry.set("veles_h_seconds", 3)
        registry.observe("veles_c_total", 0.5, buckets=(1.0,))
        registry.incr("veles_c_total", 2)  # dropped: histogram exists
        text = registry.expose()  # must not raise
        assert "veles_h_seconds_count 1" in text
        assert "veles_c_total_count 1" in text
        assert "\nveles_c_total 2" not in text
        registry.histogram_summary()  # must not raise either

    def test_hostile_slave_rows_cannot_break_master_exposition(self):
        """The fleet piggyback path: rows with exposition-breaking
        metric/label names are rejected by slave_metrics; only label
        VALUES (escaped) get through."""
        from veles_tpu.fleet.server import Server, SlaveDescription

        server = Server.__new__(Server)
        slave = SlaveDescription("slave-1", {})
        server.slaves = {"slave-1": slave}
        slave.metrics_rows = [
            ["veles_ok_total", "counter",
             [["path", 'a"} evil{b="1']], 5],          # hostile VALUE: ok
            ['veles_x{a="1"} 9 #', "counter", [], 5],  # hostile NAME
            ["veles_y_total", "counter",
             [['a"} evil{b="1', "v"]], 5],             # hostile label KEY
            ["veles_z_total", "counter", [["slave", "slave-9"]], 5],
            ["veles_b_total", "counter", [], True],    # bool is not a number
            "not-a-row",
        ]
        clean = server.slave_metrics()
        assert list(clean) == ["slave-1"]
        assert [row[0] for row in clean["slave-1"]] == ["veles_ok_total"]
        registry = MetricsRegistry(enabled=True)
        from veles_tpu.observe.metrics import publish_fleet
        server.fleet_status = lambda: {"slaves": [], "queued_jobs": 0}
        publish_fleet(registry, server)
        text = registry.expose()
        # the hostile value survives only ESCAPED inside one label —
        # the quote that would have closed the label set is \" —
        # so the line still parses as a single sample
        assert ('veles_ok_total{path="a\\"} evil{b=\\"1",'
                'slave="slave-1"} 5') in text
        assert "veles_y_total" not in text
        assert "veles_z_total" not in text

    def test_piggyback_rows_bounded_and_stale_slaves_pruned(self):
        from veles_tpu.fleet.server import Server, SlaveDescription
        from veles_tpu.observe.metrics import publish_fleet

        server = Server.__new__(Server)
        one, two = (SlaveDescription(sid, {})
                    for sid in ("slave-1", "slave-2"))
        server.slaves = {"slave-1": one, "slave-2": two}
        # volume bound: a hostile slave's giant snapshot truncates
        one.metrics_rows = [
            ["veles_r%d_total" % i, "counter", [["v", "x" * 4096]], i]
            for i in range(Server.METRICS_MAX_ROWS + 500)]
        two.metrics_rows = [["veles_t_total", "counter", [], 1]]
        clean = server.slave_metrics()
        assert len(clean["slave-1"]) == Server.METRICS_MAX_ROWS
        assert all(len(labels["v"]) <= Server.METRICS_MAX_VALUE_LEN
                   for _, _, labels, _ in clean["slave-1"])
        # churn bound: a departed slave's re-exported series retire
        registry = MetricsRegistry(enabled=True)
        server.fleet_status = lambda: {
            "slaves": [s.as_dict() for s in server.slaves.values()],
            "queued_jobs": 0}
        publish_fleet(registry, server)
        assert 'slave="slave-2"' in registry.expose()
        del server.slaves["slave-2"]
        publish_fleet(registry, server)
        text = registry.expose()
        assert 'slave="slave-2"' not in text
        assert 'veles_t_total' not in text
        assert 'slave="slave-1"' in text


def _assert_valid_exposition(text):
    """Every scrape must be a parseable exposition: sample lines match
    the format, and each histogram's cumulative buckets are monotone
    with +Inf equal to the count — under ANY interleaving with
    writers."""
    import re

    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
        r'(-?[0-9.eE+]+|[+-]Inf|NaN)$')
    buckets = {}  # (name, label-prefix) -> [counts...]
    counts = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        assert sample_re.match(line), "unparseable sample: %r" % line
        name = line.split("{")[0].split(" ")[0]
        if name.endswith("_bucket"):
            labels = line[len(name):line.rindex("}") + 1]
            series = re.sub(r',?le="[^"]*"', "", labels)
            buckets.setdefault((name, series), []).append(
                int(line.rsplit(" ", 1)[1]))
        elif name.endswith("_count"):
            series = line[len(name):].rsplit(" ", 1)[0]
            counts[(name[:-len("_count")], series)] = int(
                line.rsplit(" ", 1)[1])
    for (name, series), values in buckets.items():
        assert values == sorted(values), \
            "non-monotone buckets for %s%s: %r" % (name, series, values)
        total = counts.get((name[:-len("_bucket")], series))
        if total is not None:
            assert values[-1] == total, (name, series, values, total)


class TestConcurrentScrape:
    """ISSUE 5 satellite: N writer threads hammering counters, gauges
    and histograms while M scrapers read must yield a parseable
    exposition with monotone cumulative buckets on EVERY scrape — the
    registry's one lock is the whole consistency story and this is the
    test that would catch a torn histogram slot."""

    def test_scrapes_stay_consistent_under_mutation(self):
        registry = MetricsRegistry(enabled=True)
        stop = threading.Event()
        failures = []
        writes = [0] * 4

        def writer(i):
            while not stop.is_set():
                registry.incr("veles_cw_total",
                              labels={"w": str(i % 2)})
                registry.observe("veles_cw_seconds", 0.003 * (i + 1),
                                 buckets=(0.005, 0.01, 0.05))
                registry.set("veles_cw_gauge", i,
                             labels={"w": str(i)})
                writes[i] += 1

        def scraper():
            while not stop.is_set():
                try:
                    _assert_valid_exposition(registry.expose())
                except AssertionError as exc:
                    failures.append(exc)
                    stop.set()
                    return

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        threads += [threading.Thread(target=scraper) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join()
        assert not failures, failures[0]
        # quiesced, the totals are exact: nothing was lost or torn
        text = registry.expose()
        _assert_valid_exposition(text)
        assert "veles_cw_seconds_count %d" % sum(writes) in text
        total = sum(writes)
        got = sum(int(line.rsplit(" ", 1)[1])
                  for line in text.splitlines()
                  if line.startswith("veles_cw_total{"))
        assert got == total

    def test_openmetrics_scrapes_stay_consistent_with_exemplars(self):
        """ISSUE 10 satellite: the same hammer with exemplar-carrying
        observations and openmetrics scrapers — every scrape must stay
        parseable after stripping the exemplar suffixes, buckets
        monotone, and every exemplar line well-formed."""
        import re

        exemplar_re = re.compile(
            r' # \{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\} '
            r'[-+0-9.eE]+ [-+0-9.eE]+$')
        registry = MetricsRegistry(enabled=True)
        stop = threading.Event()
        failures = []

        def writer(i):
            n = 0
            while not stop.is_set():
                registry.observe(
                    "veles_om_seconds", 0.002 * (i + 1),
                    buckets=(0.005, 0.01),
                    exemplar={"trace_id": "t%d-%d" % (i, n)})
                n += 1

        def scraper():
            while not stop.is_set():
                try:
                    text = registry.expose(openmetrics=True)
                    assert text.rstrip().endswith("# EOF")
                    stripped = []
                    for line in text.splitlines():
                        if line == "# EOF":
                            continue
                        cut = line.find(" # {")
                        if cut != -1:
                            assert line.startswith(
                                "veles_om_seconds_bucket"), line
                            assert exemplar_re.search(line), line
                            line = line[:cut]
                        stripped.append(line)
                    _assert_valid_exposition("\n".join(stripped) + "\n")
                except AssertionError as exc:
                    failures.append(exc)
                    stop.set()
                    return

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(3)]
        threads += [threading.Thread(target=scraper) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.6)
        stop.set()
        for t in threads:
            t.join()
        assert not failures, failures[0]


class TestExemplars:
    """ISSUE 10 satellite: OpenMetrics exemplars on the latency
    histograms — exemplars appear ONLY on histogram bucket lines, only
    on openmetrics-negotiated expositions, with the label set bounded
    per the spec; the plain-Prometheus fallback stays parseable."""

    def _registry(self):
        registry = MetricsRegistry(enabled=True)
        registry.observe("veles_ex_seconds", 0.003,
                         buckets=(0.005, 0.01),
                         exemplar={"trace_id": "abc123"})
        registry.observe("veles_ex_seconds", 99.0,
                         buckets=(0.005, 0.01),
                         exemplar={"trace_id": "def456"})
        registry.incr("veles_ex_total", 2)
        registry.set("veles_ex_gauge", 1.0)
        return registry

    def test_exemplars_only_on_histogram_buckets(self):
        text = self._registry().expose(openmetrics=True)
        exemplar_lines = [line for line in text.splitlines()
                          if " # {" in line]
        assert len(exemplar_lines) == 2  # one per bucket hit (incl +Inf)
        for line in exemplar_lines:
            assert line.startswith("veles_ex_seconds_bucket"), line
        assert 'le="0.005"' in exemplar_lines[0] \
            and 'trace_id="abc123"' in exemplar_lines[0]
        assert 'le="+Inf"' in exemplar_lines[1] \
            and 'trace_id="def456"' in exemplar_lines[1]
        # counters/gauges never carry exemplars, and the exposition
        # terminates with the OpenMetrics EOF marker
        for line in text.splitlines():
            if line.startswith(("veles_ex_total", "veles_ex_gauge")):
                assert " # {" not in line
        assert text.rstrip().endswith("# EOF")
        # OpenMetrics counter FAMILIES drop the _total sample suffix
        # (a modern Prometheus negotiates openmetrics by default and
        # would refuse the 0.0.4 spelling); samples keep it
        assert "# TYPE veles_ex counter" in text
        assert "# TYPE veles_ex_total counter" not in text
        assert "\nveles_ex_total 2" in text
        # ...while the plain exposition keeps the 0.0.4 spelling
        assert "# TYPE veles_ex_total counter" in \
            self._registry().expose()

    def test_plain_scrape_fallback_is_parseable(self):
        text = self._registry().expose()
        assert " # {" not in text and "# EOF" not in text
        _assert_valid_exposition(text)

    def test_exemplar_label_set_bounded_and_validated(self):
        from veles_tpu.observe.metrics import EXEMPLAR_MAX_RUNES

        registry = MetricsRegistry(enabled=True)
        # oversized label set: the exemplar is DROPPED, the
        # observation is kept
        registry.observe("veles_big_seconds", 0.001,
                         buckets=(0.01,),
                         exemplar={"trace_id":
                                   "x" * (EXEMPLAR_MAX_RUNES + 1)})
        # invalid label name / the reserved "le": dropped too
        registry.observe("veles_big_seconds", 0.002, buckets=(0.01,),
                         exemplar={"bad name": "v"})
        registry.observe("veles_big_seconds", 0.003, buckets=(0.01,),
                         exemplar={"le": "0.01"})
        text = registry.expose(openmetrics=True)
        assert " # {" not in text
        assert "veles_big_seconds_count 3" in text

    def test_http_accept_negotiation(self, observability):
        """A scraper advertising application/openmetrics-text gets
        exemplars + # EOF; a plain scrape of the SAME surface stays
        0.0.4 text."""
        import urllib.request
        from veles_tpu.core.httpd import serve_metrics  # noqa: F401
        from veles_tpu.observe.metrics import get_metrics_registry
        from veles_tpu.serving import RESTfulAPI
        from veles_tpu.dummy import DummyWorkflow

        registry = get_metrics_registry()
        registry.observe("veles_neg_seconds", 0.002, buckets=(0.01,),
                         exemplar={"trace_id": "feed01"})
        api = RESTfulAPI(DummyWorkflow(name="neg-wf"), port=0)
        api.feed = lambda *a: None
        api.requests = []
        api.initialize()
        try:
            url = "http://127.0.0.1:%d/metrics" % api.port
            plain = get(url)
            assert " # {" not in plain and "# EOF" not in plain
            req = urllib.request.Request(
                url, headers={"Accept": "application/openmetrics-text"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                om = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
            assert "application/openmetrics-text" in ctype
            assert 'trace_id="feed01"' in om
            assert om.rstrip().endswith("# EOF")
        finally:
            api.stop()


class TestMetricNamingLint:
    """ISSUE 5 satellite, deduped by ISSUE 13: the AST walk that lived
    here moved into the shared analyzer rule (veles_tpu/analyze/
    rules.py, ``metric.naming``/``metric.help`` — `veles_tpu analyze`
    gates it in CI). This wrapper pins that (1) the shared rule still
    FIRES on a seeded violation fixture, and (2) the tree is clean —
    plus the vacuous-scan guard: the instrumented families must
    actually be in the scan."""

    def test_rule_fires_on_seeded_violation(self):
        from veles_tpu.analyze import run_analysis

        fixture = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), "fixtures", "analyze", "metric_naming.py")
        findings, errors = run_analysis([fixture],
                                        rule_filter="metric.naming")
        assert not errors
        assert len(findings) == 1
        assert findings[0].rule == "metric.naming"
        assert "_total" in findings[0].message

    def test_conventions_hold_everywhere(self):
        from veles_tpu.analyze import run_analysis
        from veles_tpu.analyze.rules import iter_metric_calls
        import ast

        package = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "veles_tpu")
        findings, errors = run_analysis([package], rule_filter="metric")
        assert not errors
        assert findings == [], "\n".join(
            f.format(relative_to=package) for f in findings)
        # the instrumented families must actually be in the scan —
        # an empty scan would "pass" vacuously
        names = set()
        for path in glob.glob(os.path.join(package, "**", "*.py"),
                              recursive=True):
            for _, _, name, _, _ in iter_metric_calls(
                    ast.parse(open(path).read())):
                names.add(name)
        assert "veles_serving_requests_total" in names
        assert "veles_xla_compiles_total" in names
        assert "veles_device_memory_bytes" in names


class TestOverheadGuard:
    """The `make metrics` guard (ISSUE satellite): disabled-path
    span()/incr() must be structural no-ops so observability can never
    silently tax the PR-3 serving hot path."""

    def test_disabled_tracer_returns_shared_null_span(self):
        tracer = Tracer(enabled=False)
        spans = {id(tracer.span("a")), id(tracer.span("b", x=1)),
                 id(tracer.event("c"))}
        assert spans == {id(NULL_SPAN)}
        with tracer.span("a") as span:
            assert span is NULL_SPAN
            assert span.context() is None

    def test_disabled_registry_mutates_nothing(self):
        registry = MetricsRegistry(enabled=False)
        registry.incr("veles_x_total")
        registry.set("veles_g", 2)
        registry.observe("veles_h_seconds", 0.1)
        registry.counter_set("veles_c_total", 9)
        assert registry._families == {}
        assert registry.expose() == "\n"

    def test_decoder_disabled_path_uses_null_span(self):
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, 1, 8, 2, 7)
        table = jnp.asarray(rng.randn(7, 8).astype(numpy.float32))
        dec = ContinuousDecoder(params, table, 2, slots=1, max_len=32,
                                n_tokens=2)
        dec._tracer = Tracer(enabled=False)
        dec.metrics = MetricsRegistry(enabled=False)
        assert dec._span("decode.dispatch", [0]) is NULL_SPAN
        dec.submit([1, 2])
        dec.run_until_drained(max_steps=8)
        assert dec.metrics._families == {}

    def test_flight_default_on_path_stays_structurally_noop(self):
        """The always-on flight recorder must pass the SAME guard: the
        decoder's default-on notes touch neither the registry nor the
        tracer, ring memory is bounded by maxlen, and a note is one
        flag check + append (no locks, no I/O)."""
        from veles_tpu.observe.flight import FlightRecorder
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, 1, 8, 2, 7)
        table = jnp.asarray(rng.randn(7, 8).astype(numpy.float32))
        dec = ContinuousDecoder(params, table, 2, slots=1, max_len=32,
                                n_tokens=2)
        dec._tracer = Tracer(enabled=False)
        dec.metrics = MetricsRegistry(enabled=False)
        dec.flight = FlightRecorder(capacity=4)  # default-ON
        dec.submit([1, 2])
        dec.run_until_drained(max_steps=8)
        # the ring recorded the dispatch path...
        kinds = {e["kind"] for e in dec.flight.entries()}
        assert "admit" in kinds
        # ...bounded, and with ZERO registry/tracer traffic
        assert len(dec.flight.entries()) <= 4
        assert dec.metrics._families == {}

    def test_history_disabled_path_stays_structurally_noop(self):
        """ISSUE 12: the metric flight recorder obeys the same guard.
        A disabled registry's sample() returns before running any
        collector (the no-scrape fast path stays allocation-free), a
        history over it books nothing — not a pass, not a rule
        evaluation — and the store carries no lock attribute anywhere
        (the flight-ring record discipline)."""
        from veles_tpu.observe.history import (AnomalyRule,
                                               IncidentRecorder,
                                               MetricHistory)

        registry = MetricsRegistry(enabled=False)
        ran = []
        registry.add_collector(lambda: ran.append(1))
        assert registry.sample() == ()
        assert ran == []
        rule = AnomalyRule("burn", "veles_b", threshold=0.0,
                           for_samples=1)
        history = MetricHistory(
            registry=registry, rules=[rule],
            incidents=IncidentRecorder(cooldown_s=3600.0))
        assert history.sample() is False
        assert history.samples_total == 0
        assert history.series_list() == []
        assert rule.streak == 0 and history.anomalies_total == 0
        assert not any("lock" in attr.lower()
                       for attr in vars(history))

    def test_memscope_record_path_stays_structurally_noop(self):
        """ISSUE 20: the HBM attribution plane obeys the same guard.
        The record-path hooks (scratch tags, lifecycle edges, pool
        points) are flag checks + GIL-atomic container ops: a scope
        carries no lock attribute anywhere, the hooks never touch a
        registry, and a disabled scope's hooks mutate nothing."""
        from veles_tpu.observe.memscope import MemScope

        scope = MemScope(leak_min_bytes=1, limit_bytes=None)
        assert not any("lock" in attr.lower() for attr in vars(scope))
        registry = MetricsRegistry(enabled=False)
        scope.scratch_note("r1", 4096)
        scope.edge_begin("breaker_rebuild")
        scope.edge_end("breaker_rebuild")
        scope.scratch_drop("r1")

        class _Pool:
            used_pages = 3
            free_pages = 5

        scope.note_pool(_Pool())
        # record-path hooks generated zero registry traffic (publish
        # is the scrape-time seam, and a disabled registry's family
        # mutators are no-ops anyway)
        assert registry._families == {}
        scope.publish(registry)
        assert registry._families == {}
        # rings are bounded; tallies recorded the activity
        assert scope.edges_total == 1
        assert len(scope._pool_points) == 1
        # a disabled scope's hooks are structural no-ops
        scope.enabled = False
        scope.scratch_note("r2", 1)
        scope.edge_begin("swap_params")
        assert scope.edge_end("swap_params") is None
        scope.note_pool(_Pool())
        assert "r2" not in scope._scratch
        assert len(scope._open_edges) == 0
        assert len(scope._pool_points) == 1

    def test_request_ledger_null_and_default_paths(self):
        """ISSUE 10: with NO ledger attached (the default) a decoder
        leaves the process ledger untouched — one attribute check per
        dispatch; with one attached, a full request costs bounded ring
        appends only, with ZERO registry/tracer traffic and no lock
        attribute anywhere on the record path."""
        from veles_tpu.observe.reqledger import (RequestLedger,
                                                 get_request_ledger)
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, 1, 8, 2, 7)
        table = jnp.asarray(rng.randn(7, 8).astype(numpy.float32))
        before = (get_request_ledger().staged_total,
                  get_request_ledger().resolved_total)
        dec = ContinuousDecoder(params, table, 2, slots=1, max_len=32,
                                n_tokens=2)
        assert dec.ledger is None
        dec.submit([1, 2])
        dec.run_until_drained(max_steps=8)
        assert (get_request_ledger().staged_total,
                get_request_ledger().resolved_total) == before
        # attached: rows record through GIL-atomic appends alone — the
        # ledger holds no lock object at all (the structural guarantee
        # behind "no locks on the record path")
        ledger = RequestLedger(capacity=2)
        assert not any("lock" in attr.lower()
                       for attr in vars(ledger))
        dec = ContinuousDecoder(params, table, 2, slots=1, max_len=32,
                                n_tokens=2, ledger=ledger)
        dec._tracer = Tracer(enabled=False)
        dec.metrics = MetricsRegistry(enabled=False)
        for i in range(4):
            row = ledger.stage(api="guard", prompt_len=2)
            dec.ledger_link(dec.submit([1, 2]), row)
            dec.run_until_drained(max_steps=8)
            ledger.resolve(row, "completed")
        assert dec.metrics._families == {}
        assert len(ledger.slowest(10)) == 2  # ring bounded
        assert ledger.resolved_total == 4
        (last,) = ledger.slowest(1)
        assert [s[0] for s in last["stages"]] == [
            "staged", "admitted", "first_token", "resolved"]

    def test_instrument_disabled_tracker_is_pure_delegation(self):
        from veles_tpu.observe.xla_stats import (CompileTracker,
                                                 instrument)
        import veles_tpu.observe.xla_stats as xla_stats_mod
        import jax
        import jax.numpy as jnp

        saved = xla_stats_mod._tracker
        tracker = CompileTracker(enabled=False)
        xla_stats_mod._tracker = tracker
        try:
            fn = instrument("veles_test_prog",
                            jax.jit(lambda x: x + 1))
            out = fn(jnp.ones(3))
            assert float(out.sum()) == 6.0
            assert tracker._compiles == {} and tracker._hits == {}
        finally:
            xla_stats_mod._tracker = saved

    def test_instrument_non_jit_callable_returned_unwrapped(self):
        from veles_tpu.observe.xla_stats import instrument

        def plain(x):
            return x

        assert instrument("veles_test_plain", plain) is plain


class TestCompileTracker:
    def test_compiles_hits_and_flops_book_per_program(self):
        from veles_tpu.observe.xla_stats import CompileTracker, instrument
        import veles_tpu.observe.xla_stats as xla_stats_mod
        import jax
        import jax.numpy as jnp

        saved = xla_stats_mod._tracker
        tracker = CompileTracker(enabled=True)
        xla_stats_mod._tracker = tracker
        try:
            fn = instrument("prog", jax.jit(lambda x: x * 2.0))
            fn(jnp.ones(4))          # compile (shape 1)
            fn(jnp.ones(4))          # hit
            fn(jnp.ones(8))          # compile (shape 2)
            assert tracker._compiles == {"prog": 2}
            assert tracker._hits == {"prog": 1}
            assert tracker._compile_seconds["prog"] > 0
            # Lowered.cost_analysis FLOPs: 8 for the second shape
            assert tracker._flops["prog"] == 8.0
        finally:
            xla_stats_mod._tracker = saved

    def test_recompilation_storm_detected_and_warned_once(self, caplog):
        import logging

        from veles_tpu.observe.xla_stats import CompileTracker

        tracker = CompileTracker(enabled=True)
        with caplog.at_level(logging.WARNING, logger="CompileTracker"):
            for _ in range(2 * tracker.STORM_THRESHOLD):
                tracker.record_compile("churner", 0.01)
        assert tracker._storms == {"churner": 2}
        warnings = [r for r in caplog.records
                    if "recompilation storm" in r.getMessage()]
        assert len(warnings) == 1  # warn-once, counter keeps counting

    def test_mfu_published_from_flops_and_step_ema(self):
        from veles_tpu.core.config import root
        from veles_tpu.observe.xla_stats import CompileTracker

        tracker = CompileTracker(enabled=True)
        tracker.set_program_flops("prog", 2e9)
        tracker.observe_step("prog", 0.01)  # 200 GFLOP/s
        saved = root.common.observe.get("peak_tflops", None)
        root.common.observe.peak_tflops = 1.0  # 1 TFLOP/s peak
        try:
            registry = MetricsRegistry(enabled=True)
            tracker.publish(registry)
            text = registry.expose()
            assert 'veles_xla_program_flops{program="prog"} 2000000000' \
                in text
            assert 'veles_mfu_ratio{program="prog"} 0.2' in text
        finally:
            root.common.observe.peak_tflops = saved

    def test_device_memory_gauges_exist_on_every_backend(self):
        from veles_tpu.observe.xla_stats import publish_device_stats

        registry = MetricsRegistry(enabled=True)
        publish_device_stats(registry)
        text = registry.expose()
        # CPU has no allocator report: the live-bytes fallback still
        # gives the family (TPU reports bytes_in_use/peak/limit)
        assert "veles_device_memory_bytes" in text
        assert 'kind="' in text


class TestEventRecorderBuffer:
    def test_preopen_buffer_capped_drop_oldest(self, tmp_path,
                                               monkeypatch):
        """A recorder configured with a path but never open()ed must
        cap its buffer (drop-oldest) instead of growing forever."""
        monkeypatch.setattr(EventRecorder, "MAX_BUFFER", 10)
        rec = EventRecorder(path=str(tmp_path / "never-opened.jsonl"))
        for i in range(25):
            rec.record(name="span-%d" % i, etype="single")
        assert len(rec._buffer) == 10
        assert rec._buffer_dropped == 15
        kept = [json.loads(line)["name"] for line in rec._buffer]
        assert kept == ["span-%d" % i for i in range(15, 25)]
        # a late open() flushes exactly the surviving tail
        out = tmp_path / "opened.jsonl"
        rec.open(str(out))
        rec.close()
        names = [json.loads(line)["name"]
                 for line in out.read_text().splitlines()]
        assert names == kept

    def test_record_carries_monotonic_stamp(self, tmp_path):
        rec = EventRecorder()
        rec.open(str(tmp_path / "events.jsonl"))
        before = time.monotonic()
        rec.record(name="x", etype="single")
        rec.close()
        event = json.loads(
            (tmp_path / "events.jsonl").read_text().splitlines()[0])
        assert before <= event["mono"] <= time.monotonic()


class TestTailEvents:
    def test_tail_reads_only_the_end_of_a_multi_mb_file(self, tmp_path):
        from veles_tpu.web_status import WebStatusServer, tail_lines

        path = tmp_path / "events.jsonl"
        n = 40000  # ~4.6 MB of lines
        with open(path, "w") as fout:
            for i in range(n):
                fout.write(json.dumps(
                    {"name": "e%06d" % i, "pad": "x" * 80}) + "\n")
        assert os.path.getsize(path) > 3 * 1024 * 1024
        server = WebStatusServer.__new__(WebStatusServer)
        server.events_path = str(path)
        out = server.tail_events(limit=200)
        assert len(out) == 200
        assert [e["name"] for e in out] == \
            ["e%06d" % i for i in range(n - 200, n)]
        # bounded reads: the backward scan may touch at most the tail
        # window plus one block of slack, never megabytes
        reads = []
        real_read = os.read

        class CountingFile:
            def __init__(self, fobj):
                self._f = fobj

            def __getattr__(self, name):
                return getattr(self._f, name)

            def read(self, size):
                reads.append(size)
                return self._f.read(size)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                self._f.close()

        import builtins
        real_open = builtins.open
        try:
            builtins.open = lambda *a, **k: CountingFile(
                real_open(*a, **k))
            tail_lines(str(path), 200)
        finally:
            builtins.open = real_open
        assert sum(reads) <= 200 * 120 + 2 * 65536, sum(reads)
        del real_read

    def test_tail_shorter_than_limit(self, tmp_path):
        from veles_tpu.web_status import tail_lines

        path = tmp_path / "short.jsonl"
        path.write_text("a\nb\nc\n")
        assert tail_lines(str(path), 200) == ["a", "b", "c"]


class TestTraceExport:
    def test_begin_end_pairs_become_complete_events(self, tmp_path):
        events = [
            {"name": "parent", "etype": "begin", "trace_id": "t1",
             "span_id": "s1", "parent_id": None, "mono": 1.0, "tid": 7,
             "pid": 1},
            {"name": "child", "etype": "begin", "trace_id": "t1",
             "span_id": "s2", "parent_id": "s1", "mono": 1.1, "tid": 7,
             "pid": 1},
            {"name": "child", "etype": "end", "trace_id": "t1",
             "span_id": "s2", "parent_id": "s1", "mono": 1.4, "tid": 7,
             "pid": 1},
            {"name": "mark", "etype": "single", "trace_id": "t1",
             "span_id": "s3", "parent_id": "s1", "mono": 1.2, "tid": 7,
             "pid": 1},
            {"name": "parent", "etype": "end", "trace_id": "t1",
             "span_id": "s1", "parent_id": None, "mono": 2.0, "tid": 7,
             "pid": 1},
        ]
        src = tmp_path / "events.jsonl"
        with open(src, "w") as fout:
            for event in events:
                fout.write(json.dumps(event) + "\n")
        out = tmp_path / "trace.json"
        count = export_chrome_trace(str(src), str(out))
        trace = json.loads(out.read_text())
        assert count == len(trace["traceEvents"])
        spans = [e for e in trace["traceEvents"] if e["ph"] != "M"]
        assert len(spans) == 3
        # the multi-process satellite: process/thread metadata rows
        # ride along so merged traces keep one row per process
        metadata = {e["name"] for e in trace["traceEvents"]
                    if e["ph"] == "M"}
        assert metadata == {"process_name", "thread_name"}
        complete = {e["name"]: e for e in trace["traceEvents"]
                    if e["ph"] == "X"}
        assert set(complete) == {"parent", "child"}
        assert complete["child"]["dur"] == pytest.approx(0.3e6)
        assert complete["parent"]["dur"] == pytest.approx(1.0e6)
        tree = span_tree(trace)["t1"]
        assert tree == {"s1": None, "s2": "s1", "s3": "s1"}

    def test_loader_skips_torn_lines(self, tmp_path):
        src = tmp_path / "events.jsonl"
        src.write_text('{"name": "ok", "etype": "single"}\n{"trunc')
        assert [e["name"] for e in load_events(str(src))] == ["ok"]


@pytest.fixture
def observability(tmp_path, monkeypatch):
    """Fresh global recorder (JSONL in tmp) + enabled tracer + reset
    registry, restored afterwards — the globals other suites also
    touch."""
    from veles_tpu.core import logger as logger_mod
    from veles_tpu.observe.metrics import get_metrics_registry
    from veles_tpu.observe.tracing import get_tracer

    events_path = str(tmp_path / "events.jsonl")
    recorder = EventRecorder()
    recorder.open(events_path)
    monkeypatch.setattr(logger_mod, "_event_recorder", recorder)
    tracer = get_tracer()
    registry = get_metrics_registry()
    was_traced, was_metered = tracer.enabled, registry.enabled
    tracer.enable()
    registry.reset()
    registry.enable()
    yield events_path
    recorder.close()
    tracer.enabled = was_traced
    registry.reset()
    registry.enabled = was_metered


def _walk_to_root(tree, span_id, stop_ids):
    seen = set()
    while True:
        assert span_id not in seen, "parent cycle at %s" % span_id
        seen.add(span_id)
        parent = tree.get(span_id, "missing")
        if parent is None or parent in stop_ids:
            return parent
        assert parent != "missing", \
            "span %s has a parent outside the tree" % span_id
        span_id = parent


class TestServingObservability:
    @pytest.fixture(scope="class")
    def model(self):
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        heads, embed, vocab = 4, 16, 11
        params = init_transformer_params(rng, 2, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        return params, table, heads, vocab

    def test_request_yields_connected_span_tree_and_metrics(
            self, model, observability, tmp_path):
        """The acceptance pair: one serving request produces ONE
        connected trace (admission -> prefill dispatch -> decode chunks
        -> collect) in the exported Chrome trace, and /metrics on the
        same surface exposes serving counters + decode histograms."""
        from veles_tpu.serving import GenerateAPI

        params, table, heads, vocab = model
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            client_trace = "c0ffee01", "ab12"
            body, headers = post(
                url + "/generate", {"tokens": [1, 2, 3]},
                headers={"X-Veles-Trace": "%s/%s" % client_trace})
            assert len(body["tokens"]) == 4
            # the response echoes the request's trace id
            echoed = parse_trace_header(headers.get("X-Veles-Trace"))
            assert echoed is not None and echoed[0] == client_trace[0]
            metrics = get(url + "/metrics")
            assert ('veles_serving_requests_total{api="generate-api"'
                    ',outcome="completed"} 1') in metrics
            assert ('veles_serving_requests_total{api="generate-api"'
                    ',outcome="admitted"} 1') in metrics
            assert "veles_decode_dispatch_seconds_bucket" in metrics
            assert "veles_decode_admit_seconds_count" in metrics
            assert 'veles_decode_dispatches_total{kind="admit"} 1' \
                in metrics
        finally:
            api.stop()
        out = str(tmp_path / "trace.json")
        export_chrome_trace(observability, out)
        trace = json.loads(open(out).read())
        trees = span_tree(trace)
        # ONE trace: the client's id, continued through every layer
        assert list(trees) == [client_trace[0]], list(trees)
        tree = trees[client_trace[0]]
        names = {e["args"]["span_id"]: e["name"]
                 for e in trace["traceEvents"]
                 if e["args"].get("trace_id") == client_trace[0]}
        by_name = {}
        for span_id, name in names.items():
            by_name.setdefault(name, []).append(span_id)
        for required in ("serve.request", "serve.submit",
                         "decode.admit", "decode.dispatch",
                         "decode.collect", "serve.complete"):
            assert required in by_name, (required, sorted(by_name))
        # every span's parent chain terminates at the client's span —
        # one CONNECTED tree, no orphans
        stop = {client_trace[1]}
        for span_id in tree:
            assert _walk_to_root(tree, span_id, stop) in stop
        # the request span is the direct child of the client context
        for span_id in by_name["serve.request"]:
            assert tree[span_id] == client_trace[1]

    def test_metrics_expose_device_truth(self, observability):
        """The ISSUE acceptance: /metrics on GenerateAPI exposes
        compile-count, device-memory and MFU gauges — fed by real
        compiles of the slot programs and the driver's chunk cadence,
        not hand-planted samples. A DISTINCT model shape guarantees
        fresh compiles even when earlier suites warmed the jit caches
        for the shared toy model."""
        from veles_tpu.core.config import root
        from veles_tpu.observe.xla_stats import get_compile_tracker
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        from veles_tpu.serving import GenerateAPI
        import jax.numpy as jnp

        rng = numpy.random.RandomState(3)
        heads, embed, vocab = 2, 12, 13
        params = init_transformer_params(rng, 1, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        tracker = get_compile_tracker()
        was_tracking = tracker.enabled
        tracker.reset()
        saved_peak = root.common.observe.get("peak_tflops", None)
        # CPU is not in the peak table; the override supplies the MFU
        # denominator (the knob unlisted devices use)
        root.common.observe.peak_tflops = 0.001
        api = GenerateAPI(params, table, heads, slots=2, max_len=64,
                          n_tokens=6, chunk=2, port=0)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            body, _ = post(url + "/generate", {"tokens": [1, 2, 3, 4]})
            assert len(body["tokens"]) == 6
            metrics = get(url + "/metrics")
            # compile counts per slot program
            assert 'veles_xla_compiles_total{program="decode.admit"}' \
                in metrics
            assert ('veles_xla_compiles_total'
                    '{program="decode.dispatch"}') in metrics
            assert "veles_xla_compile_seconds_total" in metrics
            # device memory (live-bytes fallback on CPU)
            assert "veles_device_memory_bytes" in metrics
            # online MFU: cost_analysis FLOPs over the chunk cadence
            assert ('veles_xla_program_flops'
                    '{program="decode.dispatch"}') in metrics
            assert 'veles_mfu_ratio{program="decode.dispatch"}' \
                in metrics
            assert "veles_device_peak_bf16_tflops 0.001" in metrics
        finally:
            api.stop()
            tracker.reset()
            tracker.enabled = was_tracking
            root.common.observe.peak_tflops = saved_peak

    def test_restful_api_mounts_metrics(self, observability):
        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.serving import RESTfulAPI

        api = RESTfulAPI(DummyWorkflow(), port=0, path="/api")
        api.feed = lambda data, request: None
        api.requests = []
        api.initialize()
        try:
            metrics = get("http://127.0.0.1:%d/metrics" % api.port)
            assert 'veles_serving_ready{api="restful-api"} 1' in metrics
        finally:
            api.stop()

    def test_web_status_mounts_metrics(self, observability):
        from veles_tpu.web_status import WebStatusServer

        server = WebStatusServer(port=0).start()
        try:
            metrics = get("http://127.0.0.1:%d/metrics" % server.port)
            assert "# TYPE" in metrics or metrics.strip() == ""
        finally:
            server.stop()

    def test_forge_mounts_metrics(self, observability, tmp_path):
        from veles_tpu.forge.server import ForgeServer

        server = ForgeServer(str(tmp_path / "store"), port=0).start()
        try:
            # exposition is live on the forge surface too
            get("http://127.0.0.1:%d/metrics" % server.port)
        finally:
            server.stop()


@pytest.mark.slow
class TestFleetObservability:
    def test_fleet_round_trip_metrics_and_trace(self, observability,
                                                tmp_path):
        """A real master+slave run: the master's /metrics sidecar
        aggregates fleet state incl. the slave's piggybacked counters,
        and one job reads master -> slave -> apply as a single
        connected trace."""
        from veles_tpu.core import prng
        from veles_tpu.core.config import root
        from veles_tpu.launcher import Launcher
        from veles_tpu.models.mlp import MLPWorkflow
        from sklearn.datasets import load_digits

        digits = load_digits()
        kw = dict(
            layers=(16, 10),
            loader_kwargs=dict(
                data=digits.data.astype(numpy.float32),
                labels=digits.target.astype(numpy.int32),
                class_lengths=[0, 297, 1500], minibatch_size=300,
                normalization_type="linear"),
            learning_rate=0.5, max_epochs=1)
        saved_port = root.common.observe.get("fleet_metrics_port", None)
        root.common.observe.fleet_metrics_port = 0
        try:
            prng.get("default").seed(42)
            prng.get("loader").seed(43)
            master = Launcher(listen_address="127.0.0.1:0")
            MLPWorkflow(master, name="fleet-obs", **kw)
            master.initialize()
            master_thread = threading.Thread(target=master.run,
                                             daemon=True)
            master_thread.start()
            prng.get("default").seed(42)
            prng.get("loader").seed(43)
            slave = Launcher(
                master_address="127.0.0.1:%d" % master.agent.port)
            MLPWorkflow(slave, name="fleet-obs", **kw)
            slave.initialize()
            slave_thread = threading.Thread(target=slave.run,
                                            daemon=True)
            slave_thread.start()
            deadline = time.time() + 120
            metrics_url = "http://127.0.0.1:%d/metrics" \
                % master.agent.metrics_port
            # poll mid-run until the slave's piggybacked rows show up
            piggybacked = ""
            while time.time() < deadline:
                try:
                    piggybacked = get(metrics_url, timeout=5)
                except OSError:
                    break  # master finished and closed the sidecar
                if 'slave="slave-1"' in piggybacked \
                        and "veles_fleet_jobs_total" in piggybacked:
                    break
                time.sleep(0.2)
            assert "veles_fleet_jobs_total" in piggybacked
            assert 'slave="slave-1"' in piggybacked, \
                piggybacked[-2000:]
            master_thread.join(timeout=120)
            slave_thread.join(timeout=120)
        finally:
            if saved_port is None:
                root.common.observe.fleet_metrics_port = None
            else:
                root.common.observe.fleet_metrics_port = saved_port
        events = load_events(observability)
        issues = [e for e in events if e.get("name") == "fleet.issue"]
        assert issues, "no fleet.issue events recorded"
        trace = chrome_trace(events)
        trees = span_tree(trace)
        jobs = {e["args"]["span_id"]: e for e in trace["traceEvents"]
                if e["name"] == "fleet.do_job"}
        applies = [e for e in trace["traceEvents"]
                   if e["name"] == "fleet.apply"]
        assert jobs and applies
        # every applied update chains master.issue -> slave.do_job ->
        # master.apply inside ONE trace
        verified = 0
        for apply_event in applies:
            args = apply_event["args"]
            parent = args.get("parent_id")
            if parent not in jobs:
                continue
            job = jobs[parent]
            assert job["args"]["trace_id"] == args["trace_id"]
            issue_id = job["args"].get("parent_id")
            issue = next(
                (e for e in trace["traceEvents"]
                 if e["args"].get("span_id") == issue_id), None)
            assert issue is not None and issue["name"] == "fleet.issue"
            assert issue["args"]["trace_id"] == args["trace_id"]
            verified += 1
        assert verified > 0
