"""Tests for REST serving, interactive loader, and web status (reference
test_restful.py / test_web_status.py roles)."""

import json
import os
import threading
import urllib.request
import urllib.error

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.serving import InteractiveLoader, RESTfulAPI, RestfulLoader
from veles_tpu.web_status import StatusNotifier, WebStatusServer


def post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class ServingHarness:
    """loader -> double(input) -> api loop on a background thread."""

    def __init__(self, mb=4, max_response_time=0.05):
        wf = DummyWorkflow()
        self.loader = RestfulLoader(wf, sample_shape=(3,),
                                    minibatch_size=mb,
                                    max_response_time=max_response_time)
        self.loader.initialize()
        self.api = RESTfulAPI(wf, port=0, path="/api")
        self.api.feed = self.loader.feed
        self.api.requests = []
        self.api.initialize()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self.loader.run()
            if self.loader.complete:
                return
            batch = numpy.asarray(self.loader.minibatch_data.mem)
            self.api.results = batch * 2.0
            self.api.requests = self.loader.requests
            self.api.run()

    @property
    def url(self):
        return "http://127.0.0.1:%d/api" % self.api.port

    def close(self):
        self._stop.set()
        self.loader.stop()
        self.api.stop()


@pytest.fixture
def harness():
    h = ServingHarness()
    yield h
    h.close()


class TestRESTfulAPI:
    def test_list_codec(self, harness):
        out = post(harness.url, {"input": [1.0, 2.0, 3.0],
                                 "codec": "list"})
        assert out["result"] == [2.0, 4.0, 6.0]

    def test_base64_codec(self, harness):
        import base64
        arr = numpy.array([0.5, 1.5, 2.5], numpy.float32)
        out = post(harness.url, {
            "input": base64.b64encode(arr.tobytes()).decode(),
            "codec": "base64", "shape": [3], "type": "float32"})
        assert out["result"] == [1.0, 3.0, 5.0]

    def test_concurrent_requests_batched(self, harness):
        results = {}

        def call(i):
            results[i] = post(harness.url,
                              {"input": [float(i)] * 3, "codec": "list"})

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        for i in range(3):
            assert results[i]["result"] == [2.0 * i] * 3

    def test_bad_requests(self, harness):
        for payload in ({"input": [1, 2, 3]},  # no codec
                        {"codec": "list"},  # no input
                        {"input": "x", "codec": "bogus"}):
            with pytest.raises(urllib.error.HTTPError) as err:
                post(harness.url, payload)
            assert err.value.code == 400

    def test_base64_needs_shape_and_type(self, harness):
        with pytest.raises(urllib.error.HTTPError) as err:
            post(harness.url, {"input": "QUFB", "codec": "base64"})
        assert err.value.code == 400

    def test_ragged_list_input_gets_400(self, harness):
        # regression: ragged arrays must 400, not drop the connection
        with pytest.raises(urllib.error.HTTPError) as err:
            post(harness.url, {"input": [[1], [2, 3]], "codec": "list"})
        assert err.value.code == 400

    def test_zero_max_response_time_still_flushes(self):
        # regression: max_response_time=0 meant "wait forever"
        h = ServingHarness(mb=4, max_response_time=0)
        try:
            out = post(h.url, {"input": [1.0, 1.0, 1.0], "codec": "list"},
                       timeout=15)
            assert out["result"] == [2.0, 2.0, 2.0]
        finally:
            h.close()


class TestInteractiveLoader:
    def test_feed_and_complete(self):
        loader = InteractiveLoader(DummyWorkflow(), sample_shape=(4,))
        loader.initialize()
        served = []

        def run_once():
            loader.run()
            served.append(numpy.asarray(loader.minibatch_data.mem).copy())

        t = threading.Thread(target=run_once)
        t.start()
        loader.feed(numpy.arange(4.0))
        t.join(timeout=10)
        assert not t.is_alive()
        numpy.testing.assert_array_equal(served[0][0],
                                         [0.0, 1.0, 2.0, 3.0])
        loader.feed(None)
        assert bool(loader.complete)

    def test_feed_from_npy(self, tmp_path):
        path = str(tmp_path / "x.npy")
        numpy.save(path, numpy.ones(4, numpy.float32))
        loader = InteractiveLoader(DummyWorkflow(), sample_shape=(4,))
        loader.initialize()
        t = threading.Thread(target=loader.run)
        t.start()
        loader.feed(path)
        t.join(timeout=10)
        numpy.testing.assert_array_equal(
            numpy.asarray(loader.minibatch_data.mem)[0], numpy.ones(4))


class TestWebStatus:
    @pytest.fixture
    def server(self, tmp_path):
        srv = WebStatusServer(port=0, plots_directory=str(tmp_path))
        srv.start()
        yield srv, tmp_path
        srv.stop()

    def test_update_and_service(self, server):
        srv, _ = server
        base = "http://127.0.0.1:%d" % srv.port
        post(base + "/update", {"name": "wf1", "mode": "master",
                                "slaves": [{"id": "s1"}], "runtime": 12})
        with urllib.request.urlopen(base + "/service", timeout=5) as resp:
            data = json.loads(resp.read().decode())
        (key, status), = data.items()
        assert status["name"] == "wf1" and len(status["slaves"]) == 1

    def test_dashboard_html_and_plots(self, server):
        srv, tmp_path = server
        (tmp_path / "loss.png").write_bytes(b"\x89PNG fake")
        base = "http://127.0.0.1:%d" % srv.port
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            html = resp.read().decode()
        assert "loss.png" in html
        with urllib.request.urlopen(base + "/plots/loss.png",
                                    timeout=5) as resp:
            assert resp.read() == b"\x89PNG fake"
        # path traversal blocked
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/plots/../secret", timeout=5)

    def test_update_payloads_escaped_and_coerced(self, server):
        # regression: /update is unauthenticated — hostile payloads must
        # neither script-inject nor 500 the dashboard
        srv, _ = server
        base = "http://127.0.0.1:%d" % srv.port
        post(base + "/update", {"name": "<script>alert(1)</script>",
                                "mode": "<b>x</b>", "runtime": "12s",
                                "slaves": "not-a-list"})
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            html = resp.read().decode()
        assert "<script>alert(1)</script>" not in html
        assert "&lt;script&gt;" in html
        # unhashable/heterogeneous ids must not 500 /update or /
        post(base + "/update", {"id": [1, 2], "name": "l"})
        post(base + "/update", {"id": 5, "name": "n"})
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            assert resp.status == 200

    def test_live_workflow_graph(self, server):
        """VERDICT r3 #8: the dashboard renders the running workflow's
        unit DAG (posted by the notifier) as an SVG with activity
        counters — the reference's viz.js graph page."""
        from veles_tpu.dummy import DummyLauncher
        from veles_tpu.models.mlp import MLPWorkflow

        rng = numpy.random.RandomState(0)
        wf = MLPWorkflow(
            DummyLauncher(), layers=(8, 10),
            loader_kwargs=dict(
                data=rng.rand(120, 16).astype(numpy.float32),
                labels=rng.randint(0, 10, 120).astype(numpy.int32),
                class_lengths=[0, 20, 100], minibatch_size=20),
            learning_rate=0.1, max_epochs=1, name="graph-wf")
        wf.initialize()
        wf.run()
        graph = wf.graph_snapshot()
        assert any(n["runs"] > 0 for n in graph["nodes"])
        assert graph["edges"]
        srv, _ = server
        base = "http://127.0.0.1:%d" % srv.port
        post(base + "/update", {"id": "g1", "name": "graph-wf",
                                "graph": graph})
        with urllib.request.urlopen(base + "/graph/g1.svg",
                                    timeout=5) as resp:
            svg = resp.read().decode()
        assert svg.startswith("<svg")
        assert "Repeater" in svg and "marker-end" in svg
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            html = resp.read().decode()
        assert "/graph/g1.svg" in html
        # malformed graph payloads must answer CLEANLY — a 404 or an
        # empty SVG, never a wedged connection / 500 (the /update
        # endpoint is unauthenticated)
        for bad in ("nope", {"nodes": 1}, {"nodes": [7], "edges": [3]}):
            post(base + "/update", {"id": "bad", "graph": bad})
            try:
                with urllib.request.urlopen(base + "/graph/bad.svg",
                                            timeout=5) as resp:
                    body = resp.read().decode()
                assert body.startswith("<svg") and "<rect" not in body
            except urllib.error.HTTPError as err:
                assert err.code == 404
        # keys that need percent-encoding round-trip through the page
        post(base + "/update", {"id": "my wf", "name": "my wf",
                                "graph": graph})
        with urllib.request.urlopen(base + "/graph/my%20wf.svg",
                                    timeout=5) as resp:
            assert resp.read().decode().startswith("<svg")

    def test_live_stream_pushes_plot_refresh(self, server):
        """VERDICT r4 #7 (live plot viewing): /stream is an SSE feed —
        one state event on connect, another when a plot file lands or
        is re-rendered (mtime bump) — driving one full refresh cycle
        the way the dashboard JS does."""
        srv, tmp_path = server
        srv.STREAM_POLL = 0.05
        base = "http://127.0.0.1:%d" % srv.port
        post(base + "/update", {"name": "wf-live", "mode": "master",
                                "runtime": 1})

        def next_event(resp):
            payload = []
            while True:
                line = resp.readline().decode()
                if line.startswith("data:"):
                    payload.append(line[len("data:"):].strip())
                elif line.strip() == "" and payload:
                    return json.loads("".join(payload))

        resp = urllib.request.urlopen(base + "/stream", timeout=10)
        try:
            first = next_event(resp)
            assert first["workflows"][0]["name"] == "wf-live"
            assert first["plots"] == []
            # a plot renders -> the stream pushes the new state
            (tmp_path / "loss.png").write_bytes(b"\x89PNG live")
            second = next_event(resp)
            assert second["plots"][0]["name"] == "loss.png"
            stamp = second["plots"][0]["mtime"]
            # re-render (mtime bump) -> another push with a new
            # cache-buster
            os.utime(tmp_path / "loss.png", (stamp + 5, stamp + 5))
            third = next_event(resp)
            assert third["plots"][0]["mtime"] == stamp + 5
        finally:
            resp.close()
        # the polling fallback sees the same state
        with urllib.request.urlopen(base + "/plots.json",
                                    timeout=5) as r:
            plots = json.loads(r.read().decode())
        assert plots[0]["name"] == "loss.png"

    def test_notifier(self, server):
        srv, _ = server

        class FakeAgent:
            @staticmethod
            def fleet_status():
                return {"slaves": [{"id": "s1"}, {"id": "s2"}]}

        class FakeLauncher:
            workflow = type("W", (), {"name": "notified"})()
            mode = "master"
            agent = FakeAgent()

        notifier = StatusNotifier(
            FakeLauncher(), url="http://127.0.0.1:%d/update" % srv.port)
        assert notifier.notify_once()
        statuses = srv.statuses()
        status = next(iter(statuses.values()))
        assert status["name"] == "notified"
        assert len(status["slaves"]) == 2

    def test_live_plot_viewer_cache_busting(self, server):
        """The remote live-plot viewer (reference epgm multicast role):
        plot <img> tags carry an mtime cache-buster so the 3s
        meta-refresh re-fetches re-rendered figures, and the query
        string is stripped when serving."""
        srv, tmp_path = server
        (tmp_path / "err.png").write_bytes(b"\x89PNG v1")
        base = "http://127.0.0.1:%d" % srv.port
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            html = resp.read().decode()
        assert 'src="/plots/err.png?t=' in html
        # the busted URL must serve the CURRENT bytes
        import re
        url = re.search(r'src="(/plots/err\.png\?t=\d+)"', html).group(1)
        with urllib.request.urlopen(base + url, timeout=5) as resp:
            assert resp.read() == b"\x89PNG v1"


class TestContinuousDecoder:
    """Continuous batching: sequences joining mid-flight must decode
    exactly what single-request generate() produces (VERDICT r4 #10)."""

    @pytest.fixture(scope="class")
    def model(self):
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        heads, embed, vocab = 4, 16, 11
        params = init_transformer_params(rng, 2, embed, heads, vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        return params, table, heads, vocab

    def test_staggered_requests_match_generate(self, model):
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(1)
        prompts = [rng.randint(0, vocab, n) for n in (5, 3, 7, 4, 6)]
        budgets = [6, 4, 5, 7, 3]

        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=8)
        # two requests start; the rest join as slots free up
        ids = [dec.submit(prompts[0], budgets[0]),
               dec.submit(prompts[1], budgets[1])]
        dec.step()
        ids.append(dec.submit(prompts[2], budgets[2]))  # queued: full
        dec.step()
        dec.step()
        dec.step()  # request 1 (budget 4) retires here or earlier
        ids.append(dec.submit(prompts[3], budgets[3]))
        ids.append(dec.submit(prompts[4], budgets[4]))
        results = dec.run_until_drained()

        for rid, prompt, budget in zip(ids, prompts, budgets):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=budget)
            assert results[rid] == numpy.asarray(want)[0].tolist(), \
                "request %d diverged from single-request decode" % rid
        assert not dec.busy
        assert dec.tokens_out == sum(budgets)

    def test_eos_retires_early_and_slot_recycles(self, model):
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(2)
        prompt = rng.randint(0, vocab, 5)
        ref, _ = generate(params, table, jnp.asarray(prompt)[None],
                          heads, n_tokens=8)
        ref = numpy.asarray(ref)[0].tolist()
        eos = ref[2]
        # a sequence stops at its FIRST eos occurrence (greedy decode
        # often repeats tokens, so derive the expectation from ref)
        expect = ref[:ref.index(eos) + 1]
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=32, n_tokens=8, eos=eos)
        first = dec.submit(prompt)
        second = dec.submit(prompt)  # queued until the slot recycles
        results = dec.run_until_drained()
        assert results[first] == expect
        assert results[second] == expect
        assert len(expect) < len(ref)  # it really did stop early

    def test_step_many_matches_stepwise(self, model):
        """The chunked throughput mode produces the same token streams
        as per-token stepping (tail tokens past a budget discarded)."""
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(3)
        prompts = [rng.randint(0, vocab, n) for n in (4, 6, 5)]
        budgets = [5, 9, 3]

        ref = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=8)
        ref_ids = [ref.submit(p, b) for p, b in zip(prompts, budgets)]
        ref.run_until_drained()

        fast = ContinuousDecoder(params, table, heads, slots=2,
                                 max_len=32, n_tokens=8)
        ids = [fast.submit(p, b) for p, b in zip(prompts, budgets)]
        fast.run_until_drained(chunk=4)

        for a, b in zip(ref_ids, ids):
            assert ref.results[a] == fast.results[b]
        assert fast.tokens_out == sum(budgets)

    def test_drain_pipelined_matches_stepwise(self, model):
        """The lag-1 pipelined drain (readback hidden behind the next
        chunk) yields the same streams as per-token stepping."""
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(4)
        prompts = [rng.randint(0, vocab, n) for n in (4, 6, 5, 3)]
        budgets = [5, 9, 3, 7]

        ref = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=48, n_tokens=9)
        ref_ids = [ref.submit(p, b) for p, b in zip(prompts, budgets)]
        ref.run_until_drained()

        piped = ContinuousDecoder(params, table, heads, slots=2,
                                  max_len=48, n_tokens=9)
        ids = [piped.submit(p, b) for p, b in zip(prompts, budgets)]
        piped.drain_pipelined(chunk=4)

        for a, b in zip(ref_ids, ids):
            assert ref.results[a] == piped.results[b]
        assert piped.tokens_out == sum(budgets)
        assert not piped.busy

    def test_sampled_streams_match_generate_per_request(self, model):
        """Temperature sampling: each request draws from its OWN key
        stream (fold_in(base, rid)), so its tokens equal
        generate(batch=1, key=that key) no matter which slot it lands
        in or who shares the batch — and two requests with the same
        prompt still differ."""
        import jax
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(5)
        prompts = [rng.randint(0, vocab, n) for n in (5, 5, 4)]
        base = jax.random.key(99)
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=32, n_tokens=6,
                                temperature=0.8, key=base)
        ids = [dec.submit(p) for p in prompts]
        results = dec.run_until_drained()
        for rid, prompt in zip(ids, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=6, temperature=0.8,
                               key=jax.random.fold_in(base, rid))
            assert results[rid] == numpy.asarray(want)[0].tolist(), \
                "request %d sampled stream diverged" % rid
        # same prompt, different request ids -> different streams
        assert results[ids[0]] != results[ids[1]]

    def test_bucketed_admission_across_prompt_lengths(self, model):
        """Prompts landing in different power-of-two buckets (the
        right-padded prefill path) decode the same tokens as
        generate(); the pad positions never leak into the stream."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(6)
        # bucket 16, bucket 32 and an exact-bucket length
        prompts = [rng.randint(0, vocab, n) for n in (7, 20, 16)]
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=64, n_tokens=5)
        ids = [dec.submit(p) for p in prompts]
        results = dec.run_until_drained()
        for rid, prompt in zip(ids, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=5, max_len=64)
            assert results[rid] == numpy.asarray(want)[0].tolist(), \
                "prompt len %d diverged through the padded prefill" \
                % len(prompt)

    def test_budget_overflow_rejected(self, model):
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads, vocab = model
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=16, n_tokens=8)
        with pytest.raises(ValueError):
            dec.submit(numpy.arange(12) % vocab)

    def test_batched_admission_one_dispatch_per_bucket(self, model):
        """The admission perf contract (docs/serving_performance.md):
        every same-bucket queued prompt admits in ONE slot_admit_many
        dispatch — the dispatch-counting CI hook proves it — and the
        streams stay bit-identical to single-request generate()."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(11)
        # three prompts in bucket 16, one in bucket 32
        prompts = [rng.randint(0, vocab, n) for n in (5, 9, 12, 20)]
        dec = ContinuousDecoder(params, table, heads, slots=4,
                                max_len=64, n_tokens=4)
        ids = [dec.submit(p) for p in prompts]
        dec.step()  # admits everything queued
        assert dec.dispatch_counts["admit"] == 2  # one per bucket group
        assert dec.dispatch_counts["admit_requests"] == 4
        results = dec.run_until_drained()
        for rid, prompt in zip(ids, prompts):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=4, max_len=64)
            assert results[rid] == numpy.asarray(want)[0].tolist()

    def test_tiled_pipelined_join_cancel_bit_identity(self, model):
        """The full PR-3 composite on the numerical contract: a small
        span tile (spans vary as sequences grow), batched admission,
        the lag-1 pipelined drain, requests joining mid-flight AND one
        cancelled mid-chunk — surviving streams exactly equal greedy
        generate()."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(12)
        prompts = [rng.randint(0, vocab, n) for n in (4, 6, 5, 3)]
        budgets = [5, 9, 3, 7]
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=48, n_tokens=9, tile=8)
        # the victim is submitted FIRST so it owns a slot immediately:
        # cancelling it at pass 2 happens while the pass-1 chunk that
        # contains its tokens is still in flight (a true mid-chunk
        # cancel), and the freed slot re-admits a queued request
        victim = dec.submit(rng.randint(0, vocab, 5), 9)
        ids = [dec.submit(prompts[0], budgets[0]),
               dec.submit(prompts[1], budgets[1])]
        late = list(zip(prompts[2:], budgets[2:]))
        state = {"passes": 0}

        def admit():
            state["passes"] += 1
            if state["passes"] == 2:
                # cancel with a chunk in flight: its tail tokens must
                # be discarded at collect, the slot recycled cleanly
                assert dec.cancel(victim)
            if late:
                prompt, budget = late.pop(0)
                ids.append(dec.submit(prompt, budget))

        dec.drain_pipelined(chunk=4, admit=admit)
        assert victim not in dec.results
        assert not dec.busy
        for rid, prompt, budget in zip(ids, prompts, budgets):
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=budget, max_len=48)
            assert dec.results[rid] == \
                numpy.asarray(want)[0].tolist(), \
                "request %d diverged under tile+pipeline+cancel" % rid

    def test_quantized_slot_streams_match_generate(self, model):
        """The int8 serving tiers plumbed into the slot engine: with
        quantize="int8" (W8A16 weights) and "int8-kv" (plus int8 slot
        KV cache) a request's stream equals generate() under the SAME
        quantize mode — asserted exactly on CPU."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import ContinuousDecoder
        import jax.numpy as jnp

        params, table, heads, vocab = model
        rng = numpy.random.RandomState(13)
        prompts = [rng.randint(0, vocab, n) for n in (5, 3, 7)]
        for mode in ("int8", "int8-kv"):
            dec = ContinuousDecoder(params, table, heads, slots=2,
                                    max_len=32, n_tokens=6,
                                    quantize=mode)
            ids = [dec.submit(p) for p in prompts]
            results = dec.run_until_drained()
            for rid, prompt in zip(ids, prompts):
                want, _ = generate(params, table,
                                   jnp.asarray(prompt)[None], heads,
                                   n_tokens=6, max_len=32,
                                   quantize=mode)
                assert results[rid] == \
                    numpy.asarray(want)[0].tolist(), \
                    "quantize=%s request %d diverged" % (mode, rid)

    def test_live_driver_lag1_pipelining_and_bit_identity(self, model):
        """The GenerateAPI driver is lag-1 double-buffered: the
        dispatch log shows chunk N+1 dispatched BEFORE chunk N is
        collected, streams stay bit-identical to generate(), a request
        joining mid-flight completes, and the health window records
        ttft/queue-wait percentiles."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import GenerateAPI
        import jax.numpy as jnp

        params, table, heads, vocab = model
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=6, chunk=2, port=0)
        api.decoder.dispatch_log = log = []
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            rng = numpy.random.RandomState(14)
            prompts = [rng.randint(0, vocab, n).tolist()
                       for n in (4, 6, 5)]
            results = {}

            def call(i):
                results[i] = post(url, {"tokens": prompts[i]},
                                  timeout=60)

            threads = [threading.Thread(target=call, args=(0,)),
                       threading.Thread(target=call, args=(1,))]
            for t in threads:
                t.start()
            # the third request joins while the first two are decoding
            t_late = threading.Thread(target=call, args=(2,))
            t_late.start()
            for t in threads + [t_late]:
                t.join(timeout=90)
            for i, prompt in enumerate(prompts):
                want, _ = generate(params, table,
                                   jnp.asarray(prompt)[None], heads,
                                   n_tokens=6, max_len=32)
                assert results[i]["tokens"] == \
                    numpy.asarray(want)[0].tolist()
            # lag-1: somewhere in the trace two dispatches run
            # back-to-back with no intervening collect (the second
            # chunk is enqueued while the first is still uncollected)
            kinds = [entry[0] for entry in log
                     if entry[0] in ("dispatch", "collect")]
            assert any(a == b == "dispatch"
                       for a, b in zip(kinds, kinds[1:])), kinds
            # the latency windows saw the requests
            lat = api.health.snapshot()["latency_ms"]
            assert lat["ttft"]["count"] >= 3
            assert lat["queue_wait"]["count"] >= 3
            assert lat["ttft"]["p95"] is not None
        finally:
            api.stop()

    def test_generate_api_http_roundtrip(self, model):
        """The LLM serving HTTP surface: concurrent POSTs batch into
        the slot pool, each answer equals single-request generate()."""
        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import GenerateAPI
        import jax.numpy as jnp

        params, table, heads, vocab = model
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=5, chunk=2, port=0)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port
            rng = numpy.random.RandomState(7)
            prompts = [rng.randint(0, vocab, n).tolist()
                       for n in (4, 6, 5)]
            results = {}

            def call(i):
                results[i] = post(url, {"tokens": prompts[i]},
                                  timeout=60)

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=90)
            for i, prompt in enumerate(prompts):
                want, _ = generate(params, table,
                                   jnp.asarray(prompt)[None], heads,
                                   n_tokens=5, max_len=32)
                assert results[i]["tokens"] == \
                    numpy.asarray(want)[0].tolist()
            # malformed requests 400 cleanly
            for payload in ({"tokens": []}, {"tokens": "x"},
                            {"tokens": [vocab + 5]},
                            {"tokens": [1], "n_tokens": 0},
                            {"tokens": list(range(3)) * 20}):
                with pytest.raises(urllib.error.HTTPError) as err:
                    post(url, payload)
                assert err.value.code == 400
        finally:
            api.stop()

    def test_generate_api_driver_failure_sheds_then_heals(self, model):
        """A device/runtime error in the driver loop must resolve every
        in-flight request with a retryable error (no 300 s timeout
        wedge), trip the breaker, and SELF-HEAL: the decoder is rebuilt
        from the held params and a retried request succeeds without a
        process restart (docs/serving_robustness.md)."""
        import time

        from veles_tpu.parallel.decode import generate
        from veles_tpu.serving import GenerateAPI
        import jax.numpy as jnp

        params, table, heads, vocab = model
        api = GenerateAPI(params, table, heads, slots=1, max_len=32,
                          n_tokens=4, chunk=2, port=0,
                          rebuild_backoff=0.02)
        api.start()
        try:
            url = "http://127.0.0.1:%d/generate" % api.port

            def boom(*a, **k):
                raise RuntimeError("injected device failure")

            api.decoder.dispatch_chunk = boom
            with pytest.raises(urllib.error.HTTPError) as err:
                post(url, {"tokens": [1, 2, 3]}, timeout=30)
            assert err.value.code == 503  # shed, retryable
            assert "injected device failure" in \
                err.value.read().decode()
            # the breaker tripped and the rebuild closes it again
            deadline = time.time() + 30
            while not api.health.ready and time.time() < deadline:
                time.sleep(0.02)
            assert api.health.ready, api.health.snapshot()
            snap = api.health.snapshot()
            assert snap["counters"]["trips"] == 1
            assert snap["counters"]["rebuilds"] == 1
            assert snap["counters"]["shed"] == 1
            # the rebuilt decoder serves correct tokens (the injected
            # failure died with the old decoder instance)
            out = post(url, {"tokens": [2, 3]}, timeout=60)
            want, _ = generate(params, table,
                               jnp.asarray([2, 3])[None], heads,
                               n_tokens=4, max_len=32)
            assert out["tokens"] == numpy.asarray(want)[0].tolist()
        finally:
            api.stop()
