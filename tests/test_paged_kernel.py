"""Fused Pallas paged-attention kernel (docs/paged_kv.md "The fused
kernel"): the kernel tier must stream bit-identical tokens to the
page-table gather path it replaces — proven on CPU via Pallas
interpret mode (bf16/f32 and int8-KV, staggered mid-flight joins,
shared-prefix tail and hit admissions) — plus the fast CPU invariants:
the capability-probe fallback matrix, the kernel math vs the masked
reference attend, the ragged admission path's single-dispatch /
no-duplication / exact-page-allocation contract, tile_pad waste
accounting with span/page overshoot pinned 0, and the warmed-sweep
zero-retrace guard under the existing ``paged.*`` program names.
`make paged-kernel` runs this file standalone (the interpret-mode
composites ride the `slow` marker so tier-1 keeps its timeout
margin)."""

import math

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.observe.xla_stats import get_compile_tracker
from veles_tpu.ops import paged_attention as pgatt
from veles_tpu.parallel.kv_pool import pages_for
from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.serving import ContinuousDecoder

pytestmark = pytest.mark.paged_kernel

PS = 8  # page size: tiny so short prompts span several pages


@pytest.fixture
def force_kernel():
    """Engage the kernel tier on CPU (Pallas interpret mode) and clear
    the jit caches both ways: the jitted paged step reads the probe at
    TRACE time, so a cached gather-path program would otherwise keep
    serving after the toggle."""
    prev = pgatt.FORCE_PAGED_KERNEL
    pgatt.FORCE_PAGED_KERNEL = True
    jax.clear_caches()
    yield
    pgatt.FORCE_PAGED_KERNEL = prev
    jax.clear_caches()


@pytest.fixture(scope="module")
def model():
    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads, vocab


class TestCapabilityProbe:
    """The ACT doctrine: accelerator codegen behind a probe with a
    portable fallback — FORCE toggle beats config beats backend auto."""

    def test_force_toggle_wins(self):
        prev = pgatt.FORCE_PAGED_KERNEL
        try:
            pgatt.FORCE_PAGED_KERNEL = True
            assert pgatt.use_paged_kernel() is True
            pgatt.FORCE_PAGED_KERNEL = False
            assert pgatt.use_paged_kernel() is False
        finally:
            pgatt.FORCE_PAGED_KERNEL = prev

    def test_config_layer_overrides_backend_auto(self):
        from veles_tpu.core.config import root
        prev = root.common.serve.get("paged_kernel", None)
        try:
            root.common.serve.paged_kernel = True
            assert pgatt.use_paged_kernel() is True
            root.common.serve.paged_kernel = False
            assert pgatt.use_paged_kernel() is False
        finally:
            root.common.serve.paged_kernel = prev

    def test_backend_auto_gathers_off_tpu(self):
        # the CPU test env: auto must fall back to the gather path
        assert jax.default_backend() == "cpu"
        assert pgatt.use_paged_kernel() is False

    def test_decoder_resolves_probe(self, model):
        params, table, heads, _ = model
        auto = ContinuousDecoder(params, table, heads, slots=2,
                                 max_len=32, paged=True, page_size=PS)
        assert auto.paged_kernel is False  # CPU backend auto
        forced = ContinuousDecoder(params, table, heads, slots=2,
                                   max_len=32, paged=True,
                                   page_size=PS, paged_kernel=True)
        assert forced.paged_kernel is True
        dense = ContinuousDecoder(params, table, heads, slots=2,
                                  max_len=32, paged_kernel=True)
        assert dense.paged_kernel is False  # meaningless without paged


class TestKernelMath:
    """paged_attend / paged_attend_int8 (interpret mode) vs the masked
    reference softmax over the gathered span — ragged lengths, scratch
    pages in the dead page-table tail."""

    def _problem(self, heads=4, head_dim=8, slots=3, pb=3,
                 pool_pages=10):
        rng = numpy.random.RandomState(7)
        q = rng.randn(slots, heads, head_dim).astype(numpy.float32)
        k = rng.randn(pool_pages, PS, heads, head_dim).astype(
            numpy.float32)
        v = rng.randn(pool_pages, PS, heads, head_dim).astype(
            numpy.float32)
        # live pages 1.. + SCRATCH_PAGE-padded dead tail, ragged
        # lengths crossing page boundaries (incl. length 0: position
        # 0 visible, the append-precedes-attend contract)
        page_table = numpy.zeros((slots, pb), numpy.int32)
        lengths = numpy.asarray([0, PS, 2 * PS + 3], numpy.int32)
        nxt = 1
        for s in range(slots):
            for p in range(int(lengths[s]) // PS + 1):
                page_table[s, p] = nxt
                nxt += 1
        return q, k, v, page_table, lengths

    @staticmethod
    def _reference(q, kg, vg, lengths):
        slots, span = kg.shape[0], kg.shape[1]
        mask = numpy.arange(span)[None, :] <= lengths[:, None]
        s = numpy.einsum("shd,skhd->shk", q, kg) \
            / math.sqrt(float(q.shape[-1]))
        s = numpy.where(mask[:, None, :], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = numpy.exp(s)
        p = p / p.sum(axis=-1, keepdims=True)
        return numpy.einsum("shk,skhd->shd", p, vg)

    def test_float_matches_reference(self):
        q, k, v, pt, lens = self._problem()
        out = pgatt.paged_attend(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), jnp.asarray(pt),
                                 jnp.asarray(lens), page_size=PS,
                                 interpret=True)
        kg = k[pt].reshape(3, -1, 4, 8)
        vg = v[pt].reshape(3, -1, 4, 8)
        ref = self._reference(q, kg, vg, lens)
        numpy.testing.assert_allclose(numpy.asarray(out), ref,
                                      rtol=1e-5, atol=1e-5)

    def test_int8_matches_dequant_reference(self):
        from veles_tpu.parallel.decode import _quantize_kv
        q, k, v, pt, lens = self._problem()
        # the pool's quantization: per-(page, position, head) over D,
        # then head-major (P, H, D, ps) quants + (P, H, ps) scales
        k8, ks = _quantize_kv(jnp.asarray(k))     # (P,ps,H,D), (P,ps,H)
        v8, vs = _quantize_kv(jnp.asarray(v))
        inv = 1.0 / math.sqrt(float(q.shape[-1]))
        out = pgatt.paged_attend_int8(
            jnp.asarray(q) * inv,
            jnp.transpose(k8, (0, 2, 3, 1)),
            jnp.transpose(ks, (0, 2, 1)),
            jnp.transpose(v8, (0, 2, 3, 1)),
            jnp.transpose(vs, (0, 2, 1)),
            jnp.asarray(pt), jnp.asarray(lens), page_size=PS,
            interpret=True)
        kd = numpy.asarray(k8, numpy.float32) \
            * numpy.asarray(ks)[..., None]
        vd = numpy.asarray(v8, numpy.float32) \
            * numpy.asarray(vs)[..., None]
        kg = kd[pt].reshape(3, -1, 4, 8)
        vg = vd[pt].reshape(3, -1, 4, 8)
        ref = self._reference(q, kg, vg, lens)
        numpy.testing.assert_allclose(numpy.asarray(out), ref,
                                      rtol=1e-4, atol=1e-4)


class TestTilePadAccounting:
    """The waste-plane satellite: the kernel's residual is the last
    partial page's dead lanes, never a silently-zeroed overshoot."""

    def test_tile_pad_tokens_matches_brute_force(self):
        from veles_tpu.parallel.decode import tile_pad_tokens
        rng = numpy.random.RandomState(0)
        for _ in range(25):
            lens = rng.randint(0, 40, size=3)
            ps = int(rng.choice([4, 8, 16]))
            chunk = int(rng.randint(1, 6))
            brute = 0
            for n in lens:
                for i in range(1, chunk + 1):
                    live = int(n) + i  # live to n+i-1, attends n+i pos
                    pages = (live - 1) // ps + 1
                    brute += pages * ps - live
            assert tile_pad_tokens(lens, ps, chunk) == brute

    def test_note_dispatch_books_tile_pad(self):
        from veles_tpu.observe.servescope import ServeScope
        scope = ServeScope()
        scope.note_dispatch(2, 4, 3, 11, 0.001, paged=True, pages=3,
                            kernel=True)
        assert scope.waste["tile_pad"] == 11
        assert scope.waste["page_overshoot"] == 0
        assert scope.waste["span_overshoot"] == 0
        # the accounting ring names the kernel mode
        assert scope.debug_snapshot()["dispatches"][-1][1] == "kernel"


class TestRaggedAdmission:
    """The pow2 ladder only exists to bound the gather path's jit
    cache: on the kernel path one mixed-length wave admits in ONE
    dispatch, no duplicate rows, each row owning exactly its pages."""

    def test_single_dispatch_exact_pages(self, model, force_kernel):
        params, table, heads, vocab = model
        rng = numpy.random.RandomState(2)
        dec = ContinuousDecoder(params, table, heads, slots=3,
                                max_len=32, n_tokens=4, paged=True,
                                page_size=PS)
        base = dict(dec.scope.waste)
        prompts = [rng.randint(0, vocab, n) for n in (3, 9, 17)]
        rids = [dec.submit(p, 2) for p in prompts]
        dec.step()
        # three bucket-distinct lengths, ONE ragged admission program
        assert dec.dispatch_counts["admit"] == 1
        assert dec.dispatch_counts["admit_requests"] == 3
        by_rid = {rid: prompt for rid, prompt in zip(rids, prompts)}
        for slot, rid in dec._slot_req.items():
            assert len(dec._slot_pages[slot]) == \
                pages_for(len(by_rid[rid]), PS)
        waste = {k: v - base.get(k, 0)
                 for k, v in dec.scope.waste.items()}
        assert waste["group_dup"] == 0
        # width = page-rounded max (17 -> 24): residual pad only
        assert waste["bucket_pad"] == (24 - 3) + (24 - 9) + (24 - 17)
        assert waste["span_overshoot"] == 0
        assert waste["page_overshoot"] == 0
        assert waste["tile_pad"] > 0

    def test_tail_allocates_exact_pages(self, model, force_kernel):
        params, table, heads, vocab = model
        rng = numpy.random.RandomState(3)
        system = rng.randint(0, vocab, 2 * PS)
        extended = numpy.concatenate(
            [system, rng.randint(0, vocab, 3)])
        dec = ContinuousDecoder(params, table, heads, slots=2,
                                max_len=48, n_tokens=2, paged=True,
                                page_size=PS)
        dec.submit(system, 2)
        dec.run_until_drained()
        rid = dec.submit(extended, 2)
        dec.step()
        assert dec.dispatch_counts["admit_tail"] == 1
        slot = next(s for s, r in dec._slot_req.items() if r == rid)
        # 2 shared prefix pages + exactly ONE ragged tail page (the
        # gather ladder would round the 3-token tail to its bucket)
        assert len(dec._slot_pages[slot]) == 3


@pytest.mark.slow
class TestKernelBitIdentity:
    """The acceptance composite: the kernel tier must reproduce the
    gather path's streams exactly — and both must equal greedy
    generate() — through staggered mid-flight joins and shared-prefix
    tail/hit admissions, on both KV tiers (interpret mode: emulated
    but bit-faithful kernel semantics)."""

    def _drive(self, model, quantize, force):
        params, table, heads, vocab = model
        prev = pgatt.FORCE_PAGED_KERNEL
        pgatt.FORCE_PAGED_KERNEL = force
        jax.clear_caches()
        try:
            rng = numpy.random.RandomState(1)
            prompts = [rng.randint(0, vocab, n)
                       for n in (5, 3, 16, 4, 9)]
            dec = ContinuousDecoder(params, table, heads, slots=2,
                                    max_len=32, n_tokens=6,
                                    quantize=quantize, paged=True,
                                    page_size=PS)
            base = dict(dec.scope.waste)
            pending = list(prompts)
            for _ in range(2):
                dec.submit(pending.pop(0))
            dec.drain_pipelined(
                4, admit=lambda dec=dec, pending=pending:
                    pending and dec.submit(pending.pop(0)))
            # shared-prefix families: the page-aligned prompt 2 (len
            # 16) re-admits as a HIT, its 3-token extension as a TAIL
            # (bf16 only: the int8 pool takes exact hits only)
            extra = [numpy.asarray(prompts[2])]
            if quantize is None:
                extra.append(numpy.concatenate(
                    [prompts[2], rng.randint(0, vocab, 3)]))
            for p in extra:
                dec.submit(p, 4)
            dec.run_until_drained(chunk=4)
            waste = {k: v - base.get(k, 0)
                     for k, v in dec.scope.waste.items()}
            return dec, prompts + extra, waste
        finally:
            pgatt.FORCE_PAGED_KERNEL = prev
            jax.clear_caches()

    @pytest.mark.parametrize("quantize", [None, "int8-kv"])
    def test_composite_matches_gather_and_generate(self, model,
                                                   quantize):
        from veles_tpu.parallel.decode import generate

        params, table, heads, vocab = model
        gather, prompts, w_gather = self._drive(model, quantize, False)
        kernel, _, w_kernel = self._drive(model, quantize, True)
        assert gather.results == kernel.results
        assert kernel.dispatch_counts["admit_hit"] >= 1
        if quantize is None:
            assert kernel.dispatch_counts["admit_tail"] >= 1
        for rid, prompt in enumerate(prompts):
            n = 6 if rid < 5 else 4
            want, _ = generate(params, table,
                               jnp.asarray(prompt)[None], heads,
                               n_tokens=n, max_len=32,
                               quantize=quantize)
            assert kernel.results[rid] == \
                numpy.asarray(want)[0][:len(kernel.results[rid])] \
                .tolist()
        # the acceptance counters: overshoot structurally deleted,
        # the residual booked honestly as tile_pad
        assert w_kernel["span_overshoot"] == 0
        assert w_kernel["page_overshoot"] == 0
        assert w_kernel["tile_pad"] > 0
        assert w_gather["page_overshoot"] > 0
        assert w_kernel["bucket_pad"] < w_gather["bucket_pad"]
        assert w_kernel["group_dup"] == 0


@pytest.mark.slow
class TestKernelDispatchEconomy:
    """The kernel tier rides the SAME paged.* program names: six
    same-shape waves through the ragged admission + kernel step must
    compile each program at most twice with zero recompile storms —
    veles_xla_compiles_total{paged.*} stays flat across a warmed
    sweep."""

    def test_warmed_sweep_zero_storms(self, model, force_kernel):
        params, table, heads, vocab = model
        waves = 6
        tracker = get_compile_tracker()
        was_enabled = tracker.enabled
        tracker.reset()
        tracker.enabled = True
        try:
            rng = numpy.random.RandomState(6)
            dec = ContinuousDecoder(params, table, heads, slots=2,
                                    max_len=32, n_tokens=4,
                                    paged=True, page_size=PS)
            for _ in range(waves):
                for _ in range(2):
                    dec.submit(rng.randint(0, vocab, 6))
                dec.run_until_drained(chunk=4)
            snap = tracker.snapshot()
        finally:
            tracker.reset()
            tracker.enabled = was_enabled
        assert sum(snap["storms"].values()) == 0
        assert dec.dispatch_counts["admit"] <= waves
        assert dec.dispatch_counts["admit_requests"] == 2 * waves
        for program in ("paged.admit", "paged.dispatch"):
            compiles = snap["compiles"].get(program, 0)
            hits = snap["hits"].get(program, 0)
            assert compiles <= 2, \
                "%s retraced %d times over %d same-shape waves" % (
                    program, compiles, waves)
            assert hits >= waves - 2, \
                "%s only hit %d times" % (program, hits)
