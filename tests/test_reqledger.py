"""Request-truth ledger + SLO engine tests (docs/observability.md,
ISSUE 10): bounded-memory ring semantics, the stage-ordering invariant
on a REAL GenerateAPI request, SLO window math and per-tenant labels,
AOT dispatch attribution, the ``/debug/requests`` + fleet-piggyback
round trip, and the chaos acceptance — a seeded slow-step run produces
a nonzero burn rate and an autopsy naming the stall stage. ``make
slo`` runs this module standalone."""

import json
import urllib.request

import numpy
import pytest

from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.observe.reqledger import (STAGES, RequestLedger,
                                         autopsy, format_waterfall,
                                         widest_gap)
from veles_tpu.observe.slo import (SLOEngine, observe_request,
                                   parse_objectives, row_latencies)

pytestmark = pytest.mark.slo


def get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def post(url, payload, headers=None, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


@pytest.fixture(scope="module")
def model():
    from veles_tpu.parallel.transformer_step import (
        init_transformer_params)
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    heads, embed, vocab = 4, 16, 11
    params = init_transformer_params(rng, 2, embed, heads, vocab)
    table = jnp.asarray(
        rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
    return params, table, heads, vocab


@pytest.fixture
def registry(monkeypatch):
    """Fresh-enough process registry: reset before and after, so the
    SLO bridge asserts see only this test's families."""
    from veles_tpu.observe.metrics import get_metrics_registry

    reg = get_metrics_registry()
    was = reg.enabled
    reg.reset()
    reg.enable()
    yield reg
    reg.reset()
    reg.enabled = was


def serve_api(model, **kwargs):
    from veles_tpu.serving import GenerateAPI

    params, table, heads, _ = model
    kwargs.setdefault("ledger", RequestLedger())
    return GenerateAPI(params, table, heads, slots=2, max_len=32,
                       n_tokens=4, chunk=2, port=0, **kwargs)


class TestLedgerRing:
    def test_resolved_ring_bounded_drop_oldest(self):
        ledger = RequestLedger(capacity=4)
        for i in range(10):
            row = ledger.stage(api="t", prompt_len=i)
            ledger.resolve(row, "completed")
        slowest = ledger.slowest(100)
        assert len(slowest) == 4
        assert {r["prompt_len"] for r in slowest} == {6, 7, 8, 9}
        assert ledger.resolved_total == 10
        assert ledger.inflight() == []

    def test_inflight_map_bounded_drop_oldest(self):
        ledger = RequestLedger(inflight_cap=3)
        rows = [ledger.stage(api="t", prompt_len=i) for i in range(5)]
        live = ledger.inflight()
        assert len(live) == 3
        assert [r["prompt_len"] for r in live] == [2, 3, 4]
        assert ledger.dropped_total == 2
        ledger.link(rows[4], 42)
        assert rows[4]["rid"] == 42

    def test_chunk_cadence_bounded(self):
        ledger = RequestLedger(chunk_cap=2)
        row = ledger.stage(api="t")
        ledger.link(row, 1)
        for _ in range(5):
            ledger.note_tokens(row, 2)
        assert len(row["chunks"]) == 2
        assert row["chunks_dropped"] == 3
        assert row["tokens"] == 10  # counting never stops

    def test_unlinked_row_hooks_are_noops(self):
        """An unlinked rid resolves to row=None in the decoder's
        map (direct submits, breaker probes) — every hook is a
        no-op."""
        ledger = RequestLedger()
        ledger.note_admit(None, "dense")
        ledger.note_tokens(None, 3)
        assert ledger.inflight() == [] and ledger.slowest(4) == []

    def test_resolve_is_exactly_once(self):
        ledger = RequestLedger()
        row = ledger.stage(api="t")
        ledger.resolve(row, "completed")
        ledger.resolve(row, "errors", error="late")
        (resolved,) = ledger.slowest(4)
        assert resolved["outcome"] == "completed"
        assert resolved["error"] is None
        assert ledger.resolved_total == 1

    def test_disabled_ledger_stages_nothing(self):
        ledger = RequestLedger(enabled=False)
        assert ledger.stage(api="t") is None
        ledger.mark(None, "pool_gated")  # None rows never branch
        ledger.link(None, 1)
        ledger.resolve(None, "completed")
        assert ledger.staged_total == 0


class TestStageOrdering:
    def test_real_request_carries_complete_ordered_waterfall(
            self, model):
        """The acceptance shape: after a GenerateAPI warmup every
        request row carries a COMPLETE waterfall — canonical stage
        order, monotone stamps, a chunk cadence summing to the token
        budget, dense live-dispatch attribution."""
        ledger = RequestLedger()
        api = serve_api(model, ledger=ledger)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            for _ in range(3):
                body = post(url + "/generate",
                            {"tokens": [1, 2, 3]},
                            headers={"X-Veles-Tenant": "acme"})
                assert len(body["tokens"]) == 4
        finally:
            api.stop()
        rows = ledger.slowest(8)
        assert len(rows) == 3
        order = {stage: i for i, stage in enumerate(STAGES)}
        for row in rows:
            names = [s[0] for s in row["stages"]]
            # complete: every canonical stage of the dense path
            assert names == ["staged", "admitted", "first_token",
                             "resolved"], names
            stamps = [s[1] for s in row["stages"]]
            assert stamps == sorted(stamps)
            assert [order[n] for n in names] == sorted(
                order[n] for n in names)
            assert row["outcome"] == "completed"
            assert row["tenant"] == "acme"
            assert row["tokens"] == 4
            assert sum(c[1] for c in row["chunks"]) == 4
            assert row["admit"]["kind"] == "dense"
            assert row["admit"]["program"] == "decode.admit"
            assert row["quant"] == "bf16"
            assert row["breaker_gen"] == 0
            # live-compiled serving: zero aot dispatches, >= admit +
            # one chunk live
            assert row["dispatches"]["aot"] == 0
            assert row["dispatches"]["live"] >= 2
            ttft, tpot = row_latencies(row)
            assert ttft is not None and ttft >= 0
            assert tpot is not None and tpot >= 0


class TestSLOWindows:
    def test_window_math_ratio_budget_and_burn(self):
        """80 good / 20 bad against a 0.9 availability target in one
        window: ratio 0.8, burn 2.0 (erring at twice the sustainable
        rate), budget remaining -1.0 (overdrawn)."""
        engine = SLOEngine({"availability": 0.9}, windows=(60.0,),
                           bucket_seconds=10.0)
        for i in range(100):
            engine.record(ok=i < 80, now=1000.0 + i * 0.1)
        (row,) = engine.gauges(now=1010.0)
        assert row["objective"] == "availability"
        assert row["window"] == "60s" and row["count"] == 100
        assert row["ratio"] == pytest.approx(0.8)
        assert row["burn_rate"] == pytest.approx(2.0)
        assert row["error_budget_remaining"] == pytest.approx(-1.0)

    def test_rolling_windows_age_out(self):
        """Bad traffic older than the window stops burning it; the
        longer window still sees it — the multi-window split."""
        engine = SLOEngine({"ttft_p95_ms": 100.0},
                           windows=(60.0, 600.0), bucket_seconds=10.0)
        for i in range(10):  # old, slow
            engine.record(ttft_s=0.5, ok=True, now=1000.0 + i)
        for i in range(10):  # recent, fast
            engine.record(ttft_s=0.01, ok=True, now=1300.0 + i)
        rows = {r["window"]: r for r in engine.gauges(now=1310.0)}
        assert rows["60s"]["ratio"] == pytest.approx(1.0)
        assert rows["60s"]["burn_rate"] == pytest.approx(0.0)
        assert rows["600s"]["ratio"] == pytest.approx(0.5)
        assert rows["600s"]["burn_rate"] == pytest.approx(10.0)

    def test_latency_objective_counts_failures_as_bad(self):
        """A FAILED request without a latency signal counts AGAINST
        every latency objective (it never produced its tokens); a
        COMPLETED request without a tpot signal (single-chunk stream)
        is simply not counted against tpot."""
        engine = SLOEngine({"ttft_p95_ms": 100.0, "tpot_p95_ms": 10.0},
                           windows=(60.0,))
        engine.record(ttft_s=None, tpot_s=None, ok=False, now=100.0)
        engine.record(ttft_s=0.01, tpot_s=None, ok=True, now=101.0)
        rows = {r["objective"]: r for r in engine.gauges(now=102.0)}
        assert rows["ttft_p95_ms"]["count"] == 2
        assert rows["ttft_p95_ms"]["ratio"] == pytest.approx(0.5)
        # only the failure counted: the completed no-signal request
        # did not, so the tpot ratio is 0/1
        assert rows["tpot_p95_ms"]["count"] == 1
        assert rows["tpot_p95_ms"]["ratio"] == pytest.approx(0.0)

    def test_per_tenant_labels_and_cardinality_cap(self):
        engine = SLOEngine({"availability": 0.99}, windows=(60.0,),
                           tenant_cap=2)
        engine.record(ok=True, tenant="a", now=100.0)
        engine.record(ok=False, tenant="b", now=100.0)
        engine.record(ok=True, tenant="hostile-1", now=100.0)
        engine.record(ok=True, tenant="hostile-2", now=100.0)
        rows = engine.gauges(now=101.0)
        tenants = {r["tenant"] for r in rows}
        assert tenants == {None, "a", "b", "other"}
        aggregate = [r for r in rows if r["tenant"] is None]
        assert aggregate[0]["count"] == 4
        registry = MetricsRegistry(enabled=True)
        engine.publish(registry, now=101.0)
        text = registry.expose()
        assert 'veles_slo_burn_rate{objective="availability"' \
            ',tenant="b",window="60s"}' in text
        assert 'veles_slo_objective_ratio{objective="availability"' \
            ',window="60s"} 0.75' in text

    def test_emptied_windows_stop_exporting_stale_gauges(self):
        """Review finding: publish() REPLACES the sample sets, so a
        burn rate from an incident two hours ago must not keep firing
        the pager after traffic stops — the gauges retire with the
        window, like /healthz's summary."""
        engine = SLOEngine({"availability": 0.9}, windows=(60.0,))
        engine.record(ok=False, tenant="acme", now=1000.0)
        registry = MetricsRegistry(enabled=True)
        engine.publish(registry, now=1005.0)
        hot = registry.expose()
        assert "veles_slo_burn_rate" in hot and 'tenant="acme"' in hot
        assert engine.summary(now=1005.0)["burn_rate"] > 0
        engine.publish(registry, now=1000.0 + 7200.0)
        cold = registry.expose()
        assert "veles_slo_" not in cold
        assert engine.summary(now=1000.0 + 7200.0) is None

    def test_tenant_slice_retires_with_its_windows(self):
        """Governor-PR satellite, beside the frozen-burn-rate guard
        above: a tenant whose windows ALL emptied retires in the same
        pruning pass as the global buckets — its gauges stop exporting
        AND its cardinality-cap slot frees. Previously only the global
        path was pinned: a long-dead tenant pinned the cap forever and
        every new tenant folded into "other"."""
        engine = SLOEngine({"availability": 0.9}, windows=(60.0,),
                           tenant_cap=1)
        engine.record(ok=False, tenant="acme", now=1000.0)
        registry = MetricsRegistry(enabled=True)
        engine.publish(registry, now=1005.0)
        assert 'tenant="acme"' in registry.expose()
        # global traffic continues two hours later; acme's windows all
        # emptied — the same record() pruning pass retires the slice
        engine.record(ok=True, now=1000.0 + 7200.0)
        engine.publish(registry, now=1000.0 + 7200.0)
        text = registry.expose()
        assert "veles_slo_burn_rate" in text  # global still exports
        assert "tenant=" not in text          # the slice retired
        # the freed cap slot serves the NEXT tenant, not "other"
        engine.record(ok=True, tenant="fresh", now=1000.0 + 7201.0)
        tenants = {row["tenant"]
                   for row in engine.gauges(now=1000.0 + 7202.0)}
        assert "fresh" in tenants
        assert "other" not in tenants
        assert "acme" not in tenants

    def test_objective_parsing_rejects_garbage_naming_the_flag(self):
        assert parse_objectives(None) == []
        parsed = parse_objectives("ttft_p95_ms=250, availability=0.999",
                                  flag="--serve-slo")
        assert [(o.name, o.target) for o in parsed] == [
            ("availability", 0.999), ("ttft_p95_ms", 0.95)]
        assert parsed[1].threshold_s == pytest.approx(0.25)
        for bad in ("latency=5", "ttft_p95_ms=nope", "ttft_p0_ms=5",
                    "availability=2", "oops"):
            with pytest.raises(ValueError, match="--serve-slo"):
                parse_objectives(bad, flag="--serve-slo")


class TestAotAttribution:
    def test_rows_book_aot_served_dispatches(self, model):
        """The facade's last-dispatch record flows into the rows: a
        decoder whose dispatches are served from an AOT bundle books
        them under ``dispatches.aot`` (the acceptance pairs this with
        veles_xla_compiles_total staying flat — pinned end to end in
        tests/test_aot.py)."""
        from veles_tpu.serving import ContinuousDecoder

        params, table, heads, _ = model
        ledger = RequestLedger()
        dec = ContinuousDecoder(params, table, heads, slots=1,
                                max_len=32, n_tokens=4, ledger=ledger)

        class FacadeStub:
            """Delegates to the live fns, flagging aot-served."""

            def __init__(self, decoder):
                self._dec = decoder
                self.last_dispatch = None

            def admit(self, *args, **kwargs):
                from veles_tpu.parallel.decode import slot_admit_many
                self.last_dispatch = ("decode.admit", True)
                return slot_admit_many(*args, **kwargs)

            def step_many(self, *args, **kwargs):
                from veles_tpu.parallel.decode import slot_step_many
                self.last_dispatch = ("decode.dispatch", True)
                return slot_step_many(*args, **kwargs)

        dec._aot = FacadeStub(dec)
        rid = dec.submit([1, 2, 3])
        row = ledger.stage(api="aot-test", prompt_len=3)
        dec.ledger_link(rid, row)
        dec.run_until_drained(max_steps=8, chunk=2)
        ledger.resolve(row, "completed")
        assert row["admit"] == {"kind": "dense", "group": 1,
                                "bucket": 16, "aot": True,
                                "program": "decode.admit"}
        assert row["dispatches"]["aot"] >= 2
        assert row["dispatches"]["live"] == 0
        assert row["tokens"] == 4


class TestDebugSurfaceAndPiggyback:
    def test_debug_requests_and_slo_piggyback_round_trip(
            self, model, registry):
        """The surface pair: ``GET /debug/requests`` returns the live
        ledger view; the SLO gauges land in the process registry's
        snapshot (the EXACT payload a fleet slave piggybacks on update
        frames) and re-export slave-labeled on a master registry."""
        from veles_tpu.observe.metrics import COUNTER

        engine = SLOEngine({"ttft_p95_ms": 10000.0,
                            "availability": 0.999})
        api = serve_api(model, slo=engine)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            for _ in range(2):
                post(url + "/generate", {"tokens": [1, 2]},
                     headers={"X-Veles-Tenant": "acme"})
            dbg = json.loads(get(url + "/debug/requests?n=1"))
            assert dbg["resolved_total"] == 2
            assert len(dbg["slowest"]) == 1  # ?n= honored
            row = dbg["slowest"][0]
            assert [s[0] for s in row["stages"]] == [
                "staged", "admitted", "first_token", "resolved"]
            assert row["tenant"] == "acme"
            # the SLO gauges ride the piggyback payload...
            snapshot = registry.snapshot()
            slo_rows = [r for r in snapshot
                        if str(r[0]).startswith("veles_slo_")]
            names = {r[0] for r in slo_rows}
            assert names == {"veles_slo_objective_ratio",
                             "veles_slo_error_budget_remaining",
                             "veles_slo_burn_rate"}
            tenants = {dict(r[2]).get("tenant") for r in slo_rows}
            assert "acme" in tenants
        finally:
            api.stop()
        # ...and re-export slave-labeled on the master side (the
        # publish_fleet ingestion rule, payload-level round trip)
        master = MetricsRegistry(enabled=True)
        for name, kind, labels, value in slo_rows:
            merged = dict(labels)
            merged["slave"] = "s1"
            if kind == COUNTER:
                master.counter_set(name, value, labels=merged)
            else:
                master.set(name, value, labels=merged)
        text = master.expose()
        assert 'veles_slo_burn_rate{objective="availability"' in text
        assert 'slave="s1"' in text

    def test_healthz_shows_tpot_and_burn(self, model, registry):
        engine = SLOEngine({"availability": 0.5})
        api = serve_api(model, slo=engine)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            post(url + "/generate", {"tokens": [1, 2, 3]})
            health = json.loads(get(url + "/healthz"))
            assert "tpot" in health["latency_ms"]
            assert health["latency_ms"]["tpot"]["count"] >= 1
            assert health["slo"]["objective"] == "availability"
            assert health["slo"]["burn_rate"] == 0.0
            # the request histograms carry the api label
            metrics = get(url + "/metrics")
            assert 'veles_request_ttft_seconds_count' \
                '{api="generate-api"} 1' in metrics
            assert 'veles_request_tpot_seconds_count' \
                '{api="generate-api"} 1' in metrics
        finally:
            api.stop()


class TestChaosAutopsy:
    def test_slow_step_chaos_burns_budget_and_names_the_stall(
            self, model, registry, tmp_path, capsys):
        """The ISSUE acceptance: a seeded slow-step chaos run produces
        a NONZERO veles_slo_burn_rate, and the slowest-request autopsy
        waterfall names the injected stall stage (a decode-side gap —
        never the staging bookkeeping)."""
        from veles_tpu.observe.trace_export import main as observe_main
        from veles_tpu.serving_chaos import (ServingChaosConfig,
                                             ServingChaosMonkey)

        chaos = ServingChaosMonkey(ServingChaosConfig(
            seed=3, slow_step=1.0, slow_step_ms=40.0))
        engine = SLOEngine({"ttft_p95_ms": 1.0})  # unmeetable
        ledger = RequestLedger()
        api = serve_api(model, slo=engine, ledger=ledger, chaos=chaos)
        api.start()
        try:
            url = "http://127.0.0.1:%d" % api.port
            post(url + "/generate", {"tokens": [1, 2]})  # warm compile
            for _ in range(2):
                post(url + "/generate", {"tokens": [1, 2, 3]})
            assert chaos.counters["steps_slowed"] > 0
            metrics = get(url + "/metrics")
            burn = [line for line in metrics.splitlines()
                    if line.startswith("veles_slo_burn_rate")
                    and 'objective="ttft_p95_ms"' in line
                    and 'window="60s"' in line
                    and "tenant" not in line]
            assert burn, metrics
            assert float(burn[0].rsplit(" ", 1)[1]) > 0
            saved = tmp_path / "requests.json"
            saved.write_text(get(url + "/debug/requests"))
        finally:
            api.stop()
        # the post-warmup rows stall in the decode path, not staging
        row = ledger.slowest(8)[-1]  # the fastest = a warmed request
        label, ms = widest_gap(row)
        stall_end = label.split("→")[1]
        assert stall_end in ("admitted", "first_token", "resolved") \
            or stall_end.startswith("decode["), (label, ms)
        assert stall_end != "pool_gated"
        assert ms >= 30.0, (label, ms)
        text = format_waterfall(row)
        assert "<-- stall" in text and stall_end in text
        # the autopsy CLI reads the saved /debug/requests payload
        assert observe_main(["slo", str(saved)]) == 0
        out = capsys.readouterr().out
        assert "slowest resolved" in out
        assert "<-- stall" in out

    def test_web_status_cell_renders_burn_and_tpot(self):
        """The dashboard satellite: the serving cell shows the worst
        short-window burn rate and the new tpot p95 beside the
        survival counters."""
        from veles_tpu.web_status import format_serving_health

        cell = format_serving_health({
            "ready": True,
            "latency_ms": {"tpot": {"p50": 1.2, "p95": 3.4,
                                    "count": 9}},
            "slo": {"burn_rate": 2.3, "objective": "ttft_p95_ms",
                    "window": "60s"}})
        assert "tpot p95 3.4ms" in cell
        assert "burn 2.3x (ttft_p95_ms/60s)" in cell
        # no slo summary, no burn cell — never a "burn 0.0x" banner
        assert "burn" not in format_serving_health({"ready": True})

    def test_cli_reads_blackbox_dumps(self, tmp_path, capsys):
        """``observe slo`` also autopsies flight-recorder dumps (the
        breaker-trip artifact): rows + any veles_slo_* metric rows."""
        import veles_tpu.observe.reqledger as reqledger_mod
        from veles_tpu.observe.flight import FlightRecorder
        from veles_tpu.observe.trace_export import main as observe_main

        ledger = RequestLedger()
        saved_ledger = reqledger_mod._ledger
        reqledger_mod._ledger = ledger
        try:
            row = ledger.stage(api="generate-api", trace="fade01",
                               prompt_len=4)
            ledger.link(row, 0)
            ledger.note_admit(row, "dense", group=1, bucket=16)
            ledger.note_tokens(row, 2)
            ledger.resolve(row, "shed", error="breaker open")
            recorder = FlightRecorder()
            path = recorder.dump(
                "breaker_trip", path=str(tmp_path / "box.json"))
        finally:
            reqledger_mod._ledger = saved_ledger
        assert observe_main(["slo", path]) == 0
        out = capsys.readouterr().out
        assert "outcome=shed" in out
        assert "trace=fade01" in out
        assert observe_main(["slo", str(tmp_path / "nope.json")]) == 1
