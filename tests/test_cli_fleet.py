"""Two-PROCESS CLI fleet tests.

The in-process loopback tests (test_fleet.py) share one interpreter, so a
launcher/shutdown-routing bug can hide: the round-2 slave-exits-after-
first-job bug passed every in-process test because nothing stopped the
agent thread early. These tests run the real ``python -m veles_tpu`` CLI
for master and slave as subprocesses — the actual product invocation."""

import json
import os
import subprocess
import sys
import time

import pytest

WF = """
import numpy
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(300, 8).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(8, 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 60, 240],
                            minibatch_size=60),
         learning_rate=0.3, max_epochs=2)
    main()
"""


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no 8-device mesh needed; faster startup
    env["VELES_TPU_FLEET_SECRET"] = "cli-test"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_cli_master_slave_roundtrip(tmp_path):
    """Regression: a CLI slave must serve jobs until the MASTER is done,
    not exit after its first job's on_workflow_finished."""
    wf_file = tmp_path / "wf.py"
    wf_file.write_text(WF)
    result_file = tmp_path / "res.json"
    env = _env()
    # a kernel-assigned free port: a constant would collide across
    # concurrent runs (in-process tests bind :0 for the same reason)
    import socket
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    master = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", str(wf_file), "-",
         "-l", "127.0.0.1:%d" % port,
         "--result-file", str(result_file)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        time.sleep(5)
        slave = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", str(wf_file), "-",
             "-m", "127.0.0.1:%d" % port],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        assert master.wait(timeout=180) == 0
        assert slave.wait(timeout=60) == 0
    finally:
        for proc in (master, locals().get("slave")):
            if proc is not None and proc.poll() is None:
                proc.kill()
    results = json.loads(result_file.read_text())
    assert results["epochs"] == 2
    assert results["best_validation_errors"] is not None


@pytest.mark.slow
def test_cli_nodes_spawns_local_slave(tmp_path):
    """-n localhost: the master spawns its own slave at startup
    (reference SSH slave launch; localhost runs a detached subprocess)
    and training completes without any manual slave invocation."""
    wf_file = tmp_path / "wf.py"
    wf_file.write_text(WF)
    result_file = tmp_path / "res.json"
    import socket
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    master = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", str(wf_file), "-",
         "-l", "127.0.0.1:%d" % port, "-n", "localhost",
         "--result-file", str(result_file)],
        env=_env(), stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        cwd=str(tmp_path))
    try:
        assert master.wait(timeout=240) == 0
    finally:
        if master.poll() is None:
            master.kill()
    results = json.loads(result_file.read_text())
    assert results["epochs"] == 2


def test_nodes_argv_transform_edge_forms():
    """The master->slave argv transform must strip --opt=value and fused
    -lVALUE forms too — a surviving --listen would make the 'slave' a
    second master that recursively spawns and never connects."""
    from unittest import mock
    from veles_tpu.launcher import Launcher

    lau = Launcher(listen_address="127.0.0.1:0", nodes=["localhost"])

    class FakeAgent:
        host, port = "127.0.0.1", 5050

    lau.agent = FakeAgent()
    with mock.patch("veles_tpu.fleet.respawn.respawn_recipe") as rec, \
            mock.patch("veles_tpu.fleet.respawn.default_spawner") as sp:
        rec.return_value = {
            "executable": "/usr/bin/python3",
            "argv": ["-m", "veles_tpu", "wf.py", "--listen=0.0.0.0:5050",
                     "--nodes=host1", "-l127.0.0.1:1",
                     "--result-file=r.json", "--respawn", "-b"],
            "cwd": "/tmp", "pythonpath": ""}
        lau._launch_nodes()
        cmd = sp.call_args[0][1]
    assert "--listen" not in cmd and "--nodes" not in cmd
    assert "-l127" not in cmd and "--result-file" not in cmd
    # --respawn KEPT (the slave must ship its relaunch recipe); -b
    # dropped (the spawner already detaches)
    assert "--respawn" in cmd and " -b" not in cmd
    assert cmd.endswith("-m 127.0.0.1:5050")
