"""Two-PROCESS CLI fleet tests.

The in-process loopback tests (test_fleet.py) share one interpreter, so a
launcher/shutdown-routing bug can hide: the round-2 slave-exits-after-
first-job bug passed every in-process test because nothing stopped the
agent thread early. These tests run the real ``python -m veles_tpu`` CLI
for master and slave as subprocesses — the actual product invocation."""

import json
import os
import subprocess
import sys
import time

import pytest

WF = """
import numpy
from veles_tpu.models.mlp import MLPWorkflow

def run(load, main):
    rng = numpy.random.RandomState(0)
    X = rng.rand(300, 8).astype(numpy.float32)
    y = (X[:, 0] > 0.5).astype(numpy.int32)
    load(MLPWorkflow, layers=(8, 2),
         loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 60, 240],
                            minibatch_size=60),
         learning_rate=0.3, max_epochs=2)
    main()
"""


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no 8-device mesh needed; faster startup
    env["VELES_TPU_FLEET_SECRET"] = "cli-test"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_cli_master_slave_roundtrip(tmp_path):
    """Regression: a CLI slave must serve jobs until the MASTER is done,
    not exit after its first job's on_workflow_finished."""
    wf_file = tmp_path / "wf.py"
    wf_file.write_text(WF)
    result_file = tmp_path / "res.json"
    env = _env()
    # a kernel-assigned free port: a constant would collide across
    # concurrent runs (in-process tests bind :0 for the same reason)
    import socket
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    master = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", str(wf_file), "-",
         "-l", "127.0.0.1:%d" % port,
         "--result-file", str(result_file)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        time.sleep(5)
        slave = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu", str(wf_file), "-",
             "-m", "127.0.0.1:%d" % port],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        assert master.wait(timeout=180) == 0
        assert slave.wait(timeout=60) == 0
    finally:
        for proc in (master, locals().get("slave")):
            if proc is not None and proc.poll() is None:
                proc.kill()
    results = json.loads(result_file.read_text())
    assert results["epochs"] == 2
    assert results["best_validation_errors"] is not None
