"""AOT compiled-program artifacts (docs/aot_artifacts.md): bundles of
jax.export'd StableHLO must reload with ZERO retracing and serve
bit-identical tokens — dense + paged, bf16 + int8-KV, single-chip and
the 8-device CPU mesh — behind a strict compatibility gate that refuses
stale artifacts by field name and falls back to live compilation.
`make aot` runs this file standalone."""

import hashlib
import io
import json
import os
import tarfile
import urllib.request

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.aot.artifact import (BundleBuilder, build_serving_bundle,
                                    capture_tick_programs,
                                    inspect_bundle, read_bundle)
from veles_tpu.aot.loader import (AotCompatError, check_compat,
                                  install_fused_tick, load_bundle)
from veles_tpu.observe.xla_stats import get_compile_tracker
from veles_tpu.parallel.transformer_step import init_transformer_params
from veles_tpu.serving import ContinuousDecoder, GenerateAPI

pytestmark = pytest.mark.aot

HEADS, EMBED, BLOCKS, VOCAB = 4, 16, 2, 32
#: the dense serving shape every bundle here mirrors
DENSE_KW = dict(slots=3, max_len=64, n_tokens=6, tile=16)
CHUNK = 4


@pytest.fixture(scope="module")
def model():
    rng = numpy.random.RandomState(0)
    params = init_transformer_params(rng, BLOCKS, EMBED, HEADS, VOCAB)
    table = jnp.asarray(
        rng.randn(VOCAB, EMBED).astype(numpy.float32) * 0.3)
    return params, table


@pytest.fixture(scope="module")
def dense_bundle(model, tmp_path_factory):
    params, table = model
    path = str(tmp_path_factory.mktemp("aot") / "dense.aot.tar")
    build_serving_bundle(params, table, HEADS, path, chunk=CHUNK,
                         **DENSE_KW)
    return path


def _prompts(n=7, seed=3):
    rng = numpy.random.RandomState(seed)
    return [rng.randint(0, VOCAB, k)
            for k in (5, 9, 3, 7, 6, 11, 4)[:n]]


def _drain(dec, prompts):
    pending = list(prompts)
    for _ in range(min(3, len(pending))):
        dec.submit(pending.pop(0))
    dec.drain_pipelined(
        CHUNK, admit=lambda: pending and dec.submit(pending.pop(0)))
    return dec


class TestBundleFormat:
    def test_sha_addressed_members_and_sidecar(self, dense_bundle):
        manifest, members = read_bundle(dense_bundle)
        assert manifest["kind"] == "veles-aot-bundle"
        for row in manifest["programs"]:
            blob = members[row["member"]]
            assert row["member"] == "programs/%s" \
                % hashlib.sha256(blob).hexdigest()
        info = inspect_bundle(dense_bundle)
        assert info["programs"] == len(manifest["programs"]) > 0
        assert os.path.isfile(dense_bundle + ".sha256")

    def test_build_twice_same_sha(self, model, tmp_path):
        """The sha-addressed store's dedup contract: two builds of the
        same configuration are byte-identical."""
        params, table = model
        digests = []
        for name in ("a.tar", "b.tar"):
            path = str(tmp_path / name)
            build_serving_bundle(params, table, HEADS, path,
                                 chunk=CHUNK, buckets=[16],
                                 **DENSE_KW)
            with open(path, "rb") as fin:
                digests.append(
                    hashlib.sha256(fin.read()).hexdigest())
        assert digests[0] == digests[1]

    def test_tampered_member_refused(self, dense_bundle, tmp_path):
        manifest, members = read_bundle(dense_bundle)
        victim = manifest["programs"][0]["member"]
        bad = str(tmp_path / "bad.tar")
        with tarfile.open(bad, "w") as tar:
            for name, blob in members.items():
                if name == victim:
                    blob = blob[:-1] + bytes([blob[-1] ^ 1])
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                tar.addfile(info, io.BytesIO(blob))
        with pytest.raises(ValueError, match="content hash"):
            load_bundle(bad)


class TestCompatGate:
    """The mismatch-rejection matrix: every stale field is refused BY
    NAME — never a wrong-answer execute."""

    @pytest.fixture()
    def manifest(self, dense_bundle):
        return read_bundle(dense_bundle)[0]

    @pytest.mark.parametrize("field,value", [
        ("schema", 999),
        ("jax", "0.0.1"),
        ("jaxlib", "0.0.1"),
    ])
    def test_version_fields_refused(self, manifest, field, value):
        stale = dict(manifest)
        stale[field] = value
        with pytest.raises(AotCompatError) as err:
            check_compat(stale)
        assert err.value.field == field

    def test_fingerprint_refused(self, manifest):
        stale = dict(manifest)
        stale["fingerprint"] = dict(manifest["fingerprint"],
                                    device_kind="TPU v9000")
        with pytest.raises(AotCompatError) as err:
            check_compat(stale)
        assert err.value.field == "fingerprint"

    def test_mesh_refused_both_ways(self, manifest):
        from veles_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(devices=jax.devices()[:8], data=1, model=8)
        with pytest.raises(AotCompatError) as err:
            check_compat(manifest, mesh=mesh)  # single-chip bundle
        assert err.value.field == "mesh"
        stale = dict(manifest, mesh={"axes": {"model": 2},
                                     "devices": 2})
        with pytest.raises(AotCompatError) as err:
            check_compat(stale)  # mesh bundle, no serving mesh
        assert err.value.field == "mesh"

    def test_stale_bundle_file_refused_by_name(self, dense_bundle,
                                               tmp_path):
        """End to end through load_bundle: a re-written bundle whose
        manifest records another jaxlib refuses with the field."""
        manifest, members = read_bundle(dense_bundle)
        manifest = dict(manifest, jaxlib="0.0.1")
        stale = str(tmp_path / "stale.tar")
        with tarfile.open(stale, "w") as tar:
            payload = json.dumps(manifest).encode()
            info = tarfile.TarInfo("manifest.json")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
            for name, blob in members.items():
                if name == "manifest.json":
                    continue
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                tar.addfile(info, io.BytesIO(blob))
        with pytest.raises(AotCompatError) as err:
            load_bundle(stale)
        assert err.value.field == "jaxlib"

    def test_geometry_mismatch_degrades_to_live(self, model,
                                                dense_bundle):
        """A bundle for another serving shape must NOT bind — the
        decoder logs the stale field and serves via live compilation,
        bit-identical."""
        params, table = model
        aot = load_bundle(dense_bundle, prefetch=False)
        kw = dict(DENSE_KW, slots=2)  # differs from the bundle
        dec = ContinuousDecoder(params, table, HEADS, aot=aot, **kw)
        assert not dec.aot_active
        ref = ContinuousDecoder(params, table, HEADS, **kw)
        for d in (dec, ref):
            _drain(d, _prompts(3))
        assert dec.results == ref.results


class TestBitIdentity:
    """AOT-loaded programs must stream EXACTLY what live-compiled ones
    do — the wire-format conversion is a bit-level reinterpretation."""

    def test_dense_streams(self, model, dense_bundle):
        params, table = model
        aot = load_bundle(dense_bundle, prefetch=False)
        ref = _drain(ContinuousDecoder(params, table, HEADS,
                                       **DENSE_KW), _prompts())
        got = _drain(ContinuousDecoder(params, table, HEADS, aot=aot,
                                       **DENSE_KW), _prompts())
        assert got.aot_active
        assert ref.results == got.results
        stats = aot.stats()
        assert sum(stats["hits"].values()) > 0
        assert not stats["misses"]
        # dispatch economy is preserved: same admit/chunk tallies
        assert ref.dispatch_counts == got.dispatch_counts

    @pytest.mark.slow
    def test_int8kv_streams(self, model, tmp_path):
        params, table = model
        kw = dict(slots=3, max_len=128, n_tokens=6, tile=128,
                  quantize="int8-kv")
        path = str(tmp_path / "int8kv.aot.tar")
        build_serving_bundle(params, table, HEADS, path, chunk=CHUNK,
                             buckets=[16, 128], **kw)
        aot = load_bundle(path, prefetch=False)
        ref = _drain(ContinuousDecoder(params, table, HEADS, **kw),
                     _prompts(4))
        got = _drain(ContinuousDecoder(params, table, HEADS, aot=aot,
                                       **kw), _prompts(4))
        assert got.aot_active
        assert ref.results == got.results
        assert not aot.stats()["misses"]

    @pytest.mark.slow
    def test_paged_streams_with_prefix_reuse(self, model, tmp_path):
        """Paged cold/hit admissions serve from the bundle; the tail
        family (unbounded key space) falls back to live compile —
        counted as a miss, still bit-identical."""
        params, table = model
        kw = dict(slots=3, max_len=64, n_tokens=6, tile=16,
                  paged=True, page_size=16)
        path = str(tmp_path / "paged.aot.tar")
        build_serving_bundle(params, table, HEADS, path, chunk=CHUNK,
                             **kw)
        aot = load_bundle(path, prefetch=False)
        rng = numpy.random.RandomState(5)
        system = rng.randint(0, VOCAB, 16)  # one whole page
        prompts = [system.tolist() + rng.randint(0, VOCAB, k).tolist()
                   for k in (3, 5, 0, 3)]
        results = []
        for a in (None, aot):
            dec = ContinuousDecoder(params, table, HEADS, aot=a, **kw)
            # sequential: later admissions hit the published prefix
            for prompt in prompts:
                rid = dec.submit(prompt)
                dec.run_until_drained(chunk=CHUNK)
            results.append((dec.results,
                            dict(dec.dispatch_counts)))
        (ref, ref_counts), (got, got_counts) = results
        assert ref == got
        assert got_counts == ref_counts
        assert got_counts["admit_hit"] > 0 \
            or got_counts["admit_tail"] > 0
        stats = aot.stats()
        assert stats["hits"].get("paged.admit", 0) > 0
        assert stats["hits"].get("paged.dispatch", 0) > 0

    @pytest.mark.slow
    def test_mesh_streams(self, tmp_path):
        """One 8-device mesh layout: the exported programs keep their
        pinned shardings and stream identically to the live sharded
        engine."""
        from veles_tpu.parallel.mesh import build_mesh

        heads, embed, vocab = 8, 32, 16
        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, BLOCKS, embed, heads,
                                         vocab)
        table = jnp.asarray(
            rng.randn(vocab, embed).astype(numpy.float32) * 0.3)
        mesh = build_mesh(devices=jax.devices()[:8], data=1, model=8)
        kw = dict(slots=2, max_len=64, n_tokens=5, tile=16)
        path = str(tmp_path / "mesh.aot.tar")
        build_serving_bundle(params, table, heads, path, chunk=CHUNK,
                             mesh=mesh, buckets=[16], **kw)
        aot = load_bundle(path, mesh=mesh, prefetch=False)
        prompts = [rng.randint(0, vocab, k) for k in (5, 9, 3)]
        results = []
        for a in (None, aot):
            dec = ContinuousDecoder(params, table, heads, mesh=mesh,
                                    aot=a, **kw)
            _drain(dec, prompts)
            results.append(dec)
        ref, got = results
        assert got.aot_active
        assert ref.results == got.results
        assert not got.state["k"].sharding.is_fully_replicated

    def test_fused_train_step(self, tmp_path):
        """The training half of the libVeles analogue: one captured
        fused train step replays bit-identically, and an uncovered
        minibatch shape falls back to the live tick."""
        from veles_tpu.parallel import fused

        specs = [
            {"kind": "dense", "activation": "tanh",
             "leaves": fused._WB_LEAVES, "has_params": True,
             "solver": "momentum"},
            {"kind": "dense", "activation": "linear",
             "leaves": fused._WB_LEAVES, "has_params": True,
             "solver": "momentum"},
        ]
        steps = fused.build_tick(specs, "none", with_confusion=False)
        rng = numpy.random.RandomState(0)
        w1 = rng.randn(8, 6).astype("float32")
        w2 = rng.randn(6, 3).astype("float32")

        def mk_params():
            return [{"p": {"w": jnp.asarray(w1), "b": jnp.zeros(6)},
                     "v": {"w": jnp.zeros((8, 6)), "b": jnp.zeros(6)}},
                    {"p": {"w": jnp.asarray(w2), "b": jnp.zeros(3)},
                     "v": {"w": jnp.zeros((6, 3)), "b": jnp.zeros(3)}}]

        hypers = [jnp.asarray([0.1, 0.1, 0.0, 0.0, 0.9],
                              jnp.float32)] * 2
        data = jnp.asarray(rng.randn(32, 8).astype("float32"))
        labels = jnp.asarray(rng.randint(0, 3, 32), jnp.int32)
        indices = jnp.arange(8, dtype=jnp.int32)
        args = (mk_params(), hypers, {}, data, labels, indices,
                jnp.float32(8), numpy.int64(0))
        ref_params, (ref_loss, ref_err) = steps[0](
            mk_params(), hypers, {}, data, labels, indices,
            jnp.float32(8), numpy.int64(0))
        path = str(tmp_path / "tick.aot.tar")
        builder = BundleBuilder()
        capture_tick_programs(builder, steps, args)
        builder.write(path)
        aot = load_bundle(path, prefetch=False)
        install_fused_tick(aot, specs, norm_type="none",
                           with_confusion=False)
        installed = fused.build_tick(specs, "none",
                                     with_confusion=False)
        got_params, (got_loss, got_err) = installed[0](
            mk_params(), hypers, {}, data, labels, indices,
            jnp.float32(8), numpy.int64(0))
        for a, b in zip(jax.tree.leaves(ref_params),
                        jax.tree.leaves(got_params)):
            assert (numpy.asarray(a) == numpy.asarray(b)).all()
        assert float(ref_loss) == float(got_loss)
        assert int(ref_err) == int(got_err)
        assert aot.stats()["hits"].get("fused.train_step") == 1
        # odd tail minibatch: live fallback, never a wrong shape
        installed[0](mk_params(), hypers, {}, data, labels,
                     jnp.arange(5, dtype=jnp.int32), jnp.float32(5),
                     numpy.int64(0))
        assert aot.stats()["misses"].get("fused.train_step") == 1


class TestZeroRetraceServing:
    def test_compiles_flat_across_aot_warmup(self, model,
                                             dense_bundle):
        """THE acceptance gate: an AOT-booted GenerateAPI serves a
        warmup over every bucket with veles_xla_compiles_total FLAT
        for the decode programs — zero retrace proven by the
        device-truth counter, not by timing — while every dispatch
        books as an AOT hit."""
        params, table = model
        aot = load_bundle(dense_bundle, prefetch=False)
        api = GenerateAPI(params, table, HEADS, chunk=CHUNK,
                          port=0, aot=aot, **DENSE_KW).start()
        try:
            tracker = get_compile_tracker()
            before = tracker.snapshot()["compiles"]
            hits_before = sum(aot.stats()["hits"].values())
            rng = numpy.random.RandomState(7)
            url = "http://127.0.0.1:%d/generate" % api.port
            for k in (5, 9, 17, 33, 3):  # spans every prompt bucket
                req = urllib.request.Request(
                    url, data=json.dumps(
                        {"tokens":
                         rng.randint(0, VOCAB, k).tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    out = json.loads(resp.read().decode())
                assert out["tokens"]
            after = tracker.snapshot()["compiles"]
            for name in set(before) | set(after):
                if name.startswith(("decode.", "paged.")):
                    assert after.get(name, 0) == before.get(name, 0), \
                        "live compile of %s during AOT warmup" % name
            stats = aot.stats()
            assert sum(stats["hits"].values()) > hits_before
            assert not stats["misses"]
            # the /metrics surface carries the AOT plane
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % api.port,
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "veles_aot_hits_total" in text
            assert "veles_aot_programs_loaded" in text
        finally:
            api.stop()

    def test_breaker_rebuild_reuses_loaded_programs(self, model,
                                                    dense_bundle):
        """A rebuilt decoder binds the SAME AotPrograms — a trip never
        pays a second deserialize, and the probe decode rides the
        loaded programs too."""
        params, table = model
        aot = load_bundle(dense_bundle, prefetch=False)
        api = GenerateAPI(params, table, HEADS, chunk=CHUNK, port=0,
                          aot=aot, **DENSE_KW)
        first = api.decoder
        assert first.aot_active and first.aot is aot
        assert api._rebuild()
        assert api.decoder is not first
        assert api.decoder.aot_active
        assert api.decoder.aot is aot

    def test_serve_aot_config_fallback(self, model, tmp_path,
                                       caplog):
        """root.common.serve.aot pointing at a stale bundle must boot
        a WORKING live-compiled server, loudly."""
        import logging

        from veles_tpu.core.config import root

        params, table = model
        stale = str(tmp_path / "missing.aot.tar")
        root.common.serve.aot = stale
        try:
            with caplog.at_level(logging.WARNING):
                api = GenerateAPI(params, table, HEADS, chunk=CHUNK,
                                  port=0, **DENSE_KW)
            assert not api.decoder.aot_active
            assert any("refused" in r.message for r in caplog.records)
        finally:
            root.common.serve.aot = None


class TestDeterministicPackages:
    """The determinism satellite: identical state must repack to
    identical bytes so sha-addressed stores dedupe."""

    def test_forge_pack_twice_same_sha(self, tmp_path):
        from test_forge import make_model_dir
        from veles_tpu.forge import package as pkg

        d = make_model_dir(tmp_path)
        digests = []
        for name in ("one.tar.gz", "two.tar.gz"):
            path, _ = pkg.pack(d, out_path=str(tmp_path / name))
            with open(path, "rb") as fin:
                digests.append(
                    hashlib.sha256(fin.read()).hexdigest())
        assert digests[0] == digests[1]

    def test_native_export_twice_same_sha(self, tmp_path):
        """export.py's package bytes: fixed member mtimes AND a fixed
        contents.json stamp (the old time.strftime path made every
        repack a new sha)."""
        import time

        from veles_tpu.dummy import DummyLauncher
        from veles_tpu.export import package_export
        from veles_tpu.models.mlp import MLPWorkflow

        rng = numpy.random.RandomState(0)
        data = rng.rand(40, 6).astype(numpy.float32)
        labels = (data[:, 0] > 0.5).astype(numpy.int32)
        wf = MLPWorkflow(
            DummyLauncher(), layers=(5, 2),
            loader_kwargs=dict(data=data, labels=labels,
                               class_lengths=[0, 10, 30],
                               minibatch_size=10))
        wf.initialize()
        digests = []
        for name in ("one.tar", "two.tar"):
            path = package_export(wf, str(tmp_path / name))
            time.sleep(0.01)  # a wall-clock stamp WOULD differ
            with open(path, "rb") as fin:
                digests.append(
                    hashlib.sha256(fin.read()).hexdigest())
        assert digests[0] == digests[1]


class TestForgeArtifactDistribution:
    """Artifact bundles ride forge packages; the server verifies the
    sha256 sidecar on receipt and 422s tampered uploads."""

    def _package_with_artifact(self, tmp_path, dense_bundle,
                               tamper=False):
        from test_forge import make_model_dir
        from veles_tpu.aot.cli import stage_into_package
        from veles_tpu.forge import package as pkg

        d = make_model_dir(tmp_path)
        stage_into_package(dense_bundle, d)
        if tamper:
            victim = os.path.join(d, os.path.basename(dense_bundle))
            with open(victim, "r+b") as fout:
                fout.seek(-1, os.SEEK_END)
                last = fout.read(1)
                fout.seek(-1, os.SEEK_END)
                fout.write(bytes([last[0] ^ 1]))
        path, manifest = pkg.pack(d, out_path=str(
            tmp_path / "pkg.tar.gz"))
        assert manifest["artifacts"] == [
            os.path.basename(dense_bundle)]
        with open(path, "rb") as fin:
            return fin.read()

    def test_upload_verifies_and_rejects_tamper(self, tmp_path,
                                                dense_bundle):
        from veles_tpu.forge import ForgeServer, package as pkg

        server = ForgeServer(str(tmp_path / "store"))
        blob = self._package_with_artifact(tmp_path, dense_bundle)
        assert server.upload(blob, version="1.0")["name"] == \
            "toy-model"
        bad = self._package_with_artifact(
            tmp_path.joinpath("t2"), dense_bundle, tamper=True)
        with pytest.raises(pkg.TamperedPackageError):
            server.upload(bad, version="1.1")
        # and over HTTP the refusal is 422, nothing stored
        server.start()
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:%d/upload?version=2.0"
                % server.port, data=bad,
                headers={"Content-Type": "application/octet-stream"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=30)
            assert err.value.code == 422
            meta = server.details("toy-model")
            assert "2.0" not in meta["versions"]
        finally:
            server.stop()

    def test_fetched_bundle_loads(self, tmp_path, dense_bundle,
                                  model):
        """The full distribution loop: pack -> upload -> fetch ->
        unpack -> load_bundle -> serve."""
        from veles_tpu.forge import ForgeServer, package as pkg

        server = ForgeServer(str(tmp_path / "store"))
        blob = self._package_with_artifact(tmp_path, dense_bundle)
        server.upload(blob, version="1.0")
        fetched = server.fetch("toy-model")
        dest = str(tmp_path / "fetched")
        manifest = pkg.unpack(fetched, dest)
        bundle = os.path.join(dest, manifest["artifacts"][0])
        aot = load_bundle(bundle, prefetch=False)
        params, table = model
        dec = ContinuousDecoder(params, table, HEADS, aot=aot,
                                **DENSE_KW)
        assert dec.aot_active


class TestCli:
    def test_build_inspect_verify(self, tmp_path, capsys):
        from veles_tpu.aot.cli import main

        out = str(tmp_path / "cli.aot.tar")
        assert main(["build", "--out", out, "--blocks", "1",
                     "--embed", "16", "--heads", "4", "--vocab", "32",
                     "--slots", "2", "--max-len", "32",
                     "--n-tokens", "4", "--chunk", "2",
                     "--tile", "16"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["programs"] > 0
        assert main(["inspect", out]) == 0
        assert main(["verify", out]) == 0
        assert "loadable" in capsys.readouterr().out
        # the operator's intended mesh participates in the verdict: a
        # single-chip bundle is NOT loadable for a model=8 boot
        assert main(["verify", out, "--mesh", "model=8"]) == 1
        assert "mesh" in capsys.readouterr().out
        # verify refuses a tampered file with exit 2
        with open(out, "r+b") as fout:
            fout.seek(-1, os.SEEK_END)
            last = fout.read(1)
            fout.seek(-1, os.SEEK_END)
            fout.write(bytes([last[0] ^ 1]))
        assert main(["verify", out]) == 2


class TestRegressDirections:
    def test_compiles_and_coldstart_keys_are_lower_better(self):
        from veles_tpu.observe.regress import compare, regressions

        old = {"coldstart_to_first_token_ms": 100.0,
               "warmup_compiles": 2}
        new = {"coldstart_to_first_token_ms": 150.0,
               "warmup_compiles": 6}
        bad = {f["key"] for f in regressions(compare(old, new))}
        assert "coldstart_to_first_token_ms" in bad
        assert "warmup_compiles" in bad
        assert not regressions(compare(old, dict(old)))
