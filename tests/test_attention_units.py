"""SelfAttention/LayerNorm unit tests: forward math, vjp backward vs
autodiff, and a transformer workflow assembled via StandardWorkflow."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.memory import Array
from veles_tpu.models.standard import StandardWorkflow
from veles_tpu.nn.attention import (
    GDLayerNorm, GDSelfAttention, GDTokenFFN, LayerNorm, SelfAttention,
    TokenFFN)


def _x(b=2, t=8, e=16, seed=0):
    return numpy.random.RandomState(seed).randn(b, t, e).astype(
        numpy.float32)


def test_self_attention_forward_matches_naive():
    x = _x()
    wf = DummyWorkflow()
    attn = SelfAttention(wf, heads=4, causal=False)
    attn.input = Array(x)
    attn.initialize()
    attn.run()
    # naive recomputation from the same weights
    w = attn.weights.data
    b = attn.bias.data
    qkv = jnp.asarray(x) @ w + b
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (2, 8, 4, 4)
    import math
    s = jnp.einsum("bqhd,bkhd->bhqk", q.reshape(shape),
                   k.reshape(shape)) / math.sqrt(4)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", p, v.reshape(shape)).reshape(
        2, 8, 16) @ attn.out_weights.data + attn.out_bias.data
    # the unit runs the ENGINE precision policy (bf16 projections +
    # attention core, f32 accumulation — ops/attention.attention_block)
    # while this naive reference is pure f32: the bound covers the bf16
    # operand rounding, same as the conv parity tests
    numpy.testing.assert_allclose(numpy.asarray(attn.output.mem),
                                  numpy.asarray(ref), rtol=3e-2, atol=6e-3)


def test_gd_self_attention_matches_autodiff():
    x = _x(seed=1)
    err = _x(seed=2) * 0.01
    wf = DummyWorkflow()
    attn = SelfAttention(wf, heads=4)
    attn.input = Array(x)
    attn.initialize()
    attn.run()
    w0 = numpy.asarray(attn.weights.mem).copy()
    ow0 = numpy.asarray(attn.out_weights.mem).copy()

    gd = GDSelfAttention(wf, learning_rate=1.0)
    gd.link_attention(attn, type("E", (), {"err_output": Array(err)})())
    gd.initialize()
    gd.run()

    def loss(w_qkv, w_out):
        out = attn._forward(jnp.asarray(x), w_qkv,
                            jnp.zeros_like(attn.bias.data) + 0,
                            w_out, jnp.zeros_like(attn.out_bias.data))
        return jnp.sum(out * jnp.asarray(err))

    # bias terms were initialized to zero, so loss() above matches
    g_qkv, g_out = jax.grad(loss, argnums=(0, 1))(
        jnp.asarray(w0), jnp.asarray(ow0))
    numpy.testing.assert_allclose(
        numpy.asarray(attn.weights.mem), w0 - numpy.asarray(g_qkv),
        rtol=2e-2, atol=1e-4)
    numpy.testing.assert_allclose(
        numpy.asarray(attn.out_weights.mem), ow0 - numpy.asarray(g_out),
        rtol=2e-2, atol=1e-4)
    assert gd.err_input.shape == x.shape


def test_token_ffn_forward_matches_naive():
    x = _x()
    wf = DummyWorkflow()
    ffn = TokenFFN(wf, ratio=2)
    ffn.input = Array(x)
    ffn.initialize()
    ffn.run()
    ref = jnp.asarray(x) + jax.nn.gelu(
        jnp.asarray(x) @ ffn.weights.data + ffn.bias.data
    ) @ ffn.out_weights.data + ffn.out_bias.data
    # engine precision policy (bf16 projections, f32 accumulation) vs
    # this pure-f32 reference — same bound family as the attention test
    numpy.testing.assert_allclose(numpy.asarray(ffn.output.mem),
                                  numpy.asarray(ref), rtol=3e-2,
                                  atol=6e-3)
    assert ffn.weights.shape == (16, 32)
    assert ffn.out_weights.shape == (32, 16)


def test_token_ffn_no_residual():
    x = _x()
    wf = DummyWorkflow()
    ffn = TokenFFN(wf, ratio=1, residual=False, activation="relu")
    ffn.input = Array(x)
    ffn.initialize()
    ffn.run()
    ref = jnp.maximum(
        jnp.asarray(x) @ ffn.weights.data + ffn.bias.data, 0.0
    ) @ ffn.out_weights.data + ffn.out_bias.data
    numpy.testing.assert_allclose(numpy.asarray(ffn.output.mem),
                                  numpy.asarray(ref), rtol=3e-2,
                                  atol=6e-3)


def test_gd_token_ffn_matches_autodiff():
    x = _x(seed=5)
    err = _x(seed=6) * 0.01
    wf = DummyWorkflow()
    ffn = TokenFFN(wf, ratio=2)
    ffn.input = Array(x)
    ffn.initialize()
    ffn.run()
    w0 = numpy.asarray(ffn.weights.mem).copy()
    ow0 = numpy.asarray(ffn.out_weights.mem).copy()

    gd = GDTokenFFN(wf, learning_rate=1.0)
    gd.link_ffn(ffn, type("E", (), {"err_output": Array(err)})())
    gd.initialize()
    gd.run()

    def loss(w1, w2):
        out = ffn._forward(jnp.asarray(x), w1,
                           jnp.zeros_like(ffn.bias.data),
                           w2, jnp.zeros_like(ffn.out_bias.data))
        return jnp.sum(out * jnp.asarray(err))

    g1, g2 = jax.grad(loss, argnums=(0, 1))(
        jnp.asarray(w0), jnp.asarray(ow0))
    numpy.testing.assert_allclose(
        numpy.asarray(ffn.weights.mem), w0 - numpy.asarray(g1),
        rtol=2e-2, atol=1e-4)
    numpy.testing.assert_allclose(
        numpy.asarray(ffn.out_weights.mem), ow0 - numpy.asarray(g2),
        rtol=2e-2, atol=1e-4)
    assert gd.err_input.shape == x.shape


def test_residual_attention_forward():
    x = _x()
    wf = DummyWorkflow()
    plain = SelfAttention(wf, heads=4)
    plain.input = Array(x)
    plain.initialize()
    plain.run()
    res = SelfAttention(wf, heads=4, residual=True)
    res.input = Array(x)
    res.initialize()
    # same weights so the two outputs differ exactly by x
    res.weights.data = plain.weights.data
    res.bias.data = plain.bias.data
    res.out_weights.data = plain.out_weights.data
    res.out_bias.data = plain.out_bias.data
    res.run()
    numpy.testing.assert_allclose(
        numpy.asarray(res.output.mem),
        numpy.asarray(plain.output.mem) + x, rtol=1e-5, atol=1e-5)


def test_layer_norm_forward_and_backward():
    x = _x(seed=3)
    wf = DummyWorkflow()
    ln = LayerNorm(wf)
    ln.input = Array(x)
    ln.initialize()
    ln.run()
    out = numpy.asarray(ln.output.mem)
    assert abs(out.mean(-1)).max() < 1e-5
    assert abs(out.var(-1) - 1).max() < 1e-2

    err = _x(seed=4) * 0.01
    gd = GDLayerNorm(wf, learning_rate=1.0)
    gd.link_forward(ln, type("E", (), {"err_output": Array(err)})())
    gd.initialize()
    s0 = numpy.asarray(ln.weights.mem).copy()
    gd.run()

    def loss(scale):
        return jnp.sum(ln._forward(jnp.asarray(x), scale,
                                   jnp.zeros(16)) * jnp.asarray(err))

    g = jax.grad(loss)(jnp.asarray(s0))
    numpy.testing.assert_allclose(
        numpy.asarray(ln.weights.mem), s0 - numpy.asarray(g),
        rtol=2e-2, atol=1e-4)
    assert gd.err_input.shape == x.shape


@pytest.mark.slow
def test_transformer_workflow_learns():
    """A tiny transformer classifier over synthetic sequences: class = which
    half of the sequence carries the larger marker."""
    rng = numpy.random.RandomState(0)
    n, t, e = 600, 8, 16
    X = rng.randn(n, t, e).astype(numpy.float32) * 0.1
    y = rng.randint(0, 2, n).astype(numpy.int32)
    for i in range(n):
        X[i, : t // 2 if y[i] == 0 else t, 0] += 1.0  # signal token runs
    wf = StandardWorkflow(
        DummyLauncher(),
        layers=[
            {"type": "layer_norm"},
            {"type": "self_attention", "heads": 4},
            {"type": "softmax", "output_sample_shape": (2,)},
        ],
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 100, 500],
                           minibatch_size=100),
        learning_rate=0.05, gradient_moment=0.9,
        decision_kwargs=dict(max_epochs=12), name="tiny-transformer")
    wf.initialize()
    wf.run()
    best = wf.decision.best_n_err[1]
    assert best is not None and best < 35, \
        "transformer at %s/100 validation errors" % best
