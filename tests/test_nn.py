"""Tests for the nn unit layer: forward/GD math and the training workflow.

The end-to-end case mirrors the reference's functional test tier
(znicz per-model regression tests driven by snapshot error rates): a small
MLP must actually learn a real dataset.
"""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.nn.all2all import All2All, All2AllSoftmax, All2AllTanh
from veles_tpu.nn.evaluator import EvaluatorSoftmax
from veles_tpu.nn.gd import GradientDescent
from veles_tpu.memory import Array


def test_all2all_forward_math():
    wf = DummyWorkflow()
    unit = All2All(wf, output_sample_shape=(4,))
    unit.input = Array(numpy.ones((2, 3), numpy.float32))
    unit.initialize()
    unit.run()
    w, b = numpy.asarray(unit.weights.mem), numpy.asarray(unit.bias.mem)
    expected = numpy.ones((2, 3)) @ w + b
    numpy.testing.assert_allclose(unit.output.mem, expected, atol=1e-2)


def test_all2all_weight_init_reproducible():
    from veles_tpu.core import prng
    prng.get("default").seed(1234)
    wf = DummyWorkflow()
    u1 = All2All(wf, output_sample_shape=(4,))
    u1.input = Array(numpy.ones((2, 3), numpy.float32))
    u1.initialize()
    w1 = numpy.asarray(u1.weights.mem)
    prng.get("default").seed(1234)
    u2 = All2All(wf, output_sample_shape=(4,))
    u2.input = Array(numpy.ones((2, 3), numpy.float32))
    u2.initialize()
    numpy.testing.assert_array_equal(w1, numpy.asarray(u2.weights.mem))


def test_gd_matches_autodiff():
    """The hand-derived backward (GD unit) must equal jax.grad of the
    forward + loss composition."""
    rng = numpy.random.RandomState(7)
    x = rng.rand(5, 3).astype(numpy.float32)
    w = rng.rand(3, 4).astype(numpy.float32)
    b = rng.rand(4).astype(numpy.float32)
    labels = rng.randint(0, 4, 5)
    mask = numpy.ones(5, numpy.float32)

    wf = DummyWorkflow()
    fwd = All2AllSoftmax(wf, output_sample_shape=(4,))
    fwd.input = Array(x)
    fwd.initialize()
    fwd.weights.data = jnp.asarray(w)
    fwd.bias.data = jnp.asarray(b)
    fwd.run()

    ev = EvaluatorSoftmax(wf)
    ev.input = fwd.output
    ev.labels = Array(numpy.asarray(labels))
    ev.sample_mask = Array(mask)
    ev.run()

    gd = GradientDescent(wf, learning_rate=1.0)  # lr=1: delta == -grad
    gd.input = fwd.input
    gd.output = fwd.output
    gd.weights = fwd.weights
    gd.bias = fwd.bias
    gd.err_output = ev.err_output
    gd.initialize()
    gd.run()

    def loss_fn(wb):
        logits = x @ wb[0] + wb[1]
        logp = jax.nn.log_softmax(logits)
        onehot = jax.nn.one_hot(labels, 4)
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))

    grads = jax.grad(loss_fn)((jnp.asarray(w), jnp.asarray(b)))
    numpy.testing.assert_allclose(
        numpy.asarray(gd.weights.mem), w - numpy.asarray(grads[0]),
        rtol=1e-2, atol=1e-4)
    numpy.testing.assert_allclose(
        numpy.asarray(gd.bias.mem), b - numpy.asarray(grads[1]),
        rtol=1e-2, atol=1e-4)
    # err_input shape matches forward input
    assert gd.err_input.shape == x.shape


def _digits_dataset():
    from sklearn.datasets import load_digits
    digits = load_digits()
    X = digits.data.astype(numpy.float32)
    y = digits.target.astype(numpy.int32)
    perm = numpy.random.RandomState(0).permutation(len(X))
    return X[perm], y[perm]


@pytest.mark.slow
def test_mlp_workflow_learns_digits():
    """Functional regression: the MNIST784-topology workflow must learn
    sklearn digits to <15% validation error within a few epochs."""
    X, y = _digits_dataset()
    wf = MLPWorkflow(
        DummyLauncher(), layers=(32, 10),
        loader_kwargs=dict(data=X, labels=y,
                           class_lengths=[0, 297, 1500],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=8, name="digits-test")
    wf.initialize()
    wf.run()
    best = wf.decision.best_n_err[VALID]
    assert best is not None
    assert best < 45, "validation errors %d/297 — did not learn" % best
    # improvement tracking coherent
    assert wf.decision.best_epoch >= 0
    results = wf.gather_results()
    assert results["best_validation_errors"] == best


def test_gd_gating_skips_validation_batches():
    """GD units must not update weights on validation minibatches."""
    X, y = _digits_dataset()
    wf = MLPWorkflow(
        DummyLauncher(), layers=(8, 10),
        loader_kwargs=dict(data=X[:400], labels=y[:400],
                           class_lengths=[0, 400, 0],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=None, fail_iterations=1,
        name="valid-only")
    # no TRAIN samples at all: weights must never change
    wf.initialize()
    w_before = numpy.asarray(wf.forwards[0].weights.mem).copy()
    wf.run()
    numpy.testing.assert_array_equal(
        w_before, numpy.asarray(wf.forwards[0].weights.mem))
