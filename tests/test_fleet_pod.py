"""Fleet x pod composition (VERDICT r2 #5 / SURVEY §5's stated
translation): each fleet slave's one-tick job is the shard_map-ped fused
step over the slave's LOCAL device mesh — jobs/updates ride the DCN-role
fleet protocol, the gradient merge inside the tick psums over the
ICI-role mesh."""

import threading
import time

import jax

from veles_tpu.core import prng
from veles_tpu.launcher import Launcher
from veles_tpu.loader.base import VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.parallel.mesh import build_mesh


def _digits():
    from dataset_fixtures import digits_dataset
    return digits_dataset()


def _kw(max_epochs=4, minibatch=300):
    X, y = _digits()
    return dict(
        layers=(16, 10),
        loader_kwargs=dict(data=X, labels=y, class_lengths=[0, 297, 1500],
                           minibatch_size=minibatch,
                           normalization_type="linear"),
        learning_rate=0.5, max_epochs=max_epochs)


def _seed():
    prng.get("default").seed(42)
    prng.get("loader").seed(43)


def _run_master(kw):
    _seed()
    master = Launcher(listen_address="127.0.0.1:0")
    wf = MLPWorkflow(master, name="fleet-t", **kw)
    master.initialize()
    thread = threading.Thread(target=master.run, daemon=True)
    thread.start()
    return master, wf, thread


def _run_pod_slave(port, kw, devices):
    """A slave whose local tick is the fused step over a data=2 mesh."""
    _seed()
    slave = Launcher(master_address="127.0.0.1:%d" % port)
    wf = MLPWorkflow(slave, name="fleet-t",
                     mesh=build_mesh(devices=devices, data=2), **kw)
    slave.initialize()
    assert wf.fused_tick is not None, "slave fused tick did not engage"
    assert wf.fused_tick.mesh is not None \
        and wf.fused_tick.mesh.shape["data"] == 2
    return slave, wf


class TestFleetPod:
    def test_pod_slave_matches_graph_slave(self):
        """Sequential 1-slave runs: the sharded fused slave tick must
        converge exactly like the per-unit graph slave (psum-merged
        minibatch grads == full-minibatch grads)."""
        kw = _kw(max_epochs=2)
        results = {}
        for mode in ("graph", "pod"):
            master, wf_m, thread = _run_master(kw)
            if mode == "pod":
                slave, _ = _run_pod_slave(master.agent.port, kw,
                                          jax.devices()[:2])
            else:
                _seed()
                slave = Launcher(
                    master_address="127.0.0.1:%d" % master.agent.port)
                wf_s = MLPWorkflow(slave, name="fleet-t", fused=False,
                                   **kw)
                slave.initialize()
                assert wf_s.fused_tick is None
            slave.run()
            thread.join(120)
            assert not thread.is_alive(), "master did not finish"
            results[mode] = wf_m.decision.best_n_err[VALID]
            master.stop()
            slave.stop()
        # identical job stream + mathematically identical updates (up to
        # float reassociation, which the error COUNT absorbs)
        assert results["pod"] == results["graph"], results

    def test_two_pod_slaves_converge(self):
        """Two slaves, each running data=2 over its own device pair —
        the full DCN x ICI composition — must reach the same accuracy
        class as a single slave.

        Two scheduling coin flips are pinned here (the test used to
        fail ~50%): (a) 8 epochs, not 4 — at 4 the async two-slave
        interleaving only reaches the <=40 bound when the connect race
        starves one slave (measured: even 13/12 splits land at 50-73
        errors, by epoch 8 every interleaving lands at 21-27); (b) s2
        is held back until s1 has completed its first job, so neither
        slave can drain the whole job stream before the other
        connects. The barrier deadline and joins are sized for a
        loaded tier-1 box, not an idle one — under a 6-way CPU spinner
        the run needs ~37s where an idle box needs ~5s, so the old 60s
        barrier budget was itself a coin flip."""
        kw = _kw(max_epochs=8)
        master, wf_m, thread = _run_master(kw)
        s1, w1 = _run_pod_slave(master.agent.port, kw, jax.devices()[:2])
        s2, w2 = _run_pod_slave(master.agent.port, kw,
                                jax.devices()[2:4])
        t1 = threading.Thread(target=s1.run, daemon=True)
        t1.start()
        deadline = time.time() + 120
        while s1.agent.jobs_done == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert s1.agent.jobs_done > 0, "s1 never completed a job"
        s2.run()
        t1.join(180)
        thread.join(180)
        assert not thread.is_alive(), "master did not finish"
        assert s1.agent.jobs_done > 0 and s2.agent.jobs_done > 0
        assert w1.fused_tick.ticks > 0 and w2.fused_tick.ticks > 0
        best = wf_m.decision.best_n_err[VALID]
        assert best is not None and best <= 40, best
        master.stop()
        s1.stop()
        s2.stop()

    def test_pod_slave_drop_requeues(self):
        """Kill one pod slave mid-run: the master must requeue its
        pending minibatches and finish on the survivor."""
        kw = _kw(max_epochs=3)
        master, wf_m, thread = _run_master(kw)
        s1, _ = _run_pod_slave(master.agent.port, kw, jax.devices()[:2])
        s2, _ = _run_pod_slave(master.agent.port, kw, jax.devices()[2:4])
        t1 = threading.Thread(target=s1.run, daemon=True)
        t1.start()

        def killer():
            import time
            time.sleep(1.5)
            s2.agent.stop()  # abrupt disconnect -> drop_slave + requeue

        t2 = threading.Thread(target=s2.run, daemon=True)
        killer_t = threading.Thread(target=killer, daemon=True)
        t2.start()
        killer_t.start()
        t1.join(180)
        thread.join(180)
        assert not thread.is_alive(), "master did not finish after drop"
        assert wf_m.decision.best_n_err[VALID] is not None
        master.stop()
        s1.stop()
        s2.stop()

    def test_pod_slave_on_safe_codec(self):
        """Triple composition: pod slave x fleet x pickle-free wire —
        the sharded tick's jobs/updates must survive the safe codec
        (arrays-and-scalars payloads only) and converge identically."""
        from veles_tpu.core.config import root

        saved = root.common.fleet.get("codec", "pickle")
        root.common.fleet.codec = "safe"
        master = slave = None
        try:
            kw = _kw(max_epochs=2)
            master, wf_m, thread = _run_master(kw)
            slave, wf_s = _run_pod_slave(master.agent.port, kw,
                                         jax.devices()[:2])
            slave.run()
            thread.join(120)
            assert not thread.is_alive(), "master did not finish"
            assert wf_s.fused_tick.ticks > 0
            assert wf_m.decision.best_n_err[VALID] is not None
        finally:
            # stop in the finally: a failed assert must not leak the
            # bound listener/threads into the next fleet test
            root.common.fleet.codec = saved
            if master is not None:
                master.stop()
            if slave is not None:
                slave.stop()
