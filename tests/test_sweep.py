"""Sweep-tier fusion: class-sweep scanning of arbitrary JitUnit chains.

The VERDICT-r3 #1 tier: workflows the full fused engine declines (custom
host units, custom layer types) must reach sweep-granular dispatch, not
per-tick dispatch, while matching graph mode numerically — metrics
exactly, weights to fp-reassociation tolerance. Every tier applies the
stopping epoch's final train update (graph mode holds the EndPoint's
AND-gate behind the gd chain for it — StandardWorkflow wiring).
"""

import numpy
import pytest

import jax.numpy as jnp

from veles_tpu.core import prng
from veles_tpu.core.distributable import TriviallyDistributable
from veles_tpu.core.units import Unit
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.models.mlp import MLPWorkflow
from veles_tpu.parallel.segments import FusedSegment
from veles_tpu.parallel.sweep import FusedSweep


class Observer(Unit, TriviallyDistributable):
    """A transparent host unit: counts ticks, touches no slots."""

    sweep_transparent = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.ticks = 0

    def run(self):
        self.ticks += 1


class OpaqueObserver(Observer):
    """Same unit without the transparency declaration."""

    sweep_transparent = False


def _dataset(n=1200, features=64, classes=10):
    rng = numpy.random.RandomState(7)
    data = rng.rand(n, features).astype(numpy.float32)
    labels = rng.randint(0, classes, n).astype(numpy.int32)
    return data, labels


def _build(data, labels, observer_cls=None, max_epochs=3, **kwargs):
    prng.get("default").seed(4321)
    prng.get("loader").seed(8765)
    wf = MLPWorkflow(
        DummyLauncher(), layers=(24, 10),
        loader_kwargs=dict(data=data, labels=labels,
                           class_lengths=[0, 300, 900],
                           minibatch_size=100,
                           normalization_type="linear"),
        learning_rate=0.1, max_epochs=max_epochs, name="sweep-test",
        **kwargs)
    if observer_cls is not None:
        obs = observer_cls(wf, name="observer")
        fwd1 = wf.forwards[1]
        fwd1.unlink_from(wf.forwards[0])
        obs.link_from(wf.forwards[0])
        fwd1.link_from(obs)
        wf.observer = obs
    return wf


def _train(wf):
    wf.initialize()
    wf.run()
    return wf


def _assert_parity(a, b, atol=1e-3):
    assert a.decision.best_n_err[VALID] == b.decision.best_n_err[VALID]
    assert a.decision._epochs_done == b.decision._epochs_done
    assert a.decision.last_epoch_n_err == b.decision.last_epoch_n_err
    for fa, fb in zip(a.forwards, b.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fa.weights.data), numpy.asarray(fb.weights.data),
            atol=atol)


def test_sweep_engages_and_matches_graph_mode():
    """A transparent host unit mid-chain: the full engine declines, the
    sweep tier takes over, and the results match per-unit graph mode."""
    data, labels = _dataset()
    graph = _train(_build(data, labels, Observer, fused=False))
    swept = _train(_build(data, labels, Observer, fused="auto"))
    assert swept.fused_tick is None, "full engine must decline"
    sweep_unit = getattr(swept, "sweep_unit", None)
    assert isinstance(sweep_unit, FusedSweep), "sweep tier did not engage"
    assert sweep_unit.ticks > 0
    _assert_parity(graph, swept)


def test_sweep_host_unit_fires_per_tick():
    data, labels = _dataset()
    swept = _train(_build(data, labels, Observer, fused="auto",
                          max_epochs=2))
    assert isinstance(getattr(swept, "sweep_unit", None), FusedSweep)
    # 3 VALID + 9 TRAIN minibatches per epoch, 2 epochs — graph mode
    # would have fired the observer once per tick
    graph = _train(_build(data, labels, Observer, fused=False,
                          max_epochs=2))
    assert swept.observer.ticks == graph.observer.ticks


def test_opaque_host_unit_falls_back_to_segments():
    """No transparency declaration => per-tick segment tier (the unit
    may read per-minibatch slot state)."""
    data, labels = _dataset()
    wf = _train(_build(data, labels, OpaqueObserver, fused="auto",
                       max_epochs=1))
    assert getattr(wf, "sweep_unit", None) is None
    assert any(isinstance(u, FusedSegment) for u in wf.units)


def test_sweep_custom_jit_layer():
    """A layer type the full engine has never heard of (custom JitUnit
    subclass) still reaches sweep dispatch — the generality claim."""
    from veles_tpu.nn.all2all import All2AllTanh

    class ScaledTanh(All2AllTanh):
        """Custom forward: standard tanh layer with a 1.1 output scale
        (enough to be unrecognizable to extract_model_spec by class)."""

        def compute(self, *tensors):
            return super().compute(*tensors) * 1.1

    from veles_tpu.nn.gd import GDTanh

    class GDScaledTanh(GDTanh):
        def compute(self, err_output, x, y, weights, bias, vel_w, vel_b,
                    *rest):
            # d(1.1*t)/dt: fold the scale into the incoming error and
            # undo it on the saved output the derivative reads
            return super().compute(err_output * 1.1, x, y / 1.1, weights,
                                   bias, vel_w, vel_b, *rest)

    from veles_tpu.models import standard as std
    std.FORWARD_TYPES["scaled_tanh"] = (ScaledTanh, GDScaledTanh)
    try:
        from veles_tpu.models.standard import StandardWorkflow
        data, labels = _dataset()

        def build(fused):
            prng.get("default").seed(11)
            prng.get("loader").seed(22)
            return StandardWorkflow(
                DummyLauncher(),
                layers=[{"type": "scaled_tanh",
                         "output_sample_shape": (24,)},
                        {"type": "softmax", "output_sample_shape": (10,)}],
                loader_kwargs=dict(data=data, labels=labels,
                                   class_lengths=[0, 300, 900],
                                   minibatch_size=100,
                                   normalization_type="linear"),
                learning_rate=0.05, fused=fused,
                decision_kwargs=dict(max_epochs=2), name="custom-layer")

        graph = _train(build(False))
        swept = _train(build("auto"))
        assert swept.fused_tick is None
        assert isinstance(getattr(swept, "sweep_unit", None), FusedSweep)
        _assert_parity(graph, swept)
    finally:
        del std.FORWARD_TYPES["scaled_tanh"]


def test_sweep_adam_solver_state_carries():
    """Adam's second moments + step counter ride the scan carry: a
    2-epoch graph run and a 2-epoch sweep run both end after the same
    18 updates (every tier applies the stopping epoch's final update)
    and land on the same weights and step count."""
    data, labels = _dataset()
    graph = _train(_build(data, labels, Observer, fused=False,
                          solver="adam", max_epochs=2))
    swept = _train(_build(data, labels, Observer, fused="auto",
                          solver="adam", max_epochs=2))
    assert isinstance(getattr(swept, "sweep_unit", None), FusedSweep)
    assert float(swept.gds[0]._step.data) == 18.0
    assert float(graph.gds[0]._step.data) == 18.0
    for fg, fs in zip(graph.forwards, swept.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data),
            numpy.asarray(fs.weights.data), atol=1e-3)


def test_sweep_mse_chain():
    """Regression chains (EvaluatorMSE/DecisionMSE) sweep too — the
    full engine supports them only with FullBatchLoaderMSE; here the
    sweep tier proves the generic path."""
    from veles_tpu.models.standard import StandardWorkflow

    rng = numpy.random.RandomState(3)
    data = rng.rand(800, 32).astype(numpy.float32)
    targets = rng.rand(800, 4).astype(numpy.float32)

    def build(fused):
        prng.get("default").seed(5)
        prng.get("loader").seed(6)
        wf = StandardWorkflow(
            DummyLauncher(), evaluator="mse",
            layers=[{"type": "all2all_tanh", "output_sample_shape": (16,)},
                    {"type": "all2all", "output_sample_shape": (4,)}],
            loader_kwargs=dict(data=data, targets=targets,
                               class_lengths=[0, 200, 600],
                               minibatch_size=100,
                               normalization_type="none"),
            learning_rate=0.05, fused=fused,
            decision_kwargs=dict(max_epochs=2), name="mse-sweep")
        obs = Observer(wf, name="observer")
        fwd1 = wf.forwards[1]
        fwd1.unlink_from(wf.forwards[0])
        obs.link_from(wf.forwards[0])
        fwd1.link_from(obs)
        return wf

    graph = _train(build(False))
    swept = _train(build("auto"))
    assert isinstance(getattr(swept, "sweep_unit", None), FusedSweep)
    assert swept.decision._epochs_done == graph.decision._epochs_done
    numpy.testing.assert_allclose(
        swept.decision.last_epoch_loss, graph.decision.last_epoch_loss,
        rtol=1e-4)
    for fg, fs in zip(graph.forwards, swept.forwards):
        numpy.testing.assert_allclose(
            numpy.asarray(fg.weights.data), numpy.asarray(fs.weights.data),
            atol=1e-3)


def test_sweep_gate_mutation_slow_path():
    """A birth gate .set() after the splice: the safety net executes
    per-unit and honors the gate, exactly like graph mode."""
    data, labels = _dataset()
    swept = _build(data, labels, Observer, fused="auto", max_epochs=2)
    swept.initialize()
    sweep_unit = getattr(swept, "sweep_unit", None)
    assert isinstance(sweep_unit, FusedSweep)
    # block the observer mid-run via its (birth) gate
    swept.observer.gate_skip.set()
    swept.run()
    assert swept.decision._epochs_done == 2
    assert swept.observer.ticks == 0  # the gate was honored
    assert getattr(sweep_unit, "_warned_slow_", False)


def test_sweep_pipelined_identical_on_max_epochs_stop():
    """Pipelined sweeps (metrics one epoch late, prefetched) must
    produce exactly the plain sweep run's outputs on a max_epochs
    stop."""
    data, labels = _dataset()
    plain = _train(_build(data, labels, Observer, fused="auto",
                          max_epochs=4, fused_pipeline=False))
    piped = _train(_build(data, labels, Observer, fused="auto",
                          max_epochs=4, fused_pipeline=True))
    assert piped.sweep_unit is not None and piped.sweep_unit.pipelined
    assert not plain.sweep_unit.pipelined
    assert piped.decision._epochs_done == plain.decision._epochs_done
    assert piped.decision.best_n_err[VALID] == plain.decision.best_n_err[
        VALID]
    assert piped.decision.best_epoch == plain.decision.best_epoch
    for fp, fs in zip(plain.forwards, piped.forwards):
        numpy.testing.assert_array_equal(
            numpy.asarray(fp.weights.data), numpy.asarray(fs.weights.data))


def test_sweep_pipelined_identical_on_no_improvement_stop():
    """The lagged no-improvement stop drops the speculative epoch and
    rolls the state back — outputs identical to the unpipelined run."""
    data, labels = _dataset()
    kwargs = dict(fused="auto", max_epochs=50, fail_iterations=2)
    plain = _train(_build(data, labels, Observer, fused_pipeline=False,
                          **kwargs))
    piped = _train(_build(data, labels, Observer, fused_pipeline=True,
                          **kwargs))
    assert piped.sweep_unit is not None and piped.sweep_unit.pipelined
    assert piped.decision._epochs_done == plain.decision._epochs_done
    assert piped.decision.best_n_err[VALID] == plain.decision.best_n_err[
        VALID]
    assert piped.decision.best_epoch == plain.decision.best_epoch
    for fp, fs in zip(plain.forwards, piped.forwards):
        numpy.testing.assert_array_equal(
            numpy.asarray(fp.weights.data), numpy.asarray(fs.weights.data))


def test_sweep_snapshot_resume(tmp_path):
    """A swept workflow pickles and resumes: the FusedSweep rides the
    snapshot (EPHEMERAL = excluded from checksum, not from pickle), its
    volatile plan/state rebuild, and training continues."""
    import os
    import glob

    from veles_tpu.snapshotter import SnapshotterToFile

    data, labels = _dataset()
    wf = _build(data, labels, Observer, fused="auto", max_epochs=2)
    snap = SnapshotterToFile(wf, directory=str(tmp_path), prefix="swp",
                             interval=1, time_interval=0)
    snap.link_from(wf.decision)
    snap.gate_skip = ~wf.decision.improved
    wf.end_point.unlink_from(wf.decision)
    wf.end_point.link_from(snap)
    wf.initialize()
    assert isinstance(wf.sweep_unit, FusedSweep)
    wf.run()
    assert glob.glob(os.path.join(str(tmp_path), "swp_*.pickle*"))

    restored = SnapshotterToFile.import_(snap.destination)
    assert restored.restored_from_snapshot
    restored.workflow = __import__(
        "veles_tpu.dummy", fromlist=["DummyLauncher"]).DummyLauncher()
    # the splice survived the pickle: the sweep unit is still the
    # loader's consumer and keeps its member list
    assert isinstance(restored.sweep_unit, FusedSweep)
    assert restored.sweep_unit in restored.loader.links_to
    restored.decision.max_epochs = 4
    restored.decision.complete.unset()
    restored.decision.train_ended.unset()
    restored.initialize()
    # _enable_segments must NOT have spliced a second engine
    assert sum(1 for u in restored.units
               if isinstance(u, FusedSweep)) == 1
    restored.run()
    assert restored.decision._epochs_done >= 2
    assert restored.sweep_unit.ticks > 0


def test_sweep_dispatch_count():
    """The speed claim in structural form: host dispatches per epoch are
    sweep-granular (chunked), not minibatch-granular."""
    data, labels = _dataset()
    swept = _build(data, labels, Observer, fused="auto", max_epochs=3)
    swept.initialize()
    unit = swept.sweep_unit
    assert isinstance(unit, FusedSweep)
    swept.run()
    # 2 sweeps/epoch x 3 epochs = 6 sweep ticks (12 minibatches each
    # epoch served in 2 class sweeps)
    assert unit.ticks == 6
