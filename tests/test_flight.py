"""Flight-recorder tests (docs/observability.md): the always-on
bounded ring, atomic black-box dumps, and the trigger wiring — an
injected breaker trip must leave a loadable dump holding the trip's
spans and dispatch tail (the ISSUE acceptance), plus the epoch-fence,
unit-exception and SIGTERM paths and the ``observe blackbox`` CLI."""

import json
import os
import signal

import numpy
import pytest

from veles_tpu.core.config import root
from veles_tpu.observe.flight import (FlightRecorder, blackbox_main,
                                      get_flight_recorder,
                                      install_signal_handlers,
                                      load_dump)


@pytest.fixture
def flight_home(tmp_path, monkeypatch):
    """Point the dump dir at tmp and hand out a FRESH global recorder,
    restoring the shared one afterwards (other suites' notes must not
    leak into these asserts)."""
    import veles_tpu.observe.flight as flight_mod

    monkeypatch.setattr(root.common.dirs, "run", str(tmp_path / "run"))
    recorder = FlightRecorder()
    monkeypatch.setattr(flight_mod, "_flight", recorder)
    return recorder, str(tmp_path / "run")


class TestRing:
    def test_bounded_drop_oldest(self):
        recorder = FlightRecorder(capacity=10)
        for i in range(25):
            recorder.note("tick", i=i)
        entries = recorder.entries()
        assert len(entries) == 10
        assert [e["i"] for e in entries] == list(range(15, 25))

    def test_disabled_recorder_records_nothing(self):
        recorder = FlightRecorder(enabled=False)
        recorder.note("tick")
        recorder.note_span({"name": "x"})
        assert recorder.entries() == []

    def test_entries_carry_stamps_and_kind(self):
        recorder = FlightRecorder()
        recorder.note("dispatch", chunk=4)
        (entry,) = recorder.entries()
        assert entry["kind"] == "dispatch" and entry["chunk"] == 4
        assert "t" in entry and "mono" in entry

    def test_spans_land_in_the_ring_when_tracing(self, flight_home,
                                                 monkeypatch):
        """Span._record feeds the black box beside the EventRecorder,
        whatever recorder instance is active."""
        from veles_tpu.core.logger import EventRecorder
        from veles_tpu.core import logger as logger_mod
        from veles_tpu.observe.tracing import Tracer
        import veles_tpu.observe.tracing as tracing_mod

        recorder, _ = flight_home
        monkeypatch.setattr(logger_mod, "_event_recorder",
                            EventRecorder())
        tracer = Tracer(enabled=True)
        monkeypatch.setattr(tracing_mod, "_tracer", tracer)
        with tracer.span("serve.request", rid=7):
            pass
        kinds = [(e["kind"], e.get("name"), e.get("etype"))
                 for e in recorder.entries()]
        assert ("span", "serve.request", "begin") in kinds
        assert ("span", "serve.request", "end") in kinds


class TestDump:
    def test_dump_is_atomic_and_loadable(self, flight_home):
        recorder, run_dir = flight_home
        recorder.note("dispatch", chunk=2)
        path = recorder.dump("testing", extra={"k": "v"})
        assert path and os.path.dirname(path) == run_dir
        assert not [n for n in os.listdir(run_dir) if ".tmp" in n]
        doc = load_dump(path)
        assert doc["schema"] == 1 and doc["reason"] == "testing"
        assert doc["extra"] == {"k": "v"}
        assert doc["entries"][-1]["kind"] == "dispatch"
        assert recorder.last_dump_path == path
        assert recorder.dumps == 1

    def test_dump_includes_live_registry_snapshot(self, flight_home,
                                                  monkeypatch):
        from veles_tpu.observe import metrics as metrics_mod
        from veles_tpu.observe.metrics import MetricsRegistry

        recorder, _ = flight_home
        registry = MetricsRegistry(enabled=True)
        registry.incr("veles_boxed_total", 3)
        monkeypatch.setattr(metrics_mod, "_registry", registry)
        doc = load_dump(recorder.dump("with-metrics"))
        assert ["veles_boxed_total", "counter", [], 3] \
            in doc["metrics"]

    def test_dump_embeds_request_ledger_tail(self, flight_home,
                                             monkeypatch):
        """ISSUE 10 satellite: black-box dumps carry the request
        ledger's tail — in-flight rows plus the slowest resolved — so
        a post-mortem names requests, not just counters."""
        import veles_tpu.observe.reqledger as reqledger_mod
        from veles_tpu.observe.reqledger import RequestLedger

        recorder, _ = flight_home
        ledger = RequestLedger()
        monkeypatch.setattr(reqledger_mod, "_ledger", ledger)
        done = ledger.stage(api="generate-api", trace="aa11",
                            prompt_len=7)
        ledger.link(done, 0)
        ledger.note_admit(done, "dense", group=2, bucket=16)
        ledger.note_tokens(done, 3)
        ledger.resolve(done, "completed")
        live = ledger.stage(api="generate-api", prompt_len=9)
        doc = load_dump(recorder.dump("with-requests"))
        requests = doc["requests"]
        assert [r["id"] for r in requests["inflight"]] == [live["id"]]
        (slow,) = requests["slowest"]
        assert slow["outcome"] == "completed" and slow["tokens"] == 3
        assert [s[0] for s in slow["stages"]] == [
            "staged", "admitted", "first_token", "resolved"]

    def test_dump_is_reentrant_from_the_same_thread(self, flight_home):
        """A repeated SIGTERM re-enters dump() on the main thread while
        a dump is in flight — the lock must be re-entrant or the
        process hangs instead of dumping and dying."""
        recorder, _ = flight_home
        with recorder._dump_lock:  # simulate mid-dump state
            path = recorder.dump("nested")
        assert path is not None
        assert load_dump(path)["reason"] == "nested"

    def test_dump_failure_is_warned_once_not_raised(self, flight_home,
                                                    monkeypatch):
        recorder, _ = flight_home
        monkeypatch.setattr(root.common.dirs, "run",
                            "/proc/definitely/not/writable")
        assert recorder.dump("doomed") is None
        assert recorder.dump("doomed-again") is None  # silent now
        assert recorder.dumps == 0


class TestTriggers:
    @pytest.fixture
    def model(self):
        from veles_tpu.parallel.transformer_step import (
            init_transformer_params)
        import jax.numpy as jnp

        rng = numpy.random.RandomState(0)
        params = init_transformer_params(rng, 2, 16, 4, 11)
        table = jnp.asarray(
            rng.randn(11, 16).astype(numpy.float32) * 0.3)
        return params, table, 4

    def test_breaker_trip_dumps_spans_and_dispatch_tail(
            self, model, flight_home, monkeypatch):
        """The acceptance criterion: an injected breaker trip produces
        a loadable black-box dump containing the trip's spans and the
        dispatch tail that led to it."""
        import urllib.request
        import veles_tpu.observe.reqledger as reqledger_mod
        import veles_tpu.parallel.decode as decode_mod
        from veles_tpu.core.logger import EventRecorder
        from veles_tpu.core import logger as logger_mod
        from veles_tpu.observe.reqledger import RequestLedger
        from veles_tpu.observe.tracing import get_tracer
        from veles_tpu.serving import GenerateAPI

        recorder, _ = flight_home
        monkeypatch.setattr(reqledger_mod, "_ledger", RequestLedger())
        monkeypatch.setattr(logger_mod, "_event_recorder",
                            EventRecorder())
        tracer = get_tracer()
        was_traced = tracer.enabled
        tracer.enable()
        params, table, heads = model
        api = GenerateAPI(params, table, heads, slots=2, max_len=32,
                          n_tokens=4, chunk=2, port=0)
        api.start()
        real = decode_mod.slot_step_many

        def injected(*args, **kwargs):
            raise RuntimeError("injected device failure")

        try:
            monkeypatch.setattr(decode_mod, "slot_step_many", injected)
            req = urllib.request.Request(
                "http://127.0.0.1:%d/generate" % api.port,
                data=json.dumps({"tokens": [1, 2]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=30)
            assert err.value.code == 503  # shed, retryable
        finally:
            monkeypatch.setattr(decode_mod, "slot_step_many", real)
            api.stop()
            tracer.enabled = was_traced
        path = recorder.last_dump_path
        assert path is not None, "breaker trip produced no dump"
        doc = load_dump(path)
        assert doc["reason"] == "breaker_trip"
        assert "injected device failure" in doc["extra"]["error"]
        kinds = [e["kind"] for e in doc["entries"]]
        # the dispatch tail: the admit dispatch that preceded the trip
        assert "admit" in kinds
        assert kinds[-1] == "breaker.trip"
        # the trip's spans: the request's serving spans are in the ring
        span_names = {e.get("name") for e in doc["entries"]
                      if e["kind"] == "span"}
        assert "serve.request" in span_names
        assert "serve.submit" in span_names
        # the trip ships the requests it shed (ISSUE 10 satellite):
        # the dump runs BEFORE _fail_all, so the victim is still an
        # in-flight ledger row with its waterfall up to the admit
        shed = doc["requests"]["inflight"]
        assert len(shed) == 1, doc["requests"]
        stages = [s[0] for s in shed[0]["stages"]]
        assert stages[0] == "staged" and "admitted" in stages
        assert shed[0]["outcome"] is None
        assert shed[0]["admit"]["kind"] == "dense"

    def test_unhandled_unit_exception_dumps(self, flight_home):
        from veles_tpu.dummy import DummyWorkflow

        recorder, _ = flight_home
        wf = DummyWorkflow(name="boom-wf")
        wf.on_error(RuntimeError("unit exploded"), None)
        doc = load_dump(recorder.last_dump_path)
        assert doc["reason"] == "unit_exception"
        assert "unit exploded" in doc["extra"]["error"]
        assert doc["extra"]["workflow"] == "boom-wf"

    def test_stale_epoch_fence_dumps(self, flight_home):
        from veles_tpu.fleet.ledger import (FENCE_DUPLICATE,
                                            FENCE_STALE_EPOCH,
                                            JobLedger)
        from veles_tpu.fleet.server import Server

        recorder, _ = flight_home
        server = Server.__new__(Server)
        server.ledger = JobLedger()
        server.epoch = "epoch-2"
        # non-stale verdicts only note (the ring keeps them for a later
        # dump); the stale-epoch zombie dumps immediately
        server._note_fence(FENCE_DUPLICATE, "slave-1", 7)
        assert recorder.last_dump_path is None
        server._note_fence(FENCE_STALE_EPOCH, "slave-1", 7)
        doc = load_dump(recorder.last_dump_path)
        assert doc["reason"] == "epoch_fence"
        assert doc["extra"]["slave"] == "slave-1"
        kinds = [(e["kind"], e.get("verdict")) for e in doc["entries"]]
        assert ("fleet.fence", FENCE_DUPLICATE) in kinds
        assert ("fleet.fence", FENCE_STALE_EPOCH) in kinds

    def test_sigterm_dumps_and_chains_previous_handler(
            self, flight_home):
        recorder, _ = flight_home
        chained = []
        original = signal.signal(signal.SIGTERM,
                                 lambda s, f: chained.append(s))
        try:
            previous = install_signal_handlers()
            assert signal.SIGTERM in previous
            os.kill(os.getpid(), signal.SIGTERM)
            assert chained == [signal.SIGTERM]
            doc = load_dump(recorder.last_dump_path)
            assert doc["reason"] == "sigterm"
            assert doc["entries"][-1]["kind"] == "signal"
        finally:
            signal.signal(signal.SIGTERM, original)


class TestBlackboxCLI:
    def test_single_dump_summary(self, flight_home, capsys):
        recorder, _ = flight_home
        recorder.note("dispatch", chunk=8)
        path = recorder.dump("testing")
        assert blackbox_main(path, tail=5) == 0
        out = capsys.readouterr().out
        assert "reason: testing" in out
        assert "dispatch" in out

    def test_directory_listing_newest_first(self, flight_home, capsys):
        recorder, run_dir = flight_home
        first = recorder.dump("older")
        second = recorder.dump("newer")
        os.utime(first, (1, 1))
        assert blackbox_main(run_dir) == 0
        out = capsys.readouterr().out
        assert out.index(second) < out.index(first)

    def test_empty_directory_exits_one(self, flight_home, capsys):
        _, run_dir = flight_home
        os.makedirs(run_dir, exist_ok=True)
        assert blackbox_main(run_dir) == 1
        assert "no black-box dumps" in capsys.readouterr().out

    def test_garbage_file_exits_one(self, tmp_path, capsys):
        bad = tmp_path / "not-a-dump.json"
        bad.write_text("{]")
        assert blackbox_main(str(bad)) == 1
        assert "cannot load" in capsys.readouterr().out

    def test_observe_cli_routes_blackbox(self, flight_home, capsys):
        from veles_tpu.observe.trace_export import main as observe_main

        recorder, _ = flight_home
        path = recorder.dump("via-cli")
        assert observe_main(["blackbox", path]) == 0
        assert "via-cli" in capsys.readouterr().out
